"""One-shot TPU window sprint: run every pending hardware probe in strict
priority order with per-section subprocess timeouts, so a short tunnel window
yields the most decision value before it closes.

Sections (each its own subprocess; a hang costs only its own budget):
  1. XPlane profile of the classic ResNet-50 step (cached HLO — fast) —
     the "where does the time go" breakdown VERDICT r2 #1 asks for.
  2. Pallas fused-attention microbench (hang-prone remote compile).
  3. stem_space_to_depth=True headline variant (fresh HLO — may starve).
  4. digits real-data training on the chip (fresh small HLO).

Writes one JSON line per completed section to stdout AND appends to
WINDOW_SPRINT.jsonl so partial windows still leave a record.

Usage: python tools/window_sprint.py [--skip profile,attention,s2d,digits]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "WINDOW_SPRINT.jsonl")

SECTIONS = [
    (
        "profile",
        [
            sys.executable,
            os.path.join(REPO, "tools", "profile_step.py"),
            "--preset",
            "resnet50_classic_imagenet",
            "--batch",
            "256",
            "--steps",
            "5",
            "--logdir",
            "/tmp/tfdl_sprint_prof",
        ],
        1200,
    ),
    (
        "attention",
        [sys.executable, os.path.join(REPO, "tools", "probe_attention.py")],
        1200,
    ),
    (
        "s2d",
        [
            sys.executable,
            os.path.join(REPO, "tools", "probe_extras.py"),
            "--s2d-true-only",
        ],
        1800,
    ),
    (
        "digits",
        [
            sys.executable,
            os.path.join(REPO, "examples", "train_digits.py"),
            "--model-dir",
            "/tmp/tfdl_digits_tpu",
            # the LARS large-batch recipe: best measured digits number
            # (97.2% @ 150 steps, DIGITS_RUN.json) at a third of the steps
            # of the adam run — and it exercises the 8k-preset optimizer
            # path on the real chip
            "--recipe",
            "lars",
            "--batch-size",
            "256",
            "--steps",
            "150",
            "--json-out",
            "/tmp/tfdl_digits_tpu_record.json",
        ],
        1800,
    ),
    # fresh-HLO remat probe (VERDICT r3 weak #2: batch 512 measured slower
    # than 256 — does rematerialization recover it?) — after the cached
    # probes, before bench
    (
        "remat512",
        [
            sys.executable,
            os.path.join(REPO, "tools", "probe_extras.py"),
            "--remat-batch",
            "512",
        ],
        1500,
    ),
    # real-pixel segmentation at FULL tgs_salt width on the chip (r5: the
    # CPU-budget committed run in SEG_RUN.json is width x0.125; the chip can
    # afford the real preset — Lovász + mIOU + TTA ensemble on real scans)
    (
        "seg",
        [
            sys.executable,
            os.path.join(REPO, "examples", "train_digit_seg.py"),
            "--model-dir",
            "/tmp/tfdl_seg_tpu",
            "--steps",
            "400",
            "--batch-size",
            "64",
            "--n-fold",
            "2",
            "--json-out",
            "/tmp/tfdl_seg_tpu_record.json",
        ],
        1800,
    ),
    # full bench last: refreshes the headline + extras under the
    # merge-preserving cache (its own supervisor bounds the children)
    (
        "bench",
        [sys.executable, os.path.join(REPO, "bench.py")],
        1700,
    ),
]


def record(entry: dict) -> None:
    entry["ts"] = time.strftime("%Y-%m-%d %H:%M:%S UTC", time.gmtime())
    line = json.dumps(entry)
    print(line, flush=True)
    with open(OUT, "a") as f:
        f.write(line + "\n")


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--skip", default="", help="comma-separated section names")
    args = parser.parse_args()
    skip = {s.strip() for s in args.skip.split(",") if s.strip()}

    for name, cmd, budget in SECTIONS:
        if name in skip:
            record({"section": name, "skipped": True})
            continue
        t0 = time.time()
        try:
            proc = subprocess.run(
                cmd,
                capture_output=True,
                text=True,
                timeout=budget,
                cwd=REPO,
            )
            out_lines = [
                ln for ln in proc.stdout.strip().splitlines() if ln.startswith("{")
            ]
            record(
                {
                    "section": name,
                    "rc": proc.returncode,
                    "secs": round(time.time() - t0, 1),
                    "output": [json.loads(ln) for ln in out_lines[-4:]],
                    "stderr_tail": proc.stderr[-300:] if proc.returncode else "",
                }
            )
        except subprocess.TimeoutExpired as e:
            partial = [
                ln
                for ln in (e.stdout or "").strip().splitlines()
                if ln.startswith("{")
            ]
            record(
                {
                    "section": name,
                    "timeout": budget,
                    "partial_output": [json.loads(ln) for ln in partial[-4:]],
                }
            )
    return 0


if __name__ == "__main__":
    sys.exit(main())
