"""Minimal standalone repro of the XLA:CPU cumulative-compile segfault.

Observed on this box (jax 0.9.0, CPU backend, 1 core): a single process that
keeps compiling FRESH HLO — every executable unique, nothing cache-hit —
segfaults inside ``backend_compile_and_load`` after a few hundred compiles
(full-suite runs died around test ~315; every module passes in isolation, so
the crash is cumulative process state, not any one program). The repo
contains two mitigations (conftest.py's RSS-growth ``jax.clear_caches()``
and tools/run_suite.py's process partitioning); this script is the
upstream-filable distillation: no pytest, no framework, just unique tiny
jits until the process dies.

Usage:
    python tools/repro_xla_segfault.py [--max-compiles 2000] [--report-every 25]
    # exits 0 if it survives --max-compiles; a segfault kills the process
    # with SIGSEGV (rc -11 / 139) — the repro. Run under a parent shell and
    # check $?. Each compile is unique via a baked-in constant and varying
    # shapes, defeating every cache layer (in-memory and persistent).

Observed crash point (r5, this box): see REPRO_XLA_SEGFAULT.json at the
repo root after a run — the wrapper mode below writes it.

    python tools/repro_xla_segfault.py --supervise [--mode tiny|conv|sharded]
    # spawns itself as a child, records rc + last progress line + env to
    # REPRO_XLA_SEGFAULT.json (the committable evidence, one entry per mode).

r5 FINDING (committed in REPRO_XLA_SEGFAULT.json): all three escalating
distillations SURVIVED on this box — tiny x2000, conv+BN+grad x600,
shard_map+psum over the 8-device mesh x500 — so the suite crash is NOT a
function of fresh-compile count alone; it needs full-suite cumulative state
(hundreds-of-MB RSS from real Flax modules, pytest fixtures, donated-buffer
executables). The upstream filing therefore ships this script as the
"what it is NOT" half plus tools/run_suite.py's partitioning as the
containment; the positive minimal repro remains open.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time


def run_compiles(max_compiles: int, report_every: int, mode: str = "tiny") -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    import jax.numpy as jnp

    jax.config.update("jax_platforms", "cpu")
    # match the suite's regime: no persistent cache, every HLO fresh — must go
    # through jax.config (env mutation after `import jax` is ignored; a stray
    # exported JAX_COMPILATION_CACHE_DIR would otherwise cache-hit run 2 and
    # print a false-negative SURVIVED)
    jax.config.update("jax_compilation_cache_dir", None)

    rss_path = "/proc/self/status"

    def rss_mb() -> float:
        try:
            with open(rss_path) as f:
                for line in f:
                    if line.startswith("VmRSS"):
                        return float(line.split()[1]) / 1024.0
        except OSError:
            pass
        return -1.0

    t0 = time.time()
    for i in range(max_compiles):
        # unique program: the baked-in constant and a shape that walks a
        # range make every compile a fresh HLO module (no cache hits, the
        # suite's cold-cache regime)
        n = 8 + (i % 64)
        c = float(i) + 0.5

        if mode == "tiny":

            def fresh(x, _c=c):
                y = jnp.sin(x) * _c + jnp.arange(x.shape[0], dtype=x.dtype)
                return (y @ y[:, None])[0] + _c

            arg = jnp.ones((n,), jnp.float32)
        elif mode == "sharded":
            # suite programs are shard_map'd over the forced 8-device CPU
            # mesh — the partitioner + collective thread machinery is the
            # one suite ingredient the other modes lack
            from jax.sharding import Mesh, PartitionSpec as P

            mesh = Mesh(jax.devices(), ("d",))

            def body(x, _c=c):
                y = jnp.sin(x) * _c + x.sum(axis=0, keepdims=True)
                return jax.lax.psum(y, "d") * _c

            fresh = jax.shard_map(
                body, mesh=mesh, in_specs=P("d"), out_specs=P()
            )
            arg = jnp.ones((8, n), jnp.float32)
        else:
            # 'conv' mode: the tiny variant SURVIVED 2000 compiles (r5,
            # REPRO_XLA_SEGFAULT.json) — whatever kills the suite needs
            # programs shaped like the suite's: conv + BN-ish reductions +
            # a grad, each module still unique via the baked constant and
            # a walked channel count
            ch = 4 + (i % 8)

            def fresh(x, _c=c, _ch=ch):
                k = jnp.full((3, 3, x.shape[-1], _ch), _c, x.dtype)
                y = jax.lax.conv_general_dilated(
                    x, k, (1, 1), "SAME",
                    dimension_numbers=("NHWC", "HWIO", "NHWC"),
                )
                mean = y.mean(axis=(0, 1, 2))
                var = ((y - mean) ** 2).mean(axis=(0, 1, 2))
                z = (y - mean) * jax.lax.rsqrt(var + 1e-5)
                return jnp.maximum(z, 0.0).sum()

            def fresh(x, _f=jax.grad(fresh)):  # noqa: F811 — value+grad jit
                return _f(x).sum()

            arg = jnp.ones((2, 8 + (i % 4) * 2, 8, 4), jnp.float32)

        out = jax.jit(fresh)(arg)
        out.block_until_ready()
        if (i + 1) % report_every == 0:
            print(
                f"PROGRESS {i + 1} compiles  rss_mb={rss_mb():.0f}  "
                f"elapsed={time.time() - t0:.0f}s",
                flush=True,
            )
    print(f"SURVIVED {max_compiles} fresh compiles", flush=True)
    return 0


def supervise(max_compiles: int, report_every: int, mode: str = "tiny") -> int:
    """Run the compile loop in a child; record the outcome as evidence."""
    args = [
        sys.executable,
        os.path.abspath(__file__),
        f"--max-compiles={max_compiles}",
        f"--report-every={report_every}",
        f"--mode={mode}",
    ]
    t0 = time.time()
    # generous per-compile allowance; a wedged compile (the documented
    # remote-hang failure mode) must still leave evidence, not block forever
    budget_secs = max(600, max_compiles * 3)
    hung = False
    try:
        # errors="replace" on the normal path too: the child dies by SIGSEGV
        # by design and can truncate output mid multi-byte char either way
        proc = subprocess.run(
            args,
            capture_output=True,
            text=True,
            errors="replace",
            timeout=budget_secs,
        )
        returncode, stdout, stderr = proc.returncode, proc.stdout, proc.stderr
    except subprocess.TimeoutExpired as e:
        hung = True
        returncode = None
        # errors="replace": the kill can truncate output mid multi-byte char,
        # and a decode crash here would lose the evidence record entirely
        stdout = (
            (e.stdout or b"").decode(errors="replace")
            if isinstance(e.stdout, bytes)
            else (e.stdout or "")
        )
        stderr = (
            (e.stderr or b"").decode(errors="replace")
            if isinstance(e.stderr, bytes)
            else (e.stderr or "")
        )
    lines = [ln for ln in stdout.splitlines() if ln.strip()]
    last = lines[-1] if lines else ""
    import jax

    record = {
        "script": "tools/repro_xla_segfault.py",
        "mode": mode,
        "returncode": returncode,
        # only a signal death is the repro; rc>0 is a setup failure, not a crash
        "crashed": returncode is not None and returncode < 0,
        "hung": hung,
        "signal": -returncode if (returncode or 0) < 0 else None,
        "last_progress": last,
        "max_compiles": max_compiles,
        "wall_secs": round(time.time() - t0, 1),
        "jax_version": jax.__version__,
        "stderr_tail": stderr[-500:],
    }
    out_path = os.path.abspath(os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "..",
        "REPRO_XLA_SEGFAULT.json",
    ))
    # one file, one entry per mode — the tiny negative and the conv attempt
    # are both evidence; neither may clobber the other
    try:
        with open(out_path) as f:
            existing = json.load(f)
    except (OSError, ValueError):
        existing = {}
    if "modes" not in existing:
        existing = {"modes": ({existing.get("mode", "tiny"): existing}
                              if existing else {})}
    existing["modes"][mode] = record
    with open(out_path, "w") as f:
        json.dump(existing, f, indent=1)
    print(json.dumps(record), flush=True)
    return 0


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--max-compiles", type=int, default=2000)
    parser.add_argument("--report-every", type=int, default=25)
    parser.add_argument("--supervise", action="store_true")
    parser.add_argument(
        "--mode",
        choices=("tiny", "conv", "sharded"),
        default="tiny",
        help="program shape per fresh compile: 'tiny' scalar-ish jits "
        "(SURVIVED 2000 on this box), 'conv' conv+BN-stats+grad modules "
        "(the suite's shape), 'sharded' shard_map+psum over the 8-device "
        "CPU mesh (the suite's partitioner/collective machinery)",
    )
    args = parser.parse_args()
    if args.supervise:
        return supervise(args.max_compiles, args.report_every, args.mode)
    return run_compiles(args.max_compiles, args.report_every, args.mode)


if __name__ == "__main__":
    raise SystemExit(main())
