"""Closed-loop load generator for the serve/ stack: batched vs per-request.

Builds a small synthetic params-baked model (pure jax, no checkpoint), then
drives it with N closed-loop clients (each thread issues its next request the
moment the previous one answers — the standard closed-loop load model) in up
to three configurations:

- ``per_request``: the same serving pipeline (bounded queue, single dispatch
  worker, futures) with coalescing OFF — every request is its own batch-1
  forward, serialized at the device exactly like a no-batching server in
  front of one accelerator;
- ``batched``:     identical pipeline with the bucket-ladder coalescing ON —
  the only variable is server-side batching;
- ``http``:        the full stack — ThreadingHTTPServer, JSON wire format,
  batcher, engine (enabled with ``--http``).

Also probes the backpressure contract (a full bounded queue must answer with
a structured QueueFullError, not queue unboundedly) and — when ``--ledger-dir``
is given — runs under a Telemetry recompile detector marked warm after bucket
warmup, so the record carries the post-warmup recompile count (must be 0: the
bucket ladder exists so steady-state serving never recompiles).

``--quant`` adds the precision A/B: a bigger synthetic model is EXPORTED
through the real quantized-serving seam (train/quantize.py +
train/serving.py) at every precision in ``--quant-dtypes``, each artifact is
served through its own engine from the manifest alone, and the record gains a
``precisions`` section — throughput, latency percentiles, per-bucket
padding-waste fraction, artifact bytes at rest, post-warmup recompiles (must
be 0 per precision) — plus a quantize-check accuracy verdict for every
quantized precision (the Gemma-on-TPU methodology: curves per precision, not
single points; arXiv:2605.25645). ``--quant-only`` skips the batching A/B for
a fast, CPU-reproducible gate run.

``--fleet`` adds the serving-tier soak (the Gemma-on-TPU methodology at fleet
granularity: curves across REPLICA COUNTS, not single points): a bigger
synthetic artifact is exported once, then for each count in
``--fleet-replicas`` a real fleet — N ``serve`` subprocesses supervised by
``serve.fleet.FleetManager`` behind a ``serve.router.FleetRouter`` — is
driven by closed-loop HTTP clients through the router. The record gains a
``fleet`` section: per-count throughput/latency, per-replica routed counts
and post-warmup recompiles (from the per-replica ledgers), a scaling table
(speedup and efficiency vs 1 replica), a saturation probe (tiny replica
queues, oversubscribed clients — the fleet must shed with 429 + Retry-After,
never any other 5xx, never unbounded queueing), and a kill-a-replica soak
(``--inject-fault sigkill@N`` on one replica mid-load: the router must
re-dispatch onto survivors with ZERO client-visible errors, the manager must
restart the replica, and the fleet must converge back to full strength).

``--promotion`` adds the train→serve promotion soak (serve/promote.py
through the real CLIs, closed-loop load the whole time): the
kill-mid-canary drill — promote a passing candidate across a 3-replica
fleet with ``sigkill@N`` injected into the canary's first launch; the
controller must CONVERGE (promotion complete, canary restarted on the
candidate, zero client-visible errors) — and the rollback-on-regression
drill — a poisoned candidate must pass manifest admission but be caught by
the shadow compare and rolled back automatically, fleet restored to the
incumbent fingerprint. The record gains a ``promotion`` section replayed as
hard gates by ``tools/regression_sentinel.py``.

Writes a JSON record (default BENCH_SERVE.json). ``--check`` exits non-zero
unless batched/per_request speedup >= --min-speedup, recompiles == 0, and the
backpressure probe rejected structurally — the CI serve-smoke gate
(tools/run_suite.py --serve-smoke). With ``--quant`` it additionally requires
every quantize-check to pass, zero post-warmup recompiles per precision, and
bf16-vs-f32 throughput >= --min-quant-speedup at no-worse p99 — the floor
defaults to 1.5 on TPU (the HBM-roofline win the path exists for) and to a
0.8 not-materially-slower tripwire elsewhere (XLA:CPU upcasts bf16, so the
bandwidth win does not exist off-TPU; measured on this container, see
BENCH_SERVE.json precisions.note), which keeps the gate reproducible on CPU
CI. With ``--fleet`` it additionally requires 2-replica throughput >=
``--min-fleet-scaling`` x single-replica at no-worse p99 (x``--max-fleet-p99-
ratio`` slack for tail noise), zero post-warmup recompiles on EVERY replica,
graceful shedding (429s present, zero non-drain 5xx), and the kill soak to
converge with zero lost accepted requests.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

FEATURES = 128
HIDDEN = 256
CLASSES = 16


def make_synthetic_model():
    """Params-baked jitted ``x [B, FEATURES] -> {probabilities, class}`` —
    shaped like the trainers' serving_fn closures, sized so one forward is
    dispatch-overhead-dominated at batch 1 (the regime batching exists for)."""
    import jax
    import jax.numpy as jnp

    k1, k2 = jax.random.split(jax.random.PRNGKey(0))
    w1 = jax.random.normal(k1, (FEATURES, HIDDEN), jnp.float32) * 0.05
    w2 = jax.random.normal(k2, (HIDDEN, CLASSES), jnp.float32) * 0.05

    @jax.jit
    def serve(x):
        h = jnp.maximum(x @ w1, 0.0)
        logits = h @ w2
        return {
            "probabilities": jax.nn.softmax(logits, axis=-1),
            "class": jnp.argmax(logits, axis=-1),
        }

    return serve


# the quant A/B model is bigger than the batching-A/B one on purpose: the
# precision recipes act on weight bytes, so the weights must be large enough
# that artifact sizes (and, on TPU, HBM traffic) visibly scale with dtype
QUANT_HIDDEN = 1024


def make_quant_model_params():
    """Float32 params tree for the quant A/B — flax-shaped (``kernel`` leaves)
    so the int8 per-channel recipe engages exactly like on a real model."""
    import jax
    import jax.numpy as jnp

    k1, k2 = jax.random.split(jax.random.PRNGKey(3))
    return {
        "dense1": {
            "kernel": jax.random.normal(
                k1, (FEATURES, QUANT_HIDDEN), jnp.float32
            ) * 0.05,
            "bias": jnp.zeros((QUANT_HIDDEN,), jnp.float32),
        },
        "dense2": {
            "kernel": jax.random.normal(
                k2, (QUANT_HIDDEN, CLASSES), jnp.float32
            ) * 0.05,
        },
    }


def export_quant_artifact(params, serving_dtype: str, directory: str) -> str:
    """Export the quant-A/B model at one precision through the REAL seam:
    quantize the params tree, bake dequantization into the serve closure,
    serialize with the manifest ``quantization`` section. The
    ``int8-compute`` spec traces the same model as a flax net under
    ``int8_intercept`` — the identical seam the trainers' serving closures
    use — so the artifact's graph runs the quant kernels (TPU) or their
    dequantize-f32 fallback (CPU), not the dequantize-in-graph path."""
    import jax
    import jax.numpy as jnp

    from tensorflowdistributedlearning_tpu.train import quantize
    from tensorflowdistributedlearning_tpu.train import serving as serving_lib

    qtree, section = quantize.quantize_pytree(params, serving_dtype)
    act_dtype = quantize.compute_dtype(serving_dtype)

    if section.get("compute_dtype") == "int8":
        from flax import linen as nn

        from tensorflowdistributedlearning_tpu.ops import quant_kernels

        class _QuantNet(nn.Module):
            @nn.compact
            def __call__(self, x):
                h = nn.relu(nn.Dense(QUANT_HIDDEN, name="dense1")(x))
                return nn.Dense(CLASSES, name="dense2", use_bias=False)(h)

        net = _QuantNet()

        def serve(x):
            p = quantize.dequantize_pytree(qtree, act_dtype)
            with quant_kernels.int8_intercept(qtree, act_dtype):
                logits = net.apply({"params": p}, x.astype(act_dtype))
            out = {
                "probabilities": jax.nn.softmax(logits, axis=-1),
                "class": jnp.argmax(logits, axis=-1),
            }
            return quantize.cast_outputs_float32(out)

        return serving_lib.export_serving_artifact(
            serve, (1, FEATURES), directory, quantization=section
        )

    def serve(x):
        p = quantize.dequantize_pytree(qtree, act_dtype)
        h = jnp.maximum(
            x.astype(act_dtype) @ p["dense1"]["kernel"] + p["dense1"]["bias"],
            0,
        )
        logits = h @ p["dense2"]["kernel"]
        out = {
            "probabilities": jax.nn.softmax(logits, axis=-1),
            "class": jnp.argmax(logits, axis=-1),
        }
        return quantize.cast_outputs_float32(out)

    return serving_lib.export_serving_artifact(
        serve, (1, FEATURES), directory, quantization=section
    )


def quant_precision_ab(args, telemetry) -> dict:
    """The per-precision serving A/B: export each precision, serve each from
    its manifest alone (fresh engine + registry + recompile detector per
    precision), drive the identical closed-loop load, run the accuracy gate
    for every quantized precision against the f32 reference."""
    import tempfile

    from tensorflowdistributedlearning_tpu.obs import RecompileDetector
    from tensorflowdistributedlearning_tpu.serve import (
        InferenceEngine,
        MicroBatcher,
    )
    from tensorflowdistributedlearning_tpu.serve.quant_check import (
        run_quant_check,
    )
    from tensorflowdistributedlearning_tpu.train import serving as serving_lib

    params = make_quant_model_params()
    root = tempfile.mkdtemp(prefix="bench_quant_")
    section: dict = {"precisions": {}, "quant_check": {}}
    dirs: dict = {}
    for dtype in args.quant_dtypes:
        directory = os.path.join(root, dtype)
        try:
            export_quant_artifact(params, dtype, directory)
        except Exception as e:  # noqa: BLE001 — record, keep the A/B alive
            section["precisions"][dtype] = {
                "skipped": f"{type(e).__name__}: {e}"
            }
            continue
        dirs[dtype] = directory

    for dtype, directory in dirs.items():
        print(f"precision {dtype}: {args.concurrency} clients, "
              f"{args.duration}s ...", flush=True)
        detector = RecompileDetector().attach()
        try:
            engine = InferenceEngine.from_artifact(
                directory, buckets=args.buckets
            )
            warmup_s = engine.warmup()
            detector.mark_warm()
            batcher = MicroBatcher(
                engine, max_wait_ms=args.max_wait_ms,
                max_queue=max(256, 4 * args.concurrency),
            )
            entry = best_of(
                lambda x: batcher.submit(x).result(30),
                args.concurrency, args.duration, args.trials,
            )
            batcher.close()
            entry["warmup_s"] = {str(b): s for b, s in warmup_s.items()}
            entry["bucket_hits"] = {
                str(b): n for b, n in engine.bucket_hits.items()
            }
            entry["padding_waste"] = {
                str(b): w for b, w in engine.padding_waste.items()
            }
            entry["artifact_bytes"] = os.path.getsize(
                os.path.join(directory, serving_lib.ARTIFACT_NAME)
            )
            entry["post_warmup_recompiles"] = detector.post_warmup_count
            if entry.get("requests_per_sec"):
                from tensorflowdistributedlearning_tpu.obs import (
                    capacity as capacity_lib,
                )

                entry["rps_per_chip"] = round(
                    entry["requests_per_sec"] / capacity_lib.device_count(), 1
                )
        finally:
            detector.detach()
        section["precisions"][dtype] = entry
        telemetry.event("bench_mode", mode=f"quant_{dtype}", **entry)

    f32_dir = dirs.get("float32")
    if f32_dir:
        for dtype, directory in dirs.items():
            if dtype == "float32":
                continue
            verdict = run_quant_check(
                f32_dir, directory, telemetry=telemetry
            )
            section["quant_check"][dtype] = {
                "passed": verdict["passed"],
                "failures": verdict["failures"],
                "outputs": verdict["outputs"],
            }

    f32 = section["precisions"].get("float32", {})
    for dtype in args.quant_dtypes:
        entry = section["precisions"].get(dtype, {})
        if dtype == "float32" or "requests_per_sec" not in entry:
            continue
        if f32.get("requests_per_sec"):
            entry["speedup_vs_f32"] = round(
                entry["requests_per_sec"] / f32["requests_per_sec"], 3
            )
            entry["p99_ratio_vs_f32"] = round(
                entry["latency_ms"]["p99"] / f32["latency_ms"]["p99"], 3
            )
            entry["artifact_bytes_ratio_vs_f32"] = round(
                entry["artifact_bytes"] / f32["artifact_bytes"], 3
            )
    # the storage-vs-compute delta: what switching the ARITHMETIC (not the
    # bytes — both artifacts store identical int8 records) buys or costs
    store = section["precisions"].get("int8", {})
    comp = section["precisions"].get("int8-compute", {})
    if store.get("requests_per_sec") and comp.get("requests_per_sec"):
        comp["speedup_vs_int8_store"] = round(
            comp["requests_per_sec"] / store["requests_per_sec"], 3
        )
        comp["p99_ratio_vs_int8_store"] = round(
            comp["latency_ms"]["p99"] / store["latency_ms"]["p99"], 3
        )
        comp["artifact_bytes_ratio_vs_int8_store"] = round(
            comp["artifact_bytes"] / store["artifact_bytes"], 3
        )
    return section


# -- fleet soak ---------------------------------------------------------------

# the fleet model answers with a MASK-sized output (this repo's serving
# workload is segmentation: a 101x101 mask is ~10k floats per example), so
# per-request work is dominated by the REPLICA (forward + response encoding)
# rather than by the router's byte-copy proxy path — which is what makes the
# replica-count sweep measure fleet capacity instead of front-end overhead
FLEET_HIDDEN = 1024
FLEET_OUT = 4096


def export_fleet_artifact(directory: str) -> str:
    """Export the fleet-soak model through the real serving seam so replicas
    load it exactly like production artifacts (manifest + StableHLO)."""
    import jax
    import jax.numpy as jnp

    from tensorflowdistributedlearning_tpu.train import serving as serving_lib

    k1, k2 = jax.random.split(jax.random.PRNGKey(5))
    w1 = jax.random.normal(k1, (FEATURES, FLEET_HIDDEN), jnp.float32) * 0.05
    w2 = jax.random.normal(k2, (FLEET_HIDDEN, FLEET_OUT), jnp.float32) * 0.05

    def serve(x):
        h = jnp.maximum(x @ w1, 0.0)
        return {"mask_probabilities": jax.nn.sigmoid(h @ w2)}

    return serving_lib.export_serving_artifact(serve, (1, FEATURES), directory)


def export_promotion_artifact(
    directory: str, seed: int, perturb: float = 0.0
) -> str:
    """Export a promotion-soak artifact WITH an identity section (float32
    identity recipe: dtype + sha256 source fingerprint over the params) so
    the controller's replica-identity verification runs for real. ``perturb``
    nudges the weights off the seed model: small = a passing candidate,
    large = the poisoned one the shadow gate must catch."""
    import jax
    import jax.numpy as jnp

    from tensorflowdistributedlearning_tpu.train import quantize
    from tensorflowdistributedlearning_tpu.train import serving as serving_lib

    k1, k2 = jax.random.split(jax.random.PRNGKey(seed))
    w1 = jax.random.normal(k1, (FEATURES, 256), jnp.float32) * 0.05
    w2 = jax.random.normal(k2, (256, 512), jnp.float32) * 0.05
    if perturb:
        kp = jax.random.PRNGKey(seed + 1000)
        w2 = w2 + perturb * jax.random.normal(kp, w2.shape, jnp.float32)
    params = {"l1": {"kernel": w1}, "l2": {"kernel": w2}}
    _, section = quantize.quantize_pytree(params, "float32")

    def serve(x):
        h = jnp.maximum(x @ params["l1"]["kernel"], 0.0)
        return {"mask_probabilities": jax.nn.sigmoid(h @ params["l2"]["kernel"])}

    serving_lib.export_serving_artifact(
        serve, (1, FEATURES), directory, quantization=section
    )
    return directory


class _PromotionLoad:
    """Continuous closed-loop client for the promotion soak: runs until
    stopped (a promotion's length is not known up front), counts every
    non-200 as a client-visible error."""

    def __init__(self, url: str):
        import urllib.parse

        self.parsed = urllib.parse.urlsplit(url)
        self.ok = 0
        self.errors = 0
        self._stop = threading.Event()
        rng = np.random.default_rng(17)
        self.body = json.dumps(
            {"instances": rng.normal(0, 1, (1, FEATURES)).tolist()}
        )
        self.thread = threading.Thread(target=self._run, daemon=True)
        self.thread.start()

    def _run(self):
        import http.client

        conn = None
        while not self._stop.is_set():
            try:
                if conn is None:
                    conn = http.client.HTTPConnection(
                        self.parsed.hostname, self.parsed.port, timeout=30
                    )
                conn.request("POST", "/v1/predict", self.body,
                             {"Content-Type": "application/json"})
                resp = conn.getresponse()
                resp.read()
                if resp.status == 200:
                    self.ok += 1
                else:
                    self.errors += 1
            except (OSError, http.client.HTTPException):
                try:
                    if conn is not None:
                        conn.close()
                except OSError:
                    pass
                conn = None
                self.errors += 1
            time.sleep(0.005)

    def stop(self):
        self._stop.set()
        self.thread.join(10)


def _run_promote_cli(workdir: str, candidate: str, extra=()) -> dict:
    """Drive the real ``promote`` CLI against the live fleet; returns the
    parsed terminal status plus the exit code."""
    import subprocess

    env = dict(os.environ, PYTHONPATH=REPO + os.pathsep + os.environ.get(
        "PYTHONPATH", ""))
    t0 = time.monotonic()
    out = subprocess.run(
        [sys.executable, "-m", "tensorflowdistributedlearning_tpu",
         "promote", "--workdir", workdir, "--candidate-dir", candidate,
         "--shadow-secs", "2", "--shadow-fraction", "1.0",
         "--shadow-min-requests", "8", "--observe-secs", "0.5",
         "--max-p99-ratio", "5.0", "--timeout", "420", "--json", *extra],
        capture_output=True, text=True, env=env, timeout=600,
    )
    lines = [ln for ln in out.stdout.splitlines() if ln.startswith("{")]
    status = json.loads(lines[-1]) if lines else {}
    status["rc"] = out.returncode
    status["duration_s"] = round(time.monotonic() - t0, 3)
    if out.returncode == 2:
        status["stderr"] = out.stderr.strip()[-300:]
    return status


def promotion_soak(args, telemetry) -> dict:
    """The ``promotion`` section: two drills through the REAL stack
    (serve-fleet CLI fleet + promote CLI controller, closed-loop load the
    whole time). (1) kill-mid-canary: promote a passing candidate with
    ``sigkill@N`` injected into the canary's first launch — the controller
    must CONVERGE (promotion completes, the dead canary restarted on the
    candidate) with zero client-visible errors; (2) rollback-on-regression:
    promote a poisoned candidate — the shadow compare must fire the
    automatic rollback, fleet back on the incumbent fingerprint, again with
    zero client-visible errors."""
    import tempfile
    import urllib.request

    from tensorflowdistributedlearning_tpu.obs.ledger import read_ledger
    from tensorflowdistributedlearning_tpu.train import serving as serving_lib

    root = tempfile.mkdtemp(prefix="bench_promo_")
    v1 = export_promotion_artifact(os.path.join(root, "v1"), seed=21)
    v2 = export_promotion_artifact(
        os.path.join(root, "v2"), seed=21, perturb=1e-3
    )
    poisoned = export_promotion_artifact(
        os.path.join(root, "poisoned"), seed=21, perturb=2.0
    )
    fp = {
        name: serving_lib.read_manifest(d)["quantization"][
            "source_fingerprint"].split(":", 1)[-1][:8]
        for name, d in (("v1", v1), ("v2", v2), ("poisoned", poisoned))
    }
    section: dict = {"fingerprints": fp}

    def healthz(url):
        with urllib.request.urlopen(url + "/healthz", timeout=10) as resp:
            return json.loads(resp.read())

    # drill 1: kill the canary mid-rollout; the promotion must converge
    print("promotion kill-mid-canary drill (3 replicas) ...", flush=True)
    kill_dir = os.path.join(root, "promo-kill")
    proc, router_url = _spawn_fleet_cli(
        args, v1, kill_dir, 3, window_secs=2.0
    )
    load = _PromotionLoad(router_url)
    try:
        time.sleep(1.0)
        status = _run_promote_cli(
            kill_dir, v2,
            extra=["--canary-inject-fault",
                   f"sigkill@{args.promotion_kill_after}"],
        )
        health = healthz(router_url)
        load.stop()
        kill = {
            "completed": status.get("state") == "complete",
            "state": status.get("state"),
            "reason": status.get("reason"),
            "duration_s": status.get("duration_s"),
            "kill_after_requests": args.promotion_kill_after,
            "client_ok": load.ok,
            "client_errors": load.errors,
            "converged": (
                health.get("live") == 3
                and not health.get("mixed_artifacts")
                and list(health.get("artifacts", {}))
                == [f"float32:{fp['v2']}"]
            ),
            "final_artifacts": health.get("artifacts"),
        }
    finally:
        load.stop()
        _stop_fleet_cli(proc)
    events = read_ledger(kill_dir)
    kill["restarts"] = sum(
        1 for e in events if e.get("event") == "replica_restart"
    )
    kill["shadow_compared"] = sum(
        e.get("compared", 0)
        for e in events
        if e.get("event") == "shadow_window"
    )
    section["kill_canary"] = kill
    telemetry.event("bench_mode", mode="promotion_kill_canary", **kill)

    # drill 2: a poisoned candidate must be caught by the shadow compare
    # and rolled back automatically
    print("promotion rollback-on-regression drill (2 replicas) ...",
          flush=True)
    rb_dir = os.path.join(root, "promo-rollback")
    proc, router_url = _spawn_fleet_cli(
        args, v1, rb_dir, 2, window_secs=2.0
    )
    load = _PromotionLoad(router_url)
    try:
        time.sleep(1.0)
        status = _run_promote_cli(rb_dir, poisoned)
        health = healthz(router_url)
        load.stop()
        rollback = {
            "rolled_back": status.get("state") == "rolled_back",
            "state": status.get("state"),
            "reason": status.get("reason"),
            "duration_s": status.get("duration_s"),
            "client_ok": load.ok,
            "client_errors": load.errors,
            "restored": (
                health.get("live") == 2
                and not health.get("mixed_artifacts")
                and list(health.get("artifacts", {}))
                == [f"float32:{fp['v1']}"]
            ),
            "final_artifacts": health.get("artifacts"),
        }
    finally:
        load.stop()
        _stop_fleet_cli(proc)
    section["rollback"] = rollback
    telemetry.event("bench_mode", mode="promotion_rollback", **rollback)
    return section


def _check_promotion_section(promo: dict) -> list:
    """The promotion gates (--check with --promotion): mirror of
    tools/regression_sentinel.check_promotion on a fresh run."""
    problems = []
    kill = promo.get("kill_canary")
    if kill is None:
        problems.append("promotion: kill-mid-canary drill did not run")
    else:
        if not kill.get("completed"):
            problems.append(
                f"kill-mid-canary promotion did not complete "
                f"(state {kill.get('state')}: {kill.get('reason')})"
            )
        if not kill.get("converged"):
            problems.append(
                "kill-mid-canary fleet did not converge on the candidate "
                f"fingerprint (artifacts {kill.get('final_artifacts')})"
            )
        if kill.get("client_errors"):
            problems.append(
                f"kill-mid-canary drill saw {kill['client_errors']} "
                "client-visible error(s)"
            )
        if not kill.get("restarts"):
            problems.append(
                "kill-mid-canary drill never killed the canary (0 restarts)"
            )
    rollback = promo.get("rollback")
    if rollback is None:
        problems.append("promotion: rollback drill did not run")
    else:
        if not rollback.get("rolled_back"):
            problems.append(
                "poisoned candidate was NOT rolled back "
                f"(state {rollback.get('state')})"
            )
        if not rollback.get("restored"):
            problems.append(
                "rollback did not restore the incumbent fingerprint "
                f"(artifacts {rollback.get('final_artifacts')})"
            )
        if rollback.get("client_errors"):
            problems.append(
                f"rollback drill saw {rollback['client_errors']} "
                "client-visible error(s)"
            )
    return problems


def fleet_closed_loop(
    url: str, concurrency: int, duration_s: float, model: str = None
) -> dict:
    """Closed-loop clients against the ROUTER, status-aware: 200s count
    toward throughput, 429s are recorded as shed (with Retry-After presence
    checked — the back-off contract), anything 5xx other than the drain
    family is a hard error, and transport failures are counted separately
    (a router must never drop a connection on the floor). With ``model``
    set, every request names that tenant — the router's per-model routing
    path (and the fair shedder's demand signal) under test."""
    import http.client
    import socket as socket_lib
    import urllib.parse

    parsed = urllib.parse.urlsplit(url)
    stop = time.monotonic() + duration_s
    ok = [0] * concurrency
    shed = [0] * concurrency
    shed_with_retry_after = [0] * concurrency
    no_replica = [0] * concurrency
    errors_5xx = [0] * concurrency
    errors_4xx = [0] * concurrency
    errors_conn = [0] * concurrency
    latencies: list = [[] for _ in range(concurrency)]
    barrier = threading.Barrier(concurrency + 1)
    rng = np.random.default_rng(11)
    examples = rng.normal(0, 1, (concurrency, FEATURES)).astype(np.float32)

    def client(i: int):
        payload: dict = {"instances": examples[i : i + 1].tolist()}
        if model is not None:
            payload["model"] = model
        body = json.dumps(payload)
        conn = None
        barrier.wait()
        while time.monotonic() < stop:
            if conn is None:
                try:
                    conn = http.client.HTTPConnection(
                        parsed.hostname, parsed.port, timeout=30
                    )
                    conn.connect()
                    conn.sock.setsockopt(
                        socket_lib.IPPROTO_TCP, socket_lib.TCP_NODELAY, 1
                    )
                except OSError:
                    conn = None
                    errors_conn[i] += 1
                    time.sleep(0.05)
                    continue
            t0 = time.perf_counter()
            try:
                conn.request("POST", "/v1/predict", body,
                             {"Content-Type": "application/json"})
                resp = conn.getresponse()
                resp.read()
            except (http.client.HTTPException, OSError):
                try:
                    conn.close()
                except OSError:
                    pass
                conn = None
                errors_conn[i] += 1
                continue
            if resp.status == 200:
                latencies[i].append(time.perf_counter() - t0)
                ok[i] += 1
            elif resp.status == 429:
                shed[i] += 1
                ra = resp.getheader("Retry-After")
                if ra and ra.isdigit() and int(ra) >= 1:
                    shed_with_retry_after[i] += 1
                # brief fixed backoff after a shed (a closed loop that
                # hammers straight back just measures the reject path's
                # ceiling); the full advertised Retry-After would idle the
                # soak, so honoring it end-to-end is the router tests' job
                time.sleep(0.05)
            elif resp.status == 503:
                no_replica[i] += 1
                time.sleep(0.02)
            else:
                errors_5xx[i] += resp.status >= 500
                # a 404 model_unknown here means the routing hint broke —
                # it must not hide inside a quietly-low ok count
                errors_4xx[i] += 400 <= resp.status < 500
        if conn is not None:
            try:
                conn.close()
            except OSError:
                pass

    threads = [
        threading.Thread(target=client, args=(i,), daemon=True)
        for i in range(concurrency)
    ]
    for t in threads:
        t.start()
    barrier.wait()
    t_start = time.monotonic()
    for t in threads:
        t.join(duration_s + 60)
    elapsed = time.monotonic() - t_start
    lat = np.asarray([s for per in latencies for s in per], np.float64)
    out = {
        "ok": int(sum(ok)),
        "shed_429": int(sum(shed)),
        "shed_with_retry_after": int(sum(shed_with_retry_after)),
        "no_replica_503": int(sum(no_replica)),
        "errors_5xx": int(sum(errors_5xx)),
        "errors_4xx": int(sum(errors_4xx)),
        "errors_conn": int(sum(errors_conn)),
        "elapsed_s": round(elapsed, 3),
        "requests_per_sec": round(sum(ok) / elapsed, 1) if elapsed else 0.0,
    }
    if len(lat):
        out["latency_ms"] = {
            "mean": round(float(lat.mean()) * 1000, 3),
            "p50": round(float(np.percentile(lat, 50)) * 1000, 3),
            "p99": round(float(np.percentile(lat, 99)) * 1000, 3),
        }
    return out


def _spawn_fleet_cli(
    args,
    artifact_dir: str,
    workdir: str,
    n: int,
    *,
    queue_size: int = 256,
    inject: str = None,
    window_secs: float = 2.0,
    timeout_s: float = 300.0,
    registry_path: str = None,
):
    """Launch the REAL tier — ``serve-fleet`` CLI in its own process (router
    + supervisor there, replica subprocesses under it) — and return
    ``(proc, router_url)``. Out-of-process matters for honesty: the router
    must not share the load generator's interpreter, or client-side Python
    time pollutes the fleet's measured capacity."""
    import subprocess

    env = dict(os.environ, PYTHONPATH=REPO + os.pathsep + os.environ.get(
        "PYTHONPATH", ""))
    cmd = [
        sys.executable, "-m", "tensorflowdistributedlearning_tpu",
        "serve-fleet",
        # a registry (multi-tenant) fleet takes its artifact set and initial
        # replica plan from registry.json; a plain fleet takes one artifact
        *(
            ["--registry", registry_path]
            if registry_path
            else ["--artifact-dir", artifact_dir]
        ),
        "--workdir", workdir,
        "--port", "0",
        "--replicas", str(n),
        "--no-autoscale",
        "--window-secs", str(window_secs),
        "--max-wait-ms", str(args.max_wait_ms),
        "--queue-size", str(queue_size),
        "--buckets", *[str(b) for b in args.buckets],
        "--poll-interval-s", "0.25",
    ]
    if inject:
        cmd += ["--replica-inject-fault", inject]
    os.makedirs(workdir, exist_ok=True)
    log_fh = open(os.path.join(workdir, "controller.log"), "ab")
    try:
        proc = subprocess.Popen(
            cmd, stdout=subprocess.PIPE, stderr=log_fh, env=env, text=True
        )
    finally:
        log_fh.close()
    url: dict = {}

    def reader():
        for line in proc.stdout:
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                obj = json.loads(line)
            except ValueError:
                continue
            if "router" in obj:
                url["router"] = obj["router"]
                return

    t = threading.Thread(target=reader, daemon=True)
    t.start()
    t.join(timeout_s)
    if "router" not in url:
        proc.kill()
        raise RuntimeError(
            f"serve-fleet x{n} not ready after {timeout_s}s — see "
            f"{workdir}/controller.log"
        )
    return proc, url["router"]


def _stop_fleet_cli(proc) -> None:
    """SIGTERM = drain the whole fleet; the controller exits when every
    replica finished its graceful drain."""
    import signal as signal_lib
    import subprocess

    if proc.poll() is not None:
        return
    proc.send_signal(signal_lib.SIGTERM)
    try:
        proc.wait(90)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait(10)


def _get_json(url: str, timeout: float = 5.0) -> dict:
    import urllib.request

    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read())


def _fleet_ledger_stats(workdir: str) -> dict:
    """Per-replica post-warmup recompiles + completion totals, read from the
    per-replica ledgers the fleet left behind (the same files
    ``telemetry-report`` merges)."""
    from tensorflowdistributedlearning_tpu.obs import fleet as obs_fleet

    stats: dict = {}
    for led in obs_fleet.discover_ledgers(workdir):
        windows = [
            e for e in led.events if e.get("event") == "serve_window"
        ]
        if not windows:
            continue
        last = windows[-1]
        row = {
            "completed": last.get("completed", 0),
            "recompiles_post_warmup": last.get("recompiles_post_warmup", 0),
        }
        if last.get("model"):
            row["model"] = last["model"]
        stats[str(led.process_index)] = row
    return stats


def fleet_soak(args, telemetry) -> dict:
    """The fleet section: replica-count sweep, saturation shed probe, and
    the kill-a-replica convergence soak — every phase through the REAL tier
    (the ``serve-fleet`` CLI in its own process: router + supervision there,
    one ``serve`` subprocess per replica under it)."""
    import tempfile

    root = tempfile.mkdtemp(prefix="bench_fleet_")
    artifact = os.path.join(root, "artifact")
    export_fleet_artifact(artifact)
    section: dict = {
        "model": {"features": FEATURES, "hidden": FLEET_HIDDEN,
                  "mask_out": FLEET_OUT},
        "concurrency": args.fleet_concurrency,
        "duration_s": args.fleet_duration,
        "replica_counts": {},
    }

    for n in args.fleet_replicas:
        print(f"fleet x{n}: {args.fleet_concurrency} clients, "
              f"{args.trials} x {args.fleet_duration}s ...", flush=True)
        workdir = os.path.join(root, f"fleet-{n}")
        proc, router_url = _spawn_fleet_cli(args, artifact, workdir, n)
        try:
            runs = [
                fleet_closed_loop(
                    router_url, args.fleet_concurrency, args.fleet_duration
                )
                for _ in range(args.trials)
            ]
            entry = max(runs, key=lambda r: r["requests_per_sec"])
            entry["trial_rps"] = [r["requests_per_sec"] for r in runs]
            # errors aggregate across ALL trials: best-of-N is a throughput
            # estimator, but a 5xx/transport error in any trial is a real
            # defect the --check gate must see
            for key in ("errors_5xx", "errors_conn", "no_replica_503"):
                entry[key] = sum(r.get(key, 0) for r in runs)
            try:
                metrics = _get_json(router_url + "/metrics")
                entry["per_replica_routed"] = {
                    str(r["replica"]): r["routed"]
                    for r in metrics.get("replicas", [])
                }
            except OSError:
                pass
        finally:
            _stop_fleet_cli(proc)
        entry["replicas"] = _fleet_ledger_stats(workdir)
        section["replica_counts"][str(n)] = entry
        telemetry.event("bench_mode", mode=f"fleet_{n}", **entry)

    base = section["replica_counts"].get("1")
    if base and base.get("requests_per_sec"):
        scaling: dict = {}
        for n in args.fleet_replicas:
            if n == 1:
                continue
            entry = section["replica_counts"][str(n)]
            row = {
                "speedup_vs_1": round(
                    entry["requests_per_sec"] / base["requests_per_sec"], 3
                ),
            }
            row["efficiency"] = round(row["speedup_vs_1"] / n, 3)
            if "latency_ms" in entry and "latency_ms" in base:
                row["p99_ratio_vs_1"] = round(
                    entry["latency_ms"]["p99"] / base["latency_ms"]["p99"], 3
                )
            scaling[str(n)] = row
        section["scaling"] = scaling

    # saturation probe: tiny per-replica queues + oversubscribed clients —
    # past saturation the fleet must shed with structured 429 + Retry-After,
    # never answer any other 5xx, and never queue unboundedly
    print("fleet saturation probe (tiny queues, oversubscribed) ...",
          flush=True)
    sat_dir = os.path.join(root, "fleet-sat")
    proc, router_url = _spawn_fleet_cli(
        args, artifact, sat_dir, 1, queue_size=4
    )
    try:
        sat = fleet_closed_loop(
            router_url,
            max(args.fleet_concurrency * 2, 48),
            min(args.fleet_duration, 3.0),
        )
    finally:
        _stop_fleet_cli(proc)
    sat["queue_size"] = 4
    section["saturation"] = sat
    telemetry.event("bench_mode", mode="fleet_saturation", **sat)

    # kill soak: SIGKILL one of two replicas mid-load via the fault seam
    # (`serve --inject-fault sigkill@N`); the router must lose ZERO accepted
    # requests, the supervisor must restart the dead replica, and the fleet
    # must converge back to 2 live replicas
    print("fleet kill-a-replica soak ...", flush=True)
    kill_dir = os.path.join(root, "fleet-kill")
    proc, router_url = _spawn_fleet_cli(
        args, artifact, kill_dir, 2,
        inject=f"2:sigkill@{args.fleet_kill_after}",
    )
    try:
        kill = fleet_closed_loop(
            router_url,
            args.fleet_concurrency,
            max(args.fleet_duration * 2, 6.0),
        )
        # convergence: poll the router's aggregate /healthz until both
        # replicas are live again (the restarted one included)
        converged = False
        deadline = time.monotonic() + 45
        while time.monotonic() < deadline:
            try:
                health = _get_json(router_url + "/healthz")
            except OSError:
                health = {}
            if health.get("live", 0) >= 2 and health.get("status") == "ok":
                converged = True
                break
            time.sleep(0.25)
        kill["killed_replica"] = 2
        kill["kill_after_requests"] = args.fleet_kill_after
        kill["converged"] = converged
        kill["client_errors"] = kill["errors_5xx"] + kill["errors_conn"]
    finally:
        _stop_fleet_cli(proc)
    # restart accounting from the controller's ledger (the same events
    # telemetry-report renders)
    from tensorflowdistributedlearning_tpu.obs.ledger import read_ledger

    try:
        events = read_ledger(kill_dir)
    except (OSError, ValueError):
        events = []
    kill["restarts"] = sum(
        1 for e in events if e.get("event") == "replica_restart"
    )
    section["kill_soak"] = kill
    telemetry.event("bench_mode", mode="fleet_kill_soak", **kill)
    return section


def _check_fleet(fleet: dict, args) -> list:
    """The fleet gates (--check with --fleet): scaling floor at no-worse
    p99, zero recompiles on every replica, graceful shed, kill-soak
    convergence with zero lost accepted requests."""
    problems = []
    scaling = (fleet.get("scaling") or {}).get("2")
    if scaling is None:
        problems.append("fleet: no 2-replica scaling row measured")
    else:
        if scaling["speedup_vs_1"] < args.min_fleet_scaling:
            problems.append(
                f"fleet 2-replica speedup {scaling['speedup_vs_1']} < "
                f"required {args.min_fleet_scaling}"
            )
        if scaling.get("p99_ratio_vs_1", 1.0) > args.max_fleet_p99_ratio:
            problems.append(
                f"fleet 2-replica p99 regressed "
                f"{scaling['p99_ratio_vs_1']}x vs 1 replica — throughput "
                "at degraded latency does not count"
            )
    for n, entry in fleet.get("replica_counts", {}).items():
        for rid, stats in entry.get("replicas", {}).items():
            if stats.get("recompiles_post_warmup"):
                problems.append(
                    f"fleet x{n}: replica {rid} saw "
                    f"{stats['recompiles_post_warmup']} post-warmup "
                    "recompile(s)"
                )
        if entry.get("errors_5xx") or entry.get("errors_conn"):
            problems.append(
                f"fleet x{n}: {entry.get('errors_5xx', 0)} 5xx / "
                f"{entry.get('errors_conn', 0)} transport error(s) under "
                "steady load"
            )
    sat = fleet.get("saturation")
    if sat is not None:
        if not sat.get("shed_429"):
            problems.append(
                "saturation probe shed nothing — queues grew instead of "
                "rejecting"
            )
        elif not sat.get("shed_with_retry_after"):
            problems.append("429s carried no usable Retry-After header")
        if sat.get("errors_5xx"):
            problems.append(
                f"saturation probe answered {sat['errors_5xx']} non-drain "
                "5xx(s)"
            )
    kill = fleet.get("kill_soak")
    if kill is None:
        problems.append("fleet: kill soak did not run")
    else:
        if kill.get("client_errors"):
            problems.append(
                f"kill soak lost {kill['client_errors']} accepted "
                "request(s) (client-visible errors)"
            )
        if not kill.get("restarts"):
            problems.append("kill soak: dead replica was never restarted")
        if not kill.get("converged"):
            problems.append(
                "kill soak: fleet did not converge back to 2 live replicas"
            )
    return problems


# the two tenants of the multitenant soak: alpha carries twice beta's
# fair-share weight, so under saturation with equal demand the router must
# admit alpha a strictly larger share — the fairness gate
MT_MODELS = ("alpha", "beta")
MT_WEIGHTS = {"alpha": 2.0, "beta": 1.0}


def multitenant_soak(args, telemetry) -> dict:
    """The multi-tenant section: one registry fleet, two models with their
    own artifacts behind one router. Steady phase measures per-model
    throughput and p99 against each tenant's SLO target plus fleet-wide
    rps/chip; the saturation phase (tiny queues, equal oversubscribed
    demand) must shed by weighted fair share without starving either
    tenant; every replica must finish with zero post-warmup recompiles —
    tenants must not trip each other's compilation caches. Record section:
    ``multitenant`` (replayed by the regression sentinel's hard gates)."""
    import tempfile

    from tensorflowdistributedlearning_tpu.obs import capacity as capacity_lib
    from tensorflowdistributedlearning_tpu.serve.registry import (
        ModelEntry,
        write_registry,
    )

    root = tempfile.mkdtemp(prefix="bench_mt_")
    artifacts = {
        name: export_promotion_artifact(
            os.path.join(root, f"art-{name}"), seed=31 + i
        )
        for i, name in enumerate(MT_MODELS)
    }

    def entries():
        return [
            ModelEntry(
                name=name,
                artifact_dir=artifacts[name],
                weight=MT_WEIGHTS[name],
                replicas=1,
                slo_p99_ms=args.mt_slo_p99_ms,
            )
            for name in MT_MODELS
        ]

    def run_tenants(router_url: str, per_model_clients: int,
                    duration_s: float) -> dict:
        """Drive both tenants CONCURRENTLY (the point of the soak) and
        return per-model client-side stats."""
        results: dict = {}

        def drive(name: str):
            results[name] = fleet_closed_loop(
                router_url, per_model_clients, duration_s, model=name
            )

        threads = [
            threading.Thread(target=drive, args=(m,), daemon=True)
            for m in MT_MODELS
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(duration_s + 90)
        return results

    section: dict = {
        "weights": dict(MT_WEIGHTS),
        "slo_p99_ms": args.mt_slo_p99_ms,
        "concurrency_per_model": args.fleet_concurrency // 2,
        "duration_s": args.fleet_duration,
    }

    # -- steady phase: both tenants under moderate concurrent load ----------
    print(f"multitenant steady: {len(MT_MODELS)} models x "
          f"{args.fleet_concurrency // 2} clients, "
          f"{args.fleet_duration}s ...", flush=True)
    steady_dir = os.path.join(root, "mt-steady")
    os.makedirs(steady_dir, exist_ok=True)
    reg = write_registry(steady_dir, entries())
    proc, router_url = _spawn_fleet_cli(
        args, None, steady_dir, 2, registry_path=reg.path
    )
    try:
        steady = run_tenants(
            router_url, args.fleet_concurrency // 2, args.fleet_duration
        )
        try:
            metrics = _get_json(router_url + "/metrics")
            section["router_models"] = (
                metrics.get("fleet") or {}
            ).get("models") or {}
        except OSError:
            pass
    finally:
        _stop_fleet_cli(proc)
    section["models"] = steady
    section["replicas"] = _fleet_ledger_stats(steady_dir)
    n_chips = capacity_lib.device_count()
    section["n_chips"] = n_chips
    total_ok = sum(r["ok"] for r in steady.values())
    elapsed = max(r["elapsed_s"] for r in steady.values()) or 1.0
    section["requests_per_sec_total"] = round(total_ok / elapsed, 1)
    section["rps_per_chip_total"] = round(total_ok / elapsed / n_chips, 1)
    telemetry.event("bench_mode", mode="multitenant_steady",
                    rps_per_chip_total=section["rps_per_chip_total"],
                    **{f"{m}_ok": steady[m]["ok"] for m in MT_MODELS})

    # -- saturation phase: tiny queues, equal oversubscribed demand ---------
    # fairness contract: with weight 2:1 and symmetric demand the router's
    # fair shedder must admit alpha a larger share than beta, shed the rest
    # as structured 429s, and starve neither tenant
    print("multitenant saturation (tiny queues, equal demand) ...",
          flush=True)
    sat_dir = os.path.join(root, "mt-sat")
    os.makedirs(sat_dir, exist_ok=True)
    reg = write_registry(sat_dir, entries())
    proc, router_url = _spawn_fleet_cli(
        args, None, sat_dir, 2, registry_path=reg.path, queue_size=4
    )
    try:
        sat_clients = max(args.fleet_concurrency, 24)
        sat_runs = run_tenants(
            router_url, sat_clients, min(args.fleet_duration, 3.0)
        )
    finally:
        _stop_fleet_cli(proc)
    admitted_total = sum(r["ok"] for r in sat_runs.values())
    sat: dict = {
        "queue_size": 4,
        "concurrency_per_model": sat_clients,
        "per_model": sat_runs,
        "shed_429_total": sum(r["shed_429"] for r in sat_runs.values()),
        "errors_5xx": sum(r["errors_5xx"] for r in sat_runs.values()),
    }
    if admitted_total:
        sat["admitted_shares"] = {
            m: round(sat_runs[m]["ok"] / admitted_total, 4)
            for m in MT_MODELS
        }
        sat["fair_weighted"] = (
            sat["admitted_shares"]["alpha"] >= sat["admitted_shares"]["beta"]
        )
    section["saturation"] = sat
    telemetry.event("bench_mode", mode="multitenant_saturation", **{
        k: v for k, v in sat.items() if k != "per_model"
    })
    return section


def _check_multitenant(mt: dict, args) -> list:
    """The multitenant gates (--check with --multitenant): both tenants
    actually served with zero hard errors, every model's p99 within its SLO
    target, zero cross-tenant recompiles on every replica, and weighted
    fair shedding (neither tenant starved, heavier tenant admitted at least
    the lighter one's share) under saturation."""
    problems = []
    models = mt.get("models") or {}
    for name in MT_MODELS:
        entry = models.get(name)
        if not entry:
            problems.append(f"multitenant: model {name} never measured")
            continue
        if not entry.get("ok"):
            problems.append(
                f"multitenant: model {name} completed zero requests"
            )
        for key in ("errors_5xx", "errors_4xx", "errors_conn"):
            if entry.get(key):
                problems.append(
                    f"multitenant: model {name} saw {entry[key]} {key} "
                    "under steady load"
                )
        p99 = (entry.get("latency_ms") or {}).get("p99")
        if p99 is not None and p99 > mt.get("slo_p99_ms", float("inf")):
            problems.append(
                f"multitenant: model {name} p99 {p99}ms blew its "
                f"{mt['slo_p99_ms']}ms SLO target"
            )
    for rid, stats in (mt.get("replicas") or {}).items():
        if stats.get("recompiles_post_warmup"):
            problems.append(
                f"multitenant: replica {rid} saw "
                f"{stats['recompiles_post_warmup']} post-warmup "
                "recompile(s) — cross-tenant compilation leak"
            )
    if mt.get("rps_per_chip_total") is not None and (
        mt["rps_per_chip_total"] < args.min_mt_rps_per_chip
    ):
        problems.append(
            f"multitenant: fleet-wide {mt['rps_per_chip_total']} rps/chip "
            f"< required {args.min_mt_rps_per_chip}"
        )
    sat = mt.get("saturation")
    if sat is None:
        problems.append("multitenant: saturation phase did not run")
    else:
        if not sat.get("shed_429_total"):
            problems.append(
                "multitenant saturation shed nothing — queues grew instead "
                "of rejecting"
            )
        if sat.get("errors_5xx"):
            problems.append(
                f"multitenant saturation answered {sat['errors_5xx']} "
                "non-drain 5xx(s)"
            )
        for name in MT_MODELS:
            if not (sat.get("per_model", {}).get(name) or {}).get("ok"):
                problems.append(
                    f"multitenant saturation STARVED model {name} — fair "
                    "shedding must keep every tenant serving"
                )
        if sat.get("fair_weighted") is False:
            problems.append(
                "multitenant saturation: admitted shares inverted the "
                "fair-share weights (alpha w=2 admitted less than beta w=1)"
            )
    return problems


def closed_loop(issue, concurrency: int, duration_s: float) -> dict:
    """Run ``concurrency`` closed-loop clients for ``duration_s``; returns
    completed-request throughput and client-observed latency percentiles."""
    stop = time.monotonic() + duration_s
    counts = [0] * concurrency
    latencies: list = [[] for _ in range(concurrency)]
    errors = [0] * concurrency
    barrier = threading.Barrier(concurrency + 1)
    rng = np.random.default_rng(7)
    # one example per client, pre-generated off the clock
    examples = rng.normal(0, 1, (concurrency, FEATURES)).astype(np.float32)

    def client(i: int):
        x = examples[i : i + 1]
        barrier.wait()
        while time.monotonic() < stop:
            t0 = time.perf_counter()
            try:
                issue(x)
            except Exception:  # noqa: BLE001 — count, keep looping
                errors[i] += 1
                continue
            latencies[i].append(time.perf_counter() - t0)
            counts[i] += 1

    threads = [
        threading.Thread(target=client, args=(i,), daemon=True)
        for i in range(concurrency)
    ]
    for t in threads:
        t.start()
    barrier.wait()
    t_start = time.monotonic()
    for t in threads:
        t.join(duration_s + 30)
    elapsed = time.monotonic() - t_start
    lat = np.asarray([s for per in latencies for s in per], np.float64)
    total = int(sum(counts))
    out = {
        "requests": total,
        "errors": int(sum(errors)),
        "elapsed_s": round(elapsed, 3),
        "requests_per_sec": round(total / elapsed, 1) if elapsed else 0.0,
    }
    if len(lat):
        out["latency_ms"] = {
            "mean": round(float(lat.mean()) * 1000, 3),
            "p50": round(float(np.percentile(lat, 50)) * 1000, 3),
            "p99": round(float(np.percentile(lat, 99)) * 1000, 3),
        }
    return out


def best_of(issue, concurrency: int, duration_s: float, trials: int) -> dict:
    """Best-of-N closed-loop runs per mode: this box shows multi-second
    noisy-neighbor windows that halve throughput for every mode at once; the
    max is the standard capability estimator under that noise. All trial
    rates are kept in the record so the spread is visible."""
    runs = [closed_loop(issue, concurrency, duration_s) for _ in range(trials)]
    best = max(runs, key=lambda r: r["requests_per_sec"])
    best["trial_rps"] = [r["requests_per_sec"] for r in runs]
    return best


def probe_backpressure() -> dict:
    """A full bounded queue must reject at submit time with QueueFullError —
    the structured signal — while everything already accepted completes."""
    from tensorflowdistributedlearning_tpu.serve import (
        InferenceEngine,
        MicroBatcher,
        QueueFullError,
    )

    release = threading.Event()

    def stalled_fn(x):  # holds the worker so the queue genuinely fills
        release.wait(10)
        return {"y": np.asarray(x)}

    engine = InferenceEngine(stalled_fn, (4,), buckets=(1,))
    batcher = MicroBatcher(engine, max_queue=4, max_wait_ms=0.0)
    accepted = []
    rejected = False
    x = np.zeros((1, 4), np.float32)
    try:
        # max_queue + worker-in-flight + 1 guarantees one submit sees a full
        # queue regardless of how fast the worker drains the first request
        for _ in range(batcher.max_queue + 2):
            accepted.append(batcher.submit(x))
    except QueueFullError:
        rejected = True
    release.set()
    completed = sum(1 for r in accepted if r.result(10) is not None)
    batcher.close()
    return {
        "queue_size": batcher.max_queue,
        "accepted": len(accepted),
        "completed": completed,
        "structured_reject": rejected,
    }


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--concurrency", type=int, default=32)
    parser.add_argument("--duration", type=float, default=2.0,
                        help="seconds per trial")
    parser.add_argument("--trials", type=int, default=2,
                        help="closed-loop trials per mode; the best is "
                        "reported (shared-host noise resilience)")
    parser.add_argument("--buckets", type=int, nargs="+",
                        default=(1, 4, 16, 64))
    parser.add_argument("--max-wait-ms", type=float, default=1.0)
    parser.add_argument("--http", action="store_true",
                        help="also measure the full HTTP stack (localhost)")
    parser.add_argument("--json-out", default=os.path.join(REPO, "BENCH_SERVE.json"))
    parser.add_argument("--ledger-dir", default=None,
                        help="write a telemetry ledger (enables the "
                        "recompile-detector assertion)")
    parser.add_argument("--check", action="store_true",
                        help="exit non-zero unless speedup >= --min-speedup, "
                        "zero post-warmup recompiles, and backpressure "
                        "rejected structurally (+ the quant gates when "
                        "--quant ran)")
    parser.add_argument("--min-speedup", type=float, default=3.0)
    parser.add_argument("--quant", action="store_true",
                        help="add the per-precision serving A/B: export "
                        "f32/bf16/int8/int8-compute artifacts through the "
                        "real quantized-serving seam, drive identical load "
                        "through each, run the quantize-check accuracy "
                        "gate (record section: precisions)")
    parser.add_argument("--quant-only", action="store_true",
                        help="run ONLY the precision A/B (implies --quant; "
                        "skips the batching A/B + backpressure probe) — "
                        "the fast CI gate mode")
    parser.add_argument("--quant-dtypes", nargs="+",
                        default=("float32", "bfloat16", "int8",
                                 "int8-compute"),
                        choices=("float32", "bfloat16", "int8",
                                 "int8-compute"))
    parser.add_argument("--min-quant-speedup", type=float, default=None,
                        help="--check floor for bf16-vs-f32 throughput at "
                        "no-worse p99; default 1.5 on TPU (the HBM win the "
                        "path exists for), 0.8 elsewhere (XLA:CPU upcasts "
                        "bf16 — the tripwire just catches a quantized path "
                        "that got materially slower)")
    parser.add_argument("--min-int8-compute-ratio", type=float, default=None,
                        help="--check floor for int8-compute-vs-int8-store "
                        "throughput at no-worse p99; default 1.0 on TPU "
                        "(the MXU int8 win the kernels exist for), 0.9 "
                        "elsewhere (CPU serves the dequantize-f32 fallback "
                        "— near-parity expected, the tripwire catches a "
                        "fallback that got materially slower)")
    parser.add_argument("--fleet", action="store_true",
                        help="add the serving-tier soak: sweep replica "
                        "counts through real subprocess fleets behind the "
                        "router, probe saturation shedding, and run the "
                        "kill-a-replica convergence soak (record section: "
                        "fleet)")
    parser.add_argument("--fleet-only", action="store_true",
                        help="run ONLY the fleet soak (implies --fleet)")
    parser.add_argument("--fleet-replicas", type=int, nargs="+",
                        default=(1, 2),
                        help="replica counts to sweep; must include 1 for "
                        "the scaling table and 2 for the --check gate")
    parser.add_argument("--fleet-concurrency", type=int, default=32,
                        help="closed-loop clients against the router")
    parser.add_argument("--fleet-duration", type=float, default=4.0,
                        help="seconds per fleet trial (the kill soak runs "
                        "2x this, min 6s, so death + restart + convergence "
                        "fit inside the soak)")
    parser.add_argument("--fleet-kill-after", type=int, default=200,
                        help="kill-soak drill: SIGKILL replica 2 after its "
                        "Nth answered request (serve --inject-fault "
                        "sigkill@N)")
    parser.add_argument("--promotion", action="store_true",
                        help="add the promotion soak: kill-mid-canary "
                        "convergence (promote a passing candidate across a "
                        "3-replica fleet with sigkill@N injected into the "
                        "canary, zero client-visible errors) and the "
                        "rollback-on-regression drill (a poisoned "
                        "candidate MUST be caught by the shadow compare "
                        "and rolled back) — record section: promotion")
    parser.add_argument("--promotion-only", action="store_true",
                        help="run ONLY the promotion soak (implies "
                        "--promotion)")
    parser.add_argument("--promotion-kill-after", type=int, default=25,
                        help="kill-mid-canary drill: SIGKILL the canary "
                        "after its Nth answered (shadow) request")
    parser.add_argument("--multitenant", action="store_true",
                        help="add the multi-tenant soak: a 2-model registry "
                        "fleet behind one router — concurrent per-model "
                        "load at fixed per-model SLO, weighted fair "
                        "shedding under saturation, zero cross-tenant "
                        "recompiles (record section: multitenant)")
    parser.add_argument("--multitenant-only", action="store_true",
                        help="run ONLY the multi-tenant soak (implies "
                        "--multitenant)")
    parser.add_argument("--mt-slo-p99-ms", type=float, default=750.0,
                        help="per-model p99 SLO target the multitenant "
                        "steady phase is gated against (generous for "
                        "shared CI runners; the committed record pins the "
                        "actual measured tails)")
    parser.add_argument("--min-mt-rps-per-chip", type=float, default=10.0,
                        help="--check floor for the multitenant steady "
                        "phase's fleet-wide requests/sec per chip")
    parser.add_argument("--min-fleet-scaling", type=float, default=1.6,
                        help="--check floor for 2-replica vs 1-replica "
                        "throughput")
    parser.add_argument("--max-fleet-p99-ratio", type=float, default=1.25,
                        help="--check ceiling for 2-replica p99 / 1-replica "
                        "p99 (tail-noise slack on the no-worse-p99 rule)")
    args = parser.parse_args()
    if args.quant_only:
        args.quant = True
    if args.fleet_only:
        args.fleet = True
    if args.promotion_only:
        args.promotion = True
    if args.multitenant_only:
        args.multitenant = True
    only_flags = (args.fleet_only, args.quant_only, args.promotion_only,
                  args.multitenant_only)
    if sum(only_flags) > 1:
        print("--fleet-only/--quant-only/--promotion-only/"
              "--multitenant-only are mutually exclusive", file=sys.stderr)
        return 2

    from tensorflowdistributedlearning_tpu.obs import Telemetry
    from tensorflowdistributedlearning_tpu.serve import (
        InferenceEngine,
        MicroBatcher,
        ServingServer,
    )

    telemetry = Telemetry(
        args.ledger_dir,
        enabled=args.ledger_dir is not None,
        run_info={
            "kind": "bench_serve",
            "concurrency": args.concurrency,
            "duration_s": args.duration,
            "buckets": list(args.buckets),
        },
    )
    # the zero-recompile gate must hold with or without a ledger: fall back
    # to a standalone detector when telemetry is disabled
    standalone_detector = None
    if telemetry.detector is None:
        from tensorflowdistributedlearning_tpu.obs import RecompileDetector

        standalone_detector = RecompileDetector().attach()
    detector = telemetry.detector or standalone_detector

    record: dict = {
        "model": {"features": FEATURES, "hidden": HIDDEN, "classes": CLASSES},
        "concurrency": args.concurrency,
        "duration_s": args.duration,
        "buckets": list(args.buckets),
        "max_wait_ms": args.max_wait_ms,
    }

    skip_ab = (args.quant_only or args.fleet_only or args.promotion_only
               or args.multitenant_only)
    if not skip_ab:
        serve_fn = make_synthetic_model()
        # one engine (with its OWN registry) per mode so counters and
        # per-bucket hits stay attributable to a mode — the ledger is the
        # only shared sink; all warm BEFORE the detector goes warm, after
        # that any compile is a bug
        engine_pr = InferenceEngine(serve_fn, (FEATURES,), buckets=(1,))
        engine_b = InferenceEngine(serve_fn, (FEATURES,), buckets=args.buckets)
        engine_pr.warmup()
        warmup_s = engine_b.warmup(telemetry=telemetry)
        record["warmup_s"] = {str(b): s for b, s in warmup_s.items()}
        if standalone_detector is not None:
            standalone_detector.mark_warm()

        print(f"per-request baseline: {args.concurrency} clients, "
              f"{args.duration}s ...", flush=True)
        batcher_pr = MicroBatcher(engine_pr, max_wait_ms=0.0,
                                  max_queue=max(256, 4 * args.concurrency))
        record["per_request"] = best_of(
            lambda x: batcher_pr.submit(x).result(30),
            args.concurrency, args.duration, args.trials,
        )
        batcher_pr.close()
        telemetry.event("bench_mode", mode="per_request",
                        **record["per_request"])

        print("batched (in-process micro-batcher) ...", flush=True)
        batcher = MicroBatcher(engine_b, max_wait_ms=args.max_wait_ms,
                               max_queue=max(256, 4 * args.concurrency))
        record["batched"] = best_of(
            lambda x: batcher.submit(x).result(30),
            args.concurrency, args.duration, args.trials,
        )
        record["batched"]["bucket_hits"] = {
            str(b): n for b, n in engine_b.bucket_hits.items()
        }
        record["batched"]["padding_waste"] = {
            str(b): w for b, w in engine_b.padding_waste.items()
        }
        telemetry.event("bench_mode", mode="batched", **record["batched"])

    if args.http and not skip_ab:
        print("http (full stack, localhost) ...", flush=True)
        import http.client
        import socket

        engine_h = InferenceEngine(serve_fn, (FEATURES,), buckets=args.buckets)
        engine_h.warmup()
        batcher_h = MicroBatcher(engine_h, max_wait_ms=args.max_wait_ms,
                                 max_queue=max(256, 4 * args.concurrency))
        server = ServingServer(engine_h, batcher_h, port=0,
                               telemetry=telemetry, window_secs=0).start()
        local = threading.local()  # one keep-alive connection per client

        def issue_http(x):
            conn = getattr(local, "conn", None)
            if conn is None:
                conn = local.conn = http.client.HTTPConnection(
                    server.host, server.port, timeout=30
                )
                conn.connect()
                # headers and body go out as separate writes; without
                # NODELAY the body waits out a delayed ACK (~40-200ms)
                conn.sock.setsockopt(
                    socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
                )
            body = json.dumps({"instances": x.tolist()})
            try:
                conn.request("POST", "/v1/predict", body,
                             {"Content-Type": "application/json"})
                resp = conn.getresponse()
                payload = json.loads(resp.read())
            except (http.client.HTTPException, OSError):
                local.conn = None  # reconnect next iteration
                raise
            if resp.status != 200:
                raise RuntimeError(f"HTTP {resp.status}: {payload}")

        record["http"] = best_of(
            issue_http, args.concurrency, args.duration, args.trials
        )
        telemetry.event("bench_mode", mode="http", **record["http"])
        server.shutdown()

    if not skip_ab:
        record["backpressure"] = probe_backpressure()
        pr_rps = record["per_request"]["requests_per_sec"]
        b_rps = record["batched"]["requests_per_sec"]
        record["speedup_batched_vs_per_request"] = (
            round(b_rps / pr_rps, 2) if pr_rps else None
        )
        record["post_warmup_recompiles"] = detector.post_warmup_count
        # cost-per-qps lens (obs/capacity.py; the Gemma-on-TPU serving
        # comparison's metric): per-chip request rate per mode, so the
        # committed baseline is comparable across device shapes and the
        # regression sentinel can gate serving efficiency, not just rps
        from tensorflowdistributedlearning_tpu.obs import capacity as capacity_lib

        n_chips = capacity_lib.device_count()
        record["n_chips"] = n_chips
        for mode in ("per_request", "batched", "http"):
            entry = record.get(mode)
            if entry and entry.get("requests_per_sec"):
                entry["rps_per_chip"] = round(
                    entry["requests_per_sec"] / n_chips, 1
                )

    if args.quant:
        import jax

        quant = quant_precision_ab(args, telemetry)
        quant["backend"] = jax.default_backend()
        if jax.default_backend() != "tpu":
            quant["note"] = (
                "off-TPU backends upcast bf16/int8 to f32 compute, so the "
                "HBM-bandwidth win the quantized path exists for is not "
                "measurable here — the 1.5x-at-fixed-p99 gate applies on "
                "TPU; these curves pin the CPU contract (accuracy gates "
                "pass, zero recompiles, no material slowdown, artifact "
                "bytes scale with dtype)"
            )
        record["quant"] = quant

        # the kernel-vs-XLA microbench column the sentinel's ``kernels``
        # gate replays: real Pallas int8/fused kernels on TPU (speedup
        # floor), the dispatch-overhead tripwire off-TPU (both sides run
        # the same dequantize-f32 fallback, so the ratio pins ~1.0)
        import bench_kernels as bench_kernels_mod

        if jax.default_backend() == "tpu":
            kernels = bench_kernels_mod.bench_quant()
        else:
            kernels = bench_kernels_mod.bench_quant(
                batch=16, features=128, hw=7, conv_channels=16, mask_hw=33,
                iters=4, warmup=2, repeats=4,
            )
        kernels["platform"] = jax.default_backend()
        record["kernels"] = kernels

    if args.fleet:
        record["fleet"] = fleet_soak(args, telemetry)

    if args.promotion:
        record["promotion"] = promotion_soak(args, telemetry)

    if args.multitenant:
        record["multitenant"] = multitenant_soak(args, telemetry)

    if standalone_detector is not None:
        standalone_detector.detach()
    telemetry.event("bench_serve", **{
        k: v for k, v in record.items() if k != "model"
    })
    telemetry.close(
        speedup=record.get("speedup_batched_vs_per_request"),
        recompiles_post_warmup=record.get("post_warmup_recompiles"),
    )

    with open(args.json_out, "w") as f:
        json.dump(record, f, indent=1)
    summary = {
        "per_request_rps": record.get("per_request", {}).get("requests_per_sec"),
        "batched_rps": record.get("batched", {}).get("requests_per_sec"),
        "http_rps": record.get("http", {}).get("requests_per_sec"),
        "speedup": record.get("speedup_batched_vs_per_request"),
        "post_warmup_recompiles": record.get("post_warmup_recompiles"),
        "written": args.json_out,
    }
    if "backpressure" in record:
        summary["backpressure_structured_reject"] = (
            record["backpressure"]["structured_reject"]
        )
    if args.quant:
        summary["precision_rps"] = {
            d: e.get("requests_per_sec")
            for d, e in record["quant"]["precisions"].items()
        }
        summary["quant_check_passed"] = {
            d: v["passed"] for d, v in record["quant"]["quant_check"].items()
        }
    if args.fleet:
        fleet = record["fleet"]
        summary["fleet_rps"] = {
            n: e.get("requests_per_sec")
            for n, e in fleet["replica_counts"].items()
        }
        summary["fleet_scaling"] = fleet.get("scaling")
        summary["fleet_shed_429"] = (fleet.get("saturation") or {}).get(
            "shed_429"
        )
        kill = fleet.get("kill_soak") or {}
        summary["fleet_kill_soak"] = {
            k: kill.get(k)
            for k in ("client_errors", "restarts", "converged")
        }
    if args.multitenant:
        mt = record["multitenant"]
        summary["multitenant_rps_per_chip"] = mt.get("rps_per_chip_total")
        summary["multitenant_p99_ms"] = {
            m: (e.get("latency_ms") or {}).get("p99")
            for m, e in (mt.get("models") or {}).items()
        }
        summary["multitenant_admitted_shares"] = (
            mt.get("saturation") or {}
        ).get("admitted_shares")
    if args.promotion:
        promo = record["promotion"]
        summary["promotion_kill_canary"] = {
            k: (promo.get("kill_canary") or {}).get(k)
            for k in ("completed", "converged", "client_errors", "restarts")
        }
        summary["promotion_rollback"] = {
            k: (promo.get("rollback") or {}).get(k)
            for k in ("rolled_back", "restored", "client_errors")
        }
    print(json.dumps(summary))

    if args.check:
        problems = []
        if not skip_ab:
            speedup = record["speedup_batched_vs_per_request"] or 0
            if speedup < args.min_speedup:
                problems.append(
                    f"speedup {speedup} < required {args.min_speedup}"
                )
            if record.get("post_warmup_recompiles"):
                problems.append(
                    f"{record['post_warmup_recompiles']} post-warmup "
                    "recompile(s)"
                )
            if not record["backpressure"]["structured_reject"]:
                problems.append("full queue did not reject structurally")
            if (record["backpressure"]["completed"]
                    != record["backpressure"]["accepted"]):
                problems.append(
                    "accepted requests lost during backpressure probe"
                )
        if args.quant:
            problems.extend(_check_quant(record["quant"], args))
        if args.fleet:
            problems.extend(_check_fleet(record["fleet"], args))
        if args.promotion:
            problems.extend(_check_promotion_section(record["promotion"]))
        if args.multitenant:
            problems.extend(_check_multitenant(record["multitenant"], args))
        if problems:
            print("CHECK FAILED: " + "; ".join(problems), file=sys.stderr)
            return 1
    return 0


def _check_quant(quant: dict, args) -> list:
    """The quant gates: accuracy gate passed for every quantized precision,
    zero post-warmup recompiles per precision, and bf16 throughput at or
    above the backend's floor WITHOUT a p99 regression (the fixed-p99
    framing: extra throughput bought with latency doesn't count)."""
    import jax

    problems = []
    min_speedup = args.min_quant_speedup
    if min_speedup is None:
        min_speedup = 1.5 if jax.default_backend() == "tpu" else 0.8
    for dtype, verdict in quant["quant_check"].items():
        if not verdict["passed"]:
            problems.append(
                f"quantize-check failed for {dtype}: "
                + "; ".join(verdict["failures"])
            )
    for dtype, entry in quant["precisions"].items():
        if entry.get("skipped"):
            # int8 may be unsupported on a backend; that is a recorded skip,
            # not a failure — but the headline bf16 path must always run
            if dtype == "bfloat16":
                problems.append(f"bfloat16 precision skipped: {entry['skipped']}")
            continue
        if entry.get("post_warmup_recompiles"):
            problems.append(
                f"{entry['post_warmup_recompiles']} post-warmup recompile(s) "
                f"serving the {dtype} artifact"
            )
    bf16 = quant["precisions"].get("bfloat16", {})
    if bf16.get("speedup_vs_f32") is not None:
        if bf16["speedup_vs_f32"] < min_speedup:
            problems.append(
                f"bf16-vs-f32 throughput {bf16['speedup_vs_f32']} < "
                f"required {min_speedup} on {jax.default_backend()}"
            )
        elif bf16.get("p99_ratio_vs_f32", 1.0) > 1.25:
            problems.append(
                f"bf16 p99 regressed {bf16['p99_ratio_vs_f32']}x vs f32 — "
                "throughput at degraded latency does not count"
            )
    comp = quant["precisions"].get("int8-compute", {})
    if comp.get("speedup_vs_int8_store") is not None:
        min_ratio = args.min_int8_compute_ratio
        if min_ratio is None:
            min_ratio = 1.0 if jax.default_backend() == "tpu" else 0.9
        if comp["speedup_vs_int8_store"] < min_ratio:
            problems.append(
                f"int8-compute throughput {comp['speedup_vs_int8_store']}x "
                f"vs int8-store < required {min_ratio} on "
                f"{jax.default_backend()}"
            )
        elif comp.get("p99_ratio_vs_int8_store", 1.0) > 1.25:
            problems.append(
                f"int8-compute p99 regressed "
                f"{comp['p99_ratio_vs_int8_store']}x vs int8-store — "
                "throughput at degraded latency does not count"
            )
    return problems


if __name__ == "__main__":
    sys.exit(main())
