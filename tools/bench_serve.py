"""Closed-loop load generator for the serve/ stack: batched vs per-request.

Builds a small synthetic params-baked model (pure jax, no checkpoint), then
drives it with N closed-loop clients (each thread issues its next request the
moment the previous one answers — the standard closed-loop load model) in up
to three configurations:

- ``per_request``: the same serving pipeline (bounded queue, single dispatch
  worker, futures) with coalescing OFF — every request is its own batch-1
  forward, serialized at the device exactly like a no-batching server in
  front of one accelerator;
- ``batched``:     identical pipeline with the bucket-ladder coalescing ON —
  the only variable is server-side batching;
- ``http``:        the full stack — ThreadingHTTPServer, JSON wire format,
  batcher, engine (enabled with ``--http``).

Also probes the backpressure contract (a full bounded queue must answer with
a structured QueueFullError, not queue unboundedly) and — when ``--ledger-dir``
is given — runs under a Telemetry recompile detector marked warm after bucket
warmup, so the record carries the post-warmup recompile count (must be 0: the
bucket ladder exists so steady-state serving never recompiles).

``--quant`` adds the precision A/B: a bigger synthetic model is EXPORTED
through the real quantized-serving seam (train/quantize.py +
train/serving.py) at every precision in ``--quant-dtypes``, each artifact is
served through its own engine from the manifest alone, and the record gains a
``precisions`` section — throughput, latency percentiles, per-bucket
padding-waste fraction, artifact bytes at rest, post-warmup recompiles (must
be 0 per precision) — plus a quantize-check accuracy verdict for every
quantized precision (the Gemma-on-TPU methodology: curves per precision, not
single points; arXiv:2605.25645). ``--quant-only`` skips the batching A/B for
a fast, CPU-reproducible gate run.

Writes a JSON record (default BENCH_SERVE.json). ``--check`` exits non-zero
unless batched/per_request speedup >= --min-speedup, recompiles == 0, and the
backpressure probe rejected structurally — the CI serve-smoke gate
(tools/run_suite.py --serve-smoke). With ``--quant`` it additionally requires
every quantize-check to pass, zero post-warmup recompiles per precision, and
bf16-vs-f32 throughput >= --min-quant-speedup at no-worse p99 — the floor
defaults to 1.5 on TPU (the HBM-roofline win the path exists for) and to a
0.8 not-materially-slower tripwire elsewhere (XLA:CPU upcasts bf16, so the
bandwidth win does not exist off-TPU; measured on this container, see
BENCH_SERVE.json precisions.note), which keeps the gate reproducible on CPU
CI.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

FEATURES = 128
HIDDEN = 256
CLASSES = 16


def make_synthetic_model():
    """Params-baked jitted ``x [B, FEATURES] -> {probabilities, class}`` —
    shaped like the trainers' serving_fn closures, sized so one forward is
    dispatch-overhead-dominated at batch 1 (the regime batching exists for)."""
    import jax
    import jax.numpy as jnp

    k1, k2 = jax.random.split(jax.random.PRNGKey(0))
    w1 = jax.random.normal(k1, (FEATURES, HIDDEN), jnp.float32) * 0.05
    w2 = jax.random.normal(k2, (HIDDEN, CLASSES), jnp.float32) * 0.05

    @jax.jit
    def serve(x):
        h = jnp.maximum(x @ w1, 0.0)
        logits = h @ w2
        return {
            "probabilities": jax.nn.softmax(logits, axis=-1),
            "class": jnp.argmax(logits, axis=-1),
        }

    return serve


# the quant A/B model is bigger than the batching-A/B one on purpose: the
# precision recipes act on weight bytes, so the weights must be large enough
# that artifact sizes (and, on TPU, HBM traffic) visibly scale with dtype
QUANT_HIDDEN = 1024


def make_quant_model_params():
    """Float32 params tree for the quant A/B — flax-shaped (``kernel`` leaves)
    so the int8 per-channel recipe engages exactly like on a real model."""
    import jax
    import jax.numpy as jnp

    k1, k2 = jax.random.split(jax.random.PRNGKey(3))
    return {
        "dense1": {
            "kernel": jax.random.normal(
                k1, (FEATURES, QUANT_HIDDEN), jnp.float32
            ) * 0.05,
            "bias": jnp.zeros((QUANT_HIDDEN,), jnp.float32),
        },
        "dense2": {
            "kernel": jax.random.normal(
                k2, (QUANT_HIDDEN, CLASSES), jnp.float32
            ) * 0.05,
        },
    }


def export_quant_artifact(params, serving_dtype: str, directory: str) -> str:
    """Export the quant-A/B model at one precision through the REAL seam:
    quantize the params tree, bake dequantization into the serve closure,
    serialize with the manifest ``quantization`` section."""
    import jax
    import jax.numpy as jnp

    from tensorflowdistributedlearning_tpu.train import quantize
    from tensorflowdistributedlearning_tpu.train import serving as serving_lib

    qtree, section = quantize.quantize_pytree(params, serving_dtype)
    act_dtype = quantize.compute_dtype(serving_dtype)

    def serve(x):
        p = quantize.dequantize_pytree(qtree, act_dtype)
        h = jnp.maximum(
            x.astype(act_dtype) @ p["dense1"]["kernel"] + p["dense1"]["bias"],
            0,
        )
        logits = h @ p["dense2"]["kernel"]
        out = {
            "probabilities": jax.nn.softmax(logits, axis=-1),
            "class": jnp.argmax(logits, axis=-1),
        }
        return quantize.cast_outputs_float32(out)

    return serving_lib.export_serving_artifact(
        serve, (1, FEATURES), directory, quantization=section
    )


def quant_precision_ab(args, telemetry) -> dict:
    """The per-precision serving A/B: export each precision, serve each from
    its manifest alone (fresh engine + registry + recompile detector per
    precision), drive the identical closed-loop load, run the accuracy gate
    for every quantized precision against the f32 reference."""
    import tempfile

    from tensorflowdistributedlearning_tpu.obs import RecompileDetector
    from tensorflowdistributedlearning_tpu.serve import (
        InferenceEngine,
        MicroBatcher,
    )
    from tensorflowdistributedlearning_tpu.serve.quant_check import (
        run_quant_check,
    )
    from tensorflowdistributedlearning_tpu.train import serving as serving_lib

    params = make_quant_model_params()
    root = tempfile.mkdtemp(prefix="bench_quant_")
    section: dict = {"precisions": {}, "quant_check": {}}
    dirs: dict = {}
    for dtype in args.quant_dtypes:
        directory = os.path.join(root, dtype)
        try:
            export_quant_artifact(params, dtype, directory)
        except Exception as e:  # noqa: BLE001 — record, keep the A/B alive
            section["precisions"][dtype] = {
                "skipped": f"{type(e).__name__}: {e}"
            }
            continue
        dirs[dtype] = directory

    for dtype, directory in dirs.items():
        print(f"precision {dtype}: {args.concurrency} clients, "
              f"{args.duration}s ...", flush=True)
        detector = RecompileDetector().attach()
        try:
            engine = InferenceEngine.from_artifact(
                directory, buckets=args.buckets
            )
            warmup_s = engine.warmup()
            detector.mark_warm()
            batcher = MicroBatcher(
                engine, max_wait_ms=args.max_wait_ms,
                max_queue=max(256, 4 * args.concurrency),
            )
            entry = best_of(
                lambda x: batcher.submit(x).result(30),
                args.concurrency, args.duration, args.trials,
            )
            batcher.close()
            entry["warmup_s"] = {str(b): s for b, s in warmup_s.items()}
            entry["bucket_hits"] = {
                str(b): n for b, n in engine.bucket_hits.items()
            }
            entry["padding_waste"] = {
                str(b): w for b, w in engine.padding_waste.items()
            }
            entry["artifact_bytes"] = os.path.getsize(
                os.path.join(directory, serving_lib.ARTIFACT_NAME)
            )
            entry["post_warmup_recompiles"] = detector.post_warmup_count
        finally:
            detector.detach()
        section["precisions"][dtype] = entry
        telemetry.event("bench_mode", mode=f"quant_{dtype}", **entry)

    f32_dir = dirs.get("float32")
    if f32_dir:
        for dtype, directory in dirs.items():
            if dtype == "float32":
                continue
            verdict = run_quant_check(
                f32_dir, directory, telemetry=telemetry
            )
            section["quant_check"][dtype] = {
                "passed": verdict["passed"],
                "failures": verdict["failures"],
                "outputs": verdict["outputs"],
            }

    f32 = section["precisions"].get("float32", {})
    for dtype in args.quant_dtypes:
        entry = section["precisions"].get(dtype, {})
        if dtype == "float32" or "requests_per_sec" not in entry:
            continue
        if f32.get("requests_per_sec"):
            entry["speedup_vs_f32"] = round(
                entry["requests_per_sec"] / f32["requests_per_sec"], 3
            )
            entry["p99_ratio_vs_f32"] = round(
                entry["latency_ms"]["p99"] / f32["latency_ms"]["p99"], 3
            )
            entry["artifact_bytes_ratio_vs_f32"] = round(
                entry["artifact_bytes"] / f32["artifact_bytes"], 3
            )
    return section


def closed_loop(issue, concurrency: int, duration_s: float) -> dict:
    """Run ``concurrency`` closed-loop clients for ``duration_s``; returns
    completed-request throughput and client-observed latency percentiles."""
    stop = time.monotonic() + duration_s
    counts = [0] * concurrency
    latencies: list = [[] for _ in range(concurrency)]
    errors = [0] * concurrency
    barrier = threading.Barrier(concurrency + 1)
    rng = np.random.default_rng(7)
    # one example per client, pre-generated off the clock
    examples = rng.normal(0, 1, (concurrency, FEATURES)).astype(np.float32)

    def client(i: int):
        x = examples[i : i + 1]
        barrier.wait()
        while time.monotonic() < stop:
            t0 = time.perf_counter()
            try:
                issue(x)
            except Exception:  # noqa: BLE001 — count, keep looping
                errors[i] += 1
                continue
            latencies[i].append(time.perf_counter() - t0)
            counts[i] += 1

    threads = [
        threading.Thread(target=client, args=(i,), daemon=True)
        for i in range(concurrency)
    ]
    for t in threads:
        t.start()
    barrier.wait()
    t_start = time.monotonic()
    for t in threads:
        t.join(duration_s + 30)
    elapsed = time.monotonic() - t_start
    lat = np.asarray([s for per in latencies for s in per], np.float64)
    total = int(sum(counts))
    out = {
        "requests": total,
        "errors": int(sum(errors)),
        "elapsed_s": round(elapsed, 3),
        "requests_per_sec": round(total / elapsed, 1) if elapsed else 0.0,
    }
    if len(lat):
        out["latency_ms"] = {
            "mean": round(float(lat.mean()) * 1000, 3),
            "p50": round(float(np.percentile(lat, 50)) * 1000, 3),
            "p99": round(float(np.percentile(lat, 99)) * 1000, 3),
        }
    return out


def best_of(issue, concurrency: int, duration_s: float, trials: int) -> dict:
    """Best-of-N closed-loop runs per mode: this box shows multi-second
    noisy-neighbor windows that halve throughput for every mode at once; the
    max is the standard capability estimator under that noise. All trial
    rates are kept in the record so the spread is visible."""
    runs = [closed_loop(issue, concurrency, duration_s) for _ in range(trials)]
    best = max(runs, key=lambda r: r["requests_per_sec"])
    best["trial_rps"] = [r["requests_per_sec"] for r in runs]
    return best


def probe_backpressure() -> dict:
    """A full bounded queue must reject at submit time with QueueFullError —
    the structured signal — while everything already accepted completes."""
    from tensorflowdistributedlearning_tpu.serve import (
        InferenceEngine,
        MicroBatcher,
        QueueFullError,
    )

    release = threading.Event()

    def stalled_fn(x):  # holds the worker so the queue genuinely fills
        release.wait(10)
        return {"y": np.asarray(x)}

    engine = InferenceEngine(stalled_fn, (4,), buckets=(1,))
    batcher = MicroBatcher(engine, max_queue=4, max_wait_ms=0.0)
    accepted = []
    rejected = False
    x = np.zeros((1, 4), np.float32)
    try:
        # max_queue + worker-in-flight + 1 guarantees one submit sees a full
        # queue regardless of how fast the worker drains the first request
        for _ in range(batcher.max_queue + 2):
            accepted.append(batcher.submit(x))
    except QueueFullError:
        rejected = True
    release.set()
    completed = sum(1 for r in accepted if r.result(10) is not None)
    batcher.close()
    return {
        "queue_size": batcher.max_queue,
        "accepted": len(accepted),
        "completed": completed,
        "structured_reject": rejected,
    }


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--concurrency", type=int, default=32)
    parser.add_argument("--duration", type=float, default=2.0,
                        help="seconds per trial")
    parser.add_argument("--trials", type=int, default=2,
                        help="closed-loop trials per mode; the best is "
                        "reported (shared-host noise resilience)")
    parser.add_argument("--buckets", type=int, nargs="+",
                        default=(1, 4, 16, 64))
    parser.add_argument("--max-wait-ms", type=float, default=1.0)
    parser.add_argument("--http", action="store_true",
                        help="also measure the full HTTP stack (localhost)")
    parser.add_argument("--json-out", default=os.path.join(REPO, "BENCH_SERVE.json"))
    parser.add_argument("--ledger-dir", default=None,
                        help="write a telemetry ledger (enables the "
                        "recompile-detector assertion)")
    parser.add_argument("--check", action="store_true",
                        help="exit non-zero unless speedup >= --min-speedup, "
                        "zero post-warmup recompiles, and backpressure "
                        "rejected structurally (+ the quant gates when "
                        "--quant ran)")
    parser.add_argument("--min-speedup", type=float, default=3.0)
    parser.add_argument("--quant", action="store_true",
                        help="add the per-precision serving A/B: export "
                        "f32/bf16/int8 artifacts through the real "
                        "quantized-serving seam, drive identical load "
                        "through each, run the quantize-check accuracy "
                        "gate (record section: precisions)")
    parser.add_argument("--quant-only", action="store_true",
                        help="run ONLY the precision A/B (implies --quant; "
                        "skips the batching A/B + backpressure probe) — "
                        "the fast CI gate mode")
    parser.add_argument("--quant-dtypes", nargs="+",
                        default=("float32", "bfloat16", "int8"),
                        choices=("float32", "bfloat16", "int8"))
    parser.add_argument("--min-quant-speedup", type=float, default=None,
                        help="--check floor for bf16-vs-f32 throughput at "
                        "no-worse p99; default 1.5 on TPU (the HBM win the "
                        "path exists for), 0.8 elsewhere (XLA:CPU upcasts "
                        "bf16 — the tripwire just catches a quantized path "
                        "that got materially slower)")
    args = parser.parse_args()
    if args.quant_only:
        args.quant = True

    from tensorflowdistributedlearning_tpu.obs import Telemetry
    from tensorflowdistributedlearning_tpu.serve import (
        InferenceEngine,
        MicroBatcher,
        ServingServer,
    )

    telemetry = Telemetry(
        args.ledger_dir,
        enabled=args.ledger_dir is not None,
        run_info={
            "kind": "bench_serve",
            "concurrency": args.concurrency,
            "duration_s": args.duration,
            "buckets": list(args.buckets),
        },
    )
    # the zero-recompile gate must hold with or without a ledger: fall back
    # to a standalone detector when telemetry is disabled
    standalone_detector = None
    if telemetry.detector is None:
        from tensorflowdistributedlearning_tpu.obs import RecompileDetector

        standalone_detector = RecompileDetector().attach()
    detector = telemetry.detector or standalone_detector

    record: dict = {
        "model": {"features": FEATURES, "hidden": HIDDEN, "classes": CLASSES},
        "concurrency": args.concurrency,
        "duration_s": args.duration,
        "buckets": list(args.buckets),
        "max_wait_ms": args.max_wait_ms,
    }

    if not args.quant_only:
        serve_fn = make_synthetic_model()
        # one engine (with its OWN registry) per mode so counters and
        # per-bucket hits stay attributable to a mode — the ledger is the
        # only shared sink; all warm BEFORE the detector goes warm, after
        # that any compile is a bug
        engine_pr = InferenceEngine(serve_fn, (FEATURES,), buckets=(1,))
        engine_b = InferenceEngine(serve_fn, (FEATURES,), buckets=args.buckets)
        engine_pr.warmup()
        warmup_s = engine_b.warmup(telemetry=telemetry)
        record["warmup_s"] = {str(b): s for b, s in warmup_s.items()}
        if standalone_detector is not None:
            standalone_detector.mark_warm()

        print(f"per-request baseline: {args.concurrency} clients, "
              f"{args.duration}s ...", flush=True)
        batcher_pr = MicroBatcher(engine_pr, max_wait_ms=0.0,
                                  max_queue=max(256, 4 * args.concurrency))
        record["per_request"] = best_of(
            lambda x: batcher_pr.submit(x).result(30),
            args.concurrency, args.duration, args.trials,
        )
        batcher_pr.close()
        telemetry.event("bench_mode", mode="per_request",
                        **record["per_request"])

        print("batched (in-process micro-batcher) ...", flush=True)
        batcher = MicroBatcher(engine_b, max_wait_ms=args.max_wait_ms,
                               max_queue=max(256, 4 * args.concurrency))
        record["batched"] = best_of(
            lambda x: batcher.submit(x).result(30),
            args.concurrency, args.duration, args.trials,
        )
        record["batched"]["bucket_hits"] = {
            str(b): n for b, n in engine_b.bucket_hits.items()
        }
        record["batched"]["padding_waste"] = {
            str(b): w for b, w in engine_b.padding_waste.items()
        }
        telemetry.event("bench_mode", mode="batched", **record["batched"])

    if args.http and not args.quant_only:
        print("http (full stack, localhost) ...", flush=True)
        import http.client
        import socket

        engine_h = InferenceEngine(serve_fn, (FEATURES,), buckets=args.buckets)
        engine_h.warmup()
        batcher_h = MicroBatcher(engine_h, max_wait_ms=args.max_wait_ms,
                                 max_queue=max(256, 4 * args.concurrency))
        server = ServingServer(engine_h, batcher_h, port=0,
                               telemetry=telemetry, window_secs=0).start()
        local = threading.local()  # one keep-alive connection per client

        def issue_http(x):
            conn = getattr(local, "conn", None)
            if conn is None:
                conn = local.conn = http.client.HTTPConnection(
                    server.host, server.port, timeout=30
                )
                conn.connect()
                # headers and body go out as separate writes; without
                # NODELAY the body waits out a delayed ACK (~40-200ms)
                conn.sock.setsockopt(
                    socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
                )
            body = json.dumps({"instances": x.tolist()})
            try:
                conn.request("POST", "/v1/predict", body,
                             {"Content-Type": "application/json"})
                resp = conn.getresponse()
                payload = json.loads(resp.read())
            except (http.client.HTTPException, OSError):
                local.conn = None  # reconnect next iteration
                raise
            if resp.status != 200:
                raise RuntimeError(f"HTTP {resp.status}: {payload}")

        record["http"] = best_of(
            issue_http, args.concurrency, args.duration, args.trials
        )
        telemetry.event("bench_mode", mode="http", **record["http"])
        server.shutdown()

    if not args.quant_only:
        record["backpressure"] = probe_backpressure()
        pr_rps = record["per_request"]["requests_per_sec"]
        b_rps = record["batched"]["requests_per_sec"]
        record["speedup_batched_vs_per_request"] = (
            round(b_rps / pr_rps, 2) if pr_rps else None
        )
        record["post_warmup_recompiles"] = detector.post_warmup_count

    if args.quant:
        import jax

        quant = quant_precision_ab(args, telemetry)
        quant["backend"] = jax.default_backend()
        if jax.default_backend() != "tpu":
            quant["note"] = (
                "off-TPU backends upcast bf16/int8 to f32 compute, so the "
                "HBM-bandwidth win the quantized path exists for is not "
                "measurable here — the 1.5x-at-fixed-p99 gate applies on "
                "TPU; these curves pin the CPU contract (accuracy gates "
                "pass, zero recompiles, no material slowdown, artifact "
                "bytes scale with dtype)"
            )
        record["quant"] = quant

    if standalone_detector is not None:
        standalone_detector.detach()
    telemetry.event("bench_serve", **{
        k: v for k, v in record.items() if k != "model"
    })
    telemetry.close(
        speedup=record.get("speedup_batched_vs_per_request"),
        recompiles_post_warmup=record.get("post_warmup_recompiles"),
    )

    with open(args.json_out, "w") as f:
        json.dump(record, f, indent=1)
    summary = {
        "per_request_rps": record.get("per_request", {}).get("requests_per_sec"),
        "batched_rps": record.get("batched", {}).get("requests_per_sec"),
        "http_rps": record.get("http", {}).get("requests_per_sec"),
        "speedup": record.get("speedup_batched_vs_per_request"),
        "post_warmup_recompiles": record.get("post_warmup_recompiles"),
        "written": args.json_out,
    }
    if "backpressure" in record:
        summary["backpressure_structured_reject"] = (
            record["backpressure"]["structured_reject"]
        )
    if args.quant:
        summary["precision_rps"] = {
            d: e.get("requests_per_sec")
            for d, e in record["quant"]["precisions"].items()
        }
        summary["quant_check_passed"] = {
            d: v["passed"] for d, v in record["quant"]["quant_check"].items()
        }
    print(json.dumps(summary))

    if args.check:
        problems = []
        if not args.quant_only:
            speedup = record["speedup_batched_vs_per_request"] or 0
            if speedup < args.min_speedup:
                problems.append(
                    f"speedup {speedup} < required {args.min_speedup}"
                )
            if record.get("post_warmup_recompiles"):
                problems.append(
                    f"{record['post_warmup_recompiles']} post-warmup "
                    "recompile(s)"
                )
            if not record["backpressure"]["structured_reject"]:
                problems.append("full queue did not reject structurally")
            if (record["backpressure"]["completed"]
                    != record["backpressure"]["accepted"]):
                problems.append(
                    "accepted requests lost during backpressure probe"
                )
        if args.quant:
            problems.extend(_check_quant(record["quant"], args))
        if problems:
            print("CHECK FAILED: " + "; ".join(problems), file=sys.stderr)
            return 1
    return 0


def _check_quant(quant: dict, args) -> list:
    """The quant gates: accuracy gate passed for every quantized precision,
    zero post-warmup recompiles per precision, and bf16 throughput at or
    above the backend's floor WITHOUT a p99 regression (the fixed-p99
    framing: extra throughput bought with latency doesn't count)."""
    import jax

    problems = []
    min_speedup = args.min_quant_speedup
    if min_speedup is None:
        min_speedup = 1.5 if jax.default_backend() == "tpu" else 0.8
    for dtype, verdict in quant["quant_check"].items():
        if not verdict["passed"]:
            problems.append(
                f"quantize-check failed for {dtype}: "
                + "; ".join(verdict["failures"])
            )
    for dtype, entry in quant["precisions"].items():
        if entry.get("skipped"):
            # int8 may be unsupported on a backend; that is a recorded skip,
            # not a failure — but the headline bf16 path must always run
            if dtype == "bfloat16":
                problems.append(f"bfloat16 precision skipped: {entry['skipped']}")
            continue
        if entry.get("post_warmup_recompiles"):
            problems.append(
                f"{entry['post_warmup_recompiles']} post-warmup recompile(s) "
                f"serving the {dtype} artifact"
            )
    bf16 = quant["precisions"].get("bfloat16", {})
    if bf16.get("speedup_vs_f32") is not None:
        if bf16["speedup_vs_f32"] < min_speedup:
            problems.append(
                f"bf16-vs-f32 throughput {bf16['speedup_vs_f32']} < "
                f"required {min_speedup} on {jax.default_backend()}"
            )
        elif bf16.get("p99_ratio_vs_f32", 1.0) > 1.25:
            problems.append(
                f"bf16 p99 regressed {bf16['p99_ratio_vs_f32']}x vs f32 — "
                "throughput at degraded latency does not count"
            )
    return problems


if __name__ == "__main__":
    sys.exit(main())
