"""Closed-loop load generator for the serve/ stack: batched vs per-request.

Builds a small synthetic params-baked model (pure jax, no checkpoint), then
drives it with N closed-loop clients (each thread issues its next request the
moment the previous one answers — the standard closed-loop load model) in up
to three configurations:

- ``per_request``: the same serving pipeline (bounded queue, single dispatch
  worker, futures) with coalescing OFF — every request is its own batch-1
  forward, serialized at the device exactly like a no-batching server in
  front of one accelerator;
- ``batched``:     identical pipeline with the bucket-ladder coalescing ON —
  the only variable is server-side batching;
- ``http``:        the full stack — ThreadingHTTPServer, JSON wire format,
  batcher, engine (enabled with ``--http``).

Also probes the backpressure contract (a full bounded queue must answer with
a structured QueueFullError, not queue unboundedly) and — when ``--ledger-dir``
is given — runs under a Telemetry recompile detector marked warm after bucket
warmup, so the record carries the post-warmup recompile count (must be 0: the
bucket ladder exists so steady-state serving never recompiles).

Writes a JSON record (default BENCH_SERVE.json). ``--check`` exits non-zero
unless batched/per_request speedup >= --min-speedup, recompiles == 0, and the
backpressure probe rejected structurally — the CI serve-smoke gate
(tools/run_suite.py --serve-smoke).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

FEATURES = 128
HIDDEN = 256
CLASSES = 16


def make_synthetic_model():
    """Params-baked jitted ``x [B, FEATURES] -> {probabilities, class}`` —
    shaped like the trainers' serving_fn closures, sized so one forward is
    dispatch-overhead-dominated at batch 1 (the regime batching exists for)."""
    import jax
    import jax.numpy as jnp

    k1, k2 = jax.random.split(jax.random.PRNGKey(0))
    w1 = jax.random.normal(k1, (FEATURES, HIDDEN), jnp.float32) * 0.05
    w2 = jax.random.normal(k2, (HIDDEN, CLASSES), jnp.float32) * 0.05

    @jax.jit
    def serve(x):
        h = jnp.maximum(x @ w1, 0.0)
        logits = h @ w2
        return {
            "probabilities": jax.nn.softmax(logits, axis=-1),
            "class": jnp.argmax(logits, axis=-1),
        }

    return serve


def closed_loop(issue, concurrency: int, duration_s: float) -> dict:
    """Run ``concurrency`` closed-loop clients for ``duration_s``; returns
    completed-request throughput and client-observed latency percentiles."""
    stop = time.monotonic() + duration_s
    counts = [0] * concurrency
    latencies: list = [[] for _ in range(concurrency)]
    errors = [0] * concurrency
    barrier = threading.Barrier(concurrency + 1)
    rng = np.random.default_rng(7)
    # one example per client, pre-generated off the clock
    examples = rng.normal(0, 1, (concurrency, FEATURES)).astype(np.float32)

    def client(i: int):
        x = examples[i : i + 1]
        barrier.wait()
        while time.monotonic() < stop:
            t0 = time.perf_counter()
            try:
                issue(x)
            except Exception:  # noqa: BLE001 — count, keep looping
                errors[i] += 1
                continue
            latencies[i].append(time.perf_counter() - t0)
            counts[i] += 1

    threads = [
        threading.Thread(target=client, args=(i,), daemon=True)
        for i in range(concurrency)
    ]
    for t in threads:
        t.start()
    barrier.wait()
    t_start = time.monotonic()
    for t in threads:
        t.join(duration_s + 30)
    elapsed = time.monotonic() - t_start
    lat = np.asarray([s for per in latencies for s in per], np.float64)
    total = int(sum(counts))
    out = {
        "requests": total,
        "errors": int(sum(errors)),
        "elapsed_s": round(elapsed, 3),
        "requests_per_sec": round(total / elapsed, 1) if elapsed else 0.0,
    }
    if len(lat):
        out["latency_ms"] = {
            "mean": round(float(lat.mean()) * 1000, 3),
            "p50": round(float(np.percentile(lat, 50)) * 1000, 3),
            "p99": round(float(np.percentile(lat, 99)) * 1000, 3),
        }
    return out


def best_of(issue, concurrency: int, duration_s: float, trials: int) -> dict:
    """Best-of-N closed-loop runs per mode: this box shows multi-second
    noisy-neighbor windows that halve throughput for every mode at once; the
    max is the standard capability estimator under that noise. All trial
    rates are kept in the record so the spread is visible."""
    runs = [closed_loop(issue, concurrency, duration_s) for _ in range(trials)]
    best = max(runs, key=lambda r: r["requests_per_sec"])
    best["trial_rps"] = [r["requests_per_sec"] for r in runs]
    return best


def probe_backpressure() -> dict:
    """A full bounded queue must reject at submit time with QueueFullError —
    the structured signal — while everything already accepted completes."""
    from tensorflowdistributedlearning_tpu.serve import (
        InferenceEngine,
        MicroBatcher,
        QueueFullError,
    )

    release = threading.Event()

    def stalled_fn(x):  # holds the worker so the queue genuinely fills
        release.wait(10)
        return {"y": np.asarray(x)}

    engine = InferenceEngine(stalled_fn, (4,), buckets=(1,))
    batcher = MicroBatcher(engine, max_queue=4, max_wait_ms=0.0)
    accepted = []
    rejected = False
    x = np.zeros((1, 4), np.float32)
    try:
        # max_queue + worker-in-flight + 1 guarantees one submit sees a full
        # queue regardless of how fast the worker drains the first request
        for _ in range(batcher.max_queue + 2):
            accepted.append(batcher.submit(x))
    except QueueFullError:
        rejected = True
    release.set()
    completed = sum(1 for r in accepted if r.result(10) is not None)
    batcher.close()
    return {
        "queue_size": batcher.max_queue,
        "accepted": len(accepted),
        "completed": completed,
        "structured_reject": rejected,
    }


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--concurrency", type=int, default=32)
    parser.add_argument("--duration", type=float, default=2.0,
                        help="seconds per trial")
    parser.add_argument("--trials", type=int, default=2,
                        help="closed-loop trials per mode; the best is "
                        "reported (shared-host noise resilience)")
    parser.add_argument("--buckets", type=int, nargs="+",
                        default=(1, 4, 16, 64))
    parser.add_argument("--max-wait-ms", type=float, default=1.0)
    parser.add_argument("--http", action="store_true",
                        help="also measure the full HTTP stack (localhost)")
    parser.add_argument("--json-out", default=os.path.join(REPO, "BENCH_SERVE.json"))
    parser.add_argument("--ledger-dir", default=None,
                        help="write a telemetry ledger (enables the "
                        "recompile-detector assertion)")
    parser.add_argument("--check", action="store_true",
                        help="exit non-zero unless speedup >= --min-speedup, "
                        "zero post-warmup recompiles, and backpressure "
                        "rejected structurally")
    parser.add_argument("--min-speedup", type=float, default=3.0)
    args = parser.parse_args()

    from tensorflowdistributedlearning_tpu.obs import Telemetry
    from tensorflowdistributedlearning_tpu.serve import (
        InferenceEngine,
        MicroBatcher,
        ServingServer,
    )

    telemetry = Telemetry(
        args.ledger_dir,
        enabled=args.ledger_dir is not None,
        run_info={
            "kind": "bench_serve",
            "concurrency": args.concurrency,
            "duration_s": args.duration,
            "buckets": list(args.buckets),
        },
    )
    # the zero-recompile gate must hold with or without a ledger: fall back
    # to a standalone detector when telemetry is disabled
    standalone_detector = None
    if telemetry.detector is None:
        from tensorflowdistributedlearning_tpu.obs import RecompileDetector

        standalone_detector = RecompileDetector().attach()
    detector = telemetry.detector or standalone_detector

    serve_fn = make_synthetic_model()
    record: dict = {
        "model": {"features": FEATURES, "hidden": HIDDEN, "classes": CLASSES},
        "concurrency": args.concurrency,
        "duration_s": args.duration,
        "buckets": list(args.buckets),
        "max_wait_ms": args.max_wait_ms,
    }

    # one engine (with its OWN registry) per mode so counters and per-bucket
    # hits stay attributable to a mode — the ledger is the only shared sink;
    # all warm BEFORE the detector goes warm, after that any compile is a bug
    engine_pr = InferenceEngine(serve_fn, (FEATURES,), buckets=(1,))
    engine_b = InferenceEngine(serve_fn, (FEATURES,), buckets=args.buckets)
    engine_pr.warmup()
    warmup_s = engine_b.warmup(telemetry=telemetry)
    record["warmup_s"] = {str(b): s for b, s in warmup_s.items()}
    if standalone_detector is not None:
        standalone_detector.mark_warm()

    print(f"per-request baseline: {args.concurrency} clients, "
          f"{args.duration}s ...", flush=True)
    batcher_pr = MicroBatcher(engine_pr, max_wait_ms=0.0,
                              max_queue=max(256, 4 * args.concurrency))
    record["per_request"] = best_of(
        lambda x: batcher_pr.submit(x).result(30),
        args.concurrency, args.duration, args.trials,
    )
    batcher_pr.close()
    telemetry.event("bench_mode", mode="per_request", **record["per_request"])

    print("batched (in-process micro-batcher) ...", flush=True)
    batcher = MicroBatcher(engine_b, max_wait_ms=args.max_wait_ms,
                           max_queue=max(256, 4 * args.concurrency))
    record["batched"] = best_of(
        lambda x: batcher.submit(x).result(30),
        args.concurrency, args.duration, args.trials,
    )
    record["batched"]["bucket_hits"] = {
        str(b): n for b, n in engine_b.bucket_hits.items()
    }
    telemetry.event("bench_mode", mode="batched", **record["batched"])

    if args.http:
        print("http (full stack, localhost) ...", flush=True)
        import http.client
        import socket

        engine_h = InferenceEngine(serve_fn, (FEATURES,), buckets=args.buckets)
        engine_h.warmup()
        batcher_h = MicroBatcher(engine_h, max_wait_ms=args.max_wait_ms,
                                 max_queue=max(256, 4 * args.concurrency))
        server = ServingServer(engine_h, batcher_h, port=0,
                               telemetry=telemetry, window_secs=0).start()
        local = threading.local()  # one keep-alive connection per client

        def issue_http(x):
            conn = getattr(local, "conn", None)
            if conn is None:
                conn = local.conn = http.client.HTTPConnection(
                    server.host, server.port, timeout=30
                )
                conn.connect()
                # headers and body go out as separate writes; without
                # NODELAY the body waits out a delayed ACK (~40-200ms)
                conn.sock.setsockopt(
                    socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
                )
            body = json.dumps({"instances": x.tolist()})
            try:
                conn.request("POST", "/v1/predict", body,
                             {"Content-Type": "application/json"})
                resp = conn.getresponse()
                payload = json.loads(resp.read())
            except (http.client.HTTPException, OSError):
                local.conn = None  # reconnect next iteration
                raise
            if resp.status != 200:
                raise RuntimeError(f"HTTP {resp.status}: {payload}")

        record["http"] = best_of(
            issue_http, args.concurrency, args.duration, args.trials
        )
        telemetry.event("bench_mode", mode="http", **record["http"])
        server.shutdown()

    record["backpressure"] = probe_backpressure()

    pr_rps = record["per_request"]["requests_per_sec"]
    b_rps = record["batched"]["requests_per_sec"]
    record["speedup_batched_vs_per_request"] = (
        round(b_rps / pr_rps, 2) if pr_rps else None
    )
    record["post_warmup_recompiles"] = detector.post_warmup_count
    if standalone_detector is not None:
        standalone_detector.detach()
    telemetry.event("bench_serve", **{
        k: v for k, v in record.items() if k != "model"
    })
    telemetry.close(
        speedup=record["speedup_batched_vs_per_request"],
        recompiles_post_warmup=record.get("post_warmup_recompiles"),
    )

    with open(args.json_out, "w") as f:
        json.dump(record, f, indent=1)
    print(json.dumps({
        "per_request_rps": pr_rps,
        "batched_rps": b_rps,
        "http_rps": record.get("http", {}).get("requests_per_sec"),
        "speedup": record["speedup_batched_vs_per_request"],
        "post_warmup_recompiles": record.get("post_warmup_recompiles"),
        "backpressure_structured_reject":
            record["backpressure"]["structured_reject"],
        "written": args.json_out,
    }))

    if args.check:
        problems = []
        speedup = record["speedup_batched_vs_per_request"] or 0
        if speedup < args.min_speedup:
            problems.append(
                f"speedup {speedup} < required {args.min_speedup}"
            )
        if record.get("post_warmup_recompiles"):
            problems.append(
                f"{record['post_warmup_recompiles']} post-warmup recompile(s)"
            )
        if not record["backpressure"]["structured_reject"]:
            problems.append("full queue did not reject structurally")
        if record["backpressure"]["completed"] != record["backpressure"]["accepted"]:
            problems.append("accepted requests lost during backpressure probe")
        if problems:
            print("CHECK FAILED: " + "; ".join(problems), file=sys.stderr)
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
