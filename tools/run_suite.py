"""Process-partitioned test-suite runner for the 1-core driver box.

The full suite in ONE pytest process accumulates hundreds of live XLA:CPU
executables and deterministically segfaults the compiler near test ~315
(``backend_compile_and_load``; every module passes in isolation — VERDICT r3
weak #4). conftest.py holds that off with an RSS-growth heuristic; this runner
contains it STRUCTURALLY: test modules run in a few sequential pytest
processes, so no process ever approaches the accumulation limit and the
heuristic becomes belt-and-suspenders.

Partitioning: each known-heavy module anchors its own group; the rest
round-robin over the remaining slots. Children inherit the persistent compile
cache (.jax_cache), so split-induced recompiles are mostly cache hits.

Usage: python tools/run_suite.py [--groups N] [--json-out SUITE_RUN.json]
Exit code: 0 iff every group passed.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import shlex
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# e2e-dominant modules, one per group head (measured round-3/4: these dominate
# suite wall time and executable accumulation)
HEAVY = (
    "test_trainer.py",
    "test_fit.py",
    "test_records.py",
    "test_multiprocess.py",
    "test_train_step.py",
    "test_digits_e2e.py",
)


def partition(files: list[str], n_groups: int) -> list[list[str]]:
    """Heavy modules anchor groups round-robin; light modules fill round-robin
    behind them. Deterministic for a given file list."""
    heavy = [f for f in files if os.path.basename(f) in HEAVY]
    light = [f for f in files if os.path.basename(f) not in HEAVY]
    groups: list[list[str]] = [[] for _ in range(n_groups)]
    for i, f in enumerate(heavy):
        groups[i % n_groups].append(f)
    for i, f in enumerate(light):
        groups[(i + len(heavy)) % n_groups].append(f)
    return [g for g in groups if g]


def _write_group_ledger(ledger_dir: str, group_index: int, names, **fields):
    """--aggregate: one complete mini-ledger per pytest group under the fleet
    naming contract (telemetry-{i}.jsonl; the suite's own ledger is process
    0's telemetry.jsonl), so the end-of-suite obs.fleet merge exercises the
    same discovery+aggregation path a multi-host training run uses."""
    try:
        if REPO not in sys.path:
            sys.path.insert(0, REPO)
        from tensorflowdistributedlearning_tpu.obs import RunLedger
        from tensorflowdistributedlearning_tpu.obs.ledger import (
            per_process_filename,
        )

        ledger = RunLedger(
            ledger_dir, filename=per_process_filename(group_index)
        )
        ledger.event(
            "run_header", kind="suite_group", process_index=group_index,
            files=list(names),
        )
        ledger.event("suite_group", group=group_index, files=list(names),
                     **fields)
        ledger.event("run_end", ok=fields.get("rc") == 0)
        ledger.close()
    except Exception as e:  # noqa: BLE001 — never take the suite down
        print(f"group ledger disabled: {e}", file=sys.stderr)


def _open_ledger(ledger_dir: str):
    """Suite runs write the same JSONL ledger schema training runs do
    (obs/ledger.py): a run_header, one ``suite_group`` event per pytest
    child, and a run_end with the TimeHistogram summary of group wall times
    — so suite history is greppable/mergeable with the same tooling as
    ``telemetry-report``'s inputs. Best-effort: a broken import or an
    unwritable dir must not take the suite runner down."""
    try:
        sys.path.insert(0, REPO)
        from tensorflowdistributedlearning_tpu.obs import RunLedger

        return RunLedger(ledger_dir)
    except Exception as e:  # noqa: BLE001
        print(f"suite ledger disabled: {e}", file=sys.stderr)
        return None


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--groups", type=int, default=4)
    parser.add_argument("--json-out", default=None)
    parser.add_argument("--ledger-dir", default=None,
                        help="append suite events to {dir}/telemetry.jsonl "
                        "(the obs run-ledger schema); omitted = no ledger")
    parser.add_argument("--aggregate", action="store_true",
                        help="additionally write one PER-GROUP ledger "
                        "(telemetry-{i}.jsonl, the fleet naming contract) "
                        "into --ledger-dir and finish by merging them "
                        "through obs.fleet — the multi-ledger aggregation "
                        "path proven on a real suite run")
    parser.add_argument("--pytest-args", default="-q",
                        help="extra args passed to each pytest child; values "
                        "starting with '-' need the = form "
                        "(--pytest-args='-q --durations=10') or argparse "
                        "rejects them as options")
    parser.add_argument("--group-timeout", type=int, default=1500,
                        help="seconds per pytest child before it is killed "
                        "and recorded as a timeout (a hung group must not "
                        "wedge the runner)")
    parser.add_argument("--serve-smoke", action="store_true",
                        help="after the test groups, run the closed-loop "
                        "load generator (tools/bench_serve.py --http) "
                        "against a synthetic-model server: checks the "
                        "batched-vs-per-request speedup, zero post-warmup "
                        "recompiles, and structured queue-full rejection")
    parser.add_argument("--resilience-smoke", action="store_true",
                        help="after the test groups, run the resilience "
                        "drill (tests/resilience_train_worker.py smoke): "
                        "SIGTERM-inject a tiny training run at a seeded-"
                        "random step, recover it under the restart "
                        "supervisor, and assert the final params match an "
                        "uninterrupted run bit-for-bit")
    args = parser.parse_args()
    if args.aggregate and not args.ledger_dir:
        print("--aggregate requires --ledger-dir", file=sys.stderr)
        return 2

    files = sorted(glob.glob(os.path.join(REPO, "tests", "test_*.py")))
    if not files:
        print("no tests/test_*.py found — refusing to report a vacuous pass",
              file=sys.stderr)
        return 2
    env = dict(os.environ)
    # strip the axon sitecustomize: when the TPU tunnel is down it SIGTERMs
    # long-lived python processes on this box (driver-box memory); pytest
    # re-inserts the repo root itself
    env["PYTHONPATH"] = ""
    env["JAX_PLATFORMS"] = "cpu"

    ledger = _open_ledger(args.ledger_dir) if args.ledger_dir else None
    group_times = None
    if ledger is not None:
        from tensorflowdistributedlearning_tpu.obs import TimeHistogram

        group_times = TimeHistogram("suite_group")
        ledger.event(
            "run_header", kind="test_suite", groups=args.groups,
            files=len(files),
        )

    record: dict = {"groups": [], "ok": True}
    t_all = time.time()
    for i, group in enumerate(partition(files, args.groups)):
        names = [os.path.basename(f) for f in group]
        print(f"=== group {i + 1}: {' '.join(names)}", flush=True)
        t0 = time.time()
        # own session + killpg on timeout: some modules (test_multiprocess)
        # spawn grandchildren (gloo workers); killing only the pytest child
        # would orphan them on the 1-core box and wedge the REMAINING groups
        child = subprocess.Popen(
            [sys.executable, "-m", "pytest", *group,
             *shlex.split(args.pytest_args)],
            cwd=REPO,
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            start_new_session=True,
        )
        try:
            out, err = child.communicate(timeout=args.group_timeout)
        except subprocess.TimeoutExpired:
            import signal

            try:
                os.killpg(child.pid, signal.SIGKILL)
            except ProcessLookupError:
                pass
            out, err = child.communicate()
            secs = round(time.time() - t0, 1)
            record["ok"] = False
            print(
                f"    TIMEOUT after {secs}s; partial output:\n{(out or '')[-2000:]}",
                flush=True,
            )
            record["groups"].append(
                {"files": names, "timeout": args.group_timeout, "secs": secs}
            )
            if ledger is not None:
                group_times.record(secs)
                ledger.event(
                    "suite_group", group=i + 1, files=names, secs=secs,
                    timed_out=True,
                )
            if args.aggregate:
                _write_group_ledger(
                    args.ledger_dir, i + 1, names, secs=secs, rc=-1,
                    timed_out=True,
                )
            continue

        secs = round(time.time() - t0, 1)
        out = out or ""
        tail = out.strip().splitlines()[-1] if out.strip() else ""
        summary = re.search(r"(\d+ (?:passed|failed)[^\n]*)", tail)
        print(f"    rc={child.returncode} {secs}s {tail}", flush=True)
        if child.returncode != 0:
            record["ok"] = False
            print(out[-4000:], flush=True)
            print((err or "")[-2000:], file=sys.stderr, flush=True)
        record["groups"].append(
            {
                "files": names,
                "rc": child.returncode,
                "secs": secs,
                "summary": summary.group(1) if summary else tail,
            }
        )
        if ledger is not None:
            group_times.record(secs)
            ledger.event(
                "suite_group", group=i + 1, files=names, secs=secs,
                rc=child.returncode,
                summary=summary.group(1) if summary else tail,
            )
        if args.aggregate:
            _write_group_ledger(
                args.ledger_dir, i + 1, names, secs=secs,
                rc=child.returncode,
                summary=summary.group(1) if summary else tail,
            )
    if args.serve_smoke:
        print("=== serve smoke: load generator vs synthetic-model server",
              flush=True)
        t0 = time.time()
        smoke_cmd = [
            sys.executable, os.path.join(REPO, "tools", "bench_serve.py"),
            "--http", "--concurrency", "16", "--duration", "1.5",
            "--check", "--min-speedup", "1.5",
            "--json-out", os.path.join(REPO, "SERVE_SMOKE.json"),
        ]
        if args.ledger_dir:
            smoke_cmd += ["--ledger-dir", args.ledger_dir]
        try:
            smoke = subprocess.run(
                smoke_cmd, cwd=REPO, env=env, capture_output=True, text=True,
                timeout=300,
            )
            rc, tail = smoke.returncode, (smoke.stdout or "").strip().splitlines()
            summary = tail[-1] if tail else ""
            if rc != 0:
                print((smoke.stdout or "")[-2000:], flush=True)
                print((smoke.stderr or "")[-1000:], file=sys.stderr, flush=True)
        except subprocess.TimeoutExpired:
            rc, summary = -1, "serve smoke timed out"
        secs = round(time.time() - t0, 1)
        print(f"    rc={rc} {secs}s {summary}", flush=True)
        record["serve_smoke"] = {"rc": rc, "secs": secs, "summary": summary}
        record["ok"] = record["ok"] and rc == 0
        if ledger is not None:
            ledger.event("serve_smoke", rc=rc, secs=secs, summary=summary)

    if args.resilience_smoke:
        import tempfile

        print("=== resilience smoke: inject fault, assert supervised recovery",
              flush=True)
        t0 = time.time()
        with tempfile.TemporaryDirectory(prefix="resilience_smoke_") as tmp:
            cmd = [
                sys.executable,
                os.path.join(REPO, "tests", "resilience_train_worker.py"),
                "smoke", "--workdir", tmp,
            ]
            try:
                smoke = subprocess.run(
                    cmd, cwd=REPO, env=env, capture_output=True, text=True,
                    timeout=600,
                )
                rc = smoke.returncode
                tail = (smoke.stdout or "").strip().splitlines()
                summary = tail[-1] if tail else ""
                if rc != 0:
                    print((smoke.stdout or "")[-2000:], flush=True)
                    print((smoke.stderr or "")[-1000:], file=sys.stderr,
                          flush=True)
            except subprocess.TimeoutExpired:
                rc, summary = -1, "resilience smoke timed out"
        secs = round(time.time() - t0, 1)
        print(f"    rc={rc} {secs}s {summary}", flush=True)
        record["resilience_smoke"] = {"rc": rc, "secs": secs, "summary": summary}
        record["ok"] = record["ok"] and rc == 0
        if ledger is not None:
            ledger.event("resilience_smoke", rc=rc, secs=secs, summary=summary)

    if args.aggregate:
        # merge every per-group ledger (plus the suite's own) through the
        # fleet aggregation path — the same discovery+merge telemetry-report
        # runs on a multi-host workdir
        try:
            from tensorflowdistributedlearning_tpu.obs import fleet

            agg = fleet.fleet_summary(args.ledger_dir)
            record["aggregate"] = agg
            print(
                "=== aggregate: "
                + json.dumps({
                    "ledgers": agg["processes"],
                    "parse_errors": agg["ledger_parse_errors"],
                    "groups": [
                        {"p": r["process_index"], "kind": r["kind"]}
                        for r in agg["per_process"]
                    ],
                }),
                flush=True,
            )
        except Exception as e:  # noqa: BLE001
            print(f"aggregate stage failed: {e}", file=sys.stderr)
            record["ok"] = False

    record["total_secs"] = round(time.time() - t_all, 1)
    if ledger is not None:
        ledger.event(
            "run_end", ok=record["ok"], total_secs=record["total_secs"],
            group_secs=group_times.summary() if len(group_times) else None,
        )
        ledger.close()
    print(json.dumps({"ok": record["ok"], "total_secs": record["total_secs"]}))
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(record, f, indent=1)
    return 0 if record["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
