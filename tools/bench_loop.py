"""Continuous-learning loop drill: serve -> capture -> drift -> retrain -> promote.

The headline end-to-end exercise for the ``loop/`` subsystem, driven entirely
through the REAL CLIs (``serve-fleet``, ``flywheel``, and the flywheel's own
``fit --export-serving --auto-promote`` retrain subprocess):

1. Export a synthetic seed artifact whose ``class`` output tracks the
   per-example input mean (same shape contract as the ``elastic_smoke``
   preset's export: ``(b, 16, 16, 3) -> {class, probabilities}``), and stamp
   its ``drift_baseline`` exactly like a production export.
2. Launch a 2-replica ``serve-fleet`` with the capture tee and the drift
   monitor armed, and run closed-loop clients against the router for the
   WHOLE drill — zero client-visible errors end to end is a committed gate.
3. Phase 1: standard-normal traffic (matches the pinned baseline) builds the
   captured dataset. Phase 2: mean-shifted traffic moves the served class
   distribution, and the DriftMonitor must fire a ``drift_alert``.
4. ``flywheel --max-cycles 1`` ingests the captured shards, fires on the
   alert, retrains on the REAL captured dataset, and its ``--auto-promote``
   (with loosened shadow bands — a retrained model legitimately disagrees
   with the incumbent) flips the fleet to the new fingerprint.

The committed BENCH_LOOP.json records cycle wall time, samples
captured/ingested, drift-trigger latency, the promoted fingerprint, and the
client error count; ``tools/regression_sentinel.py`` (``check_loop``) replays
those numbers as hard CI gates.

A synthetic seed model (the bench_serve idiom) rather than a barely-trained
preset model: four ``fit`` steps collapse the micro ResNet to one class for
ANY input, which would make the drift score identically zero — the drill
needs a seed whose output distribution genuinely follows its input
distribution so the alert is earned, not injected.
"""

from __future__ import annotations

import argparse
import http.client
import json
import os
import signal as signal_lib
import subprocess
import sys
import tempfile
import threading
import time
import urllib.parse
import urllib.request

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

H, W, C = 16, 16, 3  # the elastic_smoke preset's input shape
NUM_CLASSES = 4


def export_seed_artifact(directory: str) -> str:
    """Synthetic mean-responsive classifier through the real serving seam.

    ``class = argmin_c (mean(x) - center_c)^2`` over centers packed inside
    one std of the per-example mean (sigma = 1/sqrt(16*16*3) ~ 0.036 under
    standard-normal inputs), so baseline traffic spreads over classes 0-2
    and a +1.0 mean shift lands every example in class 3 — a total-variation
    distance of ~1.0, far past any sane threshold."""
    import jax
    import jax.numpy as jnp

    from tensorflowdistributedlearning_tpu.serve.quant_check import (
        stamp_drift_baseline,
    )
    from tensorflowdistributedlearning_tpu.train import quantize
    from tensorflowdistributedlearning_tpu.train import serving as serving_lib

    centers = jnp.asarray([-0.03, 0.0, 0.03, 0.5], jnp.float32)
    params = {"centers": centers}
    _, section = quantize.quantize_pytree(params, "float32")

    def serve(x):
        m = jnp.mean(x, axis=(1, 2, 3))
        logits = -((m[:, None] - params["centers"][None, :]) ** 2) / 0.002
        return {
            "class": jnp.argmax(logits, axis=-1).astype(jnp.int32),
            "probabilities": jax.nn.softmax(logits, axis=-1),
        }

    serving_lib.export_serving_artifact(
        serve,
        (1, H, W, C),
        directory,
        metadata={"task": "classification", "num_classes": NUM_CLASSES},
        quantization=section,
    )
    stamp_drift_baseline(directory)
    return directory


def spawn_fleet(artifact: str, workdir: str, capture_dir: str, args):
    """The real tier — ``serve-fleet`` CLI in its own process — with the
    capture tee and drift monitor armed; returns ``(proc, router_url)``."""
    env = dict(os.environ, PYTHONPATH=REPO + os.pathsep + os.environ.get(
        "PYTHONPATH", ""))
    cmd = [
        sys.executable, "-m", "tensorflowdistributedlearning_tpu",
        "serve-fleet",
        "--artifact-dir", artifact,
        "--workdir", workdir,
        "--port", "0",
        "--replicas", str(args.replicas),
        "--no-autoscale",
        "--window-secs", str(args.window_secs),
        "--poll-interval-s", "0.25",
        "--capture-dir", capture_dir,
        "--capture-fraction", "1.0",
        "--capture-records-per-shard", "32",
        "--drift-threshold", str(args.drift_threshold),
        "--drift-min-requests", "20",
        "--drift-sustain-windows", "2",
    ]
    os.makedirs(workdir, exist_ok=True)
    log_fh = open(os.path.join(workdir, "controller.log"), "ab")
    try:
        proc = subprocess.Popen(
            cmd, stdout=subprocess.PIPE, stderr=log_fh, env=env, text=True
        )
    finally:
        log_fh.close()
    url: dict = {}

    def reader():
        for line in proc.stdout:
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                obj = json.loads(line)
            except ValueError:
                continue
            if "router" in obj:
                url["router"] = obj["router"]
                return

    t = threading.Thread(target=reader, daemon=True)
    t.start()
    t.join(180)
    if "router" not in url:
        proc.kill()
        raise RuntimeError(
            f"serve-fleet not ready — see {workdir}/controller.log"
        )
    return proc, url["router"]


def stop_fleet(proc) -> None:
    if proc.poll() is not None:
        return
    proc.send_signal(signal_lib.SIGTERM)
    try:
        proc.wait(90)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait(10)


class LoadGen:
    """Closed-loop clients for the whole drill. ``shift`` is mutable — the
    drift phase moves the input mean without dropping a single connection.
    Every non-200 answer is a client-visible error (the zero-errors gate);
    transient transport errors during replica flips count too — the router
    is supposed to absorb them."""

    def __init__(self, url: str, concurrency: int, seed: int = 11):
        self.parsed = urllib.parse.urlsplit(url)
        self.ok = 0
        self.errors = 0
        self.shift = 0.0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        rng = np.random.default_rng(seed)
        self._bodies = [
            rng.normal(0, 1, (1, H, W, C)).astype(np.float32)
            for _ in range(8)
        ]
        self.threads = [
            threading.Thread(target=self._run, args=(i,), daemon=True)
            for i in range(concurrency)
        ]
        for t in self.threads:
            t.start()

    def _run(self, i: int):
        conn = None
        n = 0
        while not self._stop.is_set():
            base = self._bodies[(i + n) % len(self._bodies)]
            n += 1
            body = json.dumps(
                {"instances": (base + self.shift).tolist()}
            )
            try:
                if conn is None:
                    conn = http.client.HTTPConnection(
                        self.parsed.hostname, self.parsed.port, timeout=30
                    )
                conn.request("POST", "/v1/predict", body,
                             {"Content-Type": "application/json"})
                resp = conn.getresponse()
                resp.read()
                with self._lock:
                    if resp.status == 200:
                        self.ok += 1
                    else:
                        self.errors += 1
            except (OSError, http.client.HTTPException):
                try:
                    if conn is not None:
                        conn.close()
                except OSError:
                    pass
                conn = None
                with self._lock:
                    self.errors += 1
            time.sleep(0.01)

    def stop(self):
        self._stop.set()
        for t in self.threads:
            t.join(10)


def _ledger_events(workdir: str, kind: str) -> list:
    from tensorflowdistributedlearning_tpu.obs import fleet as obs_fleet

    out = []
    for led in obs_fleet.discover_ledgers(workdir):
        out.extend(e for e in led.events if e.get("event") == kind)
    return sorted(out, key=lambda e: e.get("t", 0.0))


def run_drill(args) -> dict:
    from tensorflowdistributedlearning_tpu.loop.controller import (
        scan_drift_alerts,
    )
    from tensorflowdistributedlearning_tpu.loop.ingest import (
        read_dataset_manifest,
    )

    root = tempfile.mkdtemp(prefix="bench_loop_")
    workdir = os.path.join(root, "fleet")
    capture_dir = os.path.join(root, "capture")
    dataset_dir = os.path.join(root, "dataset")
    seed_dir = export_seed_artifact(os.path.join(root, "seed"))
    t_drill0 = time.monotonic()
    proc, router = spawn_fleet(seed_dir, workdir, capture_dir, args)
    result: dict = {"router": router, "workdir": root}
    load = None
    try:
        load = LoadGen(router, args.concurrency)
        # phase 1: in-distribution traffic builds the captured dataset
        time.sleep(args.capture_secs)
        baseline_ok = load.ok
        if baseline_ok == 0:
            raise RuntimeError("no successful requests during capture phase")
        # phase 2: shift the input mean — the drift monitor must fire
        load.shift = args.shift
        t_shift = time.time()
        alert = None
        deadline = time.monotonic() + args.drift_timeout
        while time.monotonic() < deadline:
            alert = scan_drift_alerts(workdir, since_t=t_shift)
            if alert is not None:
                break
            time.sleep(0.25)
        if alert is None:
            raise RuntimeError(
                f"no drift_alert within {args.drift_timeout}s of the shift"
            )
        result["drift_alert"] = {
            "score": alert.get("score"),
            "threshold": alert.get("threshold"),
            "latency_s": round(alert["t"] - t_shift, 3),
        }
        # the flywheel closes the loop: ingest -> drift trigger -> retrain
        # (on the REAL captured dataset) -> auto-promote flips the fleet
        retrain_model_dir = os.path.join(root, "retrain")
        t_cycle0 = time.monotonic()
        fw = subprocess.run(
            [
                sys.executable, "-m", "tensorflowdistributedlearning_tpu",
                "flywheel",
                "--capture-dir", capture_dir,
                "--dataset-dir", dataset_dir,
                "--fleet-workdir", workdir,
                "--min-new-records", "0",
                "--poll-secs", "0.5",
                "--max-cycles", "1",
                "--max-wait-secs", str(args.drift_timeout),
                "--",
                "fit", "--preset", "elastic_smoke",
                "--model-dir", retrain_model_dir,
                "--data-dir", dataset_dir,
                "--steps", str(args.retrain_steps),
                "--export-serving",
                "--auto-promote",
                "--fleet-workdir", workdir,
                "--promote-shadow-secs", "2",
                "--promote-min-requests", "8",
                "--promote-max-disagree", "1.0",
                "--promote-max-abs-delta", "1e9",
                "--promote-max-mean-delta", "1e9",
                "--promote-min-iou", "0.0",
                "--promote-max-p99-ratio", "50.0",
            ],
            capture_output=True, text=True, timeout=900,
            env=dict(os.environ, PYTHONPATH=REPO + os.pathsep + os.environ.get(
                "PYTHONPATH", ""), JAX_PLATFORMS="cpu"),
        )
        cycle_wall_s = round(time.monotonic() - t_cycle0, 3)
        tail = [ln for ln in fw.stdout.splitlines() if ln.startswith("{")]
        fw_summary = json.loads(tail[-1]) if tail else {}
        if fw.returncode != 0:
            raise RuntimeError(
                f"flywheel rc={fw.returncode}: "
                + fw.stderr.strip().splitlines()[-1][:300]
                if fw.stderr.strip() else f"flywheel rc={fw.returncode}"
            )
        # let the post-flip fleet answer shifted traffic for a beat — the
        # retrained model's OWN baseline covers it, so no new alert storm
        time.sleep(2.0)
        status = json.loads(urllib.request.urlopen(
            router + "/admin/promotion", timeout=10
        ).read())
        result["artifact_mix"] = status.get("artifacts")
        load.stop()
        # -- harvest the ledgers ------------------------------------------
        manifest = read_dataset_manifest(dataset_dir)
        triggers = _ledger_events(workdir, "loop_trigger")
        promoted = _ledger_events(workdir, "loop_promoted")
        completes = _ledger_events(workdir, "promotion_complete")
        windows = _ledger_events(workdir, "capture_window")
        per_replica: dict = {}
        for w in windows:
            per_replica[w.get("replica")] = w
        captured = sum(
            w.get("total_captured", 0) for w in per_replica.values()
        )
        drift_triggers = [
            t for t in triggers if t.get("reason") == "drift"
        ]
        trig_latency = None
        if drift_triggers and drift_triggers[-1].get("drift_alert_t"):
            trig_latency = round(
                max(0.0, drift_triggers[-1]["t"]
                    - drift_triggers[-1]["drift_alert_t"]), 3,
            )
        result.update({
            "replicas": args.replicas,
            "flywheel": {
                "rc": fw.returncode,
                "cycles": fw_summary.get("cycles"),
                "promoted": fw_summary.get("promoted"),
                "rejected": fw_summary.get("rejected"),
            },
            "cycle_wall_s": cycle_wall_s,
            "samples_captured": int(captured),
            "samples_ingested": int(manifest.get("records_total", 0)),
            "dataset_version": int(manifest.get("version", 0)),
            "drift_trigger_latency_s": trig_latency,
            "promoted_fingerprint": (
                completes[-1].get("fingerprint") if completes else None
            ),
            "loop_promoted_events": len(promoted),
            "client_ok": load.ok,
            "client_errors": load.errors,
            "drill_wall_s": round(time.monotonic() - t_drill0, 3),
        })
    finally:
        if load is not None:
            load.stop()
        stop_fleet(proc)
    return result


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--replicas", type=int, default=2)
    parser.add_argument("--concurrency", type=int, default=4)
    parser.add_argument("--window-secs", type=float, default=1.0)
    parser.add_argument("--capture-secs", type=float, default=6.0,
                        help="phase-1 (in-distribution) load duration — "
                        "what the retrain dataset is captured from")
    parser.add_argument("--shift", type=float, default=1.0,
                        help="input mean shift for the drift phase")
    parser.add_argument("--drift-threshold", type=float, default=0.35)
    parser.add_argument("--drift-timeout", type=float, default=60.0)
    parser.add_argument("--retrain-steps", type=int, default=4)
    parser.add_argument("--json-out", default=None)
    args = parser.parse_args()

    result = run_drill(args)
    print(json.dumps(result, indent=1))
    if args.json_out:
        with open(args.json_out, "w", encoding="utf-8") as f:
            json.dump(result, f, indent=1)
    ok = (
        result.get("client_errors") == 0
        and result.get("flywheel", {}).get("promoted", 0) >= 1
        and result.get("samples_ingested", 0) > 0
        and result.get("promoted_fingerprint")
    )
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
