"""Run ONLY the attention kernel microbench (bench_kernels.bench_attention) on
the current backend, printing one JSON line. Split from bench_kernels.py's main
so a Pallas remote-compile hang here cannot cost the depthwise numbers, and so
a supervisor can bound just this measurement.
"""

from __future__ import annotations

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> int:
    import jax

    try:
        jax.config.update(
            "jax_compilation_cache_dir", os.path.join(REPO, ".jax_cache_tpu")
        )
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception:
        pass

    from bench_kernels import bench_attention

    kwargs = (
        {}
        if jax.default_backend() == "tpu"
        else dict(batch=2, seq_lens=(64,), iters=3, warmup=1)
    )

    # the forward snapshot prints the moment phase 1 completes: the train
    # columns are the big fresh-HLO backward compiles, and a tunnel window
    # that dies during them must still leave forward decision data on stdout
    def emit_forward(snapshot):
        snapshot["platform"] = jax.default_backend()
        print(json.dumps({"attention_fwd": snapshot}), flush=True)

    out = bench_attention(on_forward_done=emit_forward, **kwargs)
    out["platform"] = jax.default_backend()
    print(json.dumps({"attention": out}), flush=True)
    return 0


if __name__ == "__main__":
    main()
