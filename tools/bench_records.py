"""Microbenchmark: native C++ record streaming + image decode vs the pure
Python fallback, on host CPU (no TPU needed).

This is the quantitative record for the framework's native IO subsystem
(native/records.cc background-producer TFRecord reader + native/io.cc
multithreaded GIL-free image decode) against the same API driven through the
Python/PIL fallback — the tf.data-class capability the reference inherited
from TensorFlow's C++ runtime (SURVEY §2.2).

Writes synthetic PNG classification shards, then times two stages:
  records:  raw framed-record streaming (RecordStream native vs Python iter)
  end2end:  shards -> decoded [B, H, W, C] float batches
            (ClassificationRecords.batches, native io.cc vs forced PIL)

Prints one JSON line. Usage: python tools/bench_records.py [--n 2000] [--hw 64]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--n", type=int, default=2000, help="images")
    parser.add_argument("--hw", type=int, default=64, help="image side")
    parser.add_argument("--batch", type=int, default=64)
    parser.add_argument("--shards", type=int, default=4)
    args = parser.parse_args()

    import numpy as np

    from tensorflowdistributedlearning_tpu.data import records as rec
    from tensorflowdistributedlearning_tpu.native import loader

    rng = np.random.default_rng(0)
    imgs = rng.integers(0, 255, (args.n, args.hw, args.hw, 3), dtype=np.uint8)
    labels = rng.integers(0, 10, args.n).astype(np.int64)

    # two independent native libs: records.cc (streaming) and io.cc (decode)
    records_lib = rec._records_lib()
    out: dict = {
        "n_images": args.n,
        "image": f"{args.hw}x{args.hw}x3 png",
        "native_records_available": records_lib is not None,
        "native_decode_available": loader.native_available(),
    }

    with tempfile.TemporaryDirectory() as tmp:
        paths = rec.write_classification_shards(
            tmp, list(imgs), list(labels), shards=args.shards, prefix="train"
        )

        def time_stream(native: bool) -> float:
            count = 0
            t0 = time.perf_counter()
            for p in paths:
                stream = rec.RecordStream([p])
                it = (
                    stream._iter_native(records_lib)
                    if native
                    else stream._iter_python()
                )
                for _ in it:
                    count += 1
            dt = time.perf_counter() - t0
            assert count == args.n, (count, args.n)
            return dt

        time_stream(native=False)  # untimed warm pass (page cache, imports)
        python_s = time_stream(native=False)
        if records_lib is not None:
            time_stream(native=True)  # warm the native lib load
            native_s = time_stream(native=True)
            out["records_stream"] = {
                "native_recs_per_sec": round(args.n / native_s, 1),
                "python_recs_per_sec": round(args.n / python_s, 1),
                "speedup": round(python_s / native_s, 2),
            }
        else:
            out["records_stream"] = {
                "python_recs_per_sec": round(args.n / python_s, 1),
                "native": "unavailable (records.cc build/load failed)",
            }

        def time_end2end(force_pil: bool) -> float:
            src = rec.ClassificationRecords(
                tmp, split="train", image_shape=(args.hw, args.hw), channels=3
            )
            saved = loader._load
            if force_pil:
                loader._load = lambda: None  # type: ignore[assignment]
            try:
                seen = 0
                t0 = time.perf_counter()
                for batch in src.batches(args.batch, seed=0, repeat=False):
                    seen += int(batch["valid"].sum())
                dt = time.perf_counter() - t0
                assert seen == args.n, (seen, args.n)
                return dt
            finally:
                loader._load = saved  # type: ignore[assignment]

        # warm the OS page cache + lazy imports with an UNTIMED pass before
        # timing either side, so neither path pays cold-file costs
        time_end2end(force_pil=True)
        pil_e = time_end2end(force_pil=True)
        if loader.native_available():
            time_end2end(force_pil=False)  # warm the native lib load
            native_e = time_end2end(force_pil=False)
            out["end2end_decode"] = {
                "native_images_per_sec": round(args.n / native_e, 1),
                "pil_images_per_sec": round(args.n / pil_e, 1),
                "speedup": round(pil_e / native_e, 2),
            }
        else:
            out["end2end_decode"] = {
                "pil_images_per_sec": round(args.n / pil_e, 1),
                "native": "unavailable (io.cc build/load failed)",
            }

    print(json.dumps(out), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
