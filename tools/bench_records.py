"""Microbenchmark: native C++ record streaming + image decode vs the pure
Python fallback, on host CPU (no TPU needed).

This is the quantitative record for the framework's native IO subsystem
(native/records.cc background-producer TFRecord reader + native/io.cc
multithreaded GIL-free image decode) against the same API driven through the
Python/PIL fallback — the tf.data-class capability the reference inherited
from TensorFlow's C++ runtime (SURVEY §2.2).

Writes synthetic PNG classification shards, then times four stages:
  records:      raw framed-record streaming (RecordStream native vs Python)
  end2end:      shards -> decoded [B, H, W, C] float batches
                (ClassificationRecords.batches, native io.cc vs forced PIL)
  multi_worker: the streaming data service (data/service.py) at a worker
                sweep — records/sec scaling plus the resume bit-parity gate
                (batch i is a pure function of (seed, i))
  trainer_ab:   a real tiny fit() on the shards, single-thread stream vs the
                service — mean per-window data_wait fraction from the run
                ledger (the ~0 acceptance number; skip with --no-trainer-ab)

Prints one JSON line. Usage: python tools/bench_records.py [--n 2000] [--hw 64]
The committed RECORDS_BENCH.json is replayed as a CI gate by
tools/regression_sentinel.py (records bench): resume parity and the
data_wait ceiling are hard, throughput scaling has a dimensionless floor.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--n", type=int, default=2000, help="images")
    parser.add_argument("--hw", type=int, default=64, help="image side")
    parser.add_argument("--batch", type=int, default=64)
    parser.add_argument("--shards", type=int, default=4)
    parser.add_argument("--workers", default="1,2,4",
                        help="comma-separated worker counts for the "
                        "multi_worker service sweep")
    parser.add_argument("--ab-steps", type=int, default=50,
                        help="train steps per side of the trainer A/B")
    parser.add_argument("--no-trainer-ab", action="store_true",
                        help="skip the (heavier) real-fit data_wait A/B")
    args = parser.parse_args()

    import numpy as np

    from tensorflowdistributedlearning_tpu.data import records as rec
    from tensorflowdistributedlearning_tpu.native import loader

    rng = np.random.default_rng(0)
    imgs = rng.integers(0, 255, (args.n, args.hw, args.hw, 3), dtype=np.uint8)
    labels = rng.integers(0, 10, args.n).astype(np.int64)

    # two independent native libs: records.cc (streaming) and io.cc (decode)
    records_lib = rec._records_lib()
    out: dict = {
        "n_images": args.n,
        "image": f"{args.hw}x{args.hw}x3 png",
        "cpu_count": os.cpu_count(),
        "native_records_available": records_lib is not None,
        "native_decode_available": loader.native_available(),
    }

    with tempfile.TemporaryDirectory() as tmp:
        paths = rec.write_classification_shards(
            tmp, list(imgs), list(labels), shards=args.shards, prefix="train"
        )

        def time_stream(native: bool) -> float:
            count = 0
            t0 = time.perf_counter()
            for p in paths:
                stream = rec.RecordStream([p])
                it = (
                    stream._iter_native(records_lib)
                    if native
                    else stream._iter_python()
                )
                for _ in it:
                    count += 1
            dt = time.perf_counter() - t0
            assert count == args.n, (count, args.n)
            return dt

        time_stream(native=False)  # untimed warm pass (page cache, imports)
        python_s = time_stream(native=False)
        if records_lib is not None:
            time_stream(native=True)  # warm the native lib load
            native_s = time_stream(native=True)
            out["records_stream"] = {
                "native_recs_per_sec": round(args.n / native_s, 1),
                "python_recs_per_sec": round(args.n / python_s, 1),
                "speedup": round(python_s / native_s, 2),
            }
        else:
            out["records_stream"] = {
                "python_recs_per_sec": round(args.n / python_s, 1),
                "native": "unavailable (records.cc build/load failed)",
            }

        def time_end2end(force_pil: bool) -> float:
            src = rec.ClassificationRecords(
                tmp, split="train", image_shape=(args.hw, args.hw), channels=3
            )
            saved = loader._load
            if force_pil:
                loader._load = lambda: None  # type: ignore[assignment]
            try:
                seen = 0
                t0 = time.perf_counter()
                for batch in src.batches(args.batch, seed=0, repeat=False):
                    seen += int(batch["valid"].sum())
                dt = time.perf_counter() - t0
                assert seen == args.n, (seen, args.n)
                return dt
            finally:
                loader._load = saved  # type: ignore[assignment]

        # warm the OS page cache + lazy imports with an UNTIMED pass before
        # timing either side, so neither path pays cold-file costs
        time_end2end(force_pil=True)
        pil_e = time_end2end(force_pil=True)
        if loader.native_available():
            time_end2end(force_pil=False)  # warm the native lib load
            native_e = time_end2end(force_pil=False)
            out["end2end_decode"] = {
                "native_images_per_sec": round(args.n / native_e, 1),
                "pil_images_per_sec": round(args.n / pil_e, 1),
                "speedup": round(pil_e / native_e, 2),
            }
        else:
            out["end2end_decode"] = {
                "pil_images_per_sec": round(args.n / pil_e, 1),
                "native": "unavailable (io.cc build/load failed)",
            }

        # -- multi-worker data service sweep + resume bit-parity -----------
        from tensorflowdistributedlearning_tpu.data import service as svc

        def service_stream(workers: int, start: int = 0, steps: int = None):
            source = svc.ClassificationRecordSource(
                paths,
                image_shape=(args.hw, args.hw),
                channels=3,
                process_index=0,
                process_count=1,
            )
            return svc.StreamingDataService(
                source,
                batch_size=args.batch,
                seed=0,
                workers=workers,
                start_batch=start,
            ).batches(steps=steps)

        sweep_steps = max(1, args.n // args.batch)
        # 1 worker is always swept: speedup_best_vs_1 (and the sentinel gate
        # replaying it) is defined against the single-worker rate
        worker_counts = sorted(
            {1, *(int(w) for w in args.workers.split(",") if w.strip())}
        )
        per_worker: dict = {}
        for w in worker_counts:
            for item in service_stream(w, steps=2):  # warm (plans, readers)
                pass
            t0 = time.perf_counter()
            seen = 0
            for batch in service_stream(w, steps=sweep_steps):
                seen += len(batch["labels"])
            dt = time.perf_counter() - t0
            per_worker[str(w)] = {"images_per_sec": round(seen / dt, 1)}
        base_ips = per_worker[str(worker_counts[0])]["images_per_sec"]
        best_ips = max(v["images_per_sec"] for v in per_worker.values())
        # resume parity: batches k.. from a resumed service must be byte-
        # identical to the uninterrupted stream — the index-keyed contract
        full = list(service_stream(2, steps=8))
        resumed = list(service_stream(3, start=3, steps=5))
        parity = all(
            np.array_equal(a["images"], b["images"])
            and np.array_equal(a["labels"], b["labels"])
            for a, b in zip(full[3:], resumed)
        )
        out["multi_worker"] = {
            "batch_size": args.batch,
            "workers": per_worker,
            "speedup_best_vs_1": round(best_ips / base_ips, 2),
            "resume_bit_identical": bool(parity),
        }

        # -- trainer A/B: data_wait with vs without the service ------------
        if not args.no_trainer_ab:
            out["multi_worker"]["trainer_ab"] = _trainer_ab(
                tmp, args.hw, args.batch, args.ab_steps
            )

    print(json.dumps(out), flush=True)
    return 0


def _trainer_ab(data_dir: str, hw: int, batch: int, steps: int) -> dict:
    """Mean per-window data_wait fraction of a real (tiny-model) fit over the
    shards: the legacy single-thread stream (data_service_workers=0) vs the
    streaming data service — the acceptance number is the service side ~0
    (<= 5% of host time) while the baseline shows the input bound."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", "cpu")

    from tensorflowdistributedlearning_tpu.config import (
        ModelConfig,
        TrainConfig,
    )
    from tensorflowdistributedlearning_tpu.obs.ledger import read_ledger
    from tensorflowdistributedlearning_tpu.train.fit import ClassifierTrainer

    mcfg = ModelConfig(
        num_classes=10,
        input_shape=(hw, hw),
        input_channels=3,
        n_blocks=(1, 1, 1),
        base_depth=16,
        width_multiplier=0.125,
        output_stride=None,
    )

    def run(workers: int, model_dir: str, run_steps: int) -> dict:
        tcfg = TrainConfig(
            seed=0,
            checkpoint_every_steps=10 * run_steps,  # no mid-run saves
            train_log_every_steps=5,
            augmentation="none",
            data_service_workers=workers,
        )
        trainer = ClassifierTrainer(model_dir, data_dir, mcfg, tcfg)
        trainer.fit(
            batch_size=batch, steps=run_steps, eval_every_steps=10 * run_steps
        )
        windows = [
            e
            for e in read_ledger(model_dir)
            if e.get("event") == "step_window" and not e.get("dirty")
        ]
        fracs = [e["data_wait_frac"] for e in windows]
        ips = [
            e["images_per_sec"] for e in windows if "images_per_sec" in e
        ]
        return {
            "data_wait_frac": sum(fracs) / len(fracs) if fracs else 0.0,
            "images_per_sec": sum(ips) / len(ips) if ips else None,
        }

    # warm the jit cache so neither side pays the train-step compile
    run(0, os.path.join(data_dir, "_ab_warm"), 2)
    base = run(0, os.path.join(data_dir, "_ab_base"), steps)
    serviced = run(4, os.path.join(data_dir, "_ab_service"), steps)
    out = {
        "batch_size": batch,
        "steps": steps,
        "baseline_data_wait_frac": round(base["data_wait_frac"], 4),
        "service_data_wait_frac": round(serviced["data_wait_frac"], 4),
        "service_workers": 4,
    }
    if base["images_per_sec"] and serviced["images_per_sec"]:
        out["baseline_images_per_sec"] = round(base["images_per_sec"], 1)
        out["service_images_per_sec"] = round(serviced["images_per_sec"], 1)
        # the not-slower gate: moving assembly onto workers must never cost
        # steady-state throughput (>= 1.0 means the service side won or tied)
        out["throughput_ratio_service_over_baseline"] = round(
            serviced["images_per_sec"] / base["images_per_sec"], 3
        )
    return out


if __name__ == "__main__":
    sys.exit(main())
