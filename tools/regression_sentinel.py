"""Regression sentinel: replay committed BENCH_*.json baselines, fail on drift.

The repo commits its performance history (BENCH_ASYNC.json, BENCH_SERVE.json)
but until now nothing ENFORCED it — a PR could halve serving throughput or
stall the async host loop and every test would stay green. This tool is the
CI gate: run the same benches fresh, compare the numbers that matter against
the committed baselines with noise-aware slack, exit nonzero on regression.

What is compared, and with how much slack, is deliberately asymmetric:

- **Dimensionless ratios transfer across machines** and get tight bounds:
  ``step_time_ratio_async_over_sync`` (async must stay not-slower than sync),
  ``speedup_batched_vs_per_request`` (coalescing must keep paying for
  itself), ``final_params_bit_identical`` and ``post_warmup_recompiles`` are
  HARD (no slack: bitwise parity and zero recompiles are correctness, not
  performance).
- **Absolute wall-clock numbers do not transfer** (a shared CI runner is not
  the box that produced the baseline) and get loose multiplicative slack
  (default 1.75x): they only catch the catastrophic class — a 2x step-time
  or half-throughput regression — which is exactly the class that must never
  land silently.

Usage (CI runs the first form ahead of tier-1)::

    python tools/regression_sentinel.py --check
    python tools/regression_sentinel.py --check --fresh-async A.json \
        --fresh-serve S.json          # compare pre-computed results only

``--fresh-*`` skips running the benches (tests inject doctored results
through it; operators can re-check an old run). Without them the sentinel
runs ``bench.py --async-loop`` and ``tools/bench_serve.py`` on the CPU shape.

The ``records`` bench likewise REPLAYS the committed RECORDS_BENCH.json
(tools/bench_records.py): resume bit-parity and the serviced trainer's
data_wait ceiling are hard, multi-worker scaling and the native-vs-PIL
end-to-end decode ratio are dimensionless floors, and a ``--fresh-records``
record additionally gates per-worker records/sec against machine-drift
slack.

The ``kernels`` bench REPLAYS the committed BENCH_SERVE.json ``kernels`` +
``quant`` sections (bench_serve --quant records both): per-kernel speedup vs
the XLA twin (floor 1.0 on TPU where the Pallas int8/fused kernels must win;
a 0.5 dispatch tripwire off-TPU where both sides run the same dequantize-f32
fallback), int8-compute rps/chip >= int8-store at no-worse p99, and — hard —
zero post-warmup recompiles plus a passing quantize-check for the
int8-compute artifact.

The ``fleet`` bench REPLAYS the committed BENCH_SERVE.json ``fleet`` section
(bench_serve --fleet is too heavy for every CI run): the committed 2-replica
scaling must clear the 1.6x floor, every replica must report zero post-warmup
recompiles, the saturation probe must have shed with Retry-After and zero
non-drain 5xx, and the kill soak must have converged with zero lost accepted
requests — all dimensionless/hard, so no machine slack applies. A
``--fresh-serve`` record carrying its own ``fleet`` section is gated instead.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
from typing import Dict, List, Optional

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# absolute wall-clock numbers (ms, rps): machine drift is real, only the
# catastrophic class must fail — 1.75x keeps an injected 2x regression
# failing while CI-runner noise passes
DEFAULT_WALL_SLACK = 1.75
# async/sync step-time ratio: dimensionless, transfers across machines; the
# local spec is <= 1.05 (bench.py --check), CI allows shared-runner noise
DEFAULT_ASYNC_RATIO_LIMIT = 1.3
# batched/per-request speedup may shrink to this fraction of the committed
# value before it counts as a regression (dimensionless but scheduling-noisy
# on 2-core runners)
DEFAULT_SPEEDUP_FLOOR_FRAC = 0.5
# p99 tail latency is the noisiest number in the set: on an oversubscribed
# CI runner the tail legitimately swings several-fold while throughput holds
# (measured 5x on the 1-core driver box with every other gate green), so
# only the order-of-magnitude class fails — a genuinely serialized request
# path also collapses requests_per_sec and the speedup, which are tighter
DEFAULT_P99_SLACK = 6.0
# peak HBM is near-deterministic for a fixed config (the allocator's
# lifetime peak, not a timing), so the band is far tighter than wall-clock:
# 1.25x catches a working-set regression (an extra params copy, an
# un-donated buffer) while tolerating allocator/version jitter. Only the
# GROWTH direction gates — a smaller peak is an improvement.
DEFAULT_HBM_SLACK = 1.25


def _finding(
    bench: str,
    metric: str,
    baseline,
    fresh,
    limit: str,
    ok: bool,
) -> Dict:
    return {
        "bench": bench,
        "metric": metric,
        "baseline": baseline,
        "fresh": fresh,
        "limit": limit,
        "ok": bool(ok),
    }


def check_async(
    baseline: Dict,
    fresh: Dict,
    *,
    wall_slack: float = DEFAULT_WALL_SLACK,
    ratio_limit: float = DEFAULT_ASYNC_RATIO_LIMIT,
    hbm_slack: float = DEFAULT_HBM_SLACK,
) -> List[Dict]:
    """BENCH_ASYNC.json comparisons (bench.py --async-loop output shape)."""
    out: List[Dict] = []
    base_ms = (baseline.get("async") or {}).get("step_time_ms")
    fresh_ms = (fresh.get("async") or {}).get("step_time_ms")
    if base_ms and fresh_ms:
        out.append(_finding(
            "async", "async.step_time_ms", base_ms, fresh_ms,
            f"<= {wall_slack}x baseline", fresh_ms <= wall_slack * base_ms,
        ))
    base_hbm = baseline.get("peak_hbm_bytes")
    fresh_hbm = fresh.get("peak_hbm_bytes")
    if base_hbm and fresh_hbm:
        # memory is capacity, not speed: a run that silently grows its
        # working set OOMs the flagship shape long before CI notices a
        # timing change (only gated where the backend reports the peak)
        out.append(_finding(
            "async", "peak_hbm_bytes", base_hbm, fresh_hbm,
            f"<= {hbm_slack}x baseline", fresh_hbm <= hbm_slack * base_hbm,
        ))
    ratio = fresh.get("step_time_ratio_async_over_sync")
    if ratio is not None:
        out.append(_finding(
            "async", "step_time_ratio_async_over_sync",
            baseline.get("step_time_ratio_async_over_sync"), ratio,
            f"<= {ratio_limit}", ratio <= ratio_limit,
        ))
    parity = fresh.get("final_params_bit_identical")
    if parity is not None:
        out.append(_finding(
            "async", "final_params_bit_identical", True, parity,
            "== true (hard)", bool(parity),
        ))
    return out


def check_serve(
    baseline: Dict,
    fresh: Dict,
    *,
    wall_slack: float = DEFAULT_WALL_SLACK,
    speedup_floor_frac: float = DEFAULT_SPEEDUP_FLOOR_FRAC,
    p99_slack: float = DEFAULT_P99_SLACK,
) -> List[Dict]:
    """BENCH_SERVE.json comparisons (tools/bench_serve.py output shape)."""
    out: List[Dict] = []
    base_b = baseline.get("batched") or {}
    fresh_b = fresh.get("batched") or {}
    if base_b.get("requests_per_sec") and fresh_b.get("requests_per_sec"):
        floor = base_b["requests_per_sec"] / wall_slack
        out.append(_finding(
            "serve", "batched.requests_per_sec",
            base_b["requests_per_sec"], fresh_b["requests_per_sec"],
            f">= baseline / {wall_slack}",
            fresh_b["requests_per_sec"] >= floor,
        ))
    base_p99 = (base_b.get("latency_ms") or {}).get("p99")
    fresh_p99 = (fresh_b.get("latency_ms") or {}).get("p99")
    if base_p99 and fresh_p99:
        out.append(_finding(
            "serve", "batched.latency_ms.p99", base_p99, fresh_p99,
            f"<= {p99_slack}x baseline", fresh_p99 <= p99_slack * base_p99,
        ))
    # serving efficiency (the cost-per-qps lens): per-chip request rate —
    # on a fixed-shape runner this tracks requests_per_sec, but the
    # committed number stays comparable when the device count changes
    base_rpc = base_b.get("rps_per_chip")
    fresh_rpc = fresh_b.get("rps_per_chip")
    if base_rpc and fresh_rpc:
        out.append(_finding(
            "serve", "batched.rps_per_chip", base_rpc, fresh_rpc,
            f">= baseline / {wall_slack}",
            fresh_rpc >= base_rpc / wall_slack,
        ))
    base_speedup = baseline.get("speedup_batched_vs_per_request")
    fresh_speedup = fresh.get("speedup_batched_vs_per_request")
    if base_speedup and fresh_speedup:
        floor = max(1.0, speedup_floor_frac * base_speedup)
        out.append(_finding(
            "serve", "speedup_batched_vs_per_request",
            base_speedup, fresh_speedup,
            f">= max(1.0, {speedup_floor_frac} x baseline)",
            fresh_speedup >= floor,
        ))
    recompiles = fresh.get("post_warmup_recompiles")
    if recompiles is not None:
        out.append(_finding(
            "serve", "post_warmup_recompiles", 0, recompiles,
            "== 0 (hard)", recompiles == 0,
        ))
    return out


# the quant-kernel acceptance bars (BENCH_SERVE.json ``kernels`` + ``quant``
# sections): on TPU the Pallas int8/fused kernels must BEAT their XLA twins
# (that win is why they exist); off-TPU both comparison sides run the same
# dequantize-f32 fallback, so the ratio is a dispatch-overhead tripwire —
# 0.5 fails only the catastrophic class (a wrapper that doubled the cost)
# while tolerating tiny-shape scheduling noise on shared runners. The
# int8-compute-vs-int8-store serving ratio is the ISSUE-20 acceptance bar:
# >= 1.0 on TPU (the MXU win), >= 0.9 off-TPU (the fallback must stay near
# parity with dequantize-in-graph or the spec costs CPU users real rps).
DEFAULT_KERNEL_TPU_SPEEDUP_FLOOR = 1.0
DEFAULT_KERNEL_CPU_SPEEDUP_FLOOR = 0.5
DEFAULT_INT8_COMPUTE_TPU_RATIO_FLOOR = 1.0
DEFAULT_INT8_COMPUTE_CPU_RATIO_FLOOR = 0.9


def check_kernels(
    baseline: Dict,
    fresh: Optional[Dict] = None,
    *,
    tpu_speedup_floor: float = DEFAULT_KERNEL_TPU_SPEEDUP_FLOOR,
    cpu_speedup_floor: float = DEFAULT_KERNEL_CPU_SPEEDUP_FLOOR,
) -> List[Dict]:
    """Replay the BENCH_SERVE.json quant-kernel gates (bench_serve --quant
    records both sections; too heavy to re-run every CI pass):

    - per-kernel speedup vs the XLA twin (``kernels`` section): floor 1.0
      on TPU, the 0.5 dispatch tripwire elsewhere — dimensionless, no
      machine slack;
    - int8-compute rps/chip >= int8-store x platform floor at no-worse p99
      (``quant.precisions``): switching the arithmetic must never cost
      throughput against the storage-only artifact it replaces;
    - zero post-warmup recompiles serving the int8-compute artifact and a
      passing quantize-check verdict — both HARD (correctness).

    A ``--fresh-serve`` record carrying its own sections is gated instead.
    """
    record = baseline
    if fresh and (fresh.get("kernels") or fresh.get("quant")):
        record = fresh
    kernels = record.get("kernels")
    quant = record.get("quant") or {}
    out: List[Dict] = []
    if not kernels and not quant:
        raise ValueError(
            "no kernels/quant sections in the serve record — run "
            "tools/bench_serve.py --quant and commit the refreshed baseline"
        )
    if kernels:
        on_tpu = kernels.get("platform") == "tpu"
        floor = tpu_speedup_floor if on_tpu else cpu_speedup_floor
        label = "tpu kernel floor" if on_tpu else "cpu dispatch tripwire"
        for name in ("matmul", "conv", "sigmoid_mask"):
            entry = kernels.get(name) or {}
            speedup = entry.get("speedup")
            if speedup is None:
                continue
            out.append(_finding(
                "kernels", f"{name}.speedup_vs_xla", floor, speedup,
                f">= {floor} ({label})", speedup >= floor,
            ))
    precisions = quant.get("precisions") or {}
    comp = precisions.get("int8-compute") or {}
    store = precisions.get("int8") or {}
    comp_rpc = comp.get("rps_per_chip") or comp.get("requests_per_sec")
    store_rpc = store.get("rps_per_chip") or store.get("requests_per_sec")
    if comp_rpc and store_rpc:
        on_tpu = quant.get("backend") == "tpu"
        floor = (
            DEFAULT_INT8_COMPUTE_TPU_RATIO_FLOOR
            if on_tpu
            else DEFAULT_INT8_COMPUTE_CPU_RATIO_FLOOR
        )
        ratio = round(comp_rpc / store_rpc, 3)
        out.append(_finding(
            "kernels", "int8_compute.rps_per_chip_vs_int8_store",
            floor, ratio, f">= {floor}", ratio >= floor,
        ))
        p99_ratio = comp.get("p99_ratio_vs_int8_store")
        if p99_ratio is not None:
            out.append(_finding(
                "kernels", "int8_compute.p99_ratio_vs_int8_store",
                1.25, p99_ratio, "<= 1.25", p99_ratio <= 1.25,
            ))
    if comp:
        recompiles = comp.get("post_warmup_recompiles")
        out.append(_finding(
            "kernels", "int8_compute.post_warmup_recompiles",
            0, recompiles, "== 0 (hard)", recompiles == 0,
        ))
        verdict = (quant.get("quant_check") or {}).get("int8-compute")
        if verdict is not None:
            out.append(_finding(
                "kernels", "int8_compute.quant_check_passed",
                True, verdict.get("passed"), "== True (hard)",
                bool(verdict.get("passed")),
            ))
    return out


# the fleet acceptance floor: 2 replicas must buy >= 1.6x single-replica
# throughput (scaling efficiency 0.8) — below that the tier's premise
# (capacity scales with replicas) is broken, whatever the machine
DEFAULT_FLEET_SCALING_FLOOR = 1.6

# data-service floors (RECORDS_BENCH.json multi_worker section): the best
# worker count must beat one worker by this much (dimensionless — if adding
# workers stops paying, the service's premise broke), and the serviced
# trainer's mean per-window data_wait fraction must stay ~0 (the ISSUE-12
# acceptance ceiling). Both replay the COMMITTED record by default, like the
# fleet gates — a PR touching the input path must re-run tools/bench_records
# and commit numbers that still clear them.
DEFAULT_RECORDS_SCALING_FLOOR = 1.2
DEFAULT_DATA_WAIT_CEILING = 0.05
# serviced trainer throughput vs the single-thread baseline: the service
# must not cost steady-state throughput; 0.9 absorbs scheduling noise on a
# CPU backend where worker threads and "device" compute share the cores
DEFAULT_SERVICE_THROUGHPUT_FLOOR = 0.9


def check_records(
    baseline: Dict,
    fresh: Optional[Dict] = None,
    *,
    wall_slack: float = DEFAULT_WALL_SLACK,
    scaling_floor: float = DEFAULT_RECORDS_SCALING_FLOOR,
    data_wait_ceiling: float = DEFAULT_DATA_WAIT_CEILING,
) -> List[Dict]:
    """RECORDS_BENCH.json gates (tools/bench_records.py output shape).

    Default mode REPLAYS the committed record (``fresh`` falls back to the
    baseline): resume bit-parity and the serviced data_wait ceiling are HARD
    (correctness/acceptance, no machine slack); worker scaling and the
    end-to-end native-vs-PIL decode ratio are dimensionless floors. A
    ``--fresh-records`` run is gated instead, with the wall-clock throughput
    additionally held to the machine-drift slack band; the decode ratio is
    only gated when the fresh host has >= 4 cores (below that the native
    decoder's one-thread floor legitimately ties/loses to PIL — the honest
    CPU floor RECORDS_BENCH documents)."""
    record = fresh if fresh is not None else baseline
    out: List[Dict] = []
    e2e = (record.get("end2end_decode") or {}).get("speedup")
    if e2e is not None and (record.get("cpu_count") or 4) >= 4:
        out.append(_finding(
            "records", "end2end_decode.speedup", 1.0, e2e,
            ">= 1.0 (native decode must not lose to PIL)", e2e >= 1.0,
        ))
    mw = record.get("multi_worker")
    if not mw:
        return out
    parity = mw.get("resume_bit_identical")
    if parity is not None:
        out.append(_finding(
            "records", "multi_worker.resume_bit_identical", True, parity,
            "== true (hard)", bool(parity),
        ))
    speedup = mw.get("speedup_best_vs_1")
    if speedup is not None:
        out.append(_finding(
            "records", "multi_worker.speedup_best_vs_1",
            scaling_floor, speedup,
            f">= {scaling_floor} (worker scaling floor)",
            speedup >= scaling_floor,
        ))
    ab = mw.get("trainer_ab") or {}
    frac = ab.get("service_data_wait_frac")
    if frac is not None:
        out.append(_finding(
            "records", "trainer_ab.service_data_wait_frac",
            data_wait_ceiling, frac,
            f"<= {data_wait_ceiling} (data_wait ~0, hard)",
            frac <= data_wait_ceiling,
        ))
    ratio = ab.get("throughput_ratio_service_over_baseline")
    if ratio is not None:
        floor = DEFAULT_SERVICE_THROUGHPUT_FLOOR
        out.append(_finding(
            "records", "trainer_ab.throughput_ratio_service_over_baseline",
            floor, ratio,
            f">= {floor} (service must not cost steady-state throughput)",
            ratio >= floor,
        ))
    if fresh is not None:
        base_mw = (baseline.get("multi_worker") or {}).get("workers") or {}
        fresh_mw = mw.get("workers") or {}
        for w, entry in base_mw.items():
            b_ips = entry.get("images_per_sec")
            f_ips = (fresh_mw.get(w) or {}).get("images_per_sec")
            if b_ips and f_ips:
                out.append(_finding(
                    "records", f"multi_worker.workers.{w}.images_per_sec",
                    b_ips, f_ips, f">= baseline / {wall_slack}",
                    f_ips >= b_ips / wall_slack,
                ))
    return out


def check_fleet(
    baseline: Dict,
    fresh: Optional[Dict] = None,
    *,
    scaling_floor: float = DEFAULT_FLEET_SCALING_FLOOR,
) -> List[Dict]:
    """Replay the BENCH_SERVE.json ``fleet`` section against its hard gates.

    The fleet soak is too heavy to re-run on every CI invocation, so the
    default mode REPLAYS the committed section (``fresh`` falls back to the
    baseline): a PR editing the serving tier must re-run ``bench_serve
    --fleet`` and commit numbers that still clear the gates — scaling floor,
    zero post-warmup recompiles on every replica, shed-with-Retry-After and
    zero non-drain 5xx past saturation, kill-soak convergence with zero lost
    accepted requests. A ``--fresh-serve`` record carrying its own ``fleet``
    section is gated instead (dimensionless, so no machine slack needed)."""
    record = fresh if fresh and fresh.get("fleet") else baseline
    fleet = record.get("fleet")
    if not fleet:
        return []
    out: List[Dict] = []
    scaling = (fleet.get("scaling") or {}).get("2") or {}
    speedup = scaling.get("speedup_vs_1")
    if speedup is not None:
        out.append(_finding(
            "fleet", "scaling.2.speedup_vs_1", scaling_floor, speedup,
            f">= {scaling_floor} (hard)", speedup >= scaling_floor,
        ))
    recompiles = sum(
        stats.get("recompiles_post_warmup", 0) or 0
        for entry in fleet.get("replica_counts", {}).values()
        for stats in (entry.get("replicas") or {}).values()
    )
    out.append(_finding(
        "fleet", "replica_post_warmup_recompiles", 0, recompiles,
        "== 0 (hard)", recompiles == 0,
    ))
    sat = fleet.get("saturation")
    if sat is not None:
        out.append(_finding(
            "fleet", "saturation.shed_with_retry_after", ">= 1",
            sat.get("shed_with_retry_after", 0), ">= 1 (structured shed)",
            sat.get("shed_with_retry_after", 0) >= 1,
        ))
        out.append(_finding(
            "fleet", "saturation.errors_5xx", 0, sat.get("errors_5xx", 0),
            "== 0 (hard)", not sat.get("errors_5xx"),
        ))
    kill = fleet.get("kill_soak")
    if kill is not None:
        out.append(_finding(
            "fleet", "kill_soak.client_errors", 0,
            kill.get("client_errors", 0), "== 0 (hard)",
            not kill.get("client_errors"),
        ))
        out.append(_finding(
            "fleet", "kill_soak.converged", True, kill.get("converged"),
            "== true (hard)", bool(kill.get("converged")),
        ))
    return out


def check_multitenant(
    baseline: Dict,
    fresh: Optional[Dict] = None,
) -> List[Dict]:
    """Replay the BENCH_SERVE.json ``multitenant`` section's hard gates.

    Like the fleet and promotion soaks, the multi-tenant soak (``bench_serve
    --multitenant``) is too heavy for every CI run, so the default mode
    REPLAYS the committed section: every tenant must have served with zero
    hard errors and a p99 inside its recorded SLO target, every replica must
    have finished with ZERO post-warmup recompiles (tenants must not trip
    each other's compilation caches), and the saturation phase must show
    weighted fair shedding — structured 429s, no 5xx, neither tenant
    starved, and the heavier tenant admitted at least the lighter one's
    share. All gates are correctness-hard (dimensionless or gated against
    the record's own SLO box), no machine slack. A ``--fresh-serve`` record
    carrying its own ``multitenant`` section is gated instead."""
    record = fresh if fresh and fresh.get("multitenant") else baseline
    mt = record.get("multitenant")
    if not mt:
        return []
    out: List[Dict] = []
    slo = mt.get("slo_p99_ms")
    for name, entry in (mt.get("models") or {}).items():
        errors = (
            entry.get("errors_5xx", 0)
            + entry.get("errors_4xx", 0)
            + entry.get("errors_conn", 0)
        )
        out.append(_finding(
            "multitenant", f"models.{name}.errors", 0, errors,
            "== 0 (hard)", errors == 0,
        ))
        out.append(_finding(
            "multitenant", f"models.{name}.ok", ">= 1",
            entry.get("ok", 0), ">= 1 (the tenant actually served)",
            entry.get("ok", 0) >= 1,
        ))
        p99 = (entry.get("latency_ms") or {}).get("p99")
        if slo is not None and p99 is not None:
            out.append(_finding(
                "multitenant", f"models.{name}.p99_ms", slo, p99,
                f"<= {slo} (the tenant's recorded SLO target)", p99 <= slo,
            ))
    recompiles = sum(
        stats.get("recompiles_post_warmup", 0) or 0
        for stats in (mt.get("replicas") or {}).values()
    )
    out.append(_finding(
        "multitenant", "replica_post_warmup_recompiles", 0, recompiles,
        "== 0 (no cross-tenant compilation leaks)", recompiles == 0,
    ))
    sat = mt.get("saturation")
    if sat is not None:
        out.append(_finding(
            "multitenant", "saturation.shed_429_total", ">= 1",
            sat.get("shed_429_total", 0), ">= 1 (structured shed)",
            sat.get("shed_429_total", 0) >= 1,
        ))
        out.append(_finding(
            "multitenant", "saturation.errors_5xx", 0,
            sat.get("errors_5xx", 0), "== 0 (hard)",
            not sat.get("errors_5xx"),
        ))
        for name, entry in (sat.get("per_model") or {}).items():
            out.append(_finding(
                "multitenant", f"saturation.{name}.ok", ">= 1",
                entry.get("ok", 0),
                ">= 1 (fair shedding must not starve a tenant)",
                entry.get("ok", 0) >= 1,
            ))
        out.append(_finding(
            "multitenant", "saturation.fair_weighted", True,
            sat.get("fair_weighted"),
            "== true (admitted shares follow the fair-share weights)",
            bool(sat.get("fair_weighted")),
        ))
    return out


# the planner acceptance floor: auto must match or beat the hand-tuned
# preset layout (ISSUE-14); dimensionless, so it replays without machine
# slack like the fleet gates
DEFAULT_PLAN_RATIO_LIMIT = 1.05


def check_plan(
    baseline: Dict,
    fresh: Optional[Dict] = None,
    *,
    ratio_limit: float = DEFAULT_PLAN_RATIO_LIMIT,
) -> List[Dict]:
    """BENCH_PLAN.json gates (bench.py --plan output shape).

    Default mode REPLAYS the committed record (like the fleet section — a PR
    touching the planner or a preset layout must re-run ``bench.py --plan``
    and commit numbers that still clear the gates): per preset, the auto
    layout's step time must stay <= ``ratio_limit`` x the hand-tuned
    layout's (dimensionless, transfers across machines), and the planner's
    predicted params+opt+stats bytes/chip must equal the placed state's
    ``tree_bytes_per_device`` EXACTLY (accounting correctness — hard). A
    ``--fresh-plan`` record is gated instead."""
    record = fresh if fresh is not None else baseline
    out: List[Dict] = []
    for name, entry in (record.get("presets") or {}).items():
        ratio = entry.get("step_time_ratio_auto_over_hand")
        if ratio is not None:
            out.append(_finding(
                "plan", f"{name}.step_time_ratio_auto_over_hand",
                ratio_limit, ratio,
                f"<= {ratio_limit} (auto matches or beats hand-tuned)",
                ratio <= ratio_limit,
            ))
        match = (entry.get("auto") or {}).get("predicted_bytes_match")
        if match is not None:
            out.append(_finding(
                "plan", f"{name}.auto.predicted_bytes_match", True, match,
                "== true (exact tree_bytes_per_device accounting, hard)",
                bool(match),
            ))
    return out


# continuous-profiling overhead: the amortized step-time ratio with a
# sparse-cadence capture landing mid-run must stay within the documented
# <= 2% budget (dimensionless, transfers across machines)
DEFAULT_PROFILE_RATIO_LIMIT = 1.02


def check_profile(
    baseline: Dict,
    fresh: Optional[Dict] = None,
    *,
    ratio_limit: float = DEFAULT_PROFILE_RATIO_LIMIT,
) -> List[Dict]:
    """BENCH_PROFILE.json gates (bench.py --profile-overhead output shape).

    Default mode REPLAYS the committed record (like plan/elastic — ci runs
    the live A/B as its own gate step, so the sentinel's job is keeping the
    committed history honest): the profiled/plain step-time ratio must clear
    the <= 2% budget, and the profiled run must have actually landed at
    least one capture inside the timed loop — a run that never captured
    would pass the ratio vacuously. ``--fresh-profile`` gates a fresh record
    instead."""
    record = fresh if fresh is not None else baseline
    out: List[Dict] = []
    ratio = record.get("step_time_ratio_profiled_over_plain")
    out.append(_finding(
        "profile", "step_time_ratio_profiled_over_plain",
        ratio_limit, ratio,
        f"<= {ratio_limit} (cadence profiling stays inside the 2% budget)",
        ratio is not None and ratio <= ratio_limit,
    ))
    captures = (record.get("profiling_on") or {}).get("captures_per_run")
    out.append(_finding(
        "profile", "profiling_on.captures_per_run", ">= 1", captures,
        ">= 1 (the profiled side must actually capture, hard)",
        captures is not None and captures >= 1,
    ))
    return out


# elastic gates: all dimensionless/hard (replay-only, like fleet/promotion —
# the full drill spawns real multi-process worlds, too heavy for every CI
# run); the downtime ceiling applies to the committed record's own box
DEFAULT_ELASTIC_DOWNTIME_CEILING_S = 120.0
DEFAULT_ELASTIC_THROUGHPUT_FLOOR = 0.4


def check_elastic(
    baseline: Dict,
    fresh: Optional[Dict] = None,
    *,
    downtime_ceiling_s: float = DEFAULT_ELASTIC_DOWNTIME_CEILING_S,
    throughput_floor: float = DEFAULT_ELASTIC_THROUGHPUT_FLOOR,
) -> List[Dict]:
    """Replay the committed BENCH_ELASTIC.json hard gates
    (tools/bench_elastic.py output shape): the headline host-death drill
    must have actually RESIZED the world (old != new, reason host_death) and
    resumed with final params BIT-IDENTICAL to a clean dp−1 run from the
    same checkpoint — the whole point of elastic training; the measured
    resize downtime must clear the ceiling and the per-chip throughput must
    survive the resize. An elastic-path PR must re-run the bench and commit
    numbers that still clear these. ``--fresh-elastic`` gates a fresh record
    instead."""
    record = fresh if fresh is not None else baseline
    out: List[Dict] = []
    out.append(_finding(
        "elastic", "bit_identical_resume", True,
        record.get("bit_identical_resume"),
        "== true (elastic resume must equal a clean same-world resume, hard)",
        bool(record.get("bit_identical_resume")),
    ))
    resize = record.get("resize") or {}
    resized = (
        resize.get("old_world") is not None
        and resize.get("old_world") != resize.get("new_world")
    )
    out.append(_finding(
        "elastic", "resize.world_changed", True,
        f"{resize.get('old_world')}->{resize.get('new_world')}",
        "old_world != new_world (the drill must actually resize, hard)",
        resized,
    ))
    out.append(_finding(
        "elastic", "resize.reason", "host_death", resize.get("reason"),
        "== host_death (the drill kills a host, hard)",
        resize.get("reason") == "host_death",
    ))
    downtime = record.get("resize_downtime_s")
    out.append(_finding(
        "elastic", "resize_downtime_s", downtime_ceiling_s, downtime,
        f"<= {downtime_ceiling_s}s (drain + re-plan + respawn)",
        downtime is not None and downtime <= downtime_ceiling_s,
    ))
    ratio = (record.get("throughput_per_chip") or {}).get("after_over_before")
    if ratio is not None:
        out.append(_finding(
            "elastic", "throughput_per_chip.after_over_before",
            throughput_floor, ratio,
            f">= {throughput_floor} (per-chip efficiency survives the "
            "resize)",
            ratio >= throughput_floor,
        ))
    redeals = record.get("data_redeals")
    if redeals is not None:
        out.append(_finding(
            "elastic", "data_redeals", ">= 1", redeals,
            ">= 1 (the resumed world re-dealt the shard assignment, hard)",
            redeals >= 1,
        ))
    return out


def check_promotion(
    baseline: Dict,
    fresh: Optional[Dict] = None,
) -> List[Dict]:
    """Replay the BENCH_SERVE.json ``promotion`` section's hard gates.

    Like the fleet soak, the promotion soak (``bench_serve --promotion``) is
    too heavy for every CI run, so the default mode REPLAYS the committed
    section: the kill-mid-canary drill must have CONVERGED (promotion
    completed, dead canary restarted) with zero client-visible errors, and
    the poisoned-candidate drill must have actually ROLLED BACK — a
    promotion pipeline whose rollback never fires is worse than none,
    because operators trust it. All gates are correctness-hard
    (dimensionless), no machine slack. A ``--fresh-serve`` record carrying
    its own ``promotion`` section is gated instead."""
    record = fresh if fresh and fresh.get("promotion") else baseline
    promo = record.get("promotion")
    if not promo:
        return []
    out: List[Dict] = []
    kill = promo.get("kill_canary")
    if kill is not None:
        out.append(_finding(
            "promotion", "kill_canary.completed", True,
            kill.get("completed"), "== true (hard)",
            bool(kill.get("completed")),
        ))
        out.append(_finding(
            "promotion", "kill_canary.converged", True,
            kill.get("converged"), "== true (hard)",
            bool(kill.get("converged")),
        ))
        out.append(_finding(
            "promotion", "kill_canary.client_errors", 0,
            kill.get("client_errors", 0), "== 0 (hard)",
            not kill.get("client_errors"),
        ))
        out.append(_finding(
            "promotion", "kill_canary.restarts", ">= 1",
            kill.get("restarts", 0),
            ">= 1 (the drill must actually have killed the canary)",
            kill.get("restarts", 0) >= 1,
        ))
    rollback = promo.get("rollback")
    if rollback is not None:
        out.append(_finding(
            "promotion", "rollback.rolled_back", True,
            rollback.get("rolled_back"),
            "== true (an injected regression MUST fire the rollback)",
            bool(rollback.get("rolled_back")),
        ))
        out.append(_finding(
            "promotion", "rollback.client_errors", 0,
            rollback.get("client_errors", 0), "== 0 (hard)",
            not rollback.get("client_errors"),
        ))
        out.append(_finding(
            "promotion", "rollback.restored", True,
            rollback.get("restored"),
            "== true (fleet back on the incumbent fingerprint)",
            bool(rollback.get("restored")),
        ))
    return out


# cold-start gates (BENCH_COLDSTART.json, tools/bench_coldstart.py): the
# warm/cold ratios are dimensionless and transfer across machines; the
# settle comparison is an absolute delta because the elastic coordinator's
# settle time is quantized by its ~2s poll interval (a ratio gate flaps on
# one tick)
DEFAULT_COLDSTART_REPLICA_RATIO = 0.5
DEFAULT_COLDSTART_RERUN_RATIO = 0.9
DEFAULT_COLDSTART_SETTLE_DELTA_S = 4.0


def check_coldstart(
    baseline: Dict,
    fresh: Optional[Dict] = None,
    *,
    max_replica_ratio: float = DEFAULT_COLDSTART_REPLICA_RATIO,
    max_rerun_ratio: float = DEFAULT_COLDSTART_RERUN_RATIO,
    settle_delta_s: float = DEFAULT_COLDSTART_SETTLE_DELTA_S,
) -> List[Dict]:
    """Replay the committed BENCH_COLDSTART.json hard gates
    (tools/bench_coldstart.py output shape). Like elastic, the drill spawns
    real multi-process worlds and full train runs — too heavy for every CI
    invocation — so the default mode REPLAYS the committed record: the
    second same-shape train run must have LEDGERED cache hits and a reduced
    time-to-first-step; a replica loading the artifact's shipped cache
    subdir must go ready in <= half the cold time with >= 1 hit; the elastic
    drill with ``--aot-standby`` must still resume bit-identical, must have
    actually started a standby that ended ready/superseded, and must not
    settle slower than the no-standby drill by more than the poll-quantized
    slack. A cold-start-path PR must re-run the bench and commit numbers
    that still clear these. ``--fresh-coldstart`` gates a fresh record
    instead."""
    record = fresh if fresh is not None else baseline
    out: List[Dict] = []
    rerun = record.get("train_rerun") or {}
    out.append(_finding(
        "coldstart", "train_rerun.warm_cache_hits", ">= 1",
        rerun.get("warm_cache_hits"),
        ">= 1 (second same-shape run must ledger cache hits, hard)",
        (rerun.get("warm_cache_hits") or 0) >= 1,
    ))
    ratio = rerun.get("warm_over_cold")
    out.append(_finding(
        "coldstart", "train_rerun.warm_over_cold", max_rerun_ratio, ratio,
        f"<= {max_rerun_ratio} (rerun time-to-first-step must shrink)",
        ratio is not None and ratio <= max_rerun_ratio,
    ))
    replica = record.get("replica") or {}
    out.append(_finding(
        "coldstart", "replica.warm_hits", ">= 1", replica.get("warm_hits"),
        ">= 1 (the shipped artifact cache must be consumed, hard)",
        (replica.get("warm_hits") or 0) >= 1,
    ))
    r_ratio = replica.get("warm_over_cold")
    out.append(_finding(
        "coldstart", "replica.warm_over_cold", max_replica_ratio, r_ratio,
        f"<= {max_replica_ratio} (warm replica time-to-ready, the "
        "ISSUE acceptance bar)",
        r_ratio is not None and r_ratio <= max_replica_ratio,
    ))
    elastic = record.get("elastic_standby") or {}
    out.append(_finding(
        "coldstart", "elastic_standby.bit_identical_resume", True,
        elastic.get("bit_identical_resume"),
        "== true (AOT standby must not perturb the resumed math, hard)",
        bool(elastic.get("bit_identical_resume")),
    ))
    sb = elastic.get("standby") or {}
    out.append(_finding(
        "coldstart", "elastic_standby.standby.started", True,
        sb.get("standby_started"),
        "== true (the drill must actually spawn a standby, hard)",
        bool(sb.get("standby_started")),
    ))
    out.append(_finding(
        "coldstart", "elastic_standby.standby.outcome",
        "ready | superseded", sb.get("standby_outcome"),
        "in (ready, superseded) — superseded means reaped at drain with "
        "its entries already on disk",
        sb.get("standby_outcome") in ("ready", "superseded"),
    ))
    out.append(_finding(
        "coldstart", "elastic_standby.standby.post_resize_cache_hits",
        ">= 1", sb.get("post_resize_cache_hits"),
        ">= 1 (the resized world must consume the standby's entries)",
        (sb.get("post_resize_cache_hits") or 0) >= 1,
    ))
    ns_settle = (elastic.get("nostandby") or {}).get("post_resize_settle_s")
    sb_settle = sb.get("post_resize_settle_s")
    if ns_settle is not None and sb_settle is not None:
        out.append(_finding(
            "coldstart", "elastic_standby.settle_delta_s",
            f"<= {settle_delta_s}", round(sb_settle - ns_settle, 3),
            f"standby settle - no-standby settle <= {settle_delta_s}s "
            "(a standby competing with the respawn instead of pre-warming "
            "it measured +6s before the drain-time reap)",
            sb_settle - ns_settle <= settle_delta_s,
        ))
    return out


DEFAULT_LOOP_CYCLE_CEILING_S = 300.0
DEFAULT_LOOP_TRIGGER_LATENCY_CEILING_S = 30.0


def check_loop(
    baseline: Dict,
    fresh: Optional[Dict] = None,
    *,
    cycle_ceiling_s: float = DEFAULT_LOOP_CYCLE_CEILING_S,
    trigger_latency_ceiling_s: float = DEFAULT_LOOP_TRIGGER_LATENCY_CEILING_S,
) -> List[Dict]:
    """Replay the committed BENCH_LOOP.json (tools/bench_loop.py) gates.

    The continuous-learning drill is too heavy for every CI run, so the
    default mode REPLAYS the committed record — and almost every gate is
    correctness-hard, not performance: the loop must have CLOSED (one cycle,
    promoted, zero rejected), with zero client-visible errors while the
    fleet flipped under live load, on a drift alert that was actually earned
    (score past threshold), retraining on data that was actually captured
    and ingested, and the whole fleet must have converged on ONE fingerprint
    — the promoted one. The two wall-clock bounds (cycle time, drift->trigger
    latency) only catch the catastrophic class, same policy as everywhere
    else. A ``--fresh-loop`` record is gated instead."""
    record = fresh if fresh else baseline
    out: List[Dict] = []
    fw = record.get("flywheel") or {}
    out.append(_finding(
        "loop", "flywheel.promoted", ">= 1", fw.get("promoted"),
        ">= 1 (the loop must actually close)",
        (fw.get("promoted") or 0) >= 1,
    ))
    out.append(_finding(
        "loop", "flywheel.rejected", 0, fw.get("rejected"),
        "== 0 (hard)", not fw.get("rejected"),
    ))
    out.append(_finding(
        "loop", "client_errors", 0, record.get("client_errors"),
        "== 0 (zero client-visible errors through the whole drill, "
        "promotion flip included)", record.get("client_errors") == 0,
    ))
    out.append(_finding(
        "loop", "client_ok", ">= 1000", record.get("client_ok"),
        ">= 1000 (the zero-errors gate must have seen real load)",
        (record.get("client_ok") or 0) >= 1000,
    ))
    ingested = record.get("samples_ingested") or 0
    out.append(_finding(
        "loop", "samples_ingested", ">= 64", ingested,
        ">= 64 (the retrain ran on actually-captured data)",
        ingested >= 64,
    ))
    out.append(_finding(
        "loop", "samples_captured", f">= ingested ({ingested})",
        record.get("samples_captured"),
        ">= samples_ingested (capture feeds ingest, never the reverse)",
        (record.get("samples_captured") or 0) >= ingested,
    ))
    alert = record.get("drift_alert") or {}
    out.append(_finding(
        "loop", "drift_alert.score", f"> {alert.get('threshold')}",
        alert.get("score"),
        "> threshold (the alert was earned, not injected)",
        alert.get("score") is not None
        and alert.get("threshold") is not None
        and alert["score"] > alert["threshold"],
    ))
    latency = record.get("drift_trigger_latency_s")
    out.append(_finding(
        "loop", "drift_trigger_latency_s",
        f"<= {trigger_latency_ceiling_s}", latency,
        "present and bounded (the flywheel saw the alert promptly)",
        latency is not None and 0 <= latency <= trigger_latency_ceiling_s,
    ))
    out.append(_finding(
        "loop", "cycle_wall_s", f"<= {cycle_ceiling_s}",
        record.get("cycle_wall_s"),
        "bounded (catastrophic-class only, like every wall-clock gate)",
        record.get("cycle_wall_s") is not None
        and record["cycle_wall_s"] <= cycle_ceiling_s,
    ))
    fingerprint = record.get("promoted_fingerprint") or ""
    mix = record.get("artifact_mix") or {}
    converged = (
        bool(fingerprint)
        and len(mix) == 1
        and next(iter(mix)).split(":", 1)[-1] in fingerprint
    )
    out.append(_finding(
        "loop", "promoted_fingerprint", "fleet converged on it",
        {"fingerprint": fingerprint[:24], "artifact_mix": mix},
        "one artifact key in the post-flip mix, matching the promoted "
        "fingerprint", converged,
    ))
    return out


# -- fresh-run plumbing ------------------------------------------------------


def _load(path: str) -> Dict:
    with open(path, encoding="utf-8") as f:
        return json.load(f)


def run_fresh_async(timeout: int = 900) -> Dict:
    """``bench.py --async-loop`` on the CPU shape; JSON comes via stdout."""
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--async-loop", "--platform=cpu"],
        capture_output=True, text=True, timeout=timeout, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    lines = [ln for ln in out.stdout.splitlines() if ln.startswith("{")]
    if out.returncode != 0 or not lines:
        raise RuntimeError(
            "fresh async bench failed: "
            + (out.stderr.strip().splitlines() or ["no output"])[-1][:300]
        )
    return json.loads(lines[-1])


def run_fresh_serve(out_path: str, timeout: int = 900) -> Dict:
    """``tools/bench_serve.py`` (per-request + batched A/B) on CPU."""
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "bench_serve.py"),
         "--duration", "1", "--trials", "2", "--json-out", out_path],
        capture_output=True, text=True, timeout=timeout, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    if out.returncode != 0 or not os.path.exists(out_path):
        raise RuntimeError(
            "fresh serve bench failed: "
            + (out.stderr.strip().splitlines() or ["no output"])[-1][:300]
        )
    return _load(out_path)


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        description=__doc__.splitlines()[0],
    )
    parser.add_argument("--check", action="store_true",
                        help="run the comparisons and gate on them (the only "
                        "mode; the flag exists so the CI step reads as a "
                        "gate)")
    parser.add_argument("--benches",
                        default="async,serve,fleet,records,promotion,"
                        "multitenant,plan,elastic,profile,loop,coldstart,"
                        "kernels",
                        help="comma-separated subset to check")
    parser.add_argument("--baseline-async",
                        default=os.path.join(REPO, "BENCH_ASYNC.json"))
    parser.add_argument("--baseline-serve",
                        default=os.path.join(REPO, "BENCH_SERVE.json"))
    parser.add_argument("--baseline-records",
                        default=os.path.join(REPO, "RECORDS_BENCH.json"))
    parser.add_argument("--baseline-plan",
                        default=os.path.join(REPO, "BENCH_PLAN.json"))
    parser.add_argument("--baseline-elastic",
                        default=os.path.join(REPO, "BENCH_ELASTIC.json"))
    parser.add_argument("--baseline-profile",
                        default=os.path.join(REPO, "BENCH_PROFILE.json"))
    parser.add_argument("--baseline-loop",
                        default=os.path.join(REPO, "BENCH_LOOP.json"))
    parser.add_argument("--baseline-coldstart",
                        default=os.path.join(REPO, "BENCH_COLDSTART.json"))
    parser.add_argument("--fresh-coldstart", default=None, metavar="JSON",
                        help="pre-computed tools/bench_coldstart.py output "
                        "(default: replay the committed baseline's gates, "
                        "like the elastic section)")
    parser.add_argument("--coldstart-replica-ratio", type=float,
                        default=DEFAULT_COLDSTART_REPLICA_RATIO,
                        help="warm/cold replica time-to-ready ceiling on "
                        "the cold-start bench record (dimensionless; the "
                        "ISSUE acceptance bar)")
    parser.add_argument("--coldstart-rerun-ratio", type=float,
                        default=DEFAULT_COLDSTART_RERUN_RATIO,
                        help="warm/cold train time-to-first-step ceiling "
                        "on the cold-start bench record (dimensionless)")
    parser.add_argument("--fresh-loop", default=None, metavar="JSON",
                        help="pre-computed tools/bench_loop.py output "
                        "(default: replay the committed baseline's gates, "
                        "like the fleet section)")
    parser.add_argument("--loop-cycle-ceiling", type=float,
                        default=DEFAULT_LOOP_CYCLE_CEILING_S,
                        help="retrain-cycle wall-clock ceiling on the loop "
                        "bench record (seconds; catastrophic-class only)")
    parser.add_argument("--loop-trigger-latency-ceiling", type=float,
                        default=DEFAULT_LOOP_TRIGGER_LATENCY_CEILING_S,
                        help="drift-alert -> loop_trigger latency ceiling "
                        "on the loop bench record (seconds)")
    parser.add_argument("--fresh-profile", default=None, metavar="JSON",
                        help="pre-computed bench.py --profile-overhead "
                        "output (default: replay the committed baseline's "
                        "gates; ci runs the live A/B as its own step)")
    parser.add_argument("--profile-ratio-limit", type=float,
                        default=DEFAULT_PROFILE_RATIO_LIMIT,
                        help="profiled/plain step-time ratio ceiling for "
                        "the continuous-profiling bench (dimensionless; "
                        "the documented <= 2% budget)")
    parser.add_argument("--fresh-elastic", default=None, metavar="JSON",
                        help="pre-computed tools/bench_elastic.py output "
                        "(default: replay the committed baseline's gates, "
                        "like the fleet section)")
    parser.add_argument("--elastic-downtime-ceiling", type=float,
                        default=DEFAULT_ELASTIC_DOWNTIME_CEILING_S,
                        help="resize downtime ceiling on the elastic bench "
                        "record (seconds; applies to the committed record's "
                        "own box)")
    parser.add_argument("--elastic-throughput-floor", type=float,
                        default=DEFAULT_ELASTIC_THROUGHPUT_FLOOR,
                        help="floor on the elastic bench's per-chip "
                        "throughput after/before ratio")
    parser.add_argument("--fresh-plan", default=None, metavar="JSON",
                        help="pre-computed bench.py --plan output (default: "
                        "replay the committed baseline's gates, like the "
                        "fleet section)")
    parser.add_argument("--plan-ratio-limit", type=float,
                        default=DEFAULT_PLAN_RATIO_LIMIT,
                        help="auto/hand step-time ratio ceiling for the "
                        "plan bench (dimensionless; the committed record "
                        "must clear the 1.05 acceptance floor)")
    parser.add_argument("--fresh-records", default=None, metavar="JSON",
                        help="pre-computed tools/bench_records.py output "
                        "(default: replay the committed baseline's gates, "
                        "like the fleet section)")
    parser.add_argument("--fresh-async", default=None, metavar="JSON",
                        help="pre-computed bench.py --async-loop output "
                        "(skips running the bench)")
    parser.add_argument("--fresh-serve", default=None, metavar="JSON",
                        help="pre-computed tools/bench_serve.py output "
                        "(skips running the bench)")
    parser.add_argument("--wall-slack", type=float,
                        default=DEFAULT_WALL_SLACK,
                        help="multiplicative slack on absolute wall-clock "
                        "numbers (machine drift); dimensionless ratios and "
                        "hard gates ignore it")
    parser.add_argument("--async-ratio-limit", type=float,
                        default=DEFAULT_ASYNC_RATIO_LIMIT)
    parser.add_argument("--p99-slack", type=float, default=DEFAULT_P99_SLACK,
                        help="multiplicative slack on serving p99 tail "
                        "latency (the noisiest metric on shared runners; "
                        "throughput/speedup gates catch real request-path "
                        "regressions far tighter)")
    parser.add_argument("--hbm-slack", type=float, default=DEFAULT_HBM_SLACK,
                        help="multiplicative slack on the peak-HBM bench "
                        "field (near-deterministic for a fixed config, so "
                        "much tighter than wall-clock; growth-only gate)")
    parser.add_argument("--json-out", default=None)
    args = parser.parse_args(argv)

    benches = {b.strip() for b in args.benches.split(",") if b.strip()}
    findings: List[Dict] = []
    errors: List[str] = []

    if "async" in benches:
        try:
            baseline = _load(args.baseline_async)
            fresh = (
                _load(args.fresh_async)
                if args.fresh_async
                else run_fresh_async()
            )
            findings += check_async(
                baseline, fresh,
                wall_slack=args.wall_slack,
                ratio_limit=args.async_ratio_limit,
                hbm_slack=args.hbm_slack,
            )
        except (OSError, RuntimeError, ValueError,
                subprocess.TimeoutExpired) as e:
            errors.append(f"async: {e}")
    if "serve" in benches:
        try:
            baseline = _load(args.baseline_serve)
            if args.fresh_serve:
                fresh = _load(args.fresh_serve)
            else:
                # a scratch file, NOT the repo root: the fresh numbers are
                # machine-specific throwaways and must never dirty the
                # checkout (or get committed next to the real baselines)
                with tempfile.TemporaryDirectory(
                    prefix="regression_sentinel_"
                ) as tmp:
                    fresh = run_fresh_serve(
                        os.path.join(tmp, "bench_serve_fresh.json")
                    )
            findings += check_serve(
                baseline, fresh, wall_slack=args.wall_slack,
                p99_slack=args.p99_slack,
            )
        except (OSError, RuntimeError, ValueError,
                subprocess.TimeoutExpired) as e:
            errors.append(f"serve: {e}")
    if "fleet" in benches:
        try:
            baseline = _load(args.baseline_serve)
            fresh = _load(args.fresh_serve) if args.fresh_serve else None
            findings += check_fleet(baseline, fresh)
        except (OSError, ValueError) as e:
            errors.append(f"fleet: {e}")
    if "kernels" in benches:
        try:
            baseline = _load(args.baseline_serve)
            fresh = _load(args.fresh_serve) if args.fresh_serve else None
            findings += check_kernels(baseline, fresh)
        except (OSError, ValueError) as e:
            errors.append(f"kernels: {e}")
    if "promotion" in benches:
        try:
            baseline = _load(args.baseline_serve)
            fresh = _load(args.fresh_serve) if args.fresh_serve else None
            findings += check_promotion(baseline, fresh)
        except (OSError, ValueError) as e:
            errors.append(f"promotion: {e}")
    if "multitenant" in benches:
        try:
            baseline = _load(args.baseline_serve)
            fresh = _load(args.fresh_serve) if args.fresh_serve else None
            findings += check_multitenant(baseline, fresh)
        except (OSError, ValueError) as e:
            errors.append(f"multitenant: {e}")
    if "plan" in benches:
        try:
            baseline = _load(args.baseline_plan)
            fresh = _load(args.fresh_plan) if args.fresh_plan else None
            findings += check_plan(
                baseline, fresh, ratio_limit=args.plan_ratio_limit
            )
        except (OSError, ValueError) as e:
            errors.append(f"plan: {e}")
    if "elastic" in benches:
        try:
            baseline = _load(args.baseline_elastic)
            fresh = _load(args.fresh_elastic) if args.fresh_elastic else None
            findings += check_elastic(
                baseline, fresh,
                downtime_ceiling_s=args.elastic_downtime_ceiling,
                throughput_floor=args.elastic_throughput_floor,
            )
        except (OSError, ValueError) as e:
            errors.append(f"elastic: {e}")
    if "profile" in benches:
        try:
            baseline = _load(args.baseline_profile)
            fresh = _load(args.fresh_profile) if args.fresh_profile else None
            findings += check_profile(
                baseline, fresh, ratio_limit=args.profile_ratio_limit
            )
        except (OSError, ValueError) as e:
            errors.append(f"profile: {e}")
    if "loop" in benches:
        try:
            baseline = _load(args.baseline_loop)
            fresh = _load(args.fresh_loop) if args.fresh_loop else None
            findings += check_loop(
                baseline, fresh,
                cycle_ceiling_s=args.loop_cycle_ceiling,
                trigger_latency_ceiling_s=args.loop_trigger_latency_ceiling,
            )
        except (OSError, ValueError) as e:
            errors.append(f"loop: {e}")
    if "coldstart" in benches:
        try:
            baseline = _load(args.baseline_coldstart)
            fresh = (
                _load(args.fresh_coldstart) if args.fresh_coldstart else None
            )
            findings += check_coldstart(
                baseline, fresh,
                max_replica_ratio=args.coldstart_replica_ratio,
                max_rerun_ratio=args.coldstart_rerun_ratio,
            )
        except (OSError, ValueError) as e:
            errors.append(f"coldstart: {e}")
    if "records" in benches:
        try:
            baseline = _load(args.baseline_records)
            fresh = _load(args.fresh_records) if args.fresh_records else None
            findings += check_records(
                baseline, fresh, wall_slack=args.wall_slack
            )
        except (OSError, ValueError) as e:
            errors.append(f"records: {e}")

    failed = [f for f in findings if not f["ok"]]
    for f in findings:
        mark = "ok " if f["ok"] else "FAIL"
        print(
            f"[{mark}] {f['bench']}.{f['metric']}: baseline={f['baseline']} "
            f"fresh={f['fresh']} ({f['limit']})"
        )
    for e in errors:
        print(f"[ERR ] {e}", file=sys.stderr)
    verdict = {
        "ok": not failed and not errors and bool(findings),
        "checked": len(findings),
        "failed": len(failed),
        "errors": errors,
        "findings": findings,
    }
    if args.json_out:
        with open(args.json_out, "w", encoding="utf-8") as f:
            json.dump(verdict, f, indent=1)
    print(json.dumps({k: verdict[k] for k in ("ok", "checked", "failed")}))
    if not findings and not errors:
        # comparing nothing is not a pass a CI pipeline should ride on
        print("regression-sentinel: nothing compared (missing baselines?)",
              file=sys.stderr)
        return 2
    return 0 if verdict["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
