"""Standalone TPU probes for the bench extras that keep getting cut by tunnel
windows: the segmentation flagship throughput and the space-to-depth stem
variant of the classic ResNet-50 headline.

Each probe prints one JSON line as it completes (so a hang mid-script still
yields the earlier numbers) using bench.py's exact protocol: AOT-compiled
shard_map step, 3 warmup steps, value-fetch sync barrier, cost_analysis MFU.

Usage:  python tools/probe_extras.py [--seg] [--s2d] [--steps 20]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from bench import PEAK_BF16_TFLOPS  # noqa: E402


def _peak(device) -> float | None:
    kind = getattr(device, "device_kind", "").lower()
    for key, tflops in PEAK_BF16_TFLOPS.items():
        if key in kind:
            return tflops * 1e12
    return None


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--seg", action="store_true")
    parser.add_argument("--s2d", action="store_true")
    parser.add_argument(
        "--s2d-true-only",
        action="store_true",
        help="probe only the stem_space_to_depth=True variant (retry helper "
        "when the baseline already measured and the fresh compile timed out)",
    )
    parser.add_argument("--steps", type=int, default=20)
    parser.add_argument("--batch", type=int, default=256)
    parser.add_argument(
        "--remat-batch",
        type=int,
        default=0,
        help="probe classic ResNet-50 with remat=True at this batch "
        "(VERDICT r3 weak #2: batch 512 measured SLOWER than 256 — HBM "
        "pressure; rematerialization trades FLOPs for activation memory and "
        "may recover it). Fresh HLO — schedule after the cached probes.",
    )
    args = parser.parse_args()

    import jax
    import numpy as np

    try:
        jax.config.update(
            "jax_compilation_cache_dir", os.path.join(REPO, ".jax_cache_tpu")
        )
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception:
        pass

    from tensorflowdistributedlearning_tpu.config import ModelConfig, TrainConfig
    from tensorflowdistributedlearning_tpu.models import build_model
    from tensorflowdistributedlearning_tpu.parallel import (
        make_mesh,
        replicate,
        shard_batch,
    )
    from tensorflowdistributedlearning_tpu.train import (
        create_train_state,
        make_optimizer,
        make_train_step,
    )
    from tensorflowdistributedlearning_tpu.train.step import (
        ClassificationTask,
        SegmentationTask,
    )
    from tensorflowdistributedlearning_tpu.utils.profiling import sync

    dev = jax.devices()[0]
    n = 1
    mesh = make_mesh(n)
    print(
        json.dumps({"platform": jax.default_backend(), "device": dev.device_kind}),
        flush=True,
    )

    if args.seg:
        seg_cfg = ModelConfig()  # reference defaults: 101x101x2 seg flagship
        seg_model = build_model(seg_cfg)
        seg_state = replicate(
            create_train_state(
                seg_model,
                make_optimizer(TrainConfig()),
                jax.random.PRNGKey(1),
                np.zeros((1, 101, 101, 2), np.float32),
            ),
            mesh,
        )
        gen = np.random.default_rng(1)
        seg_batch = shard_batch(
            {
                "images": gen.normal(0, 1, (64 * n, 101, 101, 2)).astype(np.float32),
                "labels": (gen.uniform(0, 1, (64 * n, 101, 101, 1)) > 0.5).astype(
                    np.float32
                ),
            },
            mesh,
        )
        step = make_train_step(mesh, SegmentationTask(), donate=False)
        compiled = step.lower(seg_state, seg_batch).compile()
        for _ in range(3):
            seg_state, m = compiled(seg_state, seg_batch)
        sync(m)
        t0 = time.perf_counter()
        for _ in range(10):
            seg_state, m = compiled(seg_state, seg_batch)
        sync(m)
        dt = time.perf_counter() - t0
        print(
            json.dumps(
                {
                    "segmentation_flagship": {
                        "images_per_sec_per_chip": round(64 * 10 / dt, 2),
                        "global_batch": 64 * n,
                        "step_time_ms": round(dt / 10 * 1000, 2),
                    }
                }
            ),
            flush=True,
        )

    variants = []
    if args.s2d or args.s2d_true_only:
        variants += [
            {"stem_space_to_depth": s2d}
            for s2d in ((True,) if args.s2d_true_only else (False, True))
        ]
    if args.remat_batch:
        variants.append({"remat": True, "_batch": args.remat_batch})

    if variants:
        from tensorflowdistributedlearning_tpu.configs import get_preset

        for overrides in variants:
            preset = get_preset("resnet50_classic_imagenet")
            import dataclasses

            batch_n = overrides.pop("_batch", args.batch)
            mcfg = dataclasses.replace(preset.model, **overrides)
            model = build_model(mcfg)
            state = replicate(
                create_train_state(
                    model,
                    make_optimizer(preset.train),
                    jax.random.PRNGKey(0),
                    np.zeros((1, 224, 224, 3), np.float32),
                ),
                mesh,
            )
            gen = np.random.default_rng(0)
            batch = shard_batch(
                {
                    "images": gen.normal(0, 1, (batch_n, 224, 224, 3)).astype(
                        np.float32
                    ),
                    "labels": gen.integers(0, 1000, batch_n).astype(np.int32),
                },
                mesh,
            )
            task = ClassificationTask(label_smoothing=preset.train.label_smoothing)
            step = make_train_step(
                mesh, task, donate=False, weight_decay=mcfg.weight_decay
            )
            compiled = step.lower(state, batch).compile()
            for _ in range(3):
                state, m = compiled(state, batch)
            sync(m)
            t0 = time.perf_counter()
            for _ in range(args.steps):
                state, m = compiled(state, batch)
            sync(m)
            dt = time.perf_counter() - t0
            step_s = dt / args.steps
            out = {
                **overrides,
                "global_batch": batch_n,
                "images_per_sec_per_chip": round(batch_n * args.steps / dt, 2),
                "step_time_ms": round(step_s * 1000, 2),
            }
            try:
                cost = compiled.cost_analysis()
                flops = (cost or {}).get("flops", 0.0)
                peak = _peak(dev)
                if flops and peak:
                    out["mfu"] = round(flops / step_s / peak, 4)
                    out["model_tflops_per_step"] = round(flops / 1e12, 3)
            except Exception:
                pass
            print(json.dumps(out), flush=True)
            del compiled, state, batch

    return 0


if __name__ == "__main__":
    sys.exit(main())
