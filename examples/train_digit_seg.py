"""Train the segmentation flagship on REAL pixels: foreground (ink) masks over
the genuine 8x8 digit scans, through the full reference-parity loop — K-fold
Trainer, Lovász hinge, thresholded mIOU, best-checkpoint export, and the
fold x TTA ensemble predict (the method the reference left as a TODO,
reference: model.py:229).

The reference's production task was binary masks over real single-channel
images (TGS salt, reference: model.py:138-227); its notebooks proved the loop
learned on real data. This driver is that proof for this framework: every
committed segmentation number before it came from synthetic masks. The run
record (held-out TTA-ensemble mIOU + per-fold eval mIOU) lands in
``SEG_RUN.json`` at the repo root when run with ``--json-out``.

Usage (CPU mesh, ~tgs_salt architecture at reduced width for the 1-core box):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/train_digit_seg.py --model-dir /tmp/digit_seg \
        --steps 200 --batch-size 32 --n-fold 2 --width-multiplier 0.25

On a TPU chip the full-width preset is the default: drop --width-multiplier.
"""

from __future__ import annotations

import _bootstrap  # noqa: F401  (repo-root sys.path setup)

import argparse
import json
import logging
import os
import shutil
import time


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--model-dir", required=True)
    parser.add_argument("--data-dir", default=None,
                        help="salt-layout corpus dir (default: {model-dir}/data; "
                        "prepared automatically when absent)")
    parser.add_argument("--steps", type=int, default=200)
    parser.add_argument("--batch-size", type=int, default=32)
    parser.add_argument("--n-fold", type=int, default=2)
    parser.add_argument("--size", type=int, default=101,
                        help="square input size; 101 = the tgs_salt preset shape")
    parser.add_argument("--width-multiplier", type=float, default=1.0,
                        help="channel-width scale; 1.0 = the full tgs_salt "
                        "architecture (41.7M params — size for your chip)")
    parser.add_argument("--dtype", choices=("float32", "bfloat16"),
                        default="float32",
                        help="bfloat16 = the tgs_salt_bf16 preset's compute dtype")
    parser.add_argument("--limit", type=int, default=None,
                        help="cap examples per split (CI budgets)")
    parser.add_argument("--json-out", default=None,
                        help="write the run record (metrics/config/wall time) here")
    args = parser.parse_args()

    from tensorflowdistributedlearning_tpu.utils.devices import apply_platform_env

    apply_platform_env()
    logging.basicConfig(level=logging.INFO)

    import jax
    import numpy as np

    from tensorflowdistributedlearning_tpu.config import TrainConfig
    from tensorflowdistributedlearning_tpu.data.digits import (
        SHORT_BUDGET_BN_DECAY,
        prepare_digit_segmentation,
    )
    from tensorflowdistributedlearning_tpu.data import pipeline as pipeline_lib
    from tensorflowdistributedlearning_tpu.ops import metrics as metrics_lib
    from tensorflowdistributedlearning_tpu.train.trainer import Trainer

    data_dir = args.data_dir or os.path.join(args.model_dir, "data")
    train_dir = os.path.join(data_dir, "train")
    test_dir = os.path.join(data_dir, "test")
    # the prepared corpus depends on --size/--limit: reuse it only when a
    # manifest proves the flags match, else re-prepare — a silent reuse would
    # make the committed run record describe a corpus it never trained on
    prep_manifest = os.path.join(data_dir, "prep_manifest.json")
    wanted = {"size": [args.size, args.size], "limit": args.limit}
    corpus_exists = os.path.isdir(os.path.join(train_dir, "images"))
    # the default {model-dir}/data location is always ours to manage; an
    # explicit --data-dir may hold a hand-prepared corpus we must not delete
    managed = args.data_dir is None
    have = None
    if corpus_exists:
        try:
            with open(prep_manifest) as f:
                have = json.load(f)
        except (OSError, ValueError):
            have = None
    if corpus_exists and have is None and not managed:
        # a user-supplied corpus without a manifest was NOT written by this
        # guard (possibly a custom seed/split): reuse it untouched — deleting
        # data this script didn't create is never ok
        logging.info(
            "reusing unmanaged corpus at %s (no prep manifest; --size/--limit "
            "not verified against it)", data_dir,
        )
    elif have != wanted:
        # ours (manifest present but flags changed) or absent: (re)prepare.
        # Clear the old splits first — the writer names files d0000.png...
        # sequentially, so a shrunken --limit would otherwise leave extras.
        # Only dirs we PROVABLY wrote (default location, or manifest present)
        # are deleted; an unmanaged --data-dir tree is written into, never
        # cleared — deleting data this script didn't create is never ok
        if managed or have is not None:
            for split in (train_dir, test_dir):
                if os.path.isdir(split):
                    shutil.rmtree(split)
        # in-progress sentinel first: an interrupted prepare leaves a manifest
        # that can never equal `wanted`, so the next run re-prepares instead
        # of silently reusing a truncated corpus
        os.makedirs(data_dir, exist_ok=True)
        with open(prep_manifest, "w") as f:
            json.dump({"in_progress": True}, f)
        prepare_digit_segmentation(
            data_dir, size=(args.size, args.size), limit=args.limit
        )
        with open(prep_manifest, "w") as f:
            json.dump(wanted, f)

    t0 = time.time()
    trainer = Trainer(
        args.model_dir,
        train_dir,
        n_fold=args.n_fold,
        # reference training defaults otherwise: Adam 1e-3 (model.py:33),
        # Lovász hinge, best-export ladder
        train_config=TrainConfig(
            n_folds=args.n_fold,
            checkpoint_every_steps=max(args.steps // 2, 1),
            eval_every_steps=max(args.steps // 2, 1),
            eval_throttle_secs=0,
        ),
        # tgs_salt preset architecture (default ModelConfig), scaled by the
        # explicit knobs only
        input_shape=(args.size, args.size),
        width_multiplier=args.width_multiplier,
        dtype=args.dtype,
        # short budgets evaluate on BN running stats; the digits recipes'
        # faster decay keeps them honest (data/digits.py)
        batch_norm_decay=SHORT_BUDGET_BN_DECAY,
    )
    ids = pipeline_lib.discover_ids(train_dir)
    fold_metrics = trainer.train(ids, batch_size=args.batch_size, steps=args.steps)

    # Held-out scoring: fold x TTA ensemble over images the K-fold pool never
    # contained, scored with the same thresholded-IoU the eval loop reports.
    pred = trainer.predict(test_dir, batch_size=args.batch_size)
    truth = pipeline_lib.load_masks(test_dir, pred["ids"])
    ensemble_miou = float(
        np.mean(np.asarray(metrics_lib.iou_scores(truth, pred["masks"])))
    )

    record = {
        "task": "digit_foreground_segmentation",
        "data": "sklearn load_digits: 1797 real 8x8 scans, ink-threshold masks, "
                f"bilinear-upsampled to {args.size}x{args.size}",
        "architecture": "tgs_salt preset (ResNet-v2-beta + DeepLabV3+ head, "
                        "Lovász hinge)"
                        + (f" at width x{args.width_multiplier}"
                           if args.width_multiplier != 1.0 else ""),
        "dtype": args.dtype,
        "platform": jax.devices()[0].platform,
        "n_devices": len(jax.devices()),
        "steps": args.steps,
        "global_batch": args.batch_size,
        "n_folds": args.n_fold,
        "fold_eval_mean_iou": [
            round(m["metrics/mean_iou"], 4) for m in fold_metrics
        ],
        "tta_ensemble_test_mean_iou": round(ensemble_miou, 4),
        "n_test": len(pred["ids"]),
        "wall_time_secs": round(time.time() - t0, 1),
    }
    print(json.dumps(record))
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(record, f, indent=1)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
