"""Train a real model on real data to a real accuracy: sklearn's handwritten
digits (1797 genuine 8x8 scans from the UCI optical-recognition corpus — the
only real image dataset present in this zero-egress environment; CIFAR/ImageNet
would need a download).

The reference's notebooks were real end-to-end runs on real Kaggle data
(reference: Untitled.ipynb cells 7-8). This driver is that proof for the
streaming fit() path: the raw bitmaps are written as PNG TFRecord shards
(data/records.py), streamed through the native reader into a ResNet classifier,
trained on the device mesh, and evaluated on a held-out split the model never
saw. Measured with the default budget: 95.5% held-out top-1 on an 8-device
CPU mesh (600 steps, bf16, per-shard BN — `DIGITS_RUN.json` at the repo root
is that run's committed record).

Usage:
    python examples/train_digits.py --model-dir /tmp/digits_run \
        [--data-dir /tmp/digits_data] [--steps 600] [--batch-size 64]
        [--json-out DIGITS.json]
"""


from __future__ import annotations

import _bootstrap  # noqa: F401  (repo-root sys.path setup)


import argparse
import json
import logging
import os
import time


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--model-dir", required=True)
    parser.add_argument("--data-dir", default=None,
                        help="record-shard dir (default: {model-dir}/data)")
    parser.add_argument("--steps", type=int, default=600)
    parser.add_argument("--batch-size", type=int, default=64)
    parser.add_argument("--eval-every", type=int, default=200)
    parser.add_argument("--json-out", default=None,
                        help="write the run record (metrics/config/wall time) here")
    parser.add_argument("--backbone", choices=("resnet", "xception"),
                        default="resnet",
                        help="model family: the reference-family ResNet trunk "
                        "or the Xception-41 classifier (the family whose "
                        "training path round-4's dropout-PRNG fix unblocked)")
    parser.add_argument("--pipeline-parallel", type=int, default=1,
                        help="GPipe stages over the model mesh axis (xception "
                        "backbone: the 8 middle-flow units split into stage "
                        "groups; 1 = plain SPMD). The r5 learning proof for "
                        "pipelined-conv BN runs --backbone xception "
                        "--pipeline-parallel 2")
    parser.add_argument("--sync-bn", action="store_true",
                        help="synchronized cross-shard BatchNorm (global-"
                        "batch statistics; +7.8 points at digits scale - "
                        "DIGITS_RUN.json 'xception_adam_syncbn')")
    parser.add_argument("--recipe", choices=("adam", "sgd", "lars"),
                        default="adam",
                        help="adam = the validated short-budget recipe; sgd = "
                        "the ImageNet production recipe (Nesterov + linear-"
                        "scaled lr + warmup-cosine + wd + label smoothing) "
                        "at digits scale; lars = the large-batch 8k-preset "
                        "recipe (layer-wise trust ratios), pair with a large "
                        "--batch-size")
    args = parser.parse_args()

    from tensorflowdistributedlearning_tpu.utils.devices import apply_platform_env

    apply_platform_env()
    logging.basicConfig(level=logging.INFO)

    from tensorflowdistributedlearning_tpu.config import ModelConfig
    from tensorflowdistributedlearning_tpu.data.digits import (
        SHORT_BUDGET_BN_DECAY,
        large_batch_recipe_train_config,
        prepare_digits,
        production_recipe_train_config,
        short_budget_train_config,
    )
    from tensorflowdistributedlearning_tpu.train.fit import ClassifierTrainer

    data_dir = args.data_dir or os.path.join(args.model_dir, "data")
    if not any(f.startswith("train-") for f in
               (os.listdir(data_dir) if os.path.isdir(data_dir) else [])):
        prepare_digits(data_dir)

    if args.backbone == "xception":
        # Xception-41 at quarter width: 32x32 inputs run the full entry/
        # middle/exit flows down to 1x1 features (stride 32)
        model_cfg = ModelConfig(
            backbone="xception",
            num_classes=10,
            input_shape=(32, 32),
            input_channels=1,
            width_multiplier=0.25,
            output_stride=None,
            dtype="bfloat16",
            batch_norm_decay=SHORT_BUDGET_BN_DECAY,
        )
    else:
        # small reference-family trunk at half width: 32x32x1 inputs, ~2.7M
        # params
        model_cfg = ModelConfig(
            num_classes=10,
            input_shape=(32, 32),
            input_channels=1,
            n_blocks=(1, 1, 1),
            width_multiplier=0.5,
            output_stride=None,
            dtype="bfloat16",
            batch_norm_decay=SHORT_BUDGET_BN_DECAY,
        )
    # the shared validated recipes (data/digits.py) — the e2e test asserts
    # accuracy on exactly these settings
    pp = {"pipeline_parallel": args.pipeline_parallel} if (
        args.pipeline_parallel > 1) else {}
    if args.sync_bn:
        pp["sync_batch_norm"] = True
    if args.recipe == "sgd":
        train_cfg = production_recipe_train_config(
            args.steps, args.batch_size, **pp
        )
    elif args.recipe == "lars":
        train_cfg = large_batch_recipe_train_config(
            args.steps, args.batch_size, **pp
        )
    else:
        train_cfg = short_budget_train_config(args.steps, **pp)
    trainer = ClassifierTrainer(args.model_dir, data_dir, model_cfg, train_cfg)
    t0 = time.perf_counter()
    result = trainer.fit(
        batch_size=args.batch_size,
        steps=args.steps,
        eval_every_steps=args.eval_every,
    )
    wall = time.perf_counter() - t0
    # the run ledger fit() wrote alongside the checkpoints: surface the
    # goodput numbers in the committed record (full detail:
    # `python -m tensorflowdistributedlearning_tpu.cli telemetry-report <dir>`)
    telemetry_summary = None
    try:
        from tensorflowdistributedlearning_tpu.obs.report import build_report

        rep = build_report(args.model_dir)
        telemetry_summary = {
            "ledger": "telemetry.jsonl",
            "time_split": rep["time_split"],
            "recompiles_post_warmup": rep["recompiles"]["post_warmup_count"],
            "throughput": {
                k: v
                for k, v in rep.get("throughput", {}).items()
                if k != "trend"
            },
        }
    except Exception as e:  # noqa: BLE001 — the record stands without it
        telemetry_summary = {"error": str(e)[:200]}
    record = {
        "dataset": "sklearn load_digits (1797 real 8x8 scans, 80/20 split)",
        "val_metrics": result.final_metrics,
        "params": result.n_params,
        "steps": result.steps,
        "global_batch": args.batch_size,
        # 1797 - int(1797*0.2) = 1438 train scans (the split in
        # data/digits.py): how many passes over the corpus the
        # budget amounts to — the axis that makes recipe rows comparable
        "epochs_equivalent": round(result.steps * args.batch_size / 1438.0, 1),
        "pipeline_parallel": args.pipeline_parallel,
        "sync_batch_norm": bool(args.sync_bn),
        "wall_time_s": round(wall, 1),
        "telemetry": telemetry_summary,
        "model_config": {"backbone": model_cfg.backbone,
                         # n_blocks only shapes the resnet family; Xception-41
                         # is a fixed architecture scaled by width_multiplier
                         **({"n_blocks": list(model_cfg.n_blocks)}
                            if model_cfg.backbone == "resnet" else {}),
                         "width_multiplier": model_cfg.width_multiplier,
                         "input_shape": list(model_cfg.input_shape),
                         "dtype": model_cfg.dtype},
        "train_config": {"recipe": args.recipe,
                         "optimizer": train_cfg.optimizer, "lr": train_cfg.lr,
                         "lr_schedule": train_cfg.lr_schedule,
                         "lr_warmup_steps": train_cfg.lr_warmup_steps,
                         "weight_decay": train_cfg.weight_decay,
                         "label_smoothing": train_cfg.label_smoothing},
    }
    print(json.dumps(record))
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(record, f, indent=1)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
