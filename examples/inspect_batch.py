"""Visual batch inspection — the reference's matplotlib notebook cells as a script.

The reference eyeballed its input pipeline by pulling one batch through a
one-shot iterator and imshow-ing image/mask pairs (reference: Untitled.ipynb
cells 11-17, SURVEY §4 "visual spot checks"). This driver does the same against
this framework's pipeline: load a salt-layout dataset, run the ON-DEVICE
augmentation exactly as the trainer does (composed affine warp + Laplacian
channel, data/augment.py), and write a tiled PNG grid of
[raw image | augmented image | Laplacian channel | mask] per row.

Usage:
    python examples/inspect_batch.py --data-dir /path/to/train \
        [--out batch.png] [--n 8] [--seed 0] [--no-augment]
"""


from __future__ import annotations

import _bootstrap  # noqa: F401  (repo-root sys.path setup)


import argparse


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--data-dir", required=True,
                        help="salt layout: {data}/images/*.png + masks/*.png")
    parser.add_argument("--out", default="batch.png")
    parser.add_argument("--n", type=int, default=8)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--no-augment", action="store_true",
                        help="show the eval-path preprocessing instead")
    args = parser.parse_args()

    from tensorflowdistributedlearning_tpu.utils.devices import apply_platform_env

    apply_platform_env()

    import jax
    import numpy as np
    from PIL import Image

    from tensorflowdistributedlearning_tpu.data import augment as augment_lib
    from tensorflowdistributedlearning_tpu.data import pipeline as pipeline_lib

    dataset = pipeline_lib.InMemoryDataset.from_directory(args.data_dir)
    batch = next(
        pipeline_lib.train_batches(dataset, args.n, seed=args.seed, steps=1)
    )
    raw_images = np.asarray(batch["images"])  # [N, H, W, 1] in [0, 1]
    if args.no_augment:
        prepared = augment_lib.prepare_eval_batch(batch["images"], batch["masks"])
    else:
        prepared = augment_lib.augment_batch(
            jax.random.PRNGKey(args.seed),
            batch["images"],
            batch["masks"],
            augment_lib.AugmentConfig(crop_probability=0.0),
        )
    images = np.asarray(jax.device_get(prepared["images"]))  # [N, H, W, 2]
    masks = np.asarray(jax.device_get(prepared["labels"]))   # [N, H, W, 1]

    def to_u8(x: np.ndarray) -> np.ndarray:
        lo, hi = float(x.min()), float(x.max())
        return ((x - lo) / max(hi - lo, 1e-6) * 255).astype(np.uint8)

    n, h, w = images.shape[0], images.shape[1], images.shape[2]
    pad = 2
    grid = np.full((n * (h + pad), 4 * (w + pad)), 32, np.uint8)
    for i in range(n):
        r = i * (h + pad)
        cells = [
            to_u8(raw_images[i, :, :, 0]),
            to_u8(images[i, :, :, 0]),       # normalized/warped image channel
            to_u8(images[i, :, :, 1]),       # Laplacian feature channel
            # masks are binary: fixed scale, NOT per-cell min-max (an all-salt
            # mask must render white, not black like an empty one)
            (np.clip(masks[i, :, :, 0], 0, 1) * 255).astype(np.uint8),
        ]
        for j, cell in enumerate(cells):
            if cell.shape != (h, w):  # raw may differ from augmented size
                cell = np.asarray(
                    Image.fromarray(cell).resize((w, h), Image.NEAREST)
                )
            grid[r : r + h, j * (w + pad) : j * (w + pad) + w] = cell
    Image.fromarray(grid).save(args.out)
    print(
        f"wrote {args.out}: {n} rows x [raw | augmented | laplacian | mask] "
        f"({grid.shape[1]}x{grid.shape[0]})"
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
