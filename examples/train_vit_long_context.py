"""ViT + ring-attention sequence-parallel training driver (beyond parity).

The reference framework was CNN-only; this driver exercises the transformer
side of the stack end to end: the `vit_s16_imagenet` preset (or any custom ViT
geometry) trained by the same SPMD fit() loop, with `--sequence-parallel N`
sharding the token sequence over the mesh's sequence axis — attention then runs
as exact blockwise RING attention (parallel/ring_attention.py): K/V blocks
rotate around the device ring over ICI, one chip never materializes the full
sequence, and the result matches single-device attention exactly.

The long-context knobs: `--image-size` scales the token count quadratically
(448x448/16 = 784 tokens, 896x896/16 = 3136 tokens, ...), which is where
sequence parallelism starts paying — per-chip activation memory stays at
tokens/N. Input heights must keep whole patches per shard
(height % (patch_size * N) == 0).

Usage:
    python examples/train_vit_long_context.py --model-dir /tmp/vit \
        [--data-root /path/to/imagefolder_or_tfrecord_shards] \
        [--image-size 448] [--sequence-parallel 4] [--steps 1000]

Omit --data-root for synthetic data (scaling/throughput work without a
dataset). Record-sharded datasets ({root}/train-*.tfrecord, see
data/records.write_classification_shards) stream through the native TFRecord
reader; ImageFolder trees ({root}/train/{class}/*.png) work too.
"""


from __future__ import annotations

import _bootstrap  # noqa: F401  (repo-root sys.path setup)


import argparse
import dataclasses
import json
import logging


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--model-dir", required=True)
    parser.add_argument("--data-root", default=None)
    parser.add_argument("--image-size", type=int, default=224)
    parser.add_argument("--patch-size", type=int, default=16)
    parser.add_argument("--sequence-parallel", type=int, default=1)
    parser.add_argument("--steps", type=int, default=1000)
    parser.add_argument("--batch-size", type=int, default=256)
    parser.add_argument("--num-classes", type=int, default=1000)
    parser.add_argument("--eval-every", type=int, default=None)
    args = parser.parse_args()

    from tensorflowdistributedlearning_tpu.utils.devices import apply_platform_env

    apply_platform_env()
    logging.basicConfig(level=logging.INFO)

    from tensorflowdistributedlearning_tpu.configs import get_preset
    from tensorflowdistributedlearning_tpu.train.fit import ClassifierTrainer

    preset = get_preset("vit_s16_imagenet")
    model_cfg = dataclasses.replace(
        preset.model,
        input_shape=(args.image_size, args.image_size),
        patch_size=args.patch_size,
        num_classes=args.num_classes,
    )
    train_cfg = dataclasses.replace(
        preset.train,
        sequence_parallel=args.sequence_parallel,
        eval_every_steps=args.eval_every,
    )
    trainer = ClassifierTrainer(args.model_dir, args.data_root, model_cfg, train_cfg)
    tokens = (args.image_size // args.patch_size) ** 2
    logging.info(
        "ViT-S/%d @ %dx%d = %d tokens, sequence_parallel=%d (%d tokens/chip)",
        args.patch_size, args.image_size, args.image_size, tokens,
        args.sequence_parallel, tokens // args.sequence_parallel,
    )
    result = trainer.fit(batch_size=args.batch_size, steps=args.steps)
    print(json.dumps({
        "steps": result.steps,
        "n_params": result.n_params,
        "tokens": tokens,
        "final_metrics": result.final_metrics,
    }))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
