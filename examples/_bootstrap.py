"""Shared example bootstrap: make the repo importable when a driver runs
straight from a checkout (``python examples/<name>.py`` — no install, no
PYTHONPATH). Imported as ``import _bootstrap`` because the script's own
directory is always ``sys.path[0]``."""

import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)
