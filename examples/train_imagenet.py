"""ImageNet-class classification training driver for the BASELINE config ladder.

The reference kept a classification head in its backbone (global_pool +
num_classes, reference: core/resnet.py:246-256) but shipped no driver that could
train it. This script is that driver, built on the streaming fit() loop: pick any
classification preset (`resnet50_imagenet`, `resnet101_imagenet`,
`resnet152_imagenet`, `xception41_imagenet`, `resnet50_bf16_8k`, `cifar10_smoke`)
and point it at an ImageFolder tree:

    data_root/
      train/{class_name}/*.png
      val/{class_name}/*.png      (optional; eval falls back to train)

Usage:
    python examples/train_imagenet.py --preset resnet50_imagenet \
        --data-root /path/to/imagenet --model-dir /tmp/run \
        [--steps 112590] [--batch-size 1024] [--eval-every 1251]

Omit --data-root to run any preset end-to-end on synthetic data (shape/throughput
work without a dataset). On a v5e-16 slice the resnet50_imagenet preset at global
batch 1024 is the BASELINE.json north-star configuration. `--sequence-parallel N`
additionally H-shards the backbone over the mesh's sequence axis — the input
height must then be divisible by overall_stride*N (so the stride-32 224x224
trunks need a 256x256-style input; the validation error says exactly what fits).
"""


from __future__ import annotations

import _bootstrap  # noqa: F401  (repo-root sys.path setup)


import argparse
import dataclasses
import json
import logging


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--preset", default="resnet50_imagenet")
    parser.add_argument("--data-root", default=None)
    parser.add_argument("--model-dir", required=True)
    parser.add_argument("--steps", type=int, default=112_590)  # 90 epochs @ 1024
    parser.add_argument("--batch-size", type=int, default=None)
    parser.add_argument("--eval-every", type=int, default=None)
    parser.add_argument("--sequence-parallel", type=int, default=1)
    args = parser.parse_args()

    from tensorflowdistributedlearning_tpu.utils.devices import apply_platform_env

    apply_platform_env()

    logging.basicConfig(level=logging.INFO)

    from tensorflowdistributedlearning_tpu.configs import get_preset
    from tensorflowdistributedlearning_tpu.train.fit import ClassifierTrainer

    preset = get_preset(args.preset)
    train_cfg = preset.train
    if args.sequence_parallel != 1:
        train_cfg = dataclasses.replace(
            train_cfg, sequence_parallel=args.sequence_parallel
        )
    trainer = ClassifierTrainer(
        args.model_dir, args.data_root, preset.model, train_cfg
    )
    result = trainer.fit(
        batch_size=args.batch_size or preset.global_batch,
        steps=args.steps,
        eval_every_steps=args.eval_every,
    )
    print(
        json.dumps(
            {
                "preset": args.preset,
                "steps": result.steps,
                "n_params": result.n_params,
                "final_metrics": result.final_metrics,
            }
        )
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
