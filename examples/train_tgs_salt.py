"""End-to-end TGS Salt training driver — the reference's notebooks as a script.

The reference was driven by two notebooks (Untitled.ipynb NCHW / Test.ipynb NHWC)
that loaded `train.csv`/`depths.csv`, binned mask coverage into 11 stratification
classes, and ran `Model(...).train(X, y, 64, 10000)` on 2 GPUs (SURVEY §2.1 C13).
Equivalent flow here, against a Kaggle competition-data directory:

    data_root/
      train/images/*.png   train/masks/*.png
      test/images/*.png    (optional, for --predict)
      train.csv  depths.csv  (optional manifests)

Usage:
    python examples/train_tgs_salt.py --data-root /path/to/tgs --model-dir /tmp/run \
        [--batch-size 64] [--steps 10000] [--predict --submission sub.csv]
"""


from __future__ import annotations

import _bootstrap  # noqa: F401  (repo-root sys.path setup)


import argparse
import json
import logging
import os

from tensorflowdistributedlearning_tpu.config import TrainConfig
from tensorflowdistributedlearning_tpu.data.kaggle import (
    load_tgs_training_set,
    write_submission,
)
from tensorflowdistributedlearning_tpu.train.trainer import Trainer


def main() -> int:
    logging.basicConfig(level=logging.INFO)
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--data-root", required=True)
    p.add_argument("--model-dir", required=True)
    p.add_argument("--batch-size", type=int, default=64)  # Untitled.ipynb cell 7
    p.add_argument("--steps", type=int, default=10_000)  # Untitled.ipynb cell 8
    p.add_argument("--n-fold", type=int, default=5)
    p.add_argument("--lr", type=float, default=0.001)
    p.add_argument("--seed", type=int, default=42)
    p.add_argument("--predict", action="store_true",
                   help="after training, run the fold x TTA ensemble on test/")
    p.add_argument("--submission", default=None,
                   help="write a Kaggle submission csv here (implies --predict)")
    # architecture overrides (defaults are the reference's 101x101 flagship)
    p.add_argument("--input-shape", type=int, nargs=2, default=(101, 101))
    p.add_argument("--n-blocks", type=int, nargs="+", default=(3, 4, 6))
    p.add_argument("--base-depth", type=int, default=256)
    args = p.parse_args()

    from tensorflowdistributedlearning_tpu.utils.devices import apply_platform_env

    apply_platform_env()

    train_dir = os.path.join(args.data_root, "train")
    train_csv = os.path.join(args.data_root, "train.csv")
    ids, classes = load_tgs_training_set(
        train_dir, train_csv if os.path.exists(train_csv) else None
    )

    trainer = Trainer(
        args.model_dir,
        train_dir,
        train_config=TrainConfig(
            lr=args.lr, n_folds=args.n_fold, seed=args.seed
        ),
        input_shape=tuple(args.input_shape),
        n_blocks=tuple(args.n_blocks),
        base_depth=args.base_depth,
    )
    results = trainer.train(
        ids, classes, batch_size=args.batch_size, steps=args.steps
    )
    print(json.dumps({"folds": results, "n_params": trainer.params}))

    if args.predict or args.submission:
        test_dir = os.path.join(args.data_root, "test")
        pred = trainer.predict(test_dir, batch_size=args.batch_size, tta=True)
        if args.submission:
            write_submission(args.submission, pred["ids"], pred["masks"])
            print(json.dumps({"submission": args.submission, "n": len(pred["ids"])}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
