"""Vision Transformer classifier — the model family that consumes ring attention.

Beyond-parity: the reference framework is CNN-only (SURVEY §5.7 — no attention op
anywhere), but this framework's long-context story (``parallel/ring_attention.py``)
needs a first-class consumer in the training stack, not a standalone demo. This is
a standard pre-LN ViT (Dosovitskiy et al., arXiv:2010.11929): patch-embed conv,
learned position embeddings, N transformer blocks, global-average-pool head —
trainable through the same SPMD train step and ``fit`` loop as the CNN classifiers
(``ClassificationTask``; no BatchNorm, so the batch_stats pytree is empty).

Sequence parallelism: with ``spatial_axis_name`` set, the input arrives H-sharded
(``shard_batch_spatial``), each shard patch-embeds its own rows into a contiguous
block of the row-major token sequence, attention runs as exact blockwise RING
attention over the sequence axis (K/V rotating one ppermute hop per step), and the
pooled head ``pmean``s across shards — so one chip never materializes the full
token sequence. MLPs and LayerNorms are token-local and need no communication.

TPU notes: matmul-dominated (QKV/proj/MLP ride the MXU), compute dtype follows
``ModelConfig.dtype`` with float32 params and float32 softmax accumulation,
``remat`` wraps each block in ``jax.checkpoint`` for activation memory.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from tensorflowdistributedlearning_tpu.config import ModelConfig
from tensorflowdistributedlearning_tpu.models.layers import scaled_width
from tensorflowdistributedlearning_tpu.parallel.pipeline import stack_stage_params
from tensorflowdistributedlearning_tpu.parallel.ring_attention import (
    attention_reference,
    ring_attention,
)


# compiled-Pallas gate for the fused-attention dispatch: an alias bound in
# THIS module's globals so tests can patch vit._fused_platform_ok without
# affecting the depthwise gate; both resolve to the one shared decision
# (ops/pallas_kernels.pallas_platform_ok)
from tensorflowdistributedlearning_tpu.models.layers import (  # noqa: E402
    _pallas_platform_ok as _fused_platform_ok,
)

# PATCH-token ceiling for the fused kernel. Under the 2026-08-01
# DEVICE-DOMINATED protocol (bench_kernels._chained — single-call windows
# over the tunnel were 97%+ dispatch latency, producing the earlier
# contradictory 0.74x-1.15x train columns) the verdict at [32,T,6,64] is:
# train-step TIE at both T=196 and T=1024 (1.003x/1.005x), forward 0.97x at
# 196 and 1.14x at 1024. The gate sits at the measured ceiling — above it
# the kernel is unmeasured, and ops/flash_attention.py's own VMEM-budget
# fallback (_VMEM_KV_LIMIT_BYTES) already degrades oversized shapes to XLA.
# The ceiling counts PATCH tokens: this repo's ViT pools (no cls token), so
# its sequence length IS the patch count, and a variant that prepends
# auxiliary tokens (cls, registers) declares them via
# MultiHeadSelfAttention.num_prefix_tokens so a 1024-patch image does not
# fall back to XLA one token early (ADVICE round 5).
_FUSED_MAX_SEQ = 1024


class MultiHeadSelfAttention(nn.Module):
    """QKV projection + exact attention + output projection. ``spatial_axis_name``
    selects the ring formulation over the sequence mesh axis; both paths share the
    same float32-softmax math, so sharded and unsharded forwards agree to
    reassociation tolerance. ``use_fused`` swaps the XLA einsum path for the
    Pallas fused block-attention kernel (same contract, VMEM-resident scores) —
    on TPU only; elsewhere the flag degrades to the XLA path."""

    embed_dim: int
    num_heads: int
    spatial_axis_name: Optional[str] = None
    dtype: Optional[jnp.dtype] = None
    use_fused: bool = False
    # auxiliary tokens prepended to the patch sequence (cls token, register
    # tokens); excluded from the _FUSED_MAX_SEQ gate, whose ceiling was
    # measured in patch tokens. 0 for this repo's ViT (mean-pool head).
    num_prefix_tokens: int = 0

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        b, t, d = x.shape
        head_dim = self.embed_dim // self.num_heads
        qkv = nn.Dense(3 * self.embed_dim, dtype=self.dtype, name="qkv")(x)
        qkv = qkv.reshape(b, t, 3, self.num_heads, head_dim)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]  # [B, T, H, hd]
        if self.spatial_axis_name is not None:
            if self.use_fused:
                import warnings

                warnings.warn(
                    "use_fused_attention is ignored under sequence parallelism: "
                    "the ring formulation owns the attention math there",
                    stacklevel=2,
                )
            out = ring_attention(q, k, v, axis_name=self.spatial_axis_name)
        elif (
            self.use_fused
            and t - self.num_prefix_tokens <= _FUSED_MAX_SEQ
            and _fused_platform_ok()
        ):
            from tensorflowdistributedlearning_tpu.ops.flash_attention import (
                flash_attention,
            )

            out = flash_attention(q, k, v)
        else:
            # use_fused off-TPU degrades to the XLA path rather than the
            # Pallas interpreter (same platform gate as the depthwise
            # dispatch, models/layers.py), so presets can carry the flag
            # without slowing the CPU test mesh
            out = attention_reference(q, k, v)
        out = out.reshape(b, t, self.embed_dim)
        return nn.Dense(self.embed_dim, dtype=self.dtype, name="proj")(out)


class MoEMlp(nn.Module):
    """Switch-style top-1 mixture-of-experts FFN (arXiv:2101.03961) replacing a
    TransformerBlock's dense MLP.

    The router (float32, like the softmax accumulations elsewhere) picks one
    expert per token under a per-expert capacity; dropped tokens contribute a
    zero update (the residual carries them through). Training adds the
    load-balancing auxiliary loss, sown into the ``aux_loss`` collection —
    the train steps add every sown value to the objective; without it, top-1
    routing + capacity drops collapse onto few experts. Dispatch fractions are
    also sown into ``intermediates`` for utilization monitoring.

    ``expert_axis_name=None`` computes every expert locally
    (``dense_moe_apply`` — trainable on any mesh); with an axis name set, THIS
    shard's expert slice runs under the ``moe_apply`` all-to-all (one expert
    per shard on the mesh axis), with identical numerics — the final pmean
    clears the axis-varying type (every shard reconstructs the same combined
    tokens because the token batch is replicated across the expert axis)."""

    embed_dim: int
    mlp_dim: int
    n_experts: int
    capacity_factor: float = 1.25
    aux_weight: float = 0.01
    expert_axis_name: Optional[str] = None
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        from tensorflowdistributedlearning_tpu.parallel.expert import (
            dense_moe_apply,
            load_balance_loss,
            moe_apply,
        )

        b, t, d = x.shape
        tokens = x.reshape(b * t, d)
        router = self.param(
            "router",
            nn.initializers.normal(stddev=0.02),
            (d, self.n_experts),
            jnp.float32,
        )
        init = nn.initializers.lecun_normal(batch_axis=(0,))
        w_in = self.param(
            "w_in", init, (self.n_experts, d, self.mlp_dim), jnp.float32
        )
        b_in = self.param(
            "b_in", nn.initializers.zeros, (self.n_experts, self.mlp_dim), jnp.float32
        )
        w_out = self.param(
            "w_out", init, (self.n_experts, self.mlp_dim, d), jnp.float32
        )
        b_out = self.param(
            "b_out", nn.initializers.zeros, (self.n_experts, d), jnp.float32
        )

        # ONE float32 routing, shared by the aux-loss statistics AND the
        # dispatch below (passing gate_logits through keeps near-tie argmax
        # decisions identical between what the balance loss optimizes and
        # where tokens actually go, regardless of compute dtype)
        gate_logits = tokens.astype(jnp.float32) @ router
        if not self.is_initializing():  # init would bake stale sown values
            self.sow(
                "aux_loss",
                "load_balance",
                self.aux_weight * load_balance_loss(gate_logits),
            )
            chosen = jnp.argmax(gate_logits, axis=-1)
            fractions = jnp.mean(
                jax.nn.one_hot(chosen, self.n_experts, dtype=jnp.float32), axis=0
            )
            self.sow("intermediates", "expert_fraction", fractions)

        dtype = self.dtype or jnp.float32
        stacked = {
            "w_in": w_in.astype(dtype),
            "b_in": b_in.astype(dtype),
            "w_out": w_out.astype(dtype),
            "b_out": b_out.astype(dtype),
        }

        def expert_fn(p, xs):
            h = xs @ p["w_in"] + p["b_in"]
            h = nn.gelu(h)
            return h @ p["w_out"] + p["b_out"]

        tokens_c = tokens.astype(dtype)
        if self.expert_axis_name is None:
            out = dense_moe_apply(
                expert_fn,
                stacked,
                router,
                tokens_c,
                capacity_factor=self.capacity_factor,
                gate_logits=gate_logits,
            )
        else:
            idx = lax.axis_index(self.expert_axis_name)
            mine = jax.tree.map(
                lambda p: lax.dynamic_index_in_dim(p, idx, 0, keepdims=False),
                stacked,
            )
            out = moe_apply(
                expert_fn,
                mine,
                router,
                tokens_c,
                capacity_factor=self.capacity_factor,
                axis_name=self.expert_axis_name,
                gate_logits=gate_logits,
            )
            # every shard combines the same tokens (batch replicated across
            # the expert axis): numerically an identity, clears the varying type
            out = lax.pmean(out, self.expert_axis_name)
        return out.reshape(b, t, d)


class TransformerBlock(nn.Module):
    """Pre-LN block: x + MHSA(LN(x)); x + MLP(LN(x)). With ``moe_experts`` set,
    the MLP is the Switch-style ``MoEMlp`` instead of the dense pair."""

    embed_dim: int
    num_heads: int
    mlp_dim: int
    spatial_axis_name: Optional[str] = None
    dtype: Optional[jnp.dtype] = None
    use_fused: bool = False
    moe_experts: int = 0
    moe_capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01
    expert_axis_name: Optional[str] = None

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = False) -> jax.Array:
        h = nn.LayerNorm(dtype=self.dtype, name="ln1")(x)
        x = x + MultiHeadSelfAttention(
            self.embed_dim,
            self.num_heads,
            spatial_axis_name=self.spatial_axis_name,
            dtype=self.dtype,
            use_fused=self.use_fused,
            name="attn",
        )(h)
        h = nn.LayerNorm(dtype=self.dtype, name="ln2")(x)
        if self.moe_experts:
            return x + MoEMlp(
                self.embed_dim,
                self.mlp_dim,
                self.moe_experts,
                capacity_factor=self.moe_capacity_factor,
                aux_weight=self.moe_aux_weight,
                expert_axis_name=self.expert_axis_name,
                dtype=self.dtype,
                name="moe",
            )(h)
        h = nn.Dense(self.mlp_dim, dtype=self.dtype, name="mlp_in")(h)
        h = nn.gelu(h)
        h = nn.Dense(self.embed_dim, dtype=self.dtype, name="mlp_out")(h)
        return x + h


class ViTClassifier(nn.Module):
    """ViT classification network: [B, H, W, C] -> [B, num_classes] float32 logits.

    Under ``spatial_axis_name`` the input is the device's H-shard; its patches form
    tokens ``[axis_index * T_local, (axis_index + 1) * T_local)`` of the row-major
    global sequence (matching ring attention's block-order convention), and the
    position-embedding table is sliced accordingly."""

    config: ModelConfig
    bn_axis_name: Optional[str] = None  # accepted for factory symmetry; ViT has no BN
    spatial_axis_name: Optional[str] = None
    # expert-parallel execution for the MoE blocks (config.moe_experts > 0):
    # one expert per shard on this mesh axis, all-to-all dispatch; None runs
    # every expert locally (trainable on any mesh)
    expert_axis_name: Optional[str] = None

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = False) -> jax.Array:
        cfg = self.config
        if cfg.num_classes is None:
            raise ValueError(
                "backbone='vit' supports the classification head only "
                "(set num_classes)"
            )
        p = cfg.patch_size
        embed = scaled_width(cfg.embed_dim, cfg.width_multiplier)
        if embed % cfg.num_heads != 0:
            raise ValueError(
                f"scaled embed_dim {embed} not divisible by num_heads "
                f"{cfg.num_heads}"
            )
        h_total, w_total = cfg.input_shape
        if h_total % p or w_total % p:
            raise ValueError(
                f"input_shape {cfg.input_shape} not divisible by patch_size {p}"
            )
        # Validate the ACTUAL input against the configured geometry: the position
        # table is laid out row-major for input_shape's patch grid, so a
        # different-sized input would silently index wrong embeddings.
        h_local, w_actual = x.shape[1], x.shape[2]
        if w_actual != w_total:
            raise ValueError(
                f"input width {w_actual} != configured input_shape width {w_total}"
            )
        if self.spatial_axis_name is not None:
            degree = lax.axis_size(self.spatial_axis_name)
            if h_local * degree != h_total:
                raise ValueError(
                    f"per-shard height {h_local} x sequence degree {degree} != "
                    f"configured input height {h_total}"
                )
        elif h_local != h_total:
            raise ValueError(
                f"input height {h_local} != configured input_shape height {h_total}"
            )
        if h_local % p:
            raise ValueError(
                f"per-shard height {h_local} not divisible by patch_size {p} — "
                "lower sequence_parallel or the patch size"
            )
        dtype = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32
        x = x.astype(dtype)

        tokens = nn.Conv(
            embed,
            (p, p),
            strides=(p, p),
            padding="VALID",
            dtype=dtype,
            name="patch_embed",
        )(x)
        b = tokens.shape[0]
        t_local = tokens.shape[1] * tokens.shape[2]
        tokens = tokens.reshape(b, t_local, embed)

        t_global = (h_total // p) * (w_total // p)
        pos = self.param(
            "pos_embedding",
            nn.initializers.normal(stddev=0.02),
            (t_global, embed),
            jnp.float32,
        )
        if self.spatial_axis_name is not None:
            offset = lax.axis_index(self.spatial_axis_name) * t_local
            pos_local = lax.dynamic_slice_in_dim(pos, offset, t_local, axis=0)
        else:
            pos_local = pos[:t_local]
        tokens = tokens + pos_local.astype(dtype)[None]

        block_cls = TransformerBlock
        if cfg.remat:
            block_cls = nn.remat(block_cls, static_argnums=(2,))
        mlp_dim = int(embed * cfg.mlp_ratio)
        for i in range(cfg.vit_layers):
            # Switch-style placement: every OTHER block's FFN is a top-1 MoE
            # (block2, block4, ... — arXiv:2101.03961 alternates too); the
            # interleaved dense blocks stabilize training
            is_moe = cfg.moe_experts > 0 and i % 2 == 1
            tokens = block_cls(
                embed,
                cfg.num_heads,
                mlp_dim,
                spatial_axis_name=self.spatial_axis_name,
                dtype=dtype,
                use_fused=cfg.use_fused_attention,
                moe_experts=cfg.moe_experts if is_moe else 0,
                moe_capacity_factor=cfg.moe_capacity_factor,
                moe_aux_weight=cfg.moe_aux_weight,
                expert_axis_name=self.expert_axis_name if is_moe else None,
                name=f"block{i + 1}",
            )(tokens, train)

        tokens = nn.LayerNorm(dtype=dtype, name="ln_final")(tokens)
        pooled = jnp.mean(tokens.astype(jnp.float32), axis=1)
        if self.spatial_axis_name is not None:
            # equal-sized shards: the global token mean is the pmean of locals
            pooled = lax.pmean(pooled, self.spatial_axis_name)
        return nn.Dense(cfg.num_classes, name="logits")(pooled)


def pipeline_stage_fn(config: ModelConfig):
    """Stage function for GPipe pipeline parallelism over ViT blocks
    (parallel/pipeline.py): applies ONE TransformerBlock given its param tree.

    Takes the ``ModelConfig`` and derives embed width, MLP width, and compute
    dtype exactly as ``ViTClassifier.__call__`` does, so the pipelined blocks
    are numerically identical to the trained model's (a hand-passed dtype or
    width mismatch would diverge silently — params are float32 either way).

    ViT's repeated blocks are exactly the homogeneous-stage regime the pipeline
    runner targets (identical computation + param shapes per layer); pair with
    ``stack_vit_block_params`` to turn a trained ViT's variables into the
    stacked [K, ...] stage params the runner shards over the model axis."""
    embed = scaled_width(config.embed_dim, config.width_multiplier)
    dtype = jnp.bfloat16 if config.dtype == "bfloat16" else jnp.float32
    block = TransformerBlock(
        embed,
        config.num_heads,
        int(embed * config.mlp_ratio),
        dtype=dtype,
        use_fused=config.use_fused_attention,
    )

    def stage_fn(params, x):
        return block.apply({"params": params}, x, False)

    return stage_fn


def grouped_pipeline_stage_fn(config: ModelConfig, layers_per_stage: int):
    """Stage function over the GROUPED stacking [layers_per_stage, ...] —
    always expects the group axis, even when it is 1 (the form
    ``stack_vit_block_params(..., n_stages=K)`` produces per stage). Used by
    train/pipeline_step.py so stage params slice uniformly."""
    base = pipeline_stage_fn(config)

    def stage_fn(params, x):
        for i in range(layers_per_stage):
            x = base(jax.tree.map(lambda p, i=i: p[i], params), x)
        return x

    return stage_fn


def stack_vit_block_params(params, n_layers: int, n_stages: Optional[int] = None):
    """Stack a ViTClassifier's per-layer block params for the pipeline runner;
    layers must exist as ``block1..blockN``.

    ``n_stages=None``: [L, ...] leading stage axis (one layer per stage).
    ``n_stages=K``: grouped form [K, L/K, ...] — consecutive layers share a
    stage, matching ``pipeline_stage_fn(config, layers_per_stage=L//K)``."""
    stacked = stack_stage_params(
        [params[f"block{i + 1}"] for i in range(n_layers)]
    )
    if n_stages is None:
        return stacked
    if n_layers % n_stages:
        raise ValueError(
            f"{n_layers} ViT layers not divisible into {n_stages} pipeline stages"
        )
    group = n_layers // n_stages
    return jax.tree.map(
        lambda leaf: leaf.reshape((n_stages, group) + leaf.shape[1:]), stacked
    )


def embed_tokens(config: ModelConfig, params, x: jax.Array) -> jax.Array:
    """Patch-embed + position embeddings outside the module — the pre-block
    half of ``ViTClassifier.__call__`` (unsharded layout), applied from a
    trained model's param tree. Used by the pipeline-parallel train step, which
    runs the blocks through the GPipe runner instead of the module loop."""
    embed = scaled_width(config.embed_dim, config.width_multiplier)
    dtype = jnp.bfloat16 if config.dtype == "bfloat16" else jnp.float32
    p = config.patch_size
    x = x.astype(dtype)
    conv = nn.Conv(
        embed, (p, p), strides=(p, p), padding="VALID", dtype=dtype
    )
    tokens = conv.apply({"params": params["patch_embed"]}, x)
    b = tokens.shape[0]
    tokens = tokens.reshape(b, -1, embed)
    pos = params["pos_embedding"][: tokens.shape[1]]
    return tokens + pos.astype(dtype)[None]


def head_logits(config: ModelConfig, params, tokens: jax.Array) -> jax.Array:
    """Final LayerNorm + mean-pool + logits head — the post-block half of
    ``ViTClassifier.__call__`` (unsharded layout), for the pipeline step."""
    dtype = jnp.bfloat16 if config.dtype == "bfloat16" else jnp.float32
    tokens = nn.LayerNorm(dtype=dtype).apply({"params": params["ln_final"]}, tokens)
    pooled = jnp.mean(tokens.astype(jnp.float32), axis=1)
    return nn.Dense(config.num_classes).apply({"params": params["logits"]}, pooled)
