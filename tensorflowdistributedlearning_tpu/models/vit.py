"""Vision Transformer classifier — the model family that consumes ring attention.

Beyond-parity: the reference framework is CNN-only (SURVEY §5.7 — no attention op
anywhere), but this framework's long-context story (``parallel/ring_attention.py``)
needs a first-class consumer in the training stack, not a standalone demo. This is
a standard pre-LN ViT (Dosovitskiy et al., arXiv:2010.11929): patch-embed conv,
learned position embeddings, N transformer blocks, global-average-pool head —
trainable through the same SPMD train step and ``fit`` loop as the CNN classifiers
(``ClassificationTask``; no BatchNorm, so the batch_stats pytree is empty).

Sequence parallelism: with ``spatial_axis_name`` set, the input arrives H-sharded
(``shard_batch_spatial``), each shard patch-embeds its own rows into a contiguous
block of the row-major token sequence, attention runs as exact blockwise RING
attention over the sequence axis (K/V rotating one ppermute hop per step), and the
pooled head ``pmean``s across shards — so one chip never materializes the full
token sequence. MLPs and LayerNorms are token-local and need no communication.

TPU notes: matmul-dominated (QKV/proj/MLP ride the MXU), compute dtype follows
``ModelConfig.dtype`` with float32 params and float32 softmax accumulation,
``remat`` wraps each block in ``jax.checkpoint`` for activation memory.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from tensorflowdistributedlearning_tpu.config import ModelConfig
from tensorflowdistributedlearning_tpu.models.layers import scaled_width
from tensorflowdistributedlearning_tpu.parallel.pipeline import stack_stage_params
from tensorflowdistributedlearning_tpu.parallel.ring_attention import (
    attention_reference,
    ring_attention,
)


class MultiHeadSelfAttention(nn.Module):
    """QKV projection + exact attention + output projection. ``spatial_axis_name``
    selects the ring formulation over the sequence mesh axis; both paths share the
    same float32-softmax math, so sharded and unsharded forwards agree to
    reassociation tolerance. ``use_fused`` swaps the XLA einsum path for the
    Pallas fused block-attention kernel (same contract, VMEM-resident scores)."""

    embed_dim: int
    num_heads: int
    spatial_axis_name: Optional[str] = None
    dtype: Optional[jnp.dtype] = None
    use_fused: bool = False

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        b, t, d = x.shape
        head_dim = self.embed_dim // self.num_heads
        qkv = nn.Dense(3 * self.embed_dim, dtype=self.dtype, name="qkv")(x)
        qkv = qkv.reshape(b, t, 3, self.num_heads, head_dim)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]  # [B, T, H, hd]
        if self.spatial_axis_name is not None:
            if self.use_fused:
                import warnings

                warnings.warn(
                    "use_fused_attention is ignored under sequence parallelism: "
                    "the ring formulation owns the attention math there",
                    stacklevel=2,
                )
            out = ring_attention(q, k, v, axis_name=self.spatial_axis_name)
        elif self.use_fused:
            from tensorflowdistributedlearning_tpu.ops.flash_attention import (
                flash_attention,
            )

            out = flash_attention(q, k, v)
        else:
            out = attention_reference(q, k, v)
        out = out.reshape(b, t, self.embed_dim)
        return nn.Dense(self.embed_dim, dtype=self.dtype, name="proj")(out)


class TransformerBlock(nn.Module):
    """Pre-LN block: x + MHSA(LN(x)); x + MLP(LN(x))."""

    embed_dim: int
    num_heads: int
    mlp_dim: int
    spatial_axis_name: Optional[str] = None
    dtype: Optional[jnp.dtype] = None
    use_fused: bool = False

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = False) -> jax.Array:
        h = nn.LayerNorm(dtype=self.dtype, name="ln1")(x)
        x = x + MultiHeadSelfAttention(
            self.embed_dim,
            self.num_heads,
            spatial_axis_name=self.spatial_axis_name,
            dtype=self.dtype,
            use_fused=self.use_fused,
            name="attn",
        )(h)
        h = nn.LayerNorm(dtype=self.dtype, name="ln2")(x)
        h = nn.Dense(self.mlp_dim, dtype=self.dtype, name="mlp_in")(h)
        h = nn.gelu(h)
        h = nn.Dense(self.embed_dim, dtype=self.dtype, name="mlp_out")(h)
        return x + h


class ViTClassifier(nn.Module):
    """ViT classification network: [B, H, W, C] -> [B, num_classes] float32 logits.

    Under ``spatial_axis_name`` the input is the device's H-shard; its patches form
    tokens ``[axis_index * T_local, (axis_index + 1) * T_local)`` of the row-major
    global sequence (matching ring attention's block-order convention), and the
    position-embedding table is sliced accordingly."""

    config: ModelConfig
    bn_axis_name: Optional[str] = None  # accepted for factory symmetry; ViT has no BN
    spatial_axis_name: Optional[str] = None

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = False) -> jax.Array:
        cfg = self.config
        if cfg.num_classes is None:
            raise ValueError(
                "backbone='vit' supports the classification head only "
                "(set num_classes)"
            )
        p = cfg.patch_size
        embed = scaled_width(cfg.embed_dim, cfg.width_multiplier)
        if embed % cfg.num_heads != 0:
            raise ValueError(
                f"scaled embed_dim {embed} not divisible by num_heads "
                f"{cfg.num_heads}"
            )
        h_total, w_total = cfg.input_shape
        if h_total % p or w_total % p:
            raise ValueError(
                f"input_shape {cfg.input_shape} not divisible by patch_size {p}"
            )
        # Validate the ACTUAL input against the configured geometry: the position
        # table is laid out row-major for input_shape's patch grid, so a
        # different-sized input would silently index wrong embeddings.
        h_local, w_actual = x.shape[1], x.shape[2]
        if w_actual != w_total:
            raise ValueError(
                f"input width {w_actual} != configured input_shape width {w_total}"
            )
        if self.spatial_axis_name is not None:
            degree = lax.axis_size(self.spatial_axis_name)
            if h_local * degree != h_total:
                raise ValueError(
                    f"per-shard height {h_local} x sequence degree {degree} != "
                    f"configured input height {h_total}"
                )
        elif h_local != h_total:
            raise ValueError(
                f"input height {h_local} != configured input_shape height {h_total}"
            )
        if h_local % p:
            raise ValueError(
                f"per-shard height {h_local} not divisible by patch_size {p} — "
                "lower sequence_parallel or the patch size"
            )
        dtype = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32
        x = x.astype(dtype)

        tokens = nn.Conv(
            embed,
            (p, p),
            strides=(p, p),
            padding="VALID",
            dtype=dtype,
            name="patch_embed",
        )(x)
        b = tokens.shape[0]
        t_local = tokens.shape[1] * tokens.shape[2]
        tokens = tokens.reshape(b, t_local, embed)

        t_global = (h_total // p) * (w_total // p)
        pos = self.param(
            "pos_embedding",
            nn.initializers.normal(stddev=0.02),
            (t_global, embed),
            jnp.float32,
        )
        if self.spatial_axis_name is not None:
            offset = lax.axis_index(self.spatial_axis_name) * t_local
            pos_local = lax.dynamic_slice_in_dim(pos, offset, t_local, axis=0)
        else:
            pos_local = pos[:t_local]
        tokens = tokens + pos_local.astype(dtype)[None]

        block_cls = TransformerBlock
        if cfg.remat:
            block_cls = nn.remat(block_cls, static_argnums=(2,))
        mlp_dim = int(embed * cfg.mlp_ratio)
        for i in range(cfg.vit_layers):
            tokens = block_cls(
                embed,
                cfg.num_heads,
                mlp_dim,
                spatial_axis_name=self.spatial_axis_name,
                dtype=dtype,
                use_fused=cfg.use_fused_attention,
                name=f"block{i + 1}",
            )(tokens, train)

        tokens = nn.LayerNorm(dtype=dtype, name="ln_final")(tokens)
        pooled = jnp.mean(tokens.astype(jnp.float32), axis=1)
        if self.spatial_axis_name is not None:
            # equal-sized shards: the global token mean is the pmean of locals
            pooled = lax.pmean(pooled, self.spatial_axis_name)
        return nn.Dense(cfg.num_classes, name="logits")(pooled)


def pipeline_stage_fn(config: ModelConfig):
    """Stage function for GPipe pipeline parallelism over ViT blocks
    (parallel/pipeline.py): applies ONE TransformerBlock given its param tree.

    Takes the ``ModelConfig`` and derives embed width, MLP width, and compute
    dtype exactly as ``ViTClassifier.__call__`` does, so the pipelined blocks
    are numerically identical to the trained model's (a hand-passed dtype or
    width mismatch would diverge silently — params are float32 either way).

    ViT's repeated blocks are exactly the homogeneous-stage regime the pipeline
    runner targets (identical computation + param shapes per layer); pair with
    ``stack_vit_block_params`` to turn a trained ViT's variables into the
    stacked [K, ...] stage params the runner shards over the model axis."""
    embed = scaled_width(config.embed_dim, config.width_multiplier)
    dtype = jnp.bfloat16 if config.dtype == "bfloat16" else jnp.float32
    block = TransformerBlock(
        embed,
        config.num_heads,
        int(embed * config.mlp_ratio),
        dtype=dtype,
        use_fused=config.use_fused_attention,
    )

    def stage_fn(params, x):
        return block.apply({"params": params}, x, False)

    return stage_fn


def stack_vit_block_params(params, n_layers: int):
    """Stack a ViTClassifier's per-layer block params ([K, ...] leading stage
    axis) for the pipeline runner; layers must exist as ``block1..blockN``."""
    return stack_stage_params(
        [params[f"block{i + 1}"] for i in range(n_layers)]
    )
