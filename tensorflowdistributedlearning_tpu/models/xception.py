"""Xception-41 backbone as Flax modules (reference: core/xception.py).

The reference's Xception was dead code with three blocking defects (SURVEY §2.4.8-10):
the per-unit loop body was dedented so only one unit per block was ever built
(core/xception.py:272-275), the root block referenced an unimported ``resnet_utils``
(core/xception.py:352), and its batch-norm arg_scope covered only ``net = inputs``
(core/xception.py:345-346). This implementation is the working network those fragments
describe — the DeepLab Xception-41: every conv is followed by batch norm, all units are
built, and the root is two plain 3x3 convs (32 stride-2, then 64).

Structure (reference: core/xception.py:405-465):
  entry_flow:  block1 [128x3] conv-skip s2 | block2 [256x3] conv-skip s2 |
               block3 [728x3] conv-skip s2
  middle_flow: block1 [728x3] sum-skip s1 x 8 units
  exit_flow:   block1 [728,1024,1024] conv-skip s2 |
               block2 [1536,1536,2048] no-skip s1, activation inside separable convs,
               unit_rate_list = multi_grid
Atrous output_stride control mirrors the ResNet stacker but divides by the root's
stride of 2 (reference: core/xception.py:347-351).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from tensorflowdistributedlearning_tpu.config import ModelConfig
from tensorflowdistributedlearning_tpu.models.layers import (
    scaled_width,
    ConvBN,
    conv_kernel_init,
    fixed_padding,
    upsample,
)


class SeparableConvSame(nn.Module):
    """Depthwise + pointwise conv pair with BN after each, optional activation inside,
    and explicit-padding alignment for strides (reference: core/xception.py:39-128)."""

    features: int
    kernel_size: int = 3
    stride: int = 1
    rate: int = 1
    activation_inside: bool = False
    bn_decay: float = 0.99
    bn_epsilon: float = 0.001
    bn_scale: bool = True
    bn_axis_name: Optional[str] = None
    spatial_axis_name: Optional[str] = None
    dtype: Optional[jnp.dtype] = None

    def _bn(self, name: str, x: jax.Array, train: bool) -> jax.Array:
        return nn.BatchNorm(
            use_running_average=not train,
            momentum=self.bn_decay,
            epsilon=self.bn_epsilon,
            use_scale=self.bn_scale,
            axis_name=self.bn_axis_name,
            dtype=self.dtype,
            name=name,
        )(x)

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = False) -> jax.Array:
        in_ch = x.shape[-1]
        if self.spatial_axis_name is not None:
            # H-sharded depthwise: SpatialConv reproduces both padding phases
            # (SAME for stride 1, fixed_padding+VALID for strides) exactly
            from tensorflowdistributedlearning_tpu.models.layers import SpatialConv

            x = SpatialConv(
                in_ch,
                self.kernel_size,
                stride=self.stride,
                rate=self.rate,
                use_bias=False,
                axis_name=self.spatial_axis_name,
                feature_group_count=in_ch,
                phase="fixed" if self.stride > 1 else "same",
                kernel_init=nn.initializers.truncated_normal(stddev=0.33),
                dtype=self.dtype,
                name="depthwise",
            )(x)
        else:
            if self.stride > 1:
                x = fixed_padding(x, self.kernel_size, rate=self.rate)
                padding = "VALID"
            else:
                padding = "SAME"
            x = nn.Conv(
                in_ch,
                (self.kernel_size, self.kernel_size),
                strides=(self.stride, self.stride),
                kernel_dilation=(self.rate, self.rate),
                padding=padding,
                feature_group_count=in_ch,
                use_bias=False,
                kernel_init=nn.initializers.truncated_normal(stddev=0.33),
                dtype=self.dtype,
                name="depthwise",
            )(x)
        x = self._bn("depthwise_bn", x, train)
        if self.activation_inside:
            x = nn.relu(x)
        x = nn.Conv(
            self.features,
            (1, 1),
            use_bias=False,
            kernel_init=nn.initializers.truncated_normal(stddev=0.06),
            dtype=self.dtype,
            name="pointwise",
        )(x)
        x = self._bn("pointwise_bn", x, train)
        if self.activation_inside:
            x = nn.relu(x)
        return x


@dataclasses.dataclass(frozen=True)
class XceptionUnitSpec:
    depth_list: Tuple[int, int, int]
    skip_connection_type: str  # 'conv' | 'sum' | 'none'
    stride: int
    unit_rate_list: Tuple[int, int, int] = (1, 1, 1)
    activation_inside: bool = False


@dataclasses.dataclass(frozen=True)
class XceptionBlockSpec:
    name: str
    units: Tuple[XceptionUnitSpec, ...]


class XceptionUnit(nn.Module):
    """One Xception module: three pre-relu separable convs (stride on the third) plus a
    conv/sum/no shortcut (reference: core/xception.py:131-228)."""

    spec: XceptionUnitSpec
    rate: int = 1
    bn_decay: float = 0.99
    bn_epsilon: float = 0.001
    bn_scale: bool = True
    bn_axis_name: Optional[str] = None
    spatial_axis_name: Optional[str] = None
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = False) -> jax.Array:
        spec = self.spec
        common = dict(
            bn_decay=self.bn_decay,
            bn_epsilon=self.bn_epsilon,
            bn_scale=self.bn_scale,
            bn_axis_name=self.bn_axis_name,
            spatial_axis_name=self.spatial_axis_name,
            dtype=self.dtype,
        )
        residual = x
        for i in range(3):
            residual = nn.relu(residual)
            residual = SeparableConvSame(
                spec.depth_list[i],
                3,
                stride=spec.stride if i == 2 else 1,
                rate=self.rate * spec.unit_rate_list[i],
                activation_inside=spec.activation_inside,
                name=f"separable_conv{i + 1}",
                **common,
            )(residual, train)
        if spec.skip_connection_type == "conv":
            shortcut = nn.Conv(
                spec.depth_list[-1],
                (1, 1),
                strides=(spec.stride, spec.stride),
                use_bias=False,
                kernel_init=conv_kernel_init,
                dtype=self.dtype,
                name="shortcut",
            )(x)
            shortcut = nn.BatchNorm(
                use_running_average=not train,
                momentum=self.bn_decay,
                epsilon=self.bn_epsilon,
                use_scale=self.bn_scale,
                axis_name=self.bn_axis_name,
                dtype=self.dtype,
                name="shortcut_bn",
            )(shortcut)
            return residual + shortcut
        if spec.skip_connection_type == "sum":
            return residual + x
        if spec.skip_connection_type == "none":
            return residual
        raise ValueError("Unsupported skip connection type.")


def xception_41_block_specs(
    multi_grid: Tuple[int, int, int] = (1, 1, 1),
    width_multiplier: float = 1.0,
) -> Tuple[XceptionBlockSpec, ...]:
    """Xception-41 block table (reference: core/xception.py:405-465); widths
    scale by ``width_multiplier`` (1.0 = reference widths)."""
    def block(name, depths, skip, num_units, stride, rates=(1, 1, 1), act_inside=False):
        unit = XceptionUnitSpec(
            depth_list=tuple(scaled_width(d, width_multiplier) for d in depths),
            skip_connection_type=skip,
            stride=stride,
            unit_rate_list=tuple(rates),
            activation_inside=act_inside,
        )
        return XceptionBlockSpec(name, (unit,) * num_units)

    return (
        block("entry_block1", (128, 128, 128), "conv", 1, 2),
        block("entry_block2", (256, 256, 256), "conv", 1, 2),
        block("entry_block3", (728, 728, 728), "conv", 1, 2),
        block("middle_block1", (728, 728, 728), "sum", 8, 1),
        block("exit_block1", (728, 1024, 1024), "conv", 1, 2),
        block("exit_block2", (1536, 1536, 2048), "none", 1, 1, multi_grid, True),
    )


class XceptionBackbone(nn.Module):
    """Xception feature extractor with atrous output_stride control (reference:
    core/xception.py:295-364). Returns an end-point dict keyed by block name plus
    'root' and 'features'."""

    config: ModelConfig
    multi_grid: Tuple[int, int, int] = (1, 1, 1)
    bn_axis_name: Optional[str] = None
    spatial_axis_name: Optional[str] = None

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = False) -> Dict[str, jax.Array]:
        cfg = self.config
        dtype = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32
        x = x.astype(dtype)
        common = dict(
            bn_decay=cfg.batch_norm_decay,
            bn_epsilon=cfg.batch_norm_epsilon,
            bn_scale=cfg.batch_norm_scale,
            bn_axis_name=self.bn_axis_name,
            spatial_axis_name=self.spatial_axis_name,
            dtype=dtype,
        )
        output_stride = cfg.output_stride
        if output_stride is not None:
            if output_stride % 2 != 0:
                raise ValueError("The output_stride needs to be a multiple of 2.")
            # root conv1_1 strides by 2 (reference: core/xception.py:347-351)
            target_stride = output_stride // 2
        else:
            target_stride = None

        wm = cfg.width_multiplier
        end_points: Dict[str, jax.Array] = {}
        x = ConvBN(
            scaled_width(32, wm),
            3,
            stride=2,
            space_to_depth=cfg.stem_space_to_depth,
            name="conv1_1",
            **common,
        )(x, train)
        x = ConvBN(scaled_width(64, wm), 3, name="conv1_2", **common)(x, train)
        end_points["root"] = x

        current_stride = 1
        rate = 1
        for blk in xception_41_block_specs(self.multi_grid, cfg.width_multiplier):
            for i, unit in enumerate(blk.units):
                if target_stride is not None and current_stride == target_stride:
                    applied = dataclasses.replace(unit, stride=1)
                    unit_rate = rate
                    rate *= unit.stride
                else:
                    applied = unit
                    unit_rate = 1
                    current_stride *= unit.stride
                x = XceptionUnit(
                    spec=applied,
                    rate=unit_rate,
                    name=f"{blk.name}_unit{i + 1}",
                    **common,
                )(x, train)
            end_points[blk.name] = x
        if target_stride is not None and current_stride != target_stride:
            raise ValueError("The target output_stride cannot be reached.")
        end_points["features"] = x
        return end_points


class XceptionSegmentation(nn.Module):
    """Xception-41 + ASPP + decoder segmentation network — the DeepLabV3+
    arrangement the reference's (dead) Xception backbone was built for
    (reference: core/xception.py existed solely as a DeepLab backbone but was
    never wired to a head, SURVEY §2.4.8-10; the head layout follows the ResNet
    flagship, core/resnet.py:440-496). Skip connection comes from the stride-4
    entry_block1 features, the Xception analogue of the reference's block1 skip.
    Returns [B, H, W, 1] float32 logits at input resolution."""

    config: ModelConfig
    bn_axis_name: Optional[str] = None
    spatial_axis_name: Optional[str] = None

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = False) -> jax.Array:
        from tensorflowdistributedlearning_tpu.models.resnet import ASPP

        cfg = self.config
        dtype = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32
        common = dict(
            bn_decay=cfg.batch_norm_decay,
            bn_epsilon=cfg.batch_norm_epsilon,
            bn_scale=cfg.batch_norm_scale,
            bn_axis_name=self.bn_axis_name,
            dtype=dtype,
        )
        end_points = XceptionBackbone(
            cfg,
            multi_grid=(1, 2, 1),
            bn_axis_name=self.bn_axis_name,
            spatial_axis_name=self.spatial_axis_name,
            name="backbone",
        )(x, train)
        features = end_points["features"]
        skip = end_points["entry_block1"]
        if self.spatial_axis_name is not None:
            # backbone ran H-sharded; the head's bilinear upsamplings and the
            # per-image loss need whole maps (same arrangement as the ResNet
            # flagship, models/resnet.py)
            from tensorflowdistributedlearning_tpu.parallel.spatial import (
                spatial_gather,
            )

            features = spatial_gather(features, axis_name=self.spatial_axis_name)
            skip = spatial_gather(skip, axis_name=self.spatial_axis_name)
        aspp = ASPP(cfg, bn_axis_name=self.bn_axis_name, name="aspp")(
            features, train
        )
        aspp_up = upsample(aspp, skip.shape[1:3]).astype(dtype)
        decoder = ConvBN(cfg.base_depth, 1, name="decoder_conv_1x1", **common)(
            skip, train
        )
        decoder = jnp.concatenate([decoder, aspp_up], axis=-1)
        decoder = nn.Conv(
            1,
            (3, 3),
            padding="SAME",
            kernel_init=conv_kernel_init,
            dtype=dtype,
            name="decoder_conv_3x3",
        )(decoder)
        return upsample(decoder.astype(jnp.float32), cfg.input_shape)


# Pre-logits dropout keep probability (the reference declared keep_prob=0.5
# but never used it, core/xception.py:298). Single source for Xception41 AND
# the pipelined XceptionExitHead — the two strategies interchange checkpoints,
# so their train-mode dropout must never silently diverge.
DEFAULT_KEEP_PROB = 0.5


class Xception41(nn.Module):
    """Xception-41 classifier: backbone, global pool, pre-logits dropout (the
    reference declared ``keep_prob=0.5`` but never used it, core/xception.py:298),
    dense logits. With ``num_classes=None`` returns pooled features."""

    config: ModelConfig
    keep_prob: float = DEFAULT_KEEP_PROB
    bn_axis_name: Optional[str] = None
    spatial_axis_name: Optional[str] = None

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = False) -> jax.Array:
        cfg = self.config
        backbone_cfg = dataclasses.replace(cfg, output_stride=None)
        end_points = XceptionBackbone(
            backbone_cfg,
            bn_axis_name=self.bn_axis_name,
            spatial_axis_name=self.spatial_axis_name,
            name="backbone",
        )(x, train)
        if self.spatial_axis_name is not None:
            from tensorflowdistributedlearning_tpu.parallel.spatial import (
                spatial_global_mean,
            )

            pooled = spatial_global_mean(
                end_points["features"], axis_name=self.spatial_axis_name
            ).astype(jnp.float32)
        else:
            pooled = jnp.mean(end_points["features"], axis=(1, 2)).astype(jnp.float32)
        if cfg.num_classes is None:
            return pooled
        pooled = nn.Dropout(rate=1.0 - self.keep_prob, deterministic=not train)(pooled)
        return nn.Dense(cfg.num_classes, kernel_init=conv_kernel_init, name="logits")(
            pooled
        )


# ---------------------------------------------------------------------------
# Pipeline-parallel decomposition of the Xception-41 CLASSIFIER.
#
# The middle flow — 8 identical 728-wide sum-skip units, the documented
# homogeneous-stage case of the GPipe runner (parallel/pipeline.py) — pipelines
# over the model mesh axis; the entry flow (root + 3 conv-skip blocks) and the
# exit flow + head run replicated on every stage, mirroring how the ViT
# pipeline replicates embed/head (train/pipeline_step.py). The wrapper modules
# below reuse the SAME submodule classes under the SAME names as
# XceptionBackbone, so a canonical Xception41 param/batch-stats tree slices
# directly into them: checkpoints, serving export, and eval stay
# interchangeable with every other execution strategy.
# ---------------------------------------------------------------------------

MIDDLE_FLOW_UNITS = 8
MIDDLE_FLOW_PREFIX = "middle_block1_unit"


def _common_bn_kwargs(cfg: ModelConfig, dtype) -> dict:
    return dict(
        bn_decay=cfg.batch_norm_decay,
        bn_epsilon=cfg.batch_norm_epsilon,
        bn_scale=cfg.batch_norm_scale,
        bn_axis_name=None,
        spatial_axis_name=None,
        dtype=dtype,
    )


class XceptionEntryFlow(nn.Module):
    """Root convs + entry blocks 1-3 of the classifier layout (output_stride
    None), submodule names matching ``XceptionBackbone`` so the canonical
    ``params['backbone']`` subtree applies directly."""

    config: ModelConfig

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = False) -> jax.Array:
        cfg = self.config
        dtype = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32
        common = _common_bn_kwargs(cfg, dtype)
        wm = cfg.width_multiplier
        x = x.astype(dtype)
        x = ConvBN(
            scaled_width(32, wm),
            3,
            stride=2,
            space_to_depth=cfg.stem_space_to_depth,
            name="conv1_1",
            **common,
        )(x, train)
        x = ConvBN(scaled_width(64, wm), 3, name="conv1_2", **common)(x, train)
        for blk in xception_41_block_specs((1, 1, 1), wm)[:3]:
            for i, unit in enumerate(blk.units):
                x = XceptionUnit(
                    spec=unit, rate=1, name=f"{blk.name}_unit{i + 1}", **common
                )(x, train)
        return x


class XceptionExitHead(nn.Module):
    """Exit blocks 1-2 + global pool + dropout + logits dense, names matching
    the canonical tree (units from ``XceptionBackbone``, head from
    ``Xception41``); apply with the union of the backbone's exit-unit subtrees
    and the top-level ``logits`` params."""

    config: ModelConfig
    keep_prob: float = DEFAULT_KEEP_PROB

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = False) -> jax.Array:
        cfg = self.config
        dtype = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32
        common = _common_bn_kwargs(cfg, dtype)
        for blk in xception_41_block_specs((1, 1, 1), cfg.width_multiplier)[4:]:
            for i, unit in enumerate(blk.units):
                x = XceptionUnit(
                    spec=unit, rate=1, name=f"{blk.name}_unit{i + 1}", **common
                )(x, train)
        pooled = jnp.mean(x, axis=(1, 2)).astype(jnp.float32)
        pooled = nn.Dropout(rate=1.0 - self.keep_prob, deterministic=not train)(
            pooled
        )
        return nn.Dense(
            cfg.num_classes, kernel_init=conv_kernel_init, name="logits"
        )(pooled)


def middle_unit_module(config: ModelConfig) -> XceptionUnit:
    """One 728-wide sum-skip middle-flow unit (classifier layout: stride 1,
    rate 1) — identical computation and param shapes for all 8 units, the
    pipeline runner's homogeneous-stage requirement."""
    dtype = jnp.bfloat16 if config.dtype == "bfloat16" else jnp.float32
    wm = config.width_multiplier
    spec = XceptionUnitSpec(
        depth_list=tuple(scaled_width(d, wm) for d in (728, 728, 728)),
        skip_connection_type="sum",
        stride=1,
        unit_rate_list=(1, 1, 1),
        activation_inside=False,
    )
    return XceptionUnit(spec=spec, rate=1, **_common_bn_kwargs(config, dtype))


def stack_middle_unit_tree(backbone_tree, n_stages: int):
    """Stack the 8 middle-unit subtrees (params OR batch_stats — any tree
    keyed ``middle_block1_unit{1..8}``) into the grouped [K, 8/K, ...] form the
    pipeline shards over the model axis."""
    if MIDDLE_FLOW_UNITS % n_stages:
        raise ValueError(
            f"{MIDDLE_FLOW_UNITS} middle-flow units not divisible into "
            f"{n_stages} pipeline stages"
        )
    units = [
        backbone_tree[f"{MIDDLE_FLOW_PREFIX}{i + 1}"]
        for i in range(MIDDLE_FLOW_UNITS)
    ]
    stacked = jax.tree.map(lambda *ls: jnp.stack(ls), *units)
    group = MIDDLE_FLOW_UNITS // n_stages
    return jax.tree.map(
        lambda l: l.reshape((n_stages, group) + l.shape[1:]), stacked
    )


def unstack_middle_unit_tree(stacked_tree) -> dict:
    """Reverse ``stack_middle_unit_tree``: [K, G, ...] -> the canonical
    ``{middle_block1_unit{n}: subtree}`` dict."""
    flat = jax.tree.map(
        lambda l: l.reshape((MIDDLE_FLOW_UNITS,) + l.shape[2:]), stacked_tree
    )
    return {
        f"{MIDDLE_FLOW_PREFIX}{i + 1}": jax.tree.map(lambda l, i=i: l[i], flat)
        for i in range(MIDDLE_FLOW_UNITS)
    }


def grouped_middle_stage_fn(config: ModelConfig, units_per_stage: int, train: bool):
    """Stage function over the grouped stacking: applies this stage's
    ``units_per_stage`` consecutive middle units in sequence.

    Train form (for ``pipeline_apply_aux``): ``stage_fn((params_g, stats_g), x)
    -> (y, new_stats_g)`` — BN normalizes with the current microbatch's
    statistics (per-microbatch BN, the standard GPipe regime; exact parity with
    the plain step when microbatches share statistics) and emits the
    per-microbatch running-stat update for the runner to average.
    Eval form (for plain ``pipeline_apply``): same bundled params, running
    stats, no mutation."""
    module = middle_unit_module(config)

    def train_stage_fn(bundle, x):
        params_g, stats_g = bundle
        new_stats = []
        for i in range(units_per_stage):
            p = jax.tree.map(lambda l, i=i: l[i], params_g)
            s = jax.tree.map(lambda l, i=i: l[i], stats_g)
            x, mutated = module.apply(
                {"params": p, "batch_stats": s},
                x,
                True,
                mutable=["batch_stats"],
            )
            new_stats.append(mutated["batch_stats"])
        return x, jax.tree.map(lambda *ls: jnp.stack(ls), *new_stats)

    def eval_stage_fn(bundle, x):
        params_g, stats_g = bundle
        for i in range(units_per_stage):
            p = jax.tree.map(lambda l, i=i: l[i], params_g)
            s = jax.tree.map(lambda l, i=i: l[i], stats_g)
            x = module.apply({"params": p, "batch_stats": s}, x, False)
        return x

    return train_stage_fn if train else eval_stage_fn
