"""Shared layer library (reference: core/layers.py).

All layers are NHWC — on TPU, XLA chooses physical layouts, so the reference's
NCHW/NHWC dual-path plumbing (core/layers.py:53-109 carried transposes because
``tf.image`` is NHWC-only) collapses away; the public API still accepts NCHW at the
boundary (see train/trainer.py).
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

# slim's variance_scaling_initializer() defaults: factor=2.0, mode='FAN_IN', truncated
# normal — i.e. He init (reference: core/resnet.py:377 used it for every conv).
conv_kernel_init = nn.initializers.variance_scaling(2.0, "fan_in", "truncated_normal")


def scaled_width(channels: int, multiplier: float) -> int:
    """Stage width under ``ModelConfig.width_multiplier`` (>=1 channel); shared by
    both backbone families."""
    return max(1, int(round(channels * multiplier)))


def space_to_depth(x: jax.Array, block: int = 2) -> jax.Array:
    """[B, H, W, C] -> [B, H/block, W/block, block*block*C], channel order
    (dy, dx, c) — the TPU input transform for thin-channel stem convs: the MXU
    tiles the contracting (input-channel) dimension, so C=3 convs waste most of
    each tile; folding a 2x2 pixel block into channels quadruples the
    contraction depth at identical FLOPs (the standard MLPerf TPU ResNet
    trick)."""
    b, h, w, c = x.shape
    if h % block or w % block:
        raise ValueError(
            f"space_to_depth needs H, W divisible by {block}, got {h}x{w}"
        )
    x = x.reshape(b, h // block, block, w // block, block, c)
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(
        b, h // block, w // block, block * block * c
    )


class SpaceToDepthConv(nn.Module):
    """A 3x3 stride-2 SAME conv executed as a 2x2 stride-1 conv on the
    space-to-depth(2) transform of its input — numerically identical output,
    ~4x deeper MXU contraction for thin-channel stems.

    The parameter is the CANONICAL [3, 3, C_in, features] kernel (same name and
    shape as ``nn.Conv``), transformed at apply time, so checkpoints move
    freely between this and the plain conv path. Derivation: flax SAME with
    k=3, s=2, even H pads (0, 1), so out(i) covers input rows 2i..2i+2; pad the
    kernel to 4x4 at the high edge, split each spatial 4 as (block di, offset
    dy) with r = 2*di + dy, and fold (dy, dx, c) into the contraction to match
    ``space_to_depth`` channel order. The 2x2 conv then needs cells i..i+1 —
    explicit (0, 1) padding."""

    features: int
    kernel_init: Callable = conv_kernel_init
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        c = x.shape[-1]
        kernel = self.param("kernel", self.kernel_init, (3, 3, c, self.features))
        dtype = self.dtype or x.dtype
        k44 = jnp.pad(kernel, ((0, 1), (0, 1), (0, 0), (0, 0)))
        k2 = (
            k44.reshape(2, 2, 2, 2, c, self.features)
            .transpose(0, 2, 1, 3, 4, 5)
            .reshape(2, 2, 4 * c, self.features)
        )
        y = space_to_depth(x.astype(dtype), 2)
        return jax.lax.conv_general_dilated(
            y,
            k2.astype(dtype),
            window_strides=(1, 1),
            padding=((0, 1), (0, 1)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )


def fixed_padding(
    x: jax.Array, kernel_size: int, mode: str = "constant", rate: int = 1
) -> jax.Array:
    """Explicit spatial padding independent of input size (reference:
    core/layers.py:53-79; the rate-aware effective-kernel form is reference:
    core/xception.py:18-36). ``x`` is NHWC."""
    effective = kernel_size + (kernel_size - 1) * (rate - 1)
    pad_total = effective - 1
    pad_beg = pad_total // 2
    pad_end = pad_total - pad_beg
    if mode == "constant":
        return jnp.pad(
            x, [(0, 0), (pad_beg, pad_end), (pad_beg, pad_end), (0, 0)]
        )
    # symmetric/reflect spelled as slice+flip+concat on the SPATIAL axes only:
    # jnp.pad with these modes refuses a polymorphic batch dim even though its
    # padding is zero (jax <= 0.4.x shape-poly check), which broke jax.export
    # of any model containing upsample() — the whole segmentation family
    off = 0 if mode == "symmetric" else 1  # reflect skips the edge pixel
    for axis in (1, 2):
        size = x.shape[axis]
        parts = []
        if pad_beg:
            parts.append(
                jnp.flip(jax.lax.slice_in_dim(x, off, off + pad_beg, axis=axis), axis)
            )
        parts.append(x)
        if pad_end:
            parts.append(
                jnp.flip(
                    jax.lax.slice_in_dim(x, size - pad_end - off, size - off, axis=axis),
                    axis,
                )
            )
        x = jnp.concatenate(parts, axis=axis)
    return x


def subsample(x: jax.Array, stride: int) -> jax.Array:
    """Spatial subsampling by strided slicing — the effect of slim's
    ``resnet_utils.subsample`` (1x1 max-pool with stride) used for identity shortcuts
    (reference: core/resnet.py:76, 131)."""
    if stride == 1:
        return x
    return x[:, ::stride, ::stride, :]


def upsample(x: jax.Array, out_hw: Tuple[int, int]) -> jax.Array:
    """Bilinear upsampling with symmetric edge padding (reference: core/layers.py:83-109).

    The reference padded 1 px SYMMETRIC on each side, resized to (h+4, w+4) and trimmed
    2 px per side so interpolation never reads a zero halo. Same scheme here with
    ``jax.image.resize``; no layout transposes are needed on TPU. (The reference also
    read ``out_shape`` as (width, height) — harmless there because every call site was
    square; here the contract is unambiguously (height, width).)
    """
    h, w = int(out_hw[0]), int(out_hw[1])
    x = fixed_padding(x, 3, mode="symmetric")
    n, _, _, c = x.shape
    x = jax.image.resize(x, (n, h + 4, w + 4, c), method="bilinear")
    return x[:, 2:-2, 2:-2, :]


class SpatialConv(nn.Module):
    """``nn.Conv``-parameter-compatible conv whose H dimension is sharded over
    a mesh axis (sequence/context parallelism): halo exchange + phase-exact VALID
    convolution (parallel/spatial.py). Param tree is identical to ``nn.Conv``
    (``kernel`` [kh, kw, C_in/groups, C_out], optional ``bias`` [C_out]), so
    checkpoints transfer between sharded and unsharded execution unchanged.
    ``feature_group_count=C`` gives the depthwise flavor (Xception separable
    convs); ``phase='fixed'`` matches slim's fixed_padding+VALID strided convs.
    """

    features: int
    kernel_size: int = 3
    stride: int = 1
    rate: int = 1
    use_bias: bool = True
    axis_name: str = "sequence"
    feature_group_count: int = 1
    phase: str = "same"
    kernel_init: Callable = conv_kernel_init
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        from tensorflowdistributedlearning_tpu.parallel.spatial import spatial_conv2d

        k = self.kernel_size
        kernel = self.param(
            "kernel",
            self.kernel_init,
            (k, k, x.shape[-1] // self.feature_group_count, self.features),
        )
        dtype = self.dtype or x.dtype
        out = spatial_conv2d(
            x.astype(dtype),
            kernel.astype(dtype),
            stride=self.stride,
            rate=self.rate,
            axis_name=self.axis_name,
            feature_group_count=self.feature_group_count,
            phase=self.phase,
        )
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros, (self.features,))
            out = out + bias.astype(dtype)
        return out


class ConvBN(nn.Module):
    """Conv2D + BatchNorm + activation, the slim ``conv2d`` arg_scope default
    (reference: core/resnet.py:378-383: conv with He init, BN normalizer, relu).
    With ``use_bn=False`` it is a plain conv with bias and no activation — the
    shortcut/final-projection flavor (reference: core/resnet.py:78-80, 147-149).

    ``spatial_axis_name`` routes kernels > 1x1 through the halo-exchange
    ``SpatialConv`` for H-sharded (sequence-parallel) execution; 1x1 kernels are
    pointwise and need no halo, so ``nn.Conv`` serves them in either mode.
    """

    features: int
    kernel_size: int = 3
    stride: int = 1
    rate: int = 1
    use_bn: bool = True
    activation: Optional[Callable[[jax.Array], jax.Array]] = nn.relu
    bn_decay: float = 0.99
    bn_epsilon: float = 0.001
    bn_scale: bool = True
    bn_axis_name: Optional[str] = None
    spatial_axis_name: Optional[str] = None
    space_to_depth: bool = False
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = False) -> jax.Array:
        if self.space_to_depth:
            if self.kernel_size != 3 or self.stride != 2 or self.rate != 1:
                raise ValueError(
                    "space_to_depth implements exactly the 3x3 stride-2 rate-1 "
                    f"stem conv; got kernel_size={self.kernel_size}, "
                    f"stride={self.stride}, rate={self.rate}"
                )
            if self.spatial_axis_name is not None:
                raise ValueError(
                    "space_to_depth reshapes H into channels and cannot compose "
                    "with an H-sharded (sequence-parallel) conv"
                )
            if not self.use_bn:
                raise ValueError(
                    "space_to_depth supports the BN stem form only "
                    "(SpaceToDepthConv declares no bias parameter)"
                )
            x = SpaceToDepthConv(self.features, dtype=self.dtype, name="conv")(x)
        elif self.spatial_axis_name is not None and self.kernel_size > 1:
            x = SpatialConv(
                self.features,
                self.kernel_size,
                stride=self.stride,
                rate=self.rate,
                use_bias=not self.use_bn,
                axis_name=self.spatial_axis_name,
                dtype=self.dtype,
                name="conv",
            )(x)
        else:
            x = nn.Conv(
                self.features,
                (self.kernel_size, self.kernel_size),
                strides=(self.stride, self.stride),
                kernel_dilation=(self.rate, self.rate),
                padding="SAME",
                use_bias=not self.use_bn,
                kernel_init=conv_kernel_init,
                dtype=self.dtype,
                name="conv",
            )(x)
        if self.use_bn:
            x = nn.BatchNorm(
                use_running_average=not train,
                momentum=self.bn_decay,
                epsilon=self.bn_epsilon,
                use_scale=self.bn_scale,
                axis_name=self.bn_axis_name,
                dtype=self.dtype,
                name="bn",
            )(x)
        if self.activation is not None:
            x = self.activation(x)
        return x


def _pallas_platform_ok() -> bool:
    """Compiled-Pallas gate for the depthwise dispatch — delegates to the one
    shared decision (ops/pallas_kernels.pallas_platform_ok, also behind the
    kernel's interpret auto-select). Module-level indirection so tests can
    patch it and exercise the dispatch on the CPU mesh."""
    from tensorflowdistributedlearning_tpu.ops.pallas_kernels import (
        pallas_platform_ok,
    )

    return pallas_platform_ok()


class DepthwiseConv2D(nn.Module):
    """Stride-1 SAME depthwise conv with an optional Pallas fast path.

    Parameter tree matches ``nn.Conv(feature_group_count=C)`` — ``kernel``
    [kh, kw, 1, C] and ``bias`` [C] — so the two execution paths share checkpoints.
    ``use_pallas=True`` routes through the VMEM shift-accumulate kernel
    (ops/pallas_kernels.py); False uses XLA's grouped convolution.
    """

    kernel_size: int = 3
    rate: int = 1
    use_pallas: bool = False
    kernel_init: Callable = nn.initializers.truncated_normal(stddev=0.33)
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        c = x.shape[-1]
        k = self.kernel_size
        if k % 2 != 1:
            # both execution paths assume symmetric SAME padding; fail loudly and
            # identically rather than silently shrinking the output (XLA path) or
            # erroring deep in the kernel (Pallas path)
            raise ValueError(f"DepthwiseConv2D requires an odd kernel_size, got {k}")
        kernel = self.param("kernel", self.kernel_init, (k, k, 1, c))
        bias = self.param("bias", nn.initializers.zeros, (c,))
        dtype = self.dtype or x.dtype
        x = x.astype(dtype)
        from tensorflowdistributedlearning_tpu.ops.pallas_kernels import (
            PALLAS_DEPTHWISE_MIN_RATE,
            depthwise_conv2d,
            depthwise_conv2d_reference,
        )

        # rate-aware, PLATFORM-aware dispatch. Two levels of v5e evidence
        # (2026-08-01): per-kernel, Pallas wins every atrous rate
        # (1.46-1.61x, see PALLAS_DEPTHWISE_MIN_RATE); step-level, XLA's
        # depthwise+BN+ReLU fusion beats the custom call in the real
        # flagship step — which is why use_pallas_depthwise defaults False
        # (config.py). The gate machinery stays for opt-in unfused
        # contexts. TPU-only either way: elsewhere (the CPU test mesh)
        # Pallas runs in the slow interpreter and degrades to XLA.
        dw = (
            depthwise_conv2d
            if (
                self.use_pallas
                and self.rate >= PALLAS_DEPTHWISE_MIN_RATE
                and _pallas_platform_ok()
            )
            else depthwise_conv2d_reference
        )
        out = dw(x, kernel[:, :, 0, :].astype(dtype), self.rate)
        return out + bias.astype(dtype)


class SplitSeparableConv2D(nn.Module):
    """Separable conv split into depthwise and pointwise with an activation between
    (reference: core/layers.py:7-49 — it differs from fused separable conv exactly in
    that intermediate activation). The depthwise kernel uses truncated-normal
    stddev 0.33 and the pointwise stddev 0.06, as in the reference; the pointwise conv
    carries BN + relu (it lowered to slim.conv2d under the resnet arg_scope), the
    depthwise carries plain relu (slim.separable_conv2d defaults).
    """

    features: int
    kernel_size: int = 3
    rate: int = 1
    bn_decay: float = 0.99
    bn_epsilon: float = 0.001
    bn_scale: bool = True
    bn_axis_name: Optional[str] = None
    use_pallas: bool = False
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = False) -> jax.Array:
        x = DepthwiseConv2D(
            kernel_size=self.kernel_size,
            rate=self.rate,
            use_pallas=self.use_pallas,
            dtype=self.dtype,
            name="depthwise",
        )(x)
        x = nn.relu(x)
        x = nn.Conv(
            self.features,
            (1, 1),
            use_bias=False,
            kernel_init=nn.initializers.truncated_normal(stddev=0.06),
            dtype=self.dtype,
            name="pointwise",
        )(x)
        x = nn.BatchNorm(
            use_running_average=not train,
            momentum=self.bn_decay,
            epsilon=self.bn_epsilon,
            use_scale=self.bn_scale,
            axis_name=self.bn_axis_name,
            dtype=self.dtype,
            name="pointwise_bn",
        )(x)
        return nn.relu(x)
