"""ResNet-v2 "beta" backbone with DeepLabV3+-style segmentation head and a
classification head, as Flax modules (reference: core/resnet.py).

Re-design notes (TPU-first, not a translation):

- The reference threaded slim arg_scopes and a TF collection of end_points through the
  graph (core/resnet.py:225-257); here blocks are explicit modules and the backbone
  returns an end-point dict.
- The reference computed strided units as full-resolution conv followed by subsampling
  (core/resnet.py:85-87, 139-141); here the stride is fused into the conv — the same
  function family at 1/stride^2 of the FLOPs, which matters on the MXU.
- slim's atrous bookkeeping (``stack_blocks_dense`` with ``output_stride``, reference:
  core/resnet.py:244) is reproduced as a static Python loop: once the target stride is
  reached, further strides convert to accumulating dilation rates.
- The reference's ``block2`` used base_depth=258 — a typo for 256 that breaks
  power-of-two channel sizes (SURVEY §2.4.6); 256 is used here. Its ``output_stride /= 4``
  outside the None-guard (core/resnet.py:239, TypeError when None) is fixed by treating
  None as "no atrous" (standard stride-32 net, used by the classification path).
- The decoder upsampled ASPP output to a hard-coded (26, 26) and looked up the skip
  tensor by a scope-name string (core/resnet.py:474-480); here the skip's actual spatial
  shape is used, so any input size works.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Dict, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from tensorflowdistributedlearning_tpu.config import ModelConfig
from tensorflowdistributedlearning_tpu.models.layers import (
    scaled_width,
    ConvBN,
    SplitSeparableConv2D,
    conv_kernel_init,
    subsample,
    upsample,
)

# Reference: core/resnet.py:14 (_DEFAULT_MULTI_GRID = [2, 2, 2]); resnet_model passes
# (1, 2, 1) for the segmentation net (core/resnet.py:435).
DEFAULT_MULTI_GRID = (2, 2, 2)
SEGMENTATION_MULTI_GRID = (1, 2, 1)


@dataclasses.dataclass(frozen=True)
class UnitSpec:
    depth: int
    depth_bottleneck: int
    stride: int
    unit_rate: int = 1


@dataclasses.dataclass(frozen=True)
class BlockSpec:
    name: str
    units: Tuple[UnitSpec, ...]


def resnet_block_specs(
    n_blocks: Tuple[int, ...],
    multi_grid: Tuple[int, int, int] = SEGMENTATION_MULTI_GRID,
    width_multiplier: float = 1.0,
) -> Tuple[BlockSpec, ...]:
    """Block layout of the reference's ``resnet_v2`` (core/resnet.py:330-344):
    three stages with the stride-2 unit LAST (v2-beta convention), then an atrous
    multi-grid stage of three units (depth 1024 / bottleneck 256 / stride 1).
    All widths scale by ``width_multiplier`` (1.0 = reference widths).
    """
    if len(n_blocks) != 3:
        raise ValueError("Expect n_blocks to have length 3.")
    if len(multi_grid) != 3:
        raise ValueError("Expect multi_grid to have length 3.")

    def w(c: int) -> int:
        return scaled_width(c, width_multiplier)

    def stage(name: str, base_depth: int, num_units: int) -> BlockSpec:
        units = tuple(
            UnitSpec(depth=w(base_depth * 4), depth_bottleneck=w(base_depth), stride=1)
            for _ in range(num_units - 1)
        ) + (
            UnitSpec(depth=w(base_depth * 4), depth_bottleneck=w(base_depth), stride=2),
        )
        return BlockSpec(name, units)

    block4 = BlockSpec(
        "block4",
        tuple(
            UnitSpec(depth=w(1024), depth_bottleneck=w(256), stride=1, unit_rate=r)
            for r in multi_grid
        ),
    )
    return (
        stage("block1", 128, n_blocks[0]),
        stage("block2", 256, n_blocks[1]),  # reference had 258, a typo (SURVEY §2.4.6)
        stage("block3", 512, n_blocks[2]),
        block4,
    )


def classic_block_specs(
    n_blocks: Tuple[int, ...],
    width_multiplier: float = 1.0,
) -> Tuple[BlockSpec, ...]:
    """Standard ResNet-50/101/152 stage ladder: four stages at bottleneck widths
    64/128/256/512 (outputs 256/512/1024/2048), stride-2 unit LAST per the
    family's v2-beta convention, final stage unstrided — overall stride 32 with
    the root's 4. This is the published architecture ImageNet numbers quote
    (``n_blocks=(3, 4, 6, 3)`` = ResNet-50); the reference's own layout
    (``resnet_block_specs``) runs ~3x these FLOPs (doubled widths + the
    1024-wide atrous stage, reference: core/resnet.py:330-344)."""
    if len(n_blocks) != 4:
        raise ValueError("classic layout expects n_blocks of length 4, e.g. (3, 4, 6, 3)")

    def w(c: int) -> int:
        return scaled_width(c, width_multiplier)

    specs = []
    for name, base, num_units, last_stride in zip(
        ("block1", "block2", "block3", "block4"),
        (64, 128, 256, 512),
        n_blocks,
        (2, 2, 2, 1),
    ):
        units = tuple(
            UnitSpec(depth=w(base * 4), depth_bottleneck=w(base), stride=1)
            for _ in range(num_units - 1)
        ) + (
            UnitSpec(depth=w(base * 4), depth_bottleneck=w(base), stride=last_stride),
        )
        specs.append(BlockSpec(name, units))
    return tuple(specs)


class BottleneckUnit(nn.Module):
    """Pre-activation bottleneck residual unit (reference: core/resnet.py:94-152).

    preact BN+relu -> 1x1 reduce (BN+relu) -> 3x3 atrous (BN+relu, stride fused) ->
    1x1 expand (plain, bias) ; shortcut = identity subsample or plain 1x1 conv of the
    preactivation; output = relu(shortcut + residual).

    Returns (output, residual) — the residual branch pre-addition is what the decoder
    taps as its skip (reference: core/resnet.py:476-480 fetched the conv3 end point).
    """

    spec: UnitSpec
    rate: int = 1
    bn_decay: float = 0.99
    bn_epsilon: float = 0.001
    bn_scale: bool = True
    bn_axis_name: Optional[str] = None
    spatial_axis_name: Optional[str] = None
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = False):
        spec = self.spec
        depth_in = x.shape[-1]
        preact = nn.relu(
            nn.BatchNorm(
                use_running_average=not train,
                momentum=self.bn_decay,
                epsilon=self.bn_epsilon,
                use_scale=self.bn_scale,
                axis_name=self.bn_axis_name,
                dtype=self.dtype,
                name="preact",
            )(x)
        )
        if spec.depth == depth_in:
            shortcut = subsample(x, spec.stride)
        else:
            shortcut = nn.Conv(
                spec.depth,
                (1, 1),
                strides=(spec.stride, spec.stride),
                kernel_init=conv_kernel_init,
                dtype=self.dtype,
                name="shortcut",
            )(preact)
        common = dict(
            bn_decay=self.bn_decay,
            bn_epsilon=self.bn_epsilon,
            bn_scale=self.bn_scale,
            bn_axis_name=self.bn_axis_name,
            spatial_axis_name=self.spatial_axis_name,
            dtype=self.dtype,
        )
        residual = ConvBN(spec.depth_bottleneck, 1, 1, name="conv1", **common)(
            preact, train
        )
        residual = ConvBN(
            spec.depth_bottleneck,
            3,
            stride=spec.stride,
            rate=self.rate * spec.unit_rate,
            name="conv2",
            **common,
        )(residual, train)
        residual = nn.Conv(
            spec.depth,
            (1, 1),
            kernel_init=conv_kernel_init,
            dtype=self.dtype,
            name="conv3",
        )(residual)
        return nn.relu(shortcut + residual), residual


class BasicBlockUnit(nn.Module):
    """Pre-activation basic (two-conv) residual unit (reference: core/resnet.py:57-91).
    Output width is ``depth_bottleneck`` — the reference's basic block ignored ``depth``
    for the residual path and shortcut alike."""

    spec: UnitSpec
    rate: int = 1
    bn_decay: float = 0.99
    bn_epsilon: float = 0.001
    bn_scale: bool = True
    bn_axis_name: Optional[str] = None
    spatial_axis_name: Optional[str] = None
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = False):
        spec = self.spec
        depth_in = x.shape[-1]
        preact = nn.relu(
            nn.BatchNorm(
                use_running_average=not train,
                momentum=self.bn_decay,
                epsilon=self.bn_epsilon,
                use_scale=self.bn_scale,
                axis_name=self.bn_axis_name,
                dtype=self.dtype,
                name="preact",
            )(x)
        )
        if spec.depth_bottleneck == depth_in:
            shortcut = subsample(x, spec.stride)
        else:
            shortcut = nn.Conv(
                spec.depth_bottleneck,
                (1, 1),
                strides=(spec.stride, spec.stride),
                kernel_init=conv_kernel_init,
                dtype=self.dtype,
                name="shortcut",
            )(preact)
        residual = ConvBN(
            spec.depth_bottleneck,
            3,
            stride=spec.stride,
            bn_decay=self.bn_decay,
            bn_epsilon=self.bn_epsilon,
            bn_scale=self.bn_scale,
            bn_axis_name=self.bn_axis_name,
            spatial_axis_name=self.spatial_axis_name,
            dtype=self.dtype,
            name="conv1",
        )(preact, train)
        if self.spatial_axis_name is not None:
            from tensorflowdistributedlearning_tpu.models.layers import SpatialConv

            residual = SpatialConv(
                spec.depth_bottleneck,
                3,
                rate=self.rate * spec.unit_rate,
                axis_name=self.spatial_axis_name,
                dtype=self.dtype,
                name="conv2",
            )(residual)
        else:
            residual = nn.Conv(
                spec.depth_bottleneck,
                (3, 3),
                kernel_dilation=(self.rate * spec.unit_rate,) * 2,
                padding="SAME",
                kernel_init=conv_kernel_init,
                dtype=self.dtype,
                name="conv2",
            )(residual)
        return nn.relu(shortcut + residual), residual


class ResNetBackbone(nn.Module):
    """ResNet-v2-beta feature extractor (reference: core/resnet.py:171-257).

    Root: three 3x3 convs (64/64/128, first stride 2) replacing the classic 7x7
    (reference: core/resnet.py:155-168), SAME max-pool, post-norm BN+relu; then the four
    residual stages with atrous output_stride control. Returns an end-point dict with
    'root', each 'block{i}', 'block1_unit1_residual' (decoder skip), and 'features'.
    """

    config: ModelConfig
    multi_grid: Tuple[int, int, int] = SEGMENTATION_MULTI_GRID
    bn_axis_name: Optional[str] = None
    spatial_axis_name: Optional[str] = None

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = False) -> Dict[str, jax.Array]:
        cfg = self.config
        dtype = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32
        x = x.astype(dtype)
        common = dict(
            bn_decay=cfg.batch_norm_decay,
            bn_epsilon=cfg.batch_norm_epsilon,
            bn_scale=cfg.batch_norm_scale,
            bn_axis_name=self.bn_axis_name,
            spatial_axis_name=self.spatial_axis_name,
            dtype=dtype,
        )

        output_stride = cfg.output_stride
        if output_stride is not None:
            if output_stride % 4 != 0:
                raise ValueError("The output_stride needs to be a multiple of 4.")
            # the root block already strides by 4 (reference: core/resnet.py:236-239,
            # with the /=4-outside-the-guard defect fixed)
            target_stride = output_stride // 4
        else:
            target_stride = None

        end_points: Dict[str, jax.Array] = {}
        wm = cfg.width_multiplier
        # root (reference: core/resnet.py:155-168, 241-242)
        x = ConvBN(
            scaled_width(64, wm),
            3,
            stride=2,
            space_to_depth=cfg.stem_space_to_depth,
            name="conv1_1",
            **common,
        )(x, train)
        x = ConvBN(scaled_width(64, wm), 3, name="conv1_2", **common)(x, train)
        x = ConvBN(scaled_width(128, wm), 3, name="conv1_3", **common)(x, train)
        if self.spatial_axis_name is not None:
            from tensorflowdistributedlearning_tpu.parallel.spatial import (
                spatial_max_pool,
            )

            x = spatial_max_pool(
                x, window=3, stride=2, axis_name=self.spatial_axis_name
            )
        else:
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        x = nn.relu(
            nn.BatchNorm(
                use_running_average=not train,
                momentum=cfg.batch_norm_decay,
                epsilon=cfg.batch_norm_epsilon,
                use_scale=cfg.batch_norm_scale,
                axis_name=self.bn_axis_name,
                dtype=dtype,
                name="postnorm",
            )(x)
        )
        end_points["root"] = x

        unit_cls = BasicBlockUnit if cfg.block_type == "basic_block" else BottleneckUnit
        if cfg.remat:
            # rematerialize each residual unit on the backward pass: activations are
            # recomputed instead of stored, trading MXU FLOPs for HBM — the knob the
            # large-batch pod configs rely on (a TPU-first capability; the reference
            # had no memory-saving story). `train` is static (BN mode selection).
            unit_cls = nn.remat(unit_cls, static_argnums=(2,))
        if cfg.block_layout == "classic":
            blocks = classic_block_specs(cfg.n_blocks, wm)
        else:
            blocks = resnet_block_specs(cfg.n_blocks, self.multi_grid, wm)

        # slim stack_blocks_dense semantics (reference: core/resnet.py:244): strides
        # apply until the target stride is hit, after which they accumulate into rates.
        current_stride = 1
        rate = 1
        for block in blocks:
            for i, unit in enumerate(block.units):
                if target_stride is not None and current_stride == target_stride:
                    applied = dataclasses.replace(unit, stride=1)
                    unit_rate_accum = rate
                    rate *= unit.stride
                else:
                    applied = unit
                    unit_rate_accum = 1
                    current_stride *= unit.stride
                x, residual = unit_cls(
                    spec=applied,
                    rate=unit_rate_accum,
                    name=f"{block.name}_unit{i + 1}",
                    **common,
                )(x, train)
                if block.name == "block1" and i == 0:
                    end_points["block1_unit1_residual"] = residual
            end_points[block.name] = x
        if target_stride is not None and current_stride != target_stride:
            raise ValueError("output_stride is unreachable with this block layout.")
        end_points["features"] = x
        return end_points


class ASPP(nn.Module):
    """Atrous spatial pyramid pooling head (reference: core/resnet.py:440-472):
    1x1 conv, three split-separable atrous convs at rates 2/4/8, and a global-pool
    branch upsampled back, concatenated and fused by a 1x1 conv."""

    config: ModelConfig
    bn_axis_name: Optional[str] = None

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = False) -> jax.Array:
        cfg = self.config
        dtype = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32
        common = dict(
            bn_decay=cfg.batch_norm_decay,
            bn_epsilon=cfg.batch_norm_epsilon,
            bn_scale=cfg.batch_norm_scale,
            bn_axis_name=self.bn_axis_name,
            dtype=dtype,
        )
        depth = cfg.base_depth
        out_size = x.shape[1:3]
        sep = dict(common, use_pallas=cfg.use_pallas_depthwise)
        a1 = ConvBN(depth, 1, name="conv_1x1", **common)(x, train)
        a2 = SplitSeparableConv2D(depth, 3, rate=2, name="conv_3x3_1", **sep)(x, train)
        a3 = SplitSeparableConv2D(depth, 3, rate=4, name="conv_3x3_2", **sep)(x, train)
        a4 = SplitSeparableConv2D(depth, 3, rate=8, name="conv_3x3_3", **sep)(x, train)
        pooled = jnp.mean(x, axis=(1, 2), keepdims=True)
        pooled = ConvBN(depth, 1, name="pool_conv_1x1", **common)(pooled, train)
        a5 = upsample(pooled, out_size).astype(dtype)
        cat = jnp.concatenate([a1, a2, a3, a4, a5], axis=-1)
        return ConvBN(depth, 1, name="project", **common)(cat, train)


def deeplab_head(
    cfg: ModelConfig,
    bn_axis_name: Optional[str],
    features: jax.Array,
    skip: jax.Array,
    train: bool,
) -> jax.Array:
    """Shared DeepLabV3+ head: ASPP over the backbone features, upsample to the
    skip resolution, 1x1-projected skip concat, 3x3 fuse to one channel, bilinear
    upsample to input resolution in float32 (reference: core/resnet.py:440-496 —
    with the hard-coded (26, 26) generalized to the skip tensor's actual shape,
    SURVEY §2.4.7). MUST be called inside a module's compact ``__call__`` so the
    submodules bind to that module's parameter scope; both segmentation networks
    (ResNet, Xception) use it, keeping their heads structurally identical.
    """
    dtype = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32
    common = dict(
        bn_decay=cfg.batch_norm_decay,
        bn_epsilon=cfg.batch_norm_epsilon,
        bn_scale=cfg.batch_norm_scale,
        bn_axis_name=bn_axis_name,
        dtype=dtype,
    )
    aspp = ASPP(cfg, bn_axis_name=bn_axis_name, name="aspp")(features, train)
    aspp_up = upsample(aspp, skip.shape[1:3]).astype(dtype)
    decoder = ConvBN(cfg.base_depth, 1, name="decoder_conv_1x1", **common)(skip, train)
    decoder = jnp.concatenate([decoder, aspp_up], axis=-1)
    decoder = nn.Conv(
        1,
        (3, 3),
        padding="SAME",
        kernel_init=conv_kernel_init,
        dtype=dtype,
        name="decoder_conv_3x3",
    )(decoder)
    return upsample(decoder.astype(jnp.float32), cfg.input_shape)


class ResNetSegmentation(nn.Module):
    """Full segmentation network: backbone + ASPP + decoder with block1 skip, producing
    per-pixel logits at input resolution (reference: core/resnet.py:398-496). Logits are
    returned in float32 regardless of compute dtype."""

    config: ModelConfig
    bn_axis_name: Optional[str] = None
    spatial_axis_name: Optional[str] = None

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = False) -> jax.Array:
        cfg = self.config
        end_points = ResNetBackbone(
            cfg, multi_grid=SEGMENTATION_MULTI_GRID, bn_axis_name=self.bn_axis_name,
            spatial_axis_name=self.spatial_axis_name,
            name="backbone",
        )(x, train)
        features = end_points["features"]
        skip = end_points["block1_unit1_residual"]
        if self.spatial_axis_name is not None:
            # the backbone (where the FLOPs live) ran H-sharded; the head's bilinear
            # upsamplings and the per-image loss need whole maps, so reassemble here
            # (one all-gather per tensor over the sequence axis)
            from tensorflowdistributedlearning_tpu.parallel.spatial import (
                spatial_gather,
            )

            features = spatial_gather(features, axis_name=self.spatial_axis_name)
            skip = spatial_gather(skip, axis_name=self.spatial_axis_name)
        return deeplab_head(cfg, self.bn_axis_name, features, skip, train)


class ResNetClassifier(nn.Module):
    """Classification path (reference: core/resnet.py:246-256 kept global_pool +
    num_classes logits alongside the dense path). Uses output_stride=None semantics —
    all strides applied, overall stride 32. Returns [B, num_classes] float32 logits."""

    config: ModelConfig
    bn_axis_name: Optional[str] = None
    spatial_axis_name: Optional[str] = None

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = False) -> jax.Array:
        cfg = self.config
        if cfg.num_classes is None:
            raise ValueError("ResNetClassifier requires config.num_classes")
        backbone_cfg = dataclasses.replace(cfg, output_stride=None)
        end_points = ResNetBackbone(
            backbone_cfg,
            multi_grid=DEFAULT_MULTI_GRID,
            bn_axis_name=self.bn_axis_name,
            spatial_axis_name=self.spatial_axis_name,
            name="backbone",
        )(x, train)
        if self.spatial_axis_name is not None:
            from tensorflowdistributedlearning_tpu.parallel.spatial import (
                spatial_global_mean,
            )

            pooled = spatial_global_mean(
                end_points["features"], axis_name=self.spatial_axis_name
            )
        else:
            pooled = jnp.mean(end_points["features"], axis=(1, 2))
        logits = nn.Dense(
            cfg.num_classes,
            kernel_init=conv_kernel_init,
            name="logits",
        )(pooled.astype(jnp.float32))
        return logits


def build_model(
    config: ModelConfig,
    bn_axis_name: Optional[str] = None,
    spatial_axis_name: Optional[str] = None,
    expert_axis_name: Optional[str] = None,
) -> nn.Module:
    """Factory selecting backbone family and head from the config (the reference chose
    via ``resnet_model(...)`` arguments, model.py:356-370; Xception existed but was dead
    code — here it is a working first-class citizen).

    ``spatial_axis_name`` builds the model for H-sharded sequence-parallel
    execution inside ``shard_map`` (parallel/spatial.py); pair it with
    ``bn_axis_name`` on the same axis so BN statistics span the full spatial
    extent. Supported by both backbone families. ``expert_axis_name`` (ViT with
    ``moe_experts`` only) runs the MoE blocks expert-parallel: one expert per
    shard on that mesh axis with all-to-all dispatch (parallel/expert.py).

    Memoized: flax modules are immutable, and returning the SAME instance for the
    same arguments makes ``model.apply``/``model.init`` compare equal as jit
    statics, so compiled executables are shared across folds, Trainer instances,
    and tests (bound methods of two equal-but-distinct modules do NOT compare
    equal). The public wrapper normalizes positional/keyword call styles so every
    spelling shares one cache entry."""
    return _build_model_cached(
        config, bn_axis_name, spatial_axis_name, expert_axis_name
    )


@functools.lru_cache(maxsize=None)
def _build_model_cached(
    config: ModelConfig,
    bn_axis_name: Optional[str],
    spatial_axis_name: Optional[str],
    expert_axis_name: Optional[str],
) -> nn.Module:
    if config.backbone == "vit":
        from tensorflowdistributedlearning_tpu.models.vit import ViTClassifier

        return ViTClassifier(
            config,
            bn_axis_name=bn_axis_name,
            spatial_axis_name=spatial_axis_name,
            expert_axis_name=expert_axis_name,
        )
    if expert_axis_name is not None:
        raise ValueError(
            "expert_axis_name applies to backbone='vit' MoE models only"
        )
    if config.backbone == "resnet":
        if config.num_classes is None:
            return ResNetSegmentation(
                config,
                bn_axis_name=bn_axis_name,
                spatial_axis_name=spatial_axis_name,
            )
        return ResNetClassifier(
            config,
            bn_axis_name=bn_axis_name,
            spatial_axis_name=spatial_axis_name,
        )
    from tensorflowdistributedlearning_tpu.models.xception import (
        Xception41,
        XceptionSegmentation,
    )

    if config.num_classes is None:
        return XceptionSegmentation(
            config, bn_axis_name=bn_axis_name, spatial_axis_name=spatial_axis_name
        )
    return Xception41(
        config, bn_axis_name=bn_axis_name, spatial_axis_name=spatial_axis_name
    )
