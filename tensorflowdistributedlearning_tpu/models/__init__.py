from tensorflowdistributedlearning_tpu.models.layers import (
    SplitSeparableConv2D,
    fixed_padding,
    subsample,
    upsample,
)
from tensorflowdistributedlearning_tpu.models.resnet import (
    ResNetBackbone,
    ResNetClassifier,
    ResNetSegmentation,
    build_model,
)
from tensorflowdistributedlearning_tpu.models.vit import (
    TransformerBlock,
    ViTClassifier,
    pipeline_stage_fn,
    stack_vit_block_params,
)
from tensorflowdistributedlearning_tpu.models.xception import (
    Xception41,
    XceptionBackbone,
    XceptionSegmentation,
)

__all__ = [
    "SplitSeparableConv2D",
    "fixed_padding",
    "subsample",
    "upsample",
    "ResNetBackbone",
    "ResNetClassifier",
    "ResNetSegmentation",
    "build_model",
    "TransformerBlock",
    "ViTClassifier",
    "pipeline_stage_fn",
    "stack_vit_block_params",
    "Xception41",
    "XceptionBackbone",
    "XceptionSegmentation",
]
