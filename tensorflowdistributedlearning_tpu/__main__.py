from tensorflowdistributedlearning_tpu.cli import main

raise SystemExit(main())
