"""Streaming on-disk classification pipeline (ImageFolder layout).

The reference kept a working classification head in its backbone
(reference: core/resnet.py:246-256) but no classification input pipeline or driver —
its only data path was the TGS-salt segmentation layout. The ImageNet/CIFAR presets
(BASELINE.json's config ladder) need one, and at ImageNet scale "decode the whole
dataset into RAM" (data/pipeline.py InMemoryDataset) is not an option. This module
streams instead:

- the file list (not pixel data) is what lives in memory: ``{root}/{split}/{class}/
  {id}.{png|jpg|jpeg}``, the standard ImageFolder layout, scanned once;
- each process keeps only its round-robin shard of the file list (the per-host
  generalization of the reference's per-tower input_fn contract, model.py:156-159,
  298-299);
- batches decode on demand through the native multithreaded PNG decoder
  (native/io.cc; GIL-free, one thread per core) in the ``device_prefetch`` producer
  thread, so decode overlaps both the host->HBM copy and the device step;
- light host-side augmentation (random horizontal flip + optional padded random
  crop — the standard ImageNet-style recipe) on the decoded batch; heavier
  geometry stays on device for the segmentation task (data/augment.py).
"""

from __future__ import annotations

import os
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np


class ImageFolder:
    """A lazily-decoded labeled image dataset in ImageFolder layout.

    ``{root}/{class_name}/{id}.png`` — one directory per class, sorted class names
    map to label ids 0..K-1. Only paths and labels are held in memory.
    """

    def __init__(
        self,
        root: str,
        image_size: Tuple[int, int],
        channels: int = 3,
        paths: Optional[List[str]] = None,
        labels: Optional[np.ndarray] = None,
        class_names: Optional[List[str]] = None,
    ):
        self.root = root
        self.image_size = tuple(image_size)
        self.channels = channels
        if paths is None:
            class_names = sorted(
                d
                for d in os.listdir(root)
                if os.path.isdir(os.path.join(root, d))
            )
            if not class_names:
                raise ValueError(f"No class directories under {root}")
            exts = {".png", ".jpg", ".jpeg"}
            paths, labels_list = [], []
            for k, name in enumerate(class_names):
                class_dir = os.path.join(root, name)
                # one directory scan with case-normalized extension filtering:
                # no duplicate matches on case-insensitive filesystems, and
                # uppercase .JPG/.PNG/.JPEG (camera/ImageNet conventions) count
                files = sorted(
                    os.path.join(class_dir, f)
                    for f in os.listdir(class_dir)
                    if os.path.splitext(f)[1].lower() in exts
                )
                paths.extend(files)
                labels_list.extend([k] * len(files))
            if not paths:
                raise ValueError(
                    f"No .png/.jpg/.jpeg files under {root}/<class>/"
                )
            labels = np.asarray(labels_list, np.int32)
        self.paths = list(paths)
        self.labels = np.asarray(labels, np.int32)
        self.class_names = list(class_names or [])

    def __len__(self) -> int:
        return len(self.paths)

    @property
    def num_classes(self) -> int:
        return len(self.class_names) if self.class_names else int(self.labels.max()) + 1

    def shard(self, index: int, count: int) -> "ImageFolder":
        """Round-robin shard ``index`` of ``count`` (per-host data split)."""
        rows = np.arange(index, len(self.paths), count)
        return ImageFolder(
            self.root,
            self.image_size,
            self.channels,
            paths=[self.paths[i] for i in rows],
            labels=self.labels[rows],
            class_names=self.class_names,
        )

    def host_shard(self) -> "ImageFolder":
        import jax

        return self.shard(jax.process_index(), jax.process_count())

    def decode(self, rows: Sequence[int]) -> np.ndarray:
        """Decode the given rows to [n, H, W, C] float32 in [0, 1] via the native
        batch decoder (PNG/JPEG, any source size, bilinear resize to the target;
        PIL fallback inside)."""
        from tensorflowdistributedlearning_tpu.native import decode_image_batch

        h, w = self.image_size
        return decode_image_batch(
            [self.paths[i] for i in rows], h, w, channels=self.channels
        )


# ImageNet channel statistics (the classification analogue of the reference's
# grayscale MEAN/STD constants, preprocessing/preprocessing.py:7-8).
IMAGENET_MEAN = np.asarray([0.485, 0.456, 0.406], np.float32)
IMAGENET_STD = np.asarray([0.229, 0.224, 0.225], np.float32)


def _normalize(images: np.ndarray, channels: int) -> np.ndarray:
    if channels == 3:
        return (images - IMAGENET_MEAN) / IMAGENET_STD
    return (images - images.mean()) / max(images.std(), 1e-6)


def _augment(
    images: np.ndarray, rng: np.random.Generator, crop_padding: int
) -> np.ndarray:
    """Random horizontal flip + optional zero-padded random crop, per image."""
    n, h, w, _ = images.shape
    flip = rng.random(n) < 0.5
    images[flip] = images[flip, :, ::-1]
    if crop_padding > 0:
        p = crop_padding
        padded = np.pad(images, ((0, 0), (p, p), (p, p), (0, 0)), mode="reflect")
        ys = rng.integers(0, 2 * p + 1, n)
        xs = rng.integers(0, 2 * p + 1, n)
        images = np.stack(
            [padded[i, ys[i] : ys[i] + h, xs[i] : xs[i] + w] for i in range(n)]
        )
    return images


def train_batches(
    dataset: ImageFolder,
    batch_size: int,
    seed: int,
    steps: Optional[int] = None,
    augment: bool = True,
    crop_padding: int = 4,
) -> Iterator[Dict[str, np.ndarray]]:
    """Infinite (or ``steps``-bounded) shuffled {'images','labels'} stream, decoded
    per batch. Epoch permutations chain like data.pipeline.train_batches.

    ``augment=True`` (the default — library users get an augmented stream out of
    the box) applies the HOST-SIDE numpy flip/crop. The production path
    (train/fit.py) passes ``augment=False`` and runs the same recipe ON DEVICE
    instead (data/augment.py:augment_classification_batch); change the recipe in
    both places or not at all."""
    n = len(dataset)
    if n == 0:
        raise ValueError("Empty dataset")
    rng = np.random.default_rng(seed)
    order = rng.permutation(n)
    pos = 0
    emitted = 0
    while steps is None or emitted < steps:
        while len(order) - pos < batch_size:
            order = np.concatenate([order[pos:], rng.permutation(n)])
            pos = 0
        rows = order[pos : pos + batch_size]
        pos += batch_size
        emitted += 1
        images = dataset.decode(rows)
        if augment:
            images = _augment(images, rng, crop_padding)
        images = _normalize(images, dataset.channels)
        yield {"images": images, "labels": dataset.labels[rows]}


def eval_batches(
    dataset: ImageFolder,
    batch_size: int,
    num_batches: Optional[int] = None,
) -> Iterator[Dict[str, np.ndarray]]:
    """Ordered single pass, decoded per batch, under the shared
    ``pipeline.eval_index_batches`` padding contract (wrap-around pad rows,
    ``valid`` mask, forced multi-host step count, n=0 empty-shard edge)."""
    from tensorflowdistributedlearning_tpu.data.pipeline import eval_index_batches

    n = len(dataset)
    h, w = dataset.image_size
    for rows, valid in eval_index_batches(n, batch_size, num_batches):
        if n == 0:
            images = np.zeros((batch_size, h, w, dataset.channels), np.float32)
            labels = np.zeros(batch_size, np.int32)
        else:
            images = _normalize(dataset.decode(rows), dataset.channels)
            labels = dataset.labels[rows]
        yield {"images": images, "labels": labels, "valid": valid}


def write_synthetic_imagefolder(
    root: str,
    num_classes: int,
    per_class: int,
    image_size: Tuple[int, int],
    channels: int = 3,
    seed: int = 0,
) -> None:
    """Materialize a synthetic-but-learnable ImageFolder dataset as real PNGs
    (class-conditional brightness, the on-disk twin of
    data.synthetic.synthetic_classification_batch). Idempotent."""
    from PIL import Image

    rng = np.random.default_rng(seed)
    h, w = image_size
    for k in range(num_classes):
        d = os.path.join(root, f"class{k:03d}")
        os.makedirs(d, exist_ok=True)
        for i in range(per_class):
            path = os.path.join(d, f"im{i:04d}.png")
            if os.path.exists(path):
                continue
            base = (k + 0.5) / num_classes * 255.0
            arr = np.clip(
                rng.normal(base, 40.0, (h, w, channels)), 0, 255
            ).astype(np.uint8)
            img = Image.fromarray(arr[..., 0] if channels == 1 else arr)
            img.save(path)
