"""Kaggle TGS-salt driver helpers — the notebooks' data-prep cells as a library.

The reference's drivers loaded ``train.csv`` + ``depths.csv``, computed per-image
mask coverage, and binned it into 11 stratification classes fed to the K-fold split
(reference: Untitled.ipynb cells 2-6: ``cov_to_class``; SURVEY §2.1 C13). This module
reproduces that flow against the on-disk dataset layout, without requiring pandas
(the CSVs are two-column files).
"""

from __future__ import annotations

import csv
import os
from typing import Dict, List, Optional, Tuple

import numpy as np

from tensorflowdistributedlearning_tpu.data.folds import coverage_to_class
from tensorflowdistributedlearning_tpu.data.pipeline import discover_ids, mask_coverage


def read_two_column_csv(path: str) -> Dict[str, str]:
    """{first_column: second_column} for a headered CSV (train.csv id,rle_mask /
    depths.csv id,z). The open retries transient I/O failures
    (resilience/retry.py; injectable ``io-read`` fault site)."""
    from tensorflowdistributedlearning_tpu.resilience import faults
    import tensorflowdistributedlearning_tpu.resilience.retry as retry_lib

    def attempt():
        faults.fire(faults.SITE_IO)
        return open(path, newline="")

    out: Dict[str, str] = {}
    with retry_lib.call_with_retry(
        attempt, name="csv_open", exceptions=(OSError,)
    ) as f:
        reader = csv.reader(f)
        next(reader, None)  # header
        for row in reader:
            if row:
                out[row[0]] = row[1] if len(row) > 1 else ""
    return out


def load_depths(csv_path: str) -> Dict[str, float]:
    """id -> depth from depths.csv (the notebooks merged it for analysis)."""
    return {k: float(v) for k, v in read_two_column_csv(csv_path).items() if v}


def load_tgs_training_set(
    data_dir: str,
    train_csv: Optional[str] = None,
    n_classes: int = 11,
) -> Tuple[List[str], np.ndarray]:
    """(ids, stratification_classes) for ``Trainer.train`` — the notebooks' X and y.

    Ids come from ``train.csv`` when given (the Kaggle manifest), else from the
    images directory; classes are mask-coverage bins (``cov_to_class``,
    Untitled.ipynb cell 4) computed from the decoded masks.
    """
    if train_csv is not None:
        ids = sorted(read_two_column_csv(train_csv))
        missing = [
            i
            for i in ids
            if not os.path.exists(os.path.join(data_dir, "images", f"{i}.png"))
        ]
        if missing:
            raise FileNotFoundError(
                f"{len(missing)} ids from {train_csv} have no image under "
                f"{data_dir}/images (first: {missing[0]})"
            )
    else:
        ids = discover_ids(data_dir)
    if not ids:
        raise ValueError(f"No examples found under {data_dir}/images")
    # decode ONLY the masks (shared recipe) — images are decoded once later by
    # Trainer.train; pass the returned classes as its ``y``
    from tensorflowdistributedlearning_tpu.data.pipeline import load_masks

    classes = coverage_to_class(mask_coverage(load_masks(data_dir, ids)), n_classes)
    return ids, classes


def rle_encode(mask: np.ndarray) -> str:
    """Kaggle run-length encoding of a binary mask (column-major, 1-indexed) — the
    submission format the reference's unfinished predict path was headed for
    (reference: model.py:229-255 TODO)."""
    pixels = np.asarray(mask, np.uint8).flatten(order="F")
    padded = np.concatenate([[0], pixels, [0]])
    changes = np.flatnonzero(padded[1:] != padded[:-1]) + 1
    starts, ends = changes[::2], changes[1::2]
    return " ".join(f"{s} {e - s}" for s, e in zip(starts, ends))


def rle_decode(rle: str, shape: Tuple[int, int]) -> np.ndarray:
    """Inverse of ``rle_encode``; empty string -> empty mask."""
    mask = np.zeros(shape[0] * shape[1], np.uint8)
    if rle.strip():
        nums = np.asarray(rle.split(), np.int64)
        starts, lengths = nums[::2] - 1, nums[1::2]
        for s, l in zip(starts, lengths):
            mask[s : s + l] = 1
    return mask.reshape(shape, order="F")


def write_submission(
    path: str, ids: List[str], masks: np.ndarray
) -> None:
    """Write a Kaggle submission csv (id,rle_mask) from [N, H, W, 1] binary masks —
    finishing the ensemble-to-submission step the reference left TODO."""
    with open(path, "w", newline="") as f:
        writer = csv.writer(f)
        writer.writerow(["id", "rle_mask"])
        for i, id_ in enumerate(ids):
            writer.writerow([id_, rle_encode(masks[i, :, :, 0])])
