"""Sharded multi-host streaming data service: global shuffle, parallel
read+decode workers, deterministic index-keyed resume.

``data/records.py`` streams shards through ONE thread per process; at pod
scale (parallel/multihost.py) that single read+decode path is the bottleneck
the async-loop telemetry exposes as ``data_wait``. This module is the
input-pipeline-as-a-service answer (the tf.data service lineage of
arXiv:1605.08695, feeding the pjit-era rates of arXiv:2204.06514), built from
three deterministic pieces:

- **global-shuffle epochs with per-host shard assignment**
  (``epoch_shard_assignment``): every epoch permutes ALL shard files with a
  seeded rng and deals them round-robin across processes — each shard is
  owned by exactly one host per epoch, every host's mix changes every epoch
  (the epoch-reshuffled generalization of the static
  ``records.host_shard_paths``), and uneven ``n_shards % process_count``
  splits never starve a host (each gets >= 1 when ``n_shards >=
  process_count``, enforced at construction). Within a host's epoch the
  record order is a full seeded permutation over its records — strictly
  stronger mixing than a shuffle pool, and (unlike a pool) a pure function
  of the seed;

- **an index-keyed batch plan executed by parallel workers**: the epochs
  concatenate into one infinite virtual record sequence, and batch ``i`` is
  DEFINED as records ``[i*B, (i+1)*B)`` of that sequence — a pure function
  of ``(seed, i)``, independent of worker count or scheduling. N background
  workers claim batch indices round-robin, read their records through the
  native offset reader (``records.ShardRangeReader`` over the ``.idx``
  sidecar offsets, crc-checked in C++), decode image blobs with the native
  multithreaded decoder, and a reorder buffer hands batches back in index
  order with bounded backpressure. Reads and decodes overlap across workers
  by construction;

- **deterministic resume** (``DataServiceState``): because the stream is
  index-keyed, the full resume state is ``(seed, next batch index)`` — the
  trainers save it as a checkpoint sidecar
  (``train.checkpoint.CheckpointManager.save_data_state``) and a mid-epoch
  preemption resumes the EXACT remaining stream, so recovered params stay
  bit-identical to an uninterrupted run (the stream half of the resilience
  contract that synthetic data already had via ``index_keyed=True``).

Telemetry: per-take ready-queue depth, underrun counts, and worker busy time
flow into the registry under the ``data_service/*`` names
(obs/telemetry.py), surface per window in the ledger's ``step_window``
events, and feed the ``data_starved`` health monitor (obs/health.py). The
service's stream plugs into the existing stop-aware
``data.pipeline.device_prefetch`` producer exactly like the legacy streams.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

import tensorflowdistributedlearning_tpu.resilience.retry as retry_lib

# seed-stream tags: every rng in the service derives from a distinct
# (seed, tag, ...) SeedSequence so shard assignment, record permutations and
# any future stream can never collide
_TAG_SHARDS = 0x5A
_TAG_RECORDS = 0xC3


class _PlanCache:
    """Small thread-safe cache for per-epoch plans, keyed by the FULL
    ``(seed, epoch)`` pair — a source reused by two services with different
    seeds must never serve the first seed's permutation to the second.
    Capacity is a handful: a batch touches at most a few neighbouring
    epochs, and plans are pure functions so eviction only costs recompute."""

    def __init__(self, capacity: int = 4):
        self._capacity = int(capacity)
        self._lock = threading.Lock()
        self._plans: Dict[Tuple[int, int], object] = {}
        self._order: List[Tuple[int, int]] = []

    def get_or_build(self, seed: int, epoch: int, build):
        key = (int(seed), int(epoch))
        with self._lock:
            cached = self._plans.get(key)
        if cached is not None:
            return cached
        plan = build()
        with self._lock:
            if key not in self._plans:
                self._plans[key] = plan
                self._order.append(key)
                while len(self._order) > self._capacity:
                    self._plans.pop(self._order.pop(0), None)
        return plan


def epoch_shard_assignment(
    paths: Sequence[str],
    *,
    seed: int,
    epoch: int,
    process_index: int,
    process_count: int,
) -> List[str]:
    """This process's shard files for ``epoch``: a seeded permutation of the
    (canonically sorted) full shard list, dealt round-robin across processes.

    Deterministic given ``(seed, epoch, process_index, process_count)``; the
    per-epoch union over processes is always EXACTLY the full shard set (the
    permutation is a bijection and the round-robin deal partitions it), so no
    record is read twice or skipped within an epoch, and with ``len(paths) >=
    process_count`` every process owns at least one shard every epoch — the
    uneven-split contract ``tests/test_data_service.py`` pins."""
    if process_count < 1 or not 0 <= process_index < process_count:
        raise ValueError(
            f"bad process slot {process_index}/{process_count} for shard "
            "assignment"
        )
    order = sorted(paths)
    rng = np.random.default_rng((int(seed), _TAG_SHARDS, int(epoch)))
    perm = rng.permutation(len(order))
    return [order[perm[i]] for i in range(process_index, len(order), process_count)]


@dataclasses.dataclass(frozen=True)
class DataServiceState:
    """The stream's full resume state. Because batch ``i`` is a pure function
    of ``(seed, i)``, ``(seed, batch_index)`` pins the exact remaining
    stream — PROVIDED batch size and world size are unchanged (batch ``i``
    maps to virtual records ``[i*B, (i+1)*B)`` of this host's plan, so either
    changing silently re-trains or skips data); both ride along and are
    validated on restore. ``epoch`` is the derived position (informational —
    rendered in reports, recomputed on restore)."""

    seed: int
    batch_index: int
    epoch: int = 0
    batch_size: int = 0  # 0 = unknown (legacy sidecar): not validated
    process_count: int = 0  # 0 = unknown (legacy sidecar): not validated
    # digest of the sorted shard basenames ("" = unknown): a changed shard
    # SET re-deals every epoch plan, which is the same silent replay/skip
    # failure as a changed seed — validated when both sides know it
    shard_fingerprint: str = ""

    def to_json(self) -> Dict:
        out = {
            "seed": int(self.seed),
            "batch_index": int(self.batch_index),
            "epoch": int(self.epoch),
        }
        if self.batch_size:
            out["batch_size"] = int(self.batch_size)
        if self.process_count:
            out["process_count"] = int(self.process_count)
        if self.shard_fingerprint:
            out["shard_fingerprint"] = self.shard_fingerprint
        return out

    @classmethod
    def from_json(cls, d: Dict) -> "DataServiceState":
        return cls(
            seed=int(d["seed"]),
            batch_index=int(d["batch_index"]),
            epoch=int(d.get("epoch", 0)),
            batch_size=int(d.get("batch_size", 0)),
            process_count=int(d.get("process_count", 0)),
            shard_fingerprint=str(d.get("shard_fingerprint", "")),
        )


class ClassificationRecordSource:
    """Record-shard source for the service: classification payloads
    (``int32 label | encoded image``) read at indexed offsets and decoded to
    the fit loop's ``{'images','labels','valid'}`` batches.

    Takes the FULL shard list (not a host subset): per-epoch host assignment
    happens here, via ``epoch_shard_assignment`` over
    ``(process_index, process_count)`` — pass them explicitly in tests/tools,
    default to the jax cluster slot."""

    def __init__(
        self,
        paths: Sequence[str],
        *,
        image_shape: Tuple[int, int],
        channels: int = 3,
        num_classes: Optional[int] = None,
        process_index: Optional[int] = None,
        process_count: Optional[int] = None,
        verify_crc: bool = True,
    ):
        if not paths:
            raise ValueError("ClassificationRecordSource needs shard paths")
        if process_index is None or process_count is None:
            import jax

            process_index = jax.process_index()
            process_count = jax.process_count()
        if len(paths) < process_count:
            raise ValueError(
                f"{len(paths)} record shard(s) for {process_count} processes "
                "— every process needs at least one per epoch; re-shard the "
                "dataset (write_classification_shards(shards>=process_count))"
            )
        self.paths = [str(p) for p in paths]
        # shard-set identity for the resume contract: basenames, not full
        # paths, so the same dataset restored under a different mount still
        # matches while any re-sharding/addition/removal is caught
        import hashlib

        self.shard_fingerprint = hashlib.md5(
            "\n".join(sorted(os.path.basename(p) for p in self.paths)).encode()
        ).hexdigest()[:16]
        self.image_shape = tuple(image_shape)
        self.channels = int(channels)
        self.num_classes = num_classes
        self.process_index = int(process_index)
        self.process_count = int(process_count)
        self.verify_crc = bool(verify_crc)
        self._lock = threading.Lock()
        self._offsets: Dict[str, np.ndarray] = {}
        self._plans = _PlanCache()
        self._local = threading.local()

    # -- deterministic epoch plans ----------------------------------------

    def _shard_offsets(self, path: str) -> np.ndarray:
        from tensorflowdistributedlearning_tpu.data import records as rec

        with self._lock:
            got = self._offsets.get(path)
        if got is not None:
            return got
        offs = rec.shard_offsets(path)
        with self._lock:
            self._offsets[path] = offs
        return offs

    def _plan(self, seed: int, epoch: int):
        """(shards, shard_slot[], offset[]) for this host's ``epoch`` — the
        seeded full permutation over every record in the epoch's assigned
        shards. Cached per (seed, epoch); pure function of (seed, epoch,
        slot)."""

        def build():
            shards = epoch_shard_assignment(
                self.paths,
                seed=seed,
                epoch=epoch,
                process_index=self.process_index,
                process_count=self.process_count,
            )
            slots: List[np.ndarray] = []
            offsets: List[np.ndarray] = []
            for s, path in enumerate(shards):
                offs = self._shard_offsets(path)
                slots.append(np.full(len(offs), s, np.int64))
                offsets.append(offs)
            slot_arr = (
                np.concatenate(slots) if slots else np.empty(0, np.int64)
            )
            off_arr = (
                np.concatenate(offsets) if offsets else np.empty(0, np.uint64)
            )
            rng = np.random.default_rng(
                (int(seed), _TAG_RECORDS, int(epoch), self.process_index)
            )
            perm = rng.permutation(len(slot_arr))
            return (shards, slot_arr[perm], off_arr[perm])

        return self._plans.get_or_build(seed, epoch, build)

    def epoch_size(self, seed: int, epoch: int) -> int:
        shards = epoch_shard_assignment(
            self.paths,
            seed=seed,
            epoch=epoch,
            process_index=self.process_index,
            process_count=self.process_count,
        )
        return int(sum(len(self._shard_offsets(p)) for p in shards))

    # -- worker-side read + decode ----------------------------------------

    # per-worker-thread open-reader bound: without it a run over an
    # ImageNet-scale shard count (1024+) would hold workers x shards open
    # FILE*s (past the common 1024-fd ulimit) plus each native handle's last
    # read buffers. Reopen-on-miss is one fopen+fseek — noise next to decode.
    _MAX_READERS_PER_THREAD = 16

    def _reader(self, path: str):
        from collections import OrderedDict

        from tensorflowdistributedlearning_tpu.data import records as rec

        cache = getattr(self._local, "readers", None)
        if cache is None:
            cache = self._local.readers = OrderedDict()
        reader = cache.get(path)
        if reader is None:
            reader = cache[path] = rec.ShardRangeReader(
                path, verify_crc=self.verify_crc
            )
            while len(cache) > self._MAX_READERS_PER_THREAD:
                _, evicted = cache.popitem(last=False)
                evicted.close()
        else:
            cache.move_to_end(path)
        return reader

    def materialize(
        self, seed: int, parts: List[Tuple[int, np.ndarray]]
    ) -> Dict[str, np.ndarray]:
        """Assemble one batch from plan positions: ``parts`` is
        ``[(epoch, positions), ...]`` in batch order. Reads are grouped per
        shard (one native range call each) and scattered back into plan
        order, so the result is independent of grouping; transient read I/O
        retries through the resilience stack. Decode (label validation, blob
        decode behind the ``io-data`` fault site, normalization) is the ONE
        shared recipe ``records.decode_classification_batch`` — service-fed
        and legacy-fed batches cannot drift."""
        from tensorflowdistributedlearning_tpu.data import records as rec

        def read() -> List[bytes]:
            entries: List[Tuple[str, int]] = []
            for epoch, idxs in parts:
                shards, slot_arr, off_arr = self._plan(seed, epoch)
                for i in idxs:
                    entries.append((shards[slot_arr[i]], int(off_arr[i])))
            by_shard: Dict[str, Tuple[List[int], List[int]]] = {}
            for pos, (path, off) in enumerate(entries):
                positions, offs = by_shard.setdefault(path, ([], []))
                positions.append(pos)
                offs.append(off)
            payloads: List[Optional[bytes]] = [None] * len(entries)
            for path, (positions, offs) in by_shard.items():
                for pos, payload in zip(
                    positions, self._reader(path).read(offs)
                ):
                    payloads[pos] = payload
            return payloads

        payloads = retry_lib.call_with_retry(
            read, name="data_service_read", exceptions=(OSError,)
        )
        labels: List[int] = []
        blobs: List[bytes] = []
        for payload in payloads:
            label, img = rec.decode_classification_record(payload)
            labels.append(label)
            blobs.append(img)
        return rec.decode_classification_batch(
            blobs,
            labels,
            len(blobs),
            image_shape=self.image_shape,
            channels=self.channels,
            num_classes=self.num_classes,
        )


class ArrayBatchSource:
    """In-memory source for the service: seeded epoch permutations over host
    arrays, batches assembled by fancy indexing — the index-keyed,
    service-fed replacement for ``pipeline.train_batches``'s chained
    rng-stateful permutations (same mixing, but batch ``i`` is a pure
    function of the seed, so the K-fold trainer resumes deterministically
    without seed-folding tricks). ``arrays`` values must share a leading
    dimension (e.g. ``{'images': ..., 'masks': ...}``).

    ``process_count``: the world size the arrays were SHARDED FOR (callers
    that host-shard before constructing — the K-fold trainer's
    ``pipeline.host_shard`` fold split). When set it rides the service's
    resume sidecar, so a resumed fold that crossed a world resize re-deals
    explicitly (ledgered) instead of silently indexing a different host
    shard; None/0 = world-independent arrays (nothing validated)."""

    def __init__(
        self,
        arrays: Dict[str, np.ndarray],
        *,
        process_count: Optional[int] = None,
    ):
        if not arrays:
            raise ValueError("ArrayBatchSource needs at least one array")
        self.process_count = int(process_count or 0)
        lengths = {k: len(v) for k, v in arrays.items()}
        if len(set(lengths.values())) != 1:
            raise ValueError(f"array lengths disagree: {lengths}")
        self.n = next(iter(lengths.values()))
        if self.n == 0:
            raise ValueError("ArrayBatchSource over an empty dataset")
        self.arrays = dict(arrays)
        self._plans = _PlanCache()

    def epoch_size(self, seed: int, epoch: int) -> int:
        return self.n

    def _plan(self, seed: int, epoch: int) -> np.ndarray:
        return self._plans.get_or_build(
            seed,
            epoch,
            lambda: np.random.default_rng(
                (int(seed), _TAG_RECORDS, int(epoch))
            ).permutation(self.n),
        )

    def materialize(
        self, seed: int, parts: List[Tuple[int, np.ndarray]]
    ) -> Dict[str, np.ndarray]:
        rows = np.concatenate(
            [self._plan(seed, epoch)[idxs] for epoch, idxs in parts]
        )
        return {k: v[rows] for k, v in self.arrays.items()}


class StreamingDataService:
    """N parallel read+decode workers executing the index-keyed batch plan,
    with an in-order reorder buffer and bounded backpressure.

    One service drives ONE stream (``batches()`` is single-shot, like
    ``device_prefetch``). ``registry`` (an ``obs.metrics.MetricsRegistry``)
    receives per-take ready depth, underrun events and per-batch worker busy
    time under the ``data_service/*`` names; None records nothing.

    ``resume_state`` (a ``DataServiceState`` json dict, from the checkpoint
    sidecar) is VALIDATED against ``(seed, start_batch)``: a mismatch means
    the run is about to silently replay or skip data, which must crash, not
    train — with ONE deliberate exception: a changed ``process_count`` (an
    elastic world resize, parallel/elastic.py) re-deals the per-epoch shard
    assignment at the new world size instead of refusing. The re-deal keeps
    the epoch-boundary math intact — batch ``i`` maps onto the NEW world's
    per-host virtual record sequence through the same cumulative-epoch-size
    accounting, so the resumed stream is still a pure function of
    ``(seed, batch_index, process_index, process_count)`` and an elastic
    resume lands bit-identical to a clean same-world run from the same
    checkpoint. Seed, per-host batch size and the shard fingerprint are still
    hard-refused on mismatch (those change WHAT the indices mean, not who
    reads them); the accepted re-deal is surfaced as ``self.redeal`` so the
    trainers can ledger it."""

    def __init__(
        self,
        source,
        *,
        batch_size: int,
        seed: int,
        workers: int = 2,
        start_batch: int = 0,
        queue_depth: Optional[int] = None,
        registry=None,
        resume_state: Optional[Dict] = None,
    ):
        if batch_size < 1:
            raise ValueError(f"batch_size must be >= 1, got {batch_size}")
        if workers < 1:
            raise ValueError(
                f"data service needs >= 1 worker, got {workers} "
                "(0 selects the legacy in-line stream at the trainer level)"
            )
        if start_batch < 0:
            raise ValueError(f"start_batch must be >= 0, got {start_batch}")
        if queue_depth is not None and queue_depth < 1:
            raise ValueError(
                f"queue_depth must be >= 1, got {queue_depth} "
                "(capacity below 1 would livelock the reorder buffer)"
            )
        self.source = source
        self.batch_size = int(batch_size)
        self.seed = int(seed)
        self.workers = int(workers)
        self.start_batch = int(start_batch)
        self._capacity = (
            int(queue_depth) if queue_depth else max(2, self.workers + 1)
        )
        self._registry = registry
        # set when an accepted resume crossed a world resize: the validated
        # re-deal's facts ({"old_process_count", "new_process_count",
        # "batch_index"}) for the trainers to ledger as a `data_redeal` event
        self.redeal: Optional[Dict] = None
        if resume_state is not None:
            restored = DataServiceState.from_json(resume_state)
            fingerprint = self._shard_fingerprint()
            mismatch = (
                restored.seed != self.seed
                or restored.batch_index != self.start_batch
                or (restored.batch_size
                    and restored.batch_size != self.batch_size)
                or (restored.shard_fingerprint and fingerprint
                    and restored.shard_fingerprint != fingerprint)
            )
            if mismatch:
                raise ValueError(
                    "data service resume state mismatch: checkpoint sidecar "
                    f"has (seed={restored.seed}, "
                    f"batch_index={restored.batch_index}, "
                    f"batch_size={restored.batch_size or '?'}, "
                    f"shards={restored.shard_fingerprint or '?'}) but "
                    f"this run wants (seed={self.seed}, "
                    f"batch_index={self.start_batch}, "
                    f"batch_size={self.batch_size}, "
                    f"shards={fingerprint or '?'}) — resuming would replay "
                    "or skip training data; restore with the original "
                    "seed/step/per-host batch size and shard set"
                )
            new_count = self._process_count()
            if (
                restored.process_count
                and new_count
                and restored.process_count != new_count
            ):
                # elastic world resize: the per-epoch shard deal is a pure
                # function of (seed, epoch, process_index, process_count), so
                # the NEW world re-derives every plan from scratch — nothing
                # of the old deal survives to conflict. The epoch-boundary
                # math (cumulative epoch sizes -> (epoch, offset) of any
                # batch index) is re-priced under the new per-host epoch
                # sizes by the same _locate/_extend_cum accounting, keeping
                # the stream deterministic for every host of the new world.
                self.redeal = {
                    "old_process_count": int(restored.process_count),
                    "new_process_count": int(new_count),
                    "batch_index": int(self.start_batch),
                }
                import logging

                logging.getLogger(__name__).warning(
                    "data service resuming across a world resize: "
                    "process_count %d -> %d at batch_index %d — re-dealing "
                    "the per-epoch shard assignment (validated: seed, "
                    "per-host batch size and shard set unchanged)",
                    restored.process_count, new_count, self.start_batch,
                )
        # cumulative epoch sizes: _cum[e] = records before epoch e
        self._cum: List[int] = [0]
        self._cum_lock = threading.Lock()
        self._cond = threading.Condition()
        self._ready: Dict[int, Dict[str, np.ndarray]] = {}
        self._next_emit = self.start_batch
        self._error: Optional[BaseException] = None
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._started = False

    # -- index-keyed plan math ---------------------------------------------

    def _extend_cum_locked(self, n_epochs: Optional[int], record_j: int) -> None:
        """Grow the cumulative-size cache to cover ``n_epochs`` epochs and/or
        virtual record ``record_j``. Caller holds ``_cum_lock``. The sizes
        are cached HERE so the hot path (every worker, every batch) never
        re-derives a shard assignment the cache already priced."""
        while (n_epochs is not None and len(self._cum) <= n_epochs) or (
            record_j >= self._cum[-1]
        ):
            e = len(self._cum) - 1
            size = self.source.epoch_size(self.seed, e)
            if size < 0:
                raise ValueError(f"negative epoch size {size}")
            # a host may own only empty shards for SOME epoch, but a
            # stream that never produces a record must raise, not spin
            if size == 0 and self._cum[-1] == 0 and e >= 64:
                raise ValueError(
                    "data service source reports zero records "
                    "(empty shards?)"
                )
            self._cum.append(self._cum[-1] + size)

    def _locate(self, record_j: int) -> Tuple[int, int]:
        """(epoch, offset_within_epoch) of virtual record ``record_j``."""
        import bisect

        with self._cum_lock:
            self._extend_cum_locked(None, record_j)
            e = bisect.bisect_right(self._cum, record_j) - 1
            return e, record_j - self._cum[e]

    def _epoch_size(self, epoch: int) -> int:
        with self._cum_lock:
            self._extend_cum_locked(epoch + 1, 0)
            return self._cum[epoch + 1] - self._cum[epoch]

    def _parts(self, batch_index: int) -> List[Tuple[int, np.ndarray]]:
        start = batch_index * self.batch_size
        need = self.batch_size
        parts: List[Tuple[int, np.ndarray]] = []
        epoch, offset = self._locate(start)
        while need > 0:
            size = self._epoch_size(epoch)
            if size <= 0:
                epoch += 1
                offset = 0
                continue
            take = min(need, size - offset)
            parts.append((epoch, np.arange(offset, offset + take)))
            need -= take
            epoch += 1
            offset = 0
        return parts

    def _process_count(self) -> int:
        """The source's world size, when it has one (record sources do; the
        in-memory array source is already host-local) — 0 means unknown."""
        return int(getattr(self.source, "process_count", 0) or 0)

    def _shard_fingerprint(self) -> str:
        """The source's shard-set digest ("" when it has none — in-memory
        sources)."""
        return str(getattr(self.source, "shard_fingerprint", "") or "")

    def state(self, batch_index: Optional[int] = None) -> DataServiceState:
        """Resume state for ``batch_index`` — what the trainers sidecar into
        checkpoints. ALWAYS pass the trainer's step counter when the stream
        feeds a prefetcher (the trainers do): the default snapshots the next
        batch the raw stream would yield, which behind ``device_prefetch`` /
        dispatch-ahead runs AHEAD of the last trained step — a sidecar
        written from it would skip data on resume."""
        if batch_index is None:
            with self._cond:
                batch_index = self._next_emit
        epoch, _ = self._locate(batch_index * self.batch_size)
        return DataServiceState(
            seed=self.seed,
            batch_index=int(batch_index),
            epoch=epoch,
            batch_size=self.batch_size,
            process_count=self._process_count(),
            shard_fingerprint=self._shard_fingerprint(),
        )

    # -- the stream --------------------------------------------------------

    def batches(
        self, steps: Optional[int] = None
    ) -> Iterator[Dict[str, np.ndarray]]:
        """The service's output stream: batches ``start_batch ..
        start_batch+steps`` in index order (infinite when ``steps`` is None).
        Starts the workers eagerly; the returned generator releases them on
        close/GC, so an abandoned consumer (preemption, a test reading one
        batch) never leaks threads — the same stop-aware contract as
        ``device_prefetch``."""
        if self._started:
            raise RuntimeError(
                "StreamingDataService.batches() is single-shot; build a new "
                "service for a new stream"
            )
        self._started = True
        end = None if steps is None else self.start_batch + int(steps)
        ready_hist = under_hist = busy_hist = None
        if self._registry is not None:
            from tensorflowdistributedlearning_tpu.obs import telemetry as tm

            ready_hist = self._registry.histogram(tm.DATA_READY_HISTOGRAM)
            under_hist = self._registry.histogram(tm.DATA_UNDERRUN_HISTOGRAM)
            busy_hist = self._registry.histogram(tm.DATA_WORKER_BUSY_HISTOGRAM)
            self._registry.gauge(tm.DATA_WORKERS_GAUGE).set(self.workers)
        for w in range(self.workers):
            t = threading.Thread(
                target=self._worker,
                args=(w, end, busy_hist),
                daemon=True,
                name=f"data-service-{w}",
            )
            t.start()
            self._threads.append(t)
        gen = self._consume(end, ready_hist, under_hist)
        import weakref

        # a generator dropped before its first next() never reaches the
        # try/finally inside — the finalizer still releases the workers
        weakref.finalize(gen, self._stop.set)
        return gen

    def _worker(self, wid: int, end: Optional[int], busy_hist) -> None:
        try:
            i = self.start_batch + wid
            while (end is None or i < end) and not self._stop.is_set():
                parts = self._parts(i)
                t0 = time.perf_counter()
                batch = self.source.materialize(self.seed, parts)
                if busy_hist is not None:
                    busy_hist.record(time.perf_counter() - t0)
                with self._cond:
                    while (
                        i - self._next_emit >= self._capacity
                        and not self._stop.is_set()
                    ):
                        self._cond.wait(0.05)
                    if self._stop.is_set():
                        return
                    self._ready[i] = batch
                    self._cond.notify_all()
                i += self.workers
        except BaseException as e:  # noqa: BLE001 — re-raised consumer-side
            with self._cond:
                if self._error is None:
                    self._error = e
                self._cond.notify_all()

    def _consume(self, end, ready_hist, under_hist):
        try:
            i = self.start_batch
            while end is None or i < end:
                with self._cond:
                    if i not in self._ready:
                        if self._error is not None:
                            raise self._error
                        # the consumer arrived before the batch: an underrun
                        # (the devices would be waiting on input right now).
                        # The FIRST take is excluded — waiting for batch 0
                        # while the workers spin up is startup, not the
                        # workers failing to keep pace, and counting it
                        # would trip the report's raise-the-workers warning
                        # on every healthy run.
                        if under_hist is not None and i > self.start_batch:
                            under_hist.record(1.0)
                        while i not in self._ready:
                            if self._error is not None:
                                raise self._error
                            if self._stop.is_set():
                                # closed under the consumer (run teardown):
                                # the awaited batch was discarded with the
                                # workers — end the stream instead of
                                # polling for it forever
                                return
                            self._cond.wait(0.1)
                    batch = self._ready.pop(i)
                    self._next_emit = i + 1
                    depth = len(self._ready)
                    self._cond.notify_all()
                if ready_hist is not None:
                    ready_hist.record(float(depth))
                yield batch
                i += 1
        finally:
            self.close()

    def close(self) -> None:
        """Stop the workers and drop buffered batches. Idempotent; called by
        the stream's own ``finally``/finalizer, and by the trainers on run
        teardown for promptness."""
        self._stop.set()
        with self._cond:
            self._cond.notify_all()
        for t in self._threads:
            t.join(timeout=5.0)
        self._threads = []
