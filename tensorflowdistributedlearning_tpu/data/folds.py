"""Stratified K-fold management with index manifests.

The reference materialized folds as filesystem symlink trees —
``{model_dir}/{train,eval}/{images,masks}/fold{i}`` populated with per-fold symlinks
into the raw data directory, which the input_fns then globbed (reference:
preprocessing/preprocessing.py:33-88, model.py:174, 186, 289-294). Here folds are plain
index manifests written once as JSON: no filesystem side effects per fold, trivially
shardable across hosts, and idempotent the same way the reference's "fold has already
been processed" guard was (reference: preprocessing/preprocessing.py:80-88).

Stratification matches the reference driver: per-image mask coverage binned into 11
classes (``cov_to_class`` in the notebooks, Untitled.ipynb cell 4) fed to a stratified
K-fold split (reference: model.py:134-136, 152-154 via sklearn).
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Sequence, Tuple

import numpy as np


def coverage_to_class(coverage: np.ndarray, n_classes: int = 11) -> np.ndarray:
    """Bin mask coverage fractions in [0, 1] into ``n_classes`` stratification classes
    (the notebooks' ``cov_to_class``: ceil(coverage * 10) → 0..10)."""
    coverage = np.asarray(coverage, np.float64)
    return np.ceil(coverage * (n_classes - 1)).astype(np.int64)


def stratified_kfold(
    y: Sequence[int], n_splits: int, seed: int, shuffle: bool = True
) -> List[Tuple[np.ndarray, np.ndarray]]:
    """Stratified K-fold over class labels ``y``; returns [(train_idx, eval_idx)] per
    fold (the reference delegated to sklearn's StratifiedKFold, model.py:134-136).

    Pure-numpy round-robin-within-class assignment: samples of each class are shuffled
    and dealt to folds as evenly as possible, so every fold's class histogram differs
    from the global one by at most one sample per class — the StratifiedKFold contract.
    """
    y = np.asarray(y)
    if n_splits < 2:
        raise ValueError(f"n_splits must be >= 2, got {n_splits}")
    rng = np.random.default_rng(seed)
    fold_of = np.empty(len(y), np.int64)
    for cls in np.unique(y):
        idx = np.flatnonzero(y == cls)
        if shuffle:
            idx = rng.permutation(idx)
        fold_of[idx] = np.arange(len(idx)) % n_splits
    return [
        (np.flatnonzero(fold_of != f), np.flatnonzero(fold_of == f))
        for f in range(n_splits)
    ]


def build_fold_manifests(
    ids: Sequence[str], y: Sequence[int], n_splits: int, seed: int
) -> List[Dict[str, List[str]]]:
    """Per-fold {"train": [...ids], "eval": [...ids]} manifests."""
    ids = list(ids)
    return [
        {
            "train": [ids[i] for i in train_idx],
            "eval": [ids[i] for i in eval_idx],
        }
        for train_idx, eval_idx in stratified_kfold(y, n_splits, seed)
    ]


def write_fold_manifests(
    model_dir: str,
    ids: Sequence[str],
    y: Sequence[int],
    n_splits: int,
    seed: int,
) -> List[Dict[str, List[str]]]:
    """Write ``{model_dir}/folds.json`` once; re-running reuses the existing split —
    the idempotency the reference got from its symlink-exists check (reference:
    preprocessing/preprocessing.py:80-88)."""
    path = os.path.join(model_dir, "folds.json")
    if os.path.exists(path):
        with open(path) as f:
            return json.load(f)
    manifests = build_fold_manifests(ids, y, n_splits, seed)
    os.makedirs(model_dir, exist_ok=True)
    with open(path, "w") as f:
        json.dump(manifests, f)
    return manifests


def read_fold_manifests(model_dir: str) -> List[Dict[str, List[str]]]:
    with open(os.path.join(model_dir, "folds.json")) as f:
        return json.load(f)
