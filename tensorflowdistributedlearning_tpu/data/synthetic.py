"""Synthetic data generators for tests/smoke configs (SURVEY §7's minimum end-to-end
slice calls for a synthetic [B, H, W, 2] generator; the reference had no test data
story at all)."""

from __future__ import annotations

from typing import Dict, Iterator, Optional, Tuple

import numpy as np


def synthetic_segmentation_batch(
    rng: np.random.Generator,
    batch_size: int,
    input_shape: Tuple[int, int] = (101, 101),
    channels: int = 2,
) -> Dict[str, np.ndarray]:
    """Random-disk masks with correlated images — learnable in a few steps.

    Mimics the TGS salt layout the reference trained on: images [B, H, W, C] float32,
    labels [B, H, W, 1] in {0, 1} (reference: preprocessing/preprocessing.py:91-97).
    """
    h, w = input_shape
    yy, xx = np.mgrid[0:h, 0:w]
    images = np.empty((batch_size, h, w, channels), np.float32)
    labels = np.empty((batch_size, h, w, 1), np.float32)
    for i in range(batch_size):
        cy, cx = rng.uniform(0.2, 0.8) * h, rng.uniform(0.2, 0.8) * w
        r = rng.uniform(0.1, 0.3) * min(h, w)
        mask = ((yy - cy) ** 2 + (xx - cx) ** 2 < r**2).astype(np.float32)
        labels[i, :, :, 0] = mask
        base = mask * 1.5 - 0.75 + rng.normal(0, 0.2, (h, w))
        for c in range(channels):
            images[i, :, :, c] = base
    return {"images": images, "labels": labels}


def synthetic_classification_batch(
    rng: np.random.Generator,
    batch_size: int,
    input_shape: Tuple[int, int] = (32, 32),
    channels: int = 3,
    num_classes: int = 10,
) -> Dict[str, np.ndarray]:
    """Class-conditional Gaussian blobs; labels [B] int32."""
    h, w = input_shape
    labels = rng.integers(0, num_classes, batch_size).astype(np.int32)
    images = rng.normal(0, 0.3, (batch_size, h, w, channels)).astype(np.float32)
    images += (labels[:, None, None, None].astype(np.float32) / num_classes) - 0.5
    return {"images": images, "labels": labels}


def synthetic_batches(
    kind: str,
    batch_size: int,
    seed: int = 0,
    steps: Optional[int] = None,
    start_index: int = 0,
    index_keyed: bool = False,
    **kwargs,
) -> Iterator[Dict[str, np.ndarray]]:
    """Infinite (or ``steps``-bounded) stream of synthetic batches.

    ``index_keyed=True`` makes batch ``i`` a pure function of ``(seed, i)``
    (fresh ``default_rng((seed, i))`` per batch) and starts at
    ``start_index`` — the restart-invariant form the resilience contract
    needs: a run resumed at step k sees bit-for-bit the batches the
    uninterrupted run saw from step k. The default streaming form (one rng
    across the stream) is byte-stable with what it always produced, which the
    determinism goldens pin."""
    if kind not in ("segmentation", "classification"):
        raise ValueError(f"Unknown synthetic data kind {kind!r}")
    make = (
        synthetic_segmentation_batch
        if kind == "segmentation"
        else synthetic_classification_batch
    )
    if index_keyed:
        i = start_index
        while steps is None or i < start_index + steps:
            yield make(np.random.default_rng((seed, i)), batch_size, **kwargs)
            i += 1
        return
    if start_index:
        raise ValueError("start_index requires index_keyed=True")
    rng = np.random.default_rng(seed)
    i = 0
    while steps is None or i < steps:
        yield make(rng, batch_size, **kwargs)
        i += 1
