"""sklearn handwritten-digits corpus -> classification record shards.

The one REAL image dataset available without network access (1797 genuine 8x8
scans from the UCI optical-recognition corpus, bundled with scikit-learn).
Used by ``examples/train_digits.py`` and the end-to-end real-data test
(``tests/test_digits_e2e.py``) — one copy of the rescale/split/shard logic so
the shipped example and the suite's accuracy assertion cannot diverge.

The reference's real-data path was its Kaggle download + notebook runs
(reference: Untitled.ipynb cells 7-8); this is the zero-egress equivalent."""

from __future__ import annotations

import os
from typing import Tuple

import numpy as np


def load_digit_arrays(
    *, upscale: int = 4, val_fraction: float = 0.2, seed: int = 0
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """(train_images, train_labels, val_images, val_labels) as uint8 HxW arrays.

    8x8 inputs are nearest-upscaled by ``upscale`` (np.kron) so stride-32
    trunks retain spatial extent; intensities (0..16) rescale to uint8. The
    split is a seeded permutation — deterministic, so train/val never overlap
    across runs."""
    from sklearn.datasets import load_digits

    digits = load_digits()
    images = np.kron(
        (digits.images * (255.0 / 16.0)).astype(np.uint8),
        np.ones((upscale, upscale), np.uint8),
    )
    labels = digits.target.astype(np.int64)
    rng = np.random.default_rng(seed)
    order = rng.permutation(len(images))
    n_val = int(len(images) * val_fraction)
    val_idx, train_idx = order[:n_val], order[n_val:]
    return images[train_idx], labels[train_idx], images[val_idx], labels[val_idx]


def prepare_digits(
    data_dir: str,
    *,
    upscale: int = 4,
    val_fraction: float = 0.2,
    seed: int = 0,
    shards: int = 4,
) -> None:
    """Write the corpus as ``train-*/val-*`` record shards under ``data_dir``
    (the layout ``fit()`` auto-discovers)."""
    from tensorflowdistributedlearning_tpu.data.records import (
        write_classification_shards,
    )

    tr_x, tr_y, va_x, va_y = load_digit_arrays(
        upscale=upscale, val_fraction=val_fraction, seed=seed
    )
    os.makedirs(data_dir, exist_ok=True)
    write_classification_shards(data_dir, tr_x, tr_y, shards=shards, prefix="train")
    write_classification_shards(data_dir, va_x, va_y, shards=1, prefix="val")


def load_digit_segmentation_arrays(
    *,
    size: Tuple[int, int] = (101, 101),
    val_fraction: float = 0.2,
    seed: int = 0,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """(train_images, train_masks, val_images, val_masks) for foreground
    segmentation of the REAL 8x8 digit scans.

    The task: label every pixel that carries ink. Masks are the 8x8 scans
    thresholded at zero intensity (any recorded ink is glyph), images are the
    raw scans — so the target boundary follows real pen strokes with real
    scanner noise, not synthetic geometry. Images upsample BILINEAR to
    ``size`` (smooth gradients, like natural imagery downstream models see);
    masks upsample NEAREST from the 8x8 threshold (crisp real label edges).
    The segmentation twin of ``load_digit_arrays``: same corpus, same seeded
    split discipline. Images are uint8 [N, H, W]; masks float32 {0,1}
    [N, H, W, 1] (the layout ``InMemoryDataset``/the Trainer consume).

    The reference's production task was exactly this shape of problem — binary
    masks over real single-channel images (TGS salt, reference:
    model.py:138-227, preprocessing/preprocessing.py:112-246); this is its
    zero-egress equivalent on the one real image corpus in the environment."""
    from PIL import Image
    from sklearn.datasets import load_digits

    digits = load_digits()
    raw = (digits.images * (255.0 / 16.0)).astype(np.uint8)  # [N, 8, 8]
    fg = (digits.images > 0).astype(np.uint8)  # any ink = foreground
    h, w = size
    images = np.stack(
        [
            np.asarray(Image.fromarray(im).resize((w, h), Image.BILINEAR))
            for im in raw
        ]
    )
    masks = np.stack(
        [
            np.asarray(Image.fromarray(m * 255).resize((w, h), Image.NEAREST))
            for m in fg
        ]
    )
    masks = (masks > 127).astype(np.float32)[..., None]
    rng = np.random.default_rng(seed)
    order = rng.permutation(len(images))
    n_val = int(len(images) * val_fraction)
    val_idx, train_idx = order[:n_val], order[n_val:]
    return images[train_idx], masks[train_idx], images[val_idx], masks[val_idx]


def prepare_digit_segmentation(
    data_dir: str,
    *,
    size: Tuple[int, int] = (101, 101),
    val_fraction: float = 0.2,
    seed: int = 0,
    limit: int | None = None,
) -> Tuple[str, str]:
    """Write the digit foreground-segmentation corpus in the salt PNG layout:
    ``{data_dir}/train/{images,masks}/*.png`` (the Trainer's K-fold pool) and
    ``{data_dir}/test/{images,masks}/*.png`` (held out for TTA-ensemble
    scoring; ``predict`` reads only ``images/``, the masks are the score key).
    Returns (train_dir, test_dir). ``limit`` caps each split (CI budgets)."""
    from PIL import Image

    tr_x, tr_m, va_x, va_m = load_digit_segmentation_arrays(
        size=size, val_fraction=val_fraction, seed=seed
    )
    if limit is not None:
        tr_x, tr_m = tr_x[:limit], tr_m[:limit]
        va_x, va_m = va_x[:limit], va_m[:limit]

    def write_split(split: str, xs: np.ndarray, ms: np.ndarray) -> str:
        split_dir = os.path.join(data_dir, split)
        for sub in ("images", "masks"):
            os.makedirs(os.path.join(split_dir, sub), exist_ok=True)
        for i, (x, m) in enumerate(zip(xs, ms)):
            Image.fromarray(x).save(
                os.path.join(split_dir, "images", f"d{i:04d}.png")
            )
            Image.fromarray((m[..., 0] * 255).astype(np.uint8)).save(
                os.path.join(split_dir, "masks", f"d{i:04d}.png")
            )
        return split_dir

    return write_split("train", tr_x, tr_m), write_split("test", va_x, va_m)


# BN running stats need ~500 steps at the 0.99 default to converge; short
# digit budgets evaluate on running stats, so they track with a faster decay
SHORT_BUDGET_BN_DECAY = 0.9


def short_budget_train_config(steps: int, **overrides):
    """The validated short-budget digits recipe, shared by
    ``examples/train_digits.py`` and ``tests/test_digits_e2e.py`` so the
    committed run record and the CI assertion exercise the SAME numbers
    (they drifted apart once — lr 1e-3 vs 3e-3 — costing 24 points of
    measured top-1): cosine Adam at 3e-3 (1797 examples, ~28 steps/epoch),
    kernels-only weight decay 1e-4, crop-only augmentation (mirrored digits
    are other glyphs or garbage)."""
    from tensorflowdistributedlearning_tpu.config import TrainConfig

    base = dict(
        optimizer="adam",
        lr=3e-3,
        lr_schedule="cosine",
        lr_decay_steps=steps,
        weight_decay=1e-4,
        checkpoint_every_steps=max(steps // 3, 1),
        augmentation="crop",
    )
    base.update(overrides)
    return TrainConfig(**base)


def production_recipe_train_config(steps: int, global_batch: int = 64, **overrides):
    """The ImageNet production recipe (``configs.py:resnet50_imagenet``) scaled
    to the digits budget: SGD Nesterov momentum, linear-scaled lr
    (0.1 x batch/256 — Goyal et al.'s rule, the one the 8k LARS preset extends),
    5%-of-budget linear warmup into cosine decay, kernels-only weight decay
    1e-4, label smoothing 0.1. This is the recipe behind the 76%-top-1 north
    star (BASELINE.md); training it on the one real dataset in the image
    validates that the decay mask / warmup / smoothing code HELPS real data
    rather than only passing unit tests. Shared by
    ``examples/train_digits.py --recipe sgd`` and
    ``tests/test_digits_e2e.py`` so the committed record and the CI assertion
    run the same numbers (reference's analogue: its notebooks' real runs,
    Untitled.ipynb cells 7-8)."""
    from tensorflowdistributedlearning_tpu.config import TrainConfig

    base = dict(
        optimizer="sgd",
        sgd_momentum=0.9,
        lr=0.1 * global_batch / 256.0,
        lr_schedule="cosine",
        lr_warmup_steps=max(steps // 20, 1),
        lr_decay_steps=steps,
        weight_decay=1e-4,
        label_smoothing=0.1,
        checkpoint_every_steps=max(steps // 3, 1),
        augmentation="crop",
    )
    base.update(overrides)
    return TrainConfig(**base)


def large_batch_recipe_train_config(steps: int, global_batch: int = 256, **overrides):
    """The LARS large-batch recipe (``configs.py:resnet50_bf16_8k``) at digits
    scale: layer-wise trust ratios (You et al., arXiv:1708.03888),
    10%-of-budget warmup, cosine decay, kernels-only wd 1e-4, label
    smoothing 0.1. Proves on real data the optimizer behind the 8k pod
    preset, which otherwise had only unit tests.

    lr anchors at the MEASURED digits-scale operating point 0.8 @ batch 256
    (97.2% top-1 in 150 steps), scaled linearly in batch. The preset's own
    linear rule extrapolated down (3.2 * 256/8192 = 0.1) under-drives optax's
    trust_coefficient=0.001 normalization at short budgets — measured 25.3%
    top-1 at 200 steps — because LARS's effective per-layer step also shrinks
    with ||g||, which is large early and never gets enough optimizer steps to
    settle at digit budgets. Shared by ``examples/train_digits.py --recipe
    lars``."""
    from tensorflowdistributedlearning_tpu.config import TrainConfig

    base = dict(
        optimizer="lars",
        lr=0.8 * global_batch / 256.0,
        lr_schedule="cosine",
        lr_warmup_steps=max(steps // 10, 1),
        lr_decay_steps=steps,
        weight_decay=1e-4,
        label_smoothing=0.1,
        checkpoint_every_steps=max(steps // 3, 1),
        augmentation="crop",
    )
    base.update(overrides)
    return TrainConfig(**base)
