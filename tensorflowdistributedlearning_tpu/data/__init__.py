from tensorflowdistributedlearning_tpu.data.augment import (
    AugmentConfig,
    add_laplace_channel,
    augment_batch,
    prepare_eval_batch,
    tta_inverse,
    tta_transform,
    TTA_TRANSFORMS,
)
from tensorflowdistributedlearning_tpu.data.folds import (
    build_fold_manifests,
    coverage_to_class,
    stratified_kfold,
    write_fold_manifests,
)
from tensorflowdistributedlearning_tpu.data.pipeline import (
    InMemoryDataset,
    device_prefetch,
    eval_batches,
    host_shard,
    train_batches,
)
from tensorflowdistributedlearning_tpu.data.service import (
    ArrayBatchSource,
    ClassificationRecordSource,
    DataServiceState,
    StreamingDataService,
    epoch_shard_assignment,
)
from tensorflowdistributedlearning_tpu.data.synthetic import synthetic_batches

__all__ = [
    "AugmentConfig",
    "add_laplace_channel",
    "augment_batch",
    "prepare_eval_batch",
    "tta_inverse",
    "tta_transform",
    "TTA_TRANSFORMS",
    "build_fold_manifests",
    "coverage_to_class",
    "stratified_kfold",
    "write_fold_manifests",
    "InMemoryDataset",
    "device_prefetch",
    "eval_batches",
    "host_shard",
    "train_batches",
    "synthetic_batches",
    "ArrayBatchSource",
    "ClassificationRecordSource",
    "DataServiceState",
    "StreamingDataService",
    "epoch_shard_assignment",
]
