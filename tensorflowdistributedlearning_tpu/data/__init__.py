from tensorflowdistributedlearning_tpu.data.synthetic import synthetic_batches

__all__ = ["synthetic_batches"]
