"""Host-side input pipeline: decode, shuffle, batch, shard, prefetch.

The reference's pipeline was tf.data per GPU tower: glob symlinked fold dirs →
``from_tensor_slices`` → ``shuffle_and_repeat(10×batch)`` → per-image augmenting map →
``batch`` → ``prefetch(2×n_gpus)`` (reference: model.py:287-322). The TPU-native split
is different by design:

- the host ONLY decodes PNGs and assembles batches (decode once, cache in RAM — the
  TGS-scale datasets the reference trained on fit trivially);
- geometry/augmentation runs ON DEVICE as part of the jitted step
  (see data/augment.py), so the host never bottlenecks the MXU;
- under multi-host SPMD each process loads only its shard of every global batch
  (``jax.process_index``), the per-host generalization of the reference's per-tower
  ``batch/n_gpus`` contract (reference: model.py:156-159, 298-299);
- a double-buffered device prefetcher overlaps host→HBM copies with compute (the
  reference's ``prefetch(2×n_gpus)``, model.py:319-320).
"""

from __future__ import annotations

import os
import threading
import queue as queue_lib
import weakref
from glob import glob
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import jax
import numpy as np

from tensorflowdistributedlearning_tpu.data.augment import MEAN, STD


def load_png(path: str) -> np.ndarray:
    """Decode one grayscale PNG to [H, W, 1] float32 in [0, 1] (reference:
    preprocessing/preprocessing.py:91-97 — which called decode_jpeg on PNGs; the files
    are PNGs, SURVEY §2.4.12)."""
    from PIL import Image

    with Image.open(path) as im:
        arr = np.asarray(im.convert("L"), np.float32) / 255.0
    return arr[:, :, None]


def load_masks(data_dir: str, ids: Sequence[str]) -> np.ndarray:
    """Decode ``{data_dir}/masks/{id}.png`` to binary [N, H, W, 1] float32 masks —
    the single source of the mask-decode recipe (native batch decode + 0.5
    threshold) shared by the dataset loader and the stratification helpers."""
    from tensorflowdistributedlearning_tpu.native import decode_png_batch

    paths = [os.path.join(data_dir, "masks", f"{i}.png") for i in ids]
    h, w = load_png(paths[0]).shape[:2]
    return (decode_png_batch(paths, h, w, channels=1) > 0.5).astype(np.float32)


def discover_ids(data_dir: str) -> List[str]:
    """List example ids from ``{data_dir}/images/*.png`` (the reference globbed the
    same layout, model.py:289-294)."""
    paths = sorted(glob(os.path.join(data_dir, "images", "*.png")))
    return [os.path.splitext(os.path.basename(p))[0] for p in paths]


def mask_coverage(masks: np.ndarray) -> np.ndarray:
    """Fraction of positive pixels per mask, the notebooks' stratification signal
    (Untitled.ipynb cell 4)."""
    flat = masks.reshape(masks.shape[0], -1)
    return flat.mean(axis=1)


class InMemoryDataset:
    """Decoded, normalized examples held in host RAM.

    ``images``: [N, H, W, 1] float32, already (x-MEAN)/STD normalized;
    ``masks``: [N, H, W, 1] float32 in {0, 1} (None for test sets).
    """

    def __init__(self, images: np.ndarray, masks: Optional[np.ndarray], ids: List[str]):
        self.images = images
        self.masks = masks
        self.ids = ids

    def __len__(self) -> int:
        return len(self.ids)

    @classmethod
    def from_directory(
        cls,
        data_dir: str,
        ids: Optional[Sequence[str]] = None,
        with_masks: bool = True,
        normalize: bool = True,
    ) -> "InMemoryDataset":
        """Load ``{data_dir}/images/{id}.png`` (+ ``masks/``) for the given ids."""
        if ids is None:
            ids = discover_ids(data_dir)
        ids = list(ids)
        if not ids:
            raise ValueError(f"No examples found under {data_dir}/images")
        from tensorflowdistributedlearning_tpu.native import decode_png_batch

        image_paths = [os.path.join(data_dir, "images", f"{i}.png") for i in ids]
        # probe the first file for the dataset's (static) spatial shape
        h, w = load_png(image_paths[0]).shape[:2]
        # multithreaded native decode (GIL-free C++; PIL fallback inside)
        images = decode_png_batch(image_paths, h, w, channels=1)
        if normalize:
            images = (images - MEAN) / STD
        masks = load_masks(data_dir, ids) if with_masks else None
        return cls(images, masks, ids)

    def select(self, ids: Sequence[str]) -> "InMemoryDataset":
        index = {i: k for k, i in enumerate(self.ids)}
        rows = np.asarray([index[i] for i in ids])
        return InMemoryDataset(
            self.images[rows],
            None if self.masks is None else self.masks[rows],
            list(ids),
        )


def host_shard(ids: Sequence[str]) -> List[str]:
    """The ids this process is responsible for under multi-host SPMD. Single-host
    (the reference's only mode) returns everything."""
    n = jax.process_count()
    if n == 1:
        return list(ids)
    return list(ids)[jax.process_index() :: n]


def train_batches(
    dataset: InMemoryDataset,
    batch_size: int,
    seed: int,
    steps: Optional[int] = None,
) -> Iterator[Dict[str, np.ndarray]]:
    """Infinite (or ``steps``-bounded) stream of shuffled {'images', 'masks'} batches.

    Full reshuffle each epoch with a seeded RNG — strictly stronger mixing than the
    reference's 10×batch shuffle buffer (model.py:301-304) and reproducible, which the
    reference's was not.
    """
    n = len(dataset)
    if n == 0:
        raise ValueError("Empty dataset")
    rng = np.random.default_rng(seed)
    # Epoch permutations are chained so full batches always come off an infinite
    # stream — the reference's ``shuffle_and_repeat`` semantics (model.py:301-304),
    # which also serve folds smaller than one batch.
    order = rng.permutation(n)
    pos = 0
    emitted = 0
    while steps is None or emitted < steps:
        while len(order) - pos < batch_size:
            order = np.concatenate([order[pos:], rng.permutation(n)])
            pos = 0
        rows = order[pos : pos + batch_size]
        pos += batch_size
        emitted += 1
        yield {"images": dataset.images[rows], "masks": dataset.masks[rows]}


def eval_index_batches(
    n: int, batch_size: int, num_batches: Optional[int] = None
) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    """Yield ``(rows, valid)`` index batches covering ``n`` examples in order.

    The single source of the eval padding contract, shared by every eval stream
    (in-memory segmentation, streaming ImageFolder): the final partial batch wraps
    around (modulo ``n``) so shapes stay static for jit, and the 0/1 ``valid``
    mask excludes pad rows from the weighted streaming means — every example
    counts exactly once regardless of ``n % batch_size``.

    ``num_batches`` forces the stream to exactly that length (extra batches are
    all-padding, valid=0): under multi-host SPMD every process must run the SAME
    number of collective-bearing eval steps even when host shards differ in size
    — including n=0, the empty-shard edge, where every batch is pure padding
    (rows full of index 0 into a caller-provided placeholder) — or the jitted
    steps deadlock; see ``multihost.eval_num_batches``."""
    total = num_batches if num_batches is not None else max(1, -(-n // batch_size))
    for b in range(total):
        start = b * batch_size
        rows = np.arange(start, min(start + batch_size, n), dtype=np.int64)
        valid = np.ones(batch_size, np.float32)
        if len(rows) < batch_size:
            valid[len(rows) :] = 0.0
            pad = (
                np.arange(batch_size - len(rows), dtype=np.int64) % n
                if n > 0
                else np.zeros(batch_size - len(rows), np.int64)
            )
            rows = np.concatenate([rows, pad])
        yield rows, valid


def eval_batches(
    dataset: InMemoryDataset, batch_size: int, num_batches: Optional[int] = None
) -> Iterator[Dict[str, np.ndarray]]:
    """One ordered pass over the dataset as {'images', 'valid'[, 'masks']} batches
    under the ``eval_index_batches`` padding contract (wrap-around pad rows,
    ``valid`` mask, optional forced multi-host step count). Datasets without masks
    (test sets) yield only {'images', 'valid'}."""
    n = len(dataset)
    h, w, c = dataset.images.shape[1:]
    zero_images = np.zeros((batch_size, h, w, c), np.float32)
    for rows, valid in eval_index_batches(n, batch_size, num_batches):
        if n == 0:
            batch = {"images": zero_images, "valid": valid}
            if dataset.masks is not None:
                batch["masks"] = np.zeros((batch_size, h, w, 1), np.float32)
        else:
            batch = {"images": dataset.images[rows], "valid": valid}
            if dataset.masks is not None:
                batch["masks"] = dataset.masks[rows]
        yield batch


def device_prefetch(
    iterator: Iterator, place, depth: int = 2, registry=None
) -> Iterator:
    """Buffered host→device prefetch (the reference's ``prefetch(2×n_gpus)``,
    model.py:319-320): a daemon thread stays ``depth`` batches ahead so HBM copies
    overlap the previous step's compute. ``place`` maps a host batch to device arrays
    (e.g. ``lambda b: shard_batch(b, mesh)``); ``depth`` is
    ``TrainConfig.prefetch_depth`` in the trainers. The streaming data
    service (data/service.py) plugs its in-order batch stream into this same
    producer — assembly parallelism upstream, placement overlap here.

    ``registry`` (an ``obs.metrics.MetricsRegistry``) records the ready-queue
    depth observed at each consumer take into the ``prefetch/queue_depth``
    histogram — the per-window gauge that makes prefetch underruns visible in
    ``telemetry-report``.

    Shutdown contract: producer puts are stop-aware, so a consumer that
    abandons iteration early (a preemption raise mid-epoch, a test that reads
    one batch) releases the thread within one poll interval instead of
    leaving it blocked forever on a full queue — the consumer generator's
    ``finally`` signals stop on close, and a finalizer covers a generator
    that is dropped without ever being iterated. Depth validation and the
    thread start are EAGER (this is a plain function returning a generator),
    so a bad depth fails at the call site and prefetch begins before the
    first ``next``."""
    if depth < 1:
        raise ValueError(f"device_prefetch depth must be >= 1, got {depth}")
    q: queue_lib.Queue = queue_lib.Queue(maxsize=depth)
    stop = threading.Event()
    _done = object()
    _error = object()

    def put(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.05)
                return True
            except queue_lib.Full:
                continue
        return False

    def producer():
        try:
            for item in iterator:
                if not put(place(item)):
                    return
        except BaseException as e:  # noqa: BLE001 — re-raised on the consumer side
            put((_error, e))
            return
        put(_done)

    thread = threading.Thread(target=producer, daemon=True, name="device_prefetch")
    thread.start()
    hist = None
    if registry is not None:
        from tensorflowdistributedlearning_tpu.obs.telemetry import (
            PREFETCH_DEPTH_HISTOGRAM,
        )

        hist = registry.histogram(PREFETCH_DEPTH_HISTOGRAM)

    def consume():
        try:
            while True:
                item = q.get()
                if item is _done:
                    return
                if isinstance(item, tuple) and len(item) == 2 and item[0] is _error:
                    raise item[1]
                if hist is not None:
                    # batches still ready behind the one just taken: 0 means
                    # the consumer caught the producer (an underrun)
                    hist.record(float(q.qsize()))
                yield item
        finally:
            stop.set()

    gen = consume()
    # a generator dropped before its first next() never enters the try above,
    # so its finally cannot release the producer — the finalizer does
    weakref.finalize(gen, stop.set)
    return gen
