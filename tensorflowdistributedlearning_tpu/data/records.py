"""TFRecord streaming: native threaded reader + writer + classification stream.

The reference's input runtime was tf.data's C++ pipeline reading from disk
(SURVEY §2.2 — inherited native machinery); this module is the first-party
equivalent for record-sharded datasets (the standard on-disk form of
ImageNet-scale corpora, where per-file ImageFolder IO is seek-bound):

- ``write_records`` / ``read_records``: the public TFRecord framing
  (length + masked crc32c + payload + crc), pure Python — the writer is a
  dataset-prep tool, the reader the fallback when no C++ toolchain exists.
- ``RecordStream``: ctypes binding over ``native/records.cc`` — one background
  C++ thread per stream reads ahead (file IO overlaps decode/augment on the
  consumer side, no GIL), verifies crcs, and serves from a shuffle pool.
- ``ClassificationRecords`` + ``train_stream``/``eval_stream``: the fit-loop
  source for record shards. Payload layout: ``int32 LE label | encoded image``
  (PNG/JPEG bytes, decoded by the native batch decoder in data/imagefolder's
  pipeline style).

Sharding contract for multi-host runs: pass each process a disjoint subset of
shard files (``host_shard_paths``), the record-level generalization of
pipeline.host_shard.
"""

from __future__ import annotations

import ctypes
import glob as glob_lib
import os
import struct
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from tensorflowdistributedlearning_tpu.native import loader as native_loader
from tensorflowdistributedlearning_tpu.resilience import faults
import tensorflowdistributedlearning_tpu.resilience.retry as retry_lib


def _open_shard(path: str, mode: str = "rb"):
    """Shard-file open with transient-I/O retry (resilience/retry.py) — the
    failure mode network filesystems actually exhibit mid-epoch; the
    injectable ``io-read`` fault site lives inside the attempt."""

    def attempt():
        faults.fire(faults.SITE_IO)
        return open(path, mode)

    return retry_lib.call_with_retry(
        attempt, name="record_open", exceptions=(OSError,)
    )

# -- crc32c (Castagnoli), table-driven — mirrors native/records.cc ------------

_CRC_TABLE: List[int] = []


def _crc_table() -> List[int]:
    global _CRC_TABLE
    if not _CRC_TABLE:
        table = []
        for i in range(256):
            c = i
            for _ in range(8):
                c = (0x82F63B78 ^ (c >> 1)) if (c & 1) else (c >> 1)
            table.append(c)
        _CRC_TABLE = table
    return _CRC_TABLE


def _crc32c(data: bytes) -> int:
    table = _crc_table()
    c = 0xFFFFFFFF
    for b in data:
        c = table[(c ^ b) & 0xFF] ^ (c >> 8)
    return c ^ 0xFFFFFFFF


def masked_crc(data: bytes) -> int:
    crc = _crc32c(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


# -- pure-Python framing ------------------------------------------------------


def write_records(path: str, records: Sequence[bytes]) -> None:
    """Write one TFRecord shard (public framing, readable by any TFRecord
    consumer)."""
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "wb") as f:
        for rec in records:
            header = struct.pack("<Q", len(rec))
            f.write(header)
            f.write(struct.pack("<I", masked_crc(header)))
            f.write(rec)
            f.write(struct.pack("<I", masked_crc(rec)))


def read_records(path: str, verify: bool = True) -> Iterator[bytes]:
    """Pure-Python shard reader (fallback + oracle for the native one)."""
    with _open_shard(path) as f:
        while True:
            header = f.read(12)
            if not header:
                return
            if len(header) != 12:
                raise ValueError(f"{path}: truncated record header")
            (length,) = struct.unpack("<Q", header[:8])
            if verify:
                (want,) = struct.unpack("<I", header[8:12])
                if masked_crc(header[:8]) != want:
                    raise ValueError(f"{path}: corrupt length crc")
            data = f.read(length)
            footer = f.read(4)
            if len(data) != length or len(footer) != 4:
                raise ValueError(f"{path}: truncated record body")
            if verify:
                (want,) = struct.unpack("<I", footer)
                if masked_crc(data) != want:
                    raise ValueError(f"{path}: corrupt data crc")
            yield data


# -- native streaming reader --------------------------------------------------


def _records_lib() -> Optional[ctypes.CDLL]:
    lib = native_loader.load_extra_library(
        "records.cc",
        "libtfdl_records.so",
        link_png=False,
    )
    if lib is None:
        return None
    lib.tfdl_rec_open.restype = ctypes.c_int64
    lib.tfdl_rec_open.argtypes = [
        ctypes.POINTER(ctypes.c_char_p),
        ctypes.c_int,
        ctypes.c_int,
        ctypes.c_uint64,
        ctypes.c_int,
    ]
    lib.tfdl_rec_next.restype = ctypes.c_int
    lib.tfdl_rec_next.argtypes = [
        ctypes.c_int64,
        ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.POINTER(ctypes.c_uint64),
    ]
    lib.tfdl_rec_close.restype = None
    lib.tfdl_rec_close.argtypes = [ctypes.c_int64]
    return lib


class RecordStream:
    """Iterator of record payload bytes over a list of TFRecord shards.

    Native path: background C++ reader thread + crc verification + shuffle
    pool. Fallback: pure-Python sequential read with an equivalent shuffle
    pool (same semantics, GIL-bound)."""

    def __init__(
        self,
        paths: Sequence[str],
        *,
        shuffle_buffer: int = 1,
        seed: int = 0,
        verify_crc: bool = True,
    ):
        if not paths:
            raise ValueError("RecordStream needs at least one shard path")
        self.paths = [os.path.abspath(p) for p in paths]
        self.shuffle_buffer = max(1, int(shuffle_buffer))
        self.seed = seed
        self.verify_crc = verify_crc

    def __iter__(self) -> Iterator[bytes]:
        lib = _records_lib()
        if lib is not None:
            yield from self._iter_native(lib)
        else:
            yield from self._iter_python()

    def _iter_native(self, lib) -> Iterator[bytes]:
        arr = (ctypes.c_char_p * len(self.paths))(
            *[p.encode() for p in self.paths]
        )
        handle = lib.tfdl_rec_open(
            arr,
            len(self.paths),
            self.shuffle_buffer,
            ctypes.c_uint64(self.seed),
            1 if self.verify_crc else 0,
        )
        if handle == 0:
            raise RuntimeError("tfdl_rec_open failed")
        try:
            data = ctypes.POINTER(ctypes.c_uint8)()
            length = ctypes.c_uint64()
            while True:
                rc = lib.tfdl_rec_next(
                    handle, ctypes.byref(data), ctypes.byref(length)
                )
                if rc == 0:
                    return
                if rc == -2:
                    raise IOError(
                        "failed to open/read a TFRecord shard (missing file or "
                        "permissions) among " + ", ".join(self.paths)
                    )
                if rc == -3:
                    raise RuntimeError(
                        "RecordStream handle is invalid or already closed "
                        "(handle-lifecycle bug, not data corruption)"
                    )
                if rc < 0:
                    raise ValueError(
                        "corrupt TFRecord stream (crc/framing mismatch) in "
                        + ", ".join(self.paths)
                    )
                yield ctypes.string_at(data, length.value)
        finally:
            lib.tfdl_rec_close(handle)

    def _iter_python(self) -> Iterator[bytes]:
        rng = np.random.default_rng(self.seed)
        order = list(self.paths)
        rng.shuffle(order)
        pool: List[bytes] = []
        source = (
            rec for path in order for rec in read_records(path, self.verify_crc)
        )
        for rec in source:
            pool.append(rec)
            if len(pool) >= self.shuffle_buffer:
                idx = int(rng.integers(len(pool))) if self.shuffle_buffer > 1 else 0
                pool[idx], pool[-1] = pool[-1], pool[idx]
                yield pool.pop()
        rng.shuffle(pool)
        yield from pool


# -- classification payloads (int32 label + encoded image) --------------------


def encode_classification_record(label: int, image_bytes: bytes) -> bytes:
    return struct.pack("<i", label) + image_bytes


def decode_classification_record(payload: bytes) -> Tuple[int, bytes]:
    (label,) = struct.unpack("<i", payload[:4])
    return label, payload[4:]


def write_classification_shards(
    out_dir: str,
    images: Sequence[np.ndarray],
    labels: Sequence[int],
    *,
    shards: int = 2,
    prefix: str = "train",
) -> List[str]:
    """Encode uint8 HWC images as PNG payload records across ``shards`` files
    (dataset-prep utility; also the test fixture generator)."""
    import io

    from PIL import Image

    paths = []
    records: List[List[bytes]] = [[] for _ in range(shards)]
    for i, (img, label) in enumerate(zip(images, labels)):
        buf = io.BytesIO()
        arr = np.asarray(img)
        Image.fromarray(arr).save(buf, format="PNG")
        records[i % shards].append(
            encode_classification_record(int(label), buf.getvalue())
        )
    for s in range(shards):
        path = os.path.join(out_dir, f"{prefix}-{s:05d}-of-{shards:05d}.tfrecord")
        write_records(path, records[s])
        paths.append(path)
    return paths


def count_records(paths: Sequence[str]) -> int:
    """Number of records across shards via a header-only scan (seeks over
    payloads — no crc, no decode; cheap even for large shards)."""
    total = 0
    for path in paths:
        size = os.path.getsize(path)
        with _open_shard(path) as f:
            while True:
                header = f.read(12)
                if not header:
                    break
                if len(header) != 12:
                    raise ValueError(f"{path}: truncated record header")
                (length,) = struct.unpack("<Q", header[:8])
                f.seek(length + 4, os.SEEK_CUR)
                # seeking past EOF succeeds silently — without this check a
                # shard truncated mid-record would be COUNTED as whole while
                # the verifying reader later fails, desynchronizing the eval
                # batch count from what the stream can deliver
                if f.tell() > size:
                    raise ValueError(f"{path}: truncated record body")
                total += 1
    return total


def host_shard_paths(paths: Sequence[str]) -> List[str]:
    """This process's round-robin subset of shard files (multi-host contract)."""
    import jax

    return [
        p
        for i, p in enumerate(sorted(paths))
        if i % jax.process_count() == jax.process_index()
    ]


class ClassificationRecords:
    """Record-sharded classification source for the fit loop.

    ``root`` holds ``{split}-*.tfrecord`` shards (see
    ``write_classification_shards``). Streams decode through the native image
    decoder in batches; infinite train stream re-opens the shards each epoch
    with a reseeded shuffle."""

    def __init__(
        self,
        root: str,
        *,
        split: str = "train",
        image_shape: Tuple[int, int] = (32, 32),
        channels: int = 3,
        num_classes: Optional[int] = None,
    ):
        self.paths = sorted(
            glob_lib.glob(os.path.join(root, f"{split}-*.tfrecord"))
        )
        if not self.paths:
            raise ValueError(f"No {split}-*.tfrecord shards under {root}")
        self.image_shape = image_shape
        self.channels = channels
        self.num_classes = num_classes

    def _check_labels(self, labels: np.ndarray) -> None:
        if self.num_classes is not None and labels.size:
            lo, hi = int(labels.min()), int(labels.max())
            if lo < 0 or hi >= self.num_classes:
                raise ValueError(
                    f"record label out of range [0, {self.num_classes}): "
                    f"saw {lo}..{hi} — the shards hold more classes than the "
                    "model's num_classes"
                )

    def _emit(self, blobs: List[bytes], labels: List[int], valid_rows: int):
        from tensorflowdistributedlearning_tpu.data.imagefolder import _normalize

        h, w = self.image_shape
        arr_labels = np.asarray(labels, np.int32)
        self._check_labels(arr_labels[:valid_rows])

        def attempt():
            # decode is re-runnable from the buffered blobs, so a transient
            # decode-side I/O failure on the Nth batch (the injectable
            # ``io-data`` site) retries instead of killing the stream
            faults.fire(faults.SITE_DATA)
            return native_loader.decode_image_blobs(blobs, (h, w), self.channels)

        images = retry_lib.call_with_retry(
            attempt, name="record_batch", exceptions=(OSError,)
        )
        valid = np.zeros(len(blobs), np.float32)
        valid[:valid_rows] = 1.0
        return {
            "images": _normalize(images, self.channels),
            "labels": arr_labels,
            "valid": valid,
        }

    def batches(
        self,
        batch_size: int,
        *,
        seed: int = 0,
        shuffle_buffer: int = 1024,
        repeat: bool = True,
        steps: Optional[int] = None,
        pad_to_batches: Optional[int] = None,
    ) -> Iterator[Dict[str, np.ndarray]]:
        """Batched {'images','labels','valid'} stream.

        ``repeat=True``: infinite (or ``steps``-bounded) shuffled training
        stream, every row valid. A partial batch at an epoch boundary is
        CARRIED into the next epoch (batches may span epochs; no records are
        dropped, and datasets smaller than ``batch_size`` still emit batches
        instead of spinning forever). ``repeat=False``: one ordered pass; with
        ``pad_to_batches`` the stream is EXTENDED to exactly that many batches
        by wrapping around to the start with ``valid=0`` rows (the streaming
        analogue of pipeline.eval_batches' wrap-around padding — metrics
        exclude the padding, and every multi-host process can run the same
        number of collective-bearing eval steps)."""
        emitted = 0
        epoch = 0
        labels: List[int] = []
        blobs: List[bytes] = []
        while True:
            stream = RecordStream(
                self.paths,
                shuffle_buffer=shuffle_buffer if repeat else 1,
                seed=seed + epoch,
            )
            seen_any = False
            for payload in stream:
                seen_any = True
                label, img = decode_classification_record(payload)
                labels.append(label)
                blobs.append(img)
                if len(blobs) == batch_size:
                    yield self._emit(blobs, labels, batch_size)
                    emitted += 1
                    labels, blobs = [], []
                    if repeat and steps is not None and emitted >= steps:
                        return
                    if (
                        not repeat
                        and pad_to_batches is not None
                        and emitted >= pad_to_batches
                    ):
                        return
            if not seen_any:
                raise ValueError(
                    "record shards contain zero records: " + ", ".join(self.paths)
                )
            if not repeat:
                tail_valid = len(blobs)
                if blobs or (pad_to_batches or 0) > emitted:
                    # wrap around for padding rows (valid=0): reopen the stream
                    refill = RecordStream(self.paths, shuffle_buffer=1, seed=seed)
                    refill_iter = iter(refill)
                    target = pad_to_batches if pad_to_batches is not None else (
                        emitted + 1 if blobs else emitted
                    )
                    while emitted < target:
                        while len(blobs) < batch_size:
                            payload = next(refill_iter, None)
                            if payload is None:
                                refill_iter = iter(
                                    RecordStream(
                                        self.paths, shuffle_buffer=1, seed=seed
                                    )
                                )
                                payload = next(refill_iter)
                            label, img = decode_classification_record(payload)
                            labels.append(label)
                            blobs.append(img)
                        yield self._emit(blobs, labels, tail_valid)
                        emitted += 1
                        labels, blobs = [], []
                        tail_valid = 0  # later padded batches are fully invalid
                return
            epoch += 1
