"""TFRecord streaming: native threaded reader + writer + classification stream.

The reference's input runtime was tf.data's C++ pipeline reading from disk
(SURVEY §2.2 — inherited native machinery); this module is the first-party
equivalent for record-sharded datasets (the standard on-disk form of
ImageNet-scale corpora, where per-file ImageFolder IO is seek-bound):

- ``write_records`` / ``read_records``: the public TFRecord framing
  (length + masked crc32c + payload + crc), pure Python — the writer is a
  dataset-prep tool, the reader the fallback when no C++ toolchain exists.
- ``RecordStream``: ctypes binding over ``native/records.cc`` — one background
  C++ thread per stream reads ahead (file IO overlaps decode/augment on the
  consumer side, no GIL), verifies crcs, and serves from a shuffle pool.
- ``ClassificationRecords`` + ``train_stream``/``eval_stream``: the fit-loop
  source for record shards. Payload layout: ``int32 LE label | encoded image``
  (PNG/JPEG bytes, decoded by the native batch decoder in data/imagefolder's
  pipeline style); image decodes run ``decode_ahead`` batches ahead of the
  consumer so decode overlaps the (already background) read.
- ``write_shard_index``/``shard_offsets``: the ``.idx`` count/offset sidecar
  (written at shard-prep time, verified against the shard's byte size and
  mtime) — ``count_records`` and the data service skip the full-file scan.
- ``ShardRangeReader``: random-access record reads at indexed byte offsets
  (native fseek+crc via ``tfdl_ranges_*``, pure-Python fallback) — the
  read primitive under ``data/service.py``'s parallel workers.

Sharding contract for multi-host runs: pass each process a disjoint subset of
shard files (``host_shard_paths``), the record-level generalization of
pipeline.host_shard — or let ``data.service.epoch_shard_assignment`` re-deal
the full shard set every epoch (the global-shuffle generalization).
"""

from __future__ import annotations

import ctypes
import glob as glob_lib
import os
import struct
from zipfile import BadZipFile as zipfile_BadZipFile
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from tensorflowdistributedlearning_tpu.native import loader as native_loader
from tensorflowdistributedlearning_tpu.resilience import faults
import tensorflowdistributedlearning_tpu.resilience.retry as retry_lib


def _open_shard(path: str, mode: str = "rb"):
    """Shard-file open with transient-I/O retry (resilience/retry.py) — the
    failure mode network filesystems actually exhibit mid-epoch; the
    injectable ``io-read`` fault site lives inside the attempt."""

    def attempt():
        faults.fire(faults.SITE_IO)
        return open(path, mode)

    return retry_lib.call_with_retry(
        attempt, name="record_open", exceptions=(OSError,)
    )

# -- crc32c (Castagnoli), table-driven — mirrors native/records.cc ------------

_CRC_TABLE: List[int] = []


def _crc_table() -> List[int]:
    global _CRC_TABLE
    if not _CRC_TABLE:
        table = []
        for i in range(256):
            c = i
            for _ in range(8):
                c = (0x82F63B78 ^ (c >> 1)) if (c & 1) else (c >> 1)
            table.append(c)
        _CRC_TABLE = table
    return _CRC_TABLE


def _crc32c(data: bytes) -> int:
    table = _crc_table()
    c = 0xFFFFFFFF
    for b in data:
        c = table[(c ^ b) & 0xFF] ^ (c >> 8)
    return c ^ 0xFFFFFFFF


def masked_crc(data: bytes) -> int:
    crc = _crc32c(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


# -- pure-Python framing ------------------------------------------------------


def write_records(path: str, records: Sequence[bytes]) -> None:
    """Write one TFRecord shard (public framing, readable by any TFRecord
    consumer)."""
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "wb") as f:
        for rec in records:
            header = struct.pack("<Q", len(rec))
            f.write(header)
            f.write(struct.pack("<I", masked_crc(header)))
            f.write(rec)
            f.write(struct.pack("<I", masked_crc(rec)))
    # a rewritten shard invalidates any existing .idx sidecar NOW: a
    # same-byte-size rewrite landing within one mtime tick would otherwise
    # pass shard_offsets' freshness check and serve stale offsets
    try:
        os.remove(shard_index_path(path))
    except FileNotFoundError:
        pass


def read_records(path: str, verify: bool = True) -> Iterator[bytes]:
    """Pure-Python shard reader (fallback + oracle for the native one)."""
    with _open_shard(path) as f:
        while True:
            header = f.read(12)
            if not header:
                return
            if len(header) != 12:
                raise ValueError(f"{path}: truncated record header")
            (length,) = struct.unpack("<Q", header[:8])
            if verify:
                (want,) = struct.unpack("<I", header[8:12])
                if masked_crc(header[:8]) != want:
                    raise ValueError(f"{path}: corrupt length crc")
            data = f.read(length)
            footer = f.read(4)
            if len(data) != length or len(footer) != 4:
                raise ValueError(f"{path}: truncated record body")
            if verify:
                (want,) = struct.unpack("<I", footer)
                if masked_crc(data) != want:
                    raise ValueError(f"{path}: corrupt data crc")
            yield data


# -- native streaming reader --------------------------------------------------


def _records_lib() -> Optional[ctypes.CDLL]:
    lib = native_loader.load_extra_library(
        "records.cc",
        "libtfdl_records.so",
        link_png=False,
    )
    if lib is None:
        return None
    lib.tfdl_rec_open.restype = ctypes.c_int64
    lib.tfdl_rec_open.argtypes = [
        ctypes.POINTER(ctypes.c_char_p),
        ctypes.c_int,
        ctypes.c_int,
        ctypes.c_uint64,
        ctypes.c_int,
    ]
    lib.tfdl_rec_next.restype = ctypes.c_int
    lib.tfdl_rec_next.argtypes = [
        ctypes.c_int64,
        ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.POINTER(ctypes.c_uint64),
    ]
    lib.tfdl_rec_close.restype = None
    lib.tfdl_rec_close.argtypes = [ctypes.c_int64]
    # offset-indexed range reads (data/service.py workers); absent on a stale
    # pre-rebuild .so — callers hasattr-check and fall back to pure Python
    if hasattr(lib, "tfdl_ranges_open"):
        lib.tfdl_ranges_open.restype = ctypes.c_int64
        lib.tfdl_ranges_open.argtypes = [ctypes.c_char_p]
        lib.tfdl_ranges_read.restype = ctypes.c_int
        lib.tfdl_ranges_read.argtypes = [
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_int,
            ctypes.c_int,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.tfdl_ranges_close.restype = None
        lib.tfdl_ranges_close.argtypes = [ctypes.c_int64]
    return lib


class RecordStream:
    """Iterator of record payload bytes over a list of TFRecord shards.

    Native path: background C++ reader thread + crc verification + shuffle
    pool. Fallback: pure-Python sequential read with an equivalent shuffle
    pool (same semantics, GIL-bound)."""

    def __init__(
        self,
        paths: Sequence[str],
        *,
        shuffle_buffer: int = 1,
        seed: int = 0,
        verify_crc: bool = True,
    ):
        if not paths:
            raise ValueError("RecordStream needs at least one shard path")
        self.paths = [os.path.abspath(p) for p in paths]
        self.shuffle_buffer = max(1, int(shuffle_buffer))
        self.seed = seed
        self.verify_crc = verify_crc

    def __iter__(self) -> Iterator[bytes]:
        lib = _records_lib()
        if lib is not None:
            yield from self._iter_native(lib)
        else:
            yield from self._iter_python()

    def _iter_native(self, lib) -> Iterator[bytes]:
        arr = (ctypes.c_char_p * len(self.paths))(
            *[p.encode() for p in self.paths]
        )
        handle = lib.tfdl_rec_open(
            arr,
            len(self.paths),
            self.shuffle_buffer,
            ctypes.c_uint64(self.seed),
            1 if self.verify_crc else 0,
        )
        if handle == 0:
            raise RuntimeError("tfdl_rec_open failed")
        try:
            data = ctypes.POINTER(ctypes.c_uint8)()
            length = ctypes.c_uint64()
            while True:
                rc = lib.tfdl_rec_next(
                    handle, ctypes.byref(data), ctypes.byref(length)
                )
                if rc == 0:
                    return
                if rc == -2:
                    raise IOError(
                        "failed to open/read a TFRecord shard (missing file or "
                        "permissions) among " + ", ".join(self.paths)
                    )
                if rc == -3:
                    raise RuntimeError(
                        "RecordStream handle is invalid or already closed "
                        "(handle-lifecycle bug, not data corruption)"
                    )
                if rc < 0:
                    raise ValueError(
                        "corrupt TFRecord stream (crc/framing mismatch) in "
                        + ", ".join(self.paths)
                    )
                yield ctypes.string_at(data, length.value)
        finally:
            lib.tfdl_rec_close(handle)

    def _iter_python(self) -> Iterator[bytes]:
        rng = np.random.default_rng(self.seed)
        order = list(self.paths)
        rng.shuffle(order)
        pool: List[bytes] = []
        source = (
            rec for path in order for rec in read_records(path, self.verify_crc)
        )
        for rec in source:
            pool.append(rec)
            if len(pool) >= self.shuffle_buffer:
                idx = int(rng.integers(len(pool))) if self.shuffle_buffer > 1 else 0
                pool[idx], pool[-1] = pool[-1], pool[idx]
                yield pool.pop()
        rng.shuffle(pool)
        yield from pool


# -- classification payloads (int32 label + encoded image) --------------------


def encode_classification_record(label: int, image_bytes: bytes) -> bytes:
    return struct.pack("<i", label) + image_bytes


def check_classification_labels(
    labels: np.ndarray, num_classes: Optional[int]
) -> None:
    """Label-range validation shared by every classification record consumer
    (``None`` skips — unknown class count)."""
    if num_classes is not None and labels.size:
        lo, hi = int(labels.min()), int(labels.max())
        if lo < 0 or hi >= num_classes:
            raise ValueError(
                f"record label out of range [0, {num_classes}): "
                f"saw {lo}..{hi} — the shards hold more classes than the "
                "model's num_classes"
            )


def decode_classification_batch(
    blobs: Sequence[bytes],
    labels: Sequence[int],
    valid_rows: int,
    *,
    image_shape: Tuple[int, int],
    channels: int,
    num_classes: Optional[int] = None,
) -> Dict[str, np.ndarray]:
    """THE blobs+labels -> ``{'images','labels','valid'}`` assembly: label
    validation (valid rows only), native blob decode behind the retryable
    ``io-data`` fault site, normalization. The single decode recipe shared by
    the legacy stream (``ClassificationRecords``) and the data service's
    workers (``data/service.py``) — one place for the semantics both paths
    must agree on."""
    from tensorflowdistributedlearning_tpu.data.imagefolder import _normalize

    h, w = image_shape
    arr_labels = np.asarray(labels, np.int32)
    check_classification_labels(arr_labels[:valid_rows], num_classes)

    def attempt():
        # decode is re-runnable from the buffered blobs, so a transient
        # decode-side I/O failure on the Nth batch (the injectable
        # ``io-data`` site) retries instead of killing the stream
        faults.fire(faults.SITE_DATA)
        return native_loader.decode_image_blobs(blobs, (h, w), channels)

    images = retry_lib.call_with_retry(
        attempt, name="record_batch", exceptions=(OSError,)
    )
    valid = np.zeros(len(blobs), np.float32)
    valid[:valid_rows] = 1.0
    return {
        "images": _normalize(images, channels),
        "labels": arr_labels,
        "valid": valid,
    }


def decode_classification_record(payload: bytes) -> Tuple[int, bytes]:
    (label,) = struct.unpack("<i", payload[:4])
    return label, payload[4:]


def write_classification_shards(
    out_dir: str,
    images: Sequence[np.ndarray],
    labels: Sequence[int],
    *,
    shards: int = 2,
    prefix: str = "train",
) -> List[str]:
    """Encode uint8 HWC images as PNG payload records across ``shards`` files
    (dataset-prep utility; also the test fixture generator)."""
    import io

    from PIL import Image

    paths = []
    records: List[List[bytes]] = [[] for _ in range(shards)]
    for i, (img, label) in enumerate(zip(images, labels)):
        buf = io.BytesIO()
        arr = np.asarray(img)
        Image.fromarray(arr).save(buf, format="PNG")
        records[i % shards].append(
            encode_classification_record(int(label), buf.getvalue())
        )
    for s in range(shards):
        path = os.path.join(out_dir, f"{prefix}-{s:05d}-of-{shards:05d}.tfrecord")
        write_records(path, records[s])
        # count/offset sidecar at prep time: count_records and the data
        # service's offset-indexed workers skip the full-file scan
        write_shard_index(path)
        paths.append(path)
    return paths


# -- shard record index (.idx sidecar) ----------------------------------------

INDEX_SUFFIX = ".idx"


def shard_index_path(path: str) -> str:
    return path + INDEX_SUFFIX


def _scan_offsets(path: str) -> np.ndarray:
    """Record start offsets via a header-only scan (seeks over payloads — no
    crc, no decode; cheap even for large shards). Raises on truncation."""
    offsets: List[int] = []
    size = os.path.getsize(path)
    with _open_shard(path) as f:
        pos = 0
        while True:
            header = f.read(12)
            if not header:
                break
            if len(header) != 12:
                raise ValueError(f"{path}: truncated record header")
            (length,) = struct.unpack("<Q", header[:8])
            f.seek(length + 4, os.SEEK_CUR)
            # seeking past EOF succeeds silently — without this check a
            # shard truncated mid-record would be COUNTED as whole while
            # the verifying reader later fails, desynchronizing the eval
            # batch count from what the stream can deliver
            if f.tell() > size:
                raise ValueError(f"{path}: truncated record body")
            offsets.append(pos)
            pos += 12 + length + 4
    return np.asarray(offsets, np.uint64)


def write_shard_index(path: str) -> np.ndarray:
    """Write the ``.idx`` count/offset sidecar for one shard: record start
    offsets plus the shard's byte size for staleness detection. Written by
    ``write_classification_shards`` at prep time so ``count_records`` and the
    data service never pay the full-file scan; atomic install, so a torn
    writer cannot leave a half-index that parses. Returns the offsets it
    indexed (callers wanting the count need not re-read the sidecar)."""
    idx = shard_index_path(path)
    offsets = _scan_offsets(path)
    tmp = f"{idx}.{os.getpid()}.tmp"
    with open(tmp, "wb") as f:
        np.savez(f, offsets=offsets, file_size=np.int64(os.path.getsize(path)))
    os.replace(tmp, idx)
    return offsets


def shard_offsets(path: str) -> np.ndarray:
    """Record start offsets for one shard: from the ``.idx`` sidecar when it
    is present and FRESH (stored byte size matches the shard and the sidecar
    is not older than it — a rewritten shard invalidates its index), else a
    header scan. Never trusts a stale index: wrong offsets would read garbage
    framing and fail far from the cause."""
    idx = shard_index_path(path)
    try:
        if os.path.getmtime(idx) >= os.path.getmtime(path):
            with np.load(idx) as z:
                if int(z["file_size"]) == os.path.getsize(path):
                    return z["offsets"].astype(np.uint64)
    except (OSError, KeyError, ValueError, zipfile_BadZipFile):
        pass  # missing/corrupt/legacy sidecar: the scan is the oracle
    return _scan_offsets(path)


def count_records(paths: Sequence[str]) -> int:
    """Number of records across shards — the ``.idx`` sidecar when fresh
    (O(1) per shard), else the header-only scan."""
    return sum(len(shard_offsets(p)) for p in paths)


class ShardRangeReader:
    """Random-access record reads at known byte offsets — the data-service
    worker read path (offsets come from ``shard_offsets``). Native fseek/fread
    with crc verification in C++ when available, pure-Python fallback with the
    same semantics. One reader serves ONE thread; each service worker opens
    its own."""

    def __init__(self, path: str, *, verify_crc: bool = True):
        self.path = os.path.abspath(path)
        self.verify_crc = verify_crc
        self._lib = None
        self._handle = 0
        self._file = None
        lib = _records_lib()
        if lib is not None and hasattr(lib, "tfdl_ranges_open"):
            handle = lib.tfdl_ranges_open(self.path.encode())
            if handle == 0:
                raise IOError(f"cannot open record shard {self.path}")
            self._lib, self._handle = lib, handle
        else:
            self._file = _open_shard(self.path)

    def read(self, offsets: Sequence[int]) -> List[bytes]:
        """Record payloads at ``offsets``, in the given order."""
        offsets = list(offsets)
        if not offsets:
            return []
        if self._lib is not None:
            n = len(offsets)
            arr = (ctypes.c_uint64 * n)(*[int(o) for o in offsets])
            datas = (ctypes.POINTER(ctypes.c_uint8) * n)()
            lens = (ctypes.c_uint64 * n)()
            rc = self._lib.tfdl_ranges_read(
                self._handle, arr, n, 1 if self.verify_crc else 0, datas, lens
            )
            if rc == -3:
                raise RuntimeError(
                    "ShardRangeReader handle is invalid or already closed"
                )
            if rc == -2:
                raise IOError(f"read failed in record shard {self.path}")
            if rc != 0:
                raise ValueError(
                    f"{self.path}: corrupt record at an indexed offset "
                    "(crc/framing mismatch — stale .idx or shard damage)"
                )
            return [ctypes.string_at(datas[i], lens[i]) for i in range(n)]
        out = []
        for off in offsets:
            self._file.seek(int(off))
            header = self._file.read(12)
            if len(header) != 12:
                raise ValueError(f"{self.path}: truncated record header")
            (length,) = struct.unpack("<Q", header[:8])
            if self.verify_crc:
                (want,) = struct.unpack("<I", header[8:12])
                if masked_crc(header[:8]) != want:
                    raise ValueError(f"{self.path}: corrupt length crc")
            data = self._file.read(length)
            footer = self._file.read(4)
            if len(data) != length or len(footer) != 4:
                raise ValueError(f"{self.path}: truncated record body")
            if self.verify_crc:
                (want,) = struct.unpack("<I", footer)
                if masked_crc(data) != want:
                    raise ValueError(f"{self.path}: corrupt data crc")
            out.append(data)
        return out

    def close(self) -> None:
        if self._lib is not None and self._handle:
            self._lib.tfdl_ranges_close(self._handle)
            self._handle = 0
            self._lib = None
        if self._file is not None:
            self._file.close()
            self._file = None

    def __enter__(self) -> "ShardRangeReader":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self):  # best-effort: workers cache readers thread-locally
        try:
            self.close()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass


def host_shard_paths(
    paths: Sequence[str],
    process_index: Optional[int] = None,
    process_count: Optional[int] = None,
) -> List[str]:
    """This process's round-robin subset of shard files (multi-host contract;
    the STATIC assignment — ``data.service.epoch_shard_assignment`` is the
    epoch-reshuffled generalization). Explicit process arguments exist for
    tests and tools; the default reads the jax cluster."""
    if process_index is None or process_count is None:
        import jax

        process_index = jax.process_index()
        process_count = jax.process_count()
    return [
        p
        for i, p in enumerate(sorted(paths))
        if i % process_count == process_index
    ]


class ClassificationRecords:
    """Record-sharded classification source for the fit loop.

    ``root`` holds ``{split}-*.tfrecord`` shards (see
    ``write_classification_shards``). Streams decode through the native image
    decoder in batches; infinite train stream re-opens the shards each epoch
    with a reseeded shuffle."""

    def __init__(
        self,
        root: str,
        *,
        split: str = "train",
        image_shape: Tuple[int, int] = (32, 32),
        channels: int = 3,
        num_classes: Optional[int] = None,
    ):
        self.paths = sorted(
            glob_lib.glob(os.path.join(root, f"{split}-*.tfrecord"))
        )
        if not self.paths:
            raise ValueError(f"No {split}-*.tfrecord shards under {root}")
        self.image_shape = image_shape
        self.channels = channels
        self.num_classes = num_classes

    def _emit(self, blobs: List[bytes], labels: List[int], valid_rows: int):
        return decode_classification_batch(
            blobs,
            labels,
            valid_rows,
            image_shape=self.image_shape,
            channels=self.channels,
            num_classes=self.num_classes,
        )

    def batches(
        self,
        batch_size: int,
        *,
        seed: int = 0,
        shuffle_buffer: int = 1024,
        repeat: bool = True,
        steps: Optional[int] = None,
        pad_to_batches: Optional[int] = None,
        decode_ahead: int = 1,
    ) -> Iterator[Dict[str, np.ndarray]]:
        """Batched {'images','labels','valid'} stream.

        ``repeat=True``: infinite (or ``steps``-bounded) shuffled training
        stream, every row valid. A partial batch at an epoch boundary is
        CARRIED into the next epoch (batches may span epochs; no records are
        dropped, and datasets smaller than ``batch_size`` still emit batches
        instead of spinning forever). ``repeat=False``: one ordered pass; with
        ``pad_to_batches`` the stream is EXTENDED to exactly that many batches
        by wrapping around to the start with ``valid=0`` rows (the streaming
        analogue of pipeline.eval_batches' wrap-around padding — metrics
        exclude the padding, and every multi-host process can run the same
        number of collective-bearing eval steps).

        ``decode_ahead``: image decodes run in a background thread up to this
        many batches ahead of the consumer, so decode OVERLAPS the (native,
        already-background) record read instead of serializing behind it —
        the end2end fix for RECORDS_BENCH's decode-loses-to-PIL regression.
        Batch order and contents are unchanged (one decode thread, in-order
        completion); 0 restores the fully in-line path."""
        assembled = self._assemble(
            batch_size,
            seed=seed,
            shuffle_buffer=shuffle_buffer,
            repeat=repeat,
            steps=steps,
            pad_to_batches=pad_to_batches,
        )
        if decode_ahead <= 0:
            for blobs, labels, valid_rows in assembled:
                yield self._emit(blobs, labels, valid_rows)
            return
        from collections import deque
        from concurrent.futures import ThreadPoolExecutor

        pending: deque = deque()
        with ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="records-decode"
        ) as pool:
            for work in assembled:
                pending.append(pool.submit(self._emit, *work))
                while len(pending) > decode_ahead:
                    yield pending.popleft().result()
            while pending:
                yield pending.popleft().result()

    def _assemble(
        self,
        batch_size: int,
        *,
        seed: int,
        shuffle_buffer: int,
        repeat: bool,
        steps: Optional[int],
        pad_to_batches: Optional[int],
    ) -> Iterator[Tuple[List[bytes], List[int], int]]:
        """The stream's accumulation half: yields ``(blobs, labels,
        valid_rows)`` work items in emission order; ``batches`` decodes them
        (inline or decode-ahead)."""
        emitted = 0
        epoch = 0
        labels: List[int] = []
        blobs: List[bytes] = []
        while True:
            stream = RecordStream(
                self.paths,
                shuffle_buffer=shuffle_buffer if repeat else 1,
                seed=seed + epoch,
            )
            seen_any = False
            for payload in stream:
                seen_any = True
                label, img = decode_classification_record(payload)
                labels.append(label)
                blobs.append(img)
                if len(blobs) == batch_size:
                    yield (blobs, labels, batch_size)
                    emitted += 1
                    labels, blobs = [], []
                    if repeat and steps is not None and emitted >= steps:
                        return
                    if (
                        not repeat
                        and pad_to_batches is not None
                        and emitted >= pad_to_batches
                    ):
                        return
            if not seen_any:
                raise ValueError(
                    "record shards contain zero records: " + ", ".join(self.paths)
                )
            if not repeat:
                tail_valid = len(blobs)
                if blobs or (pad_to_batches or 0) > emitted:
                    # wrap around for padding rows (valid=0): reopen the stream
                    refill = RecordStream(self.paths, shuffle_buffer=1, seed=seed)
                    refill_iter = iter(refill)
                    target = pad_to_batches if pad_to_batches is not None else (
                        emitted + 1 if blobs else emitted
                    )
                    while emitted < target:
                        while len(blobs) < batch_size:
                            payload = next(refill_iter, None)
                            if payload is None:
                                refill_iter = iter(
                                    RecordStream(
                                        self.paths, shuffle_buffer=1, seed=seed
                                    )
                                )
                                payload = next(refill_iter)
                            label, img = decode_classification_record(payload)
                            labels.append(label)
                            blobs.append(img)
                        yield (blobs, labels, tail_valid)
                        emitted += 1
                        labels, blobs = [], []
                        tail_valid = 0  # later padded batches are fully invalid
                return
            epoch += 1
