"""On-device, batched image augmentation (reference: preprocessing/preprocessing.py).

TPU-first redesign of the reference's per-image host-side tf.data augmentation
(reference: preprocessing/preprocessing.py:112-246):

- The whole augmentation is a jittable function of ``(key, images, masks)``; the host
  only decodes PNGs. Geometry runs on TPU as one composed inverse-warp gather per
  image (the reference likewise composed flips/rotation/shift/crop into ONE projective
  transform, reference: preprocessing/preprocessing.py:162-238 — but executed it on the
  host CPU per image).
- Randomness uses per-image PRNG keys from ``jax.random.split``, fixing the reference's
  graph-construction-time numpy RNG for shifts, which sampled ONE shift per pipeline
  and reused it for every image (reference: preprocessing/preprocessing.py:196-203,
  SURVEY §2.4.11).
- Transform semantics preserved: REFLECT pad 40 px (:150-151), random transpose at
  p=0.5 (:165-167), optional brightness jitter (:169-170), horizontal/vertical flips at
  p=0.5 (:172-188), rotation U(-rotate_range°, +rotate_range°) (:190-194), shifts
  U(-range, +range)·height (:196-211), optional zoom-crop (:213-228), BILINEAR for the
  image / NEAREST for the mask (:230-238), central crop 101/181 (:240-241), and the
  Laplacian second channel (:11-30, :243).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

# TGS Salt dataset intensity statistics (reference: preprocessing/preprocessing.py:7-8).
MEAN = 0.47194585
STD = 0.16105755

# Reference: preprocessing/preprocessing.py:27-29 — an isotropic 3x3 Laplacian stencil.
_LAPLACE_KERNEL = (
    (0.5, 1.0, 0.5),
    (1.0, -6.0, 1.0),
    (0.5, 1.0, 0.5),
)


@dataclasses.dataclass(frozen=True)
class AugmentConfig:
    """Knob set of ``read_and_preprocess`` (reference:
    preprocessing/preprocessing.py:112-123), same defaults."""

    horizontal_flip: bool = True
    vertical_flip: bool = True
    rotate_range: float = 10.0  # degrees
    crop_probability: float = 0.5  # the trainer passed 0 (reference: model.py:316)
    crop_min_percent: float = 0.9
    crop_max_percent: float = 1.1
    height_shift_range: float = 0.2
    width_shift_range: float = 0.2
    brightness_range: float = 0.0
    pad: int = 40  # REFLECT padding before warping (reference: :150-151)
    transpose_probability: float = 0.5


def normalize(image: jax.Array) -> jax.Array:
    """(x - MEAN) / STD (reference: preprocessing/preprocessing.py:146)."""
    return (image - MEAN) / STD


def laplacian(images: jax.Array) -> jax.Array:
    """Per-channel 3x3 Laplacian of a [B, H, W, C] batch (reference:
    preprocessing/preprocessing.py:11-30 ran a depthwise conv per image)."""
    c = images.shape[-1]
    kernel = jnp.asarray(_LAPLACE_KERNEL, images.dtype)
    kernel = jnp.tile(kernel[:, :, None, None], (1, 1, 1, c))  # HWIO, depthwise
    return lax.conv_general_dilated(
        images,
        kernel,
        window_strides=(1, 1),
        padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=c,
    )


def add_laplace_channel(images: jax.Array) -> jax.Array:
    """Concatenate the Laplacian as a second channel (reference:
    preprocessing/preprocessing.py:243)."""
    return jnp.concatenate([images, laplacian(images)], axis=-1)


# ---------------------------------------------------------------------------
# Affine machinery. Matrices are 3x3 INVERSE warps: out-pixel (x, y) samples
# in-pixel (x', y', 1)^T = M @ (x, y, 1)^T — the same output->input convention the
# reference's flat [a0..c1] projective transforms used
# (reference: preprocessing/preprocessing.py:162-238). Applying A then B composes as
# M_A @ M_B.
# ---------------------------------------------------------------------------


def _identity() -> jax.Array:
    return jnp.eye(3, dtype=jnp.float32)


def _hflip(width: float) -> jax.Array:
    return jnp.asarray(
        [[-1.0, 0.0, width - 1.0], [0.0, 1.0, 0.0], [0.0, 0.0, 1.0]], jnp.float32
    )


def _vflip(height: float) -> jax.Array:
    return jnp.asarray(
        [[1.0, 0.0, 0.0], [0.0, -1.0, height - 1.0], [0.0, 0.0, 1.0]], jnp.float32
    )


def _rotation(angle: jax.Array, height: float, width: float) -> jax.Array:
    """Rotation about the image center (the reference used
    ``angles_to_projective_transforms``, preference for same center convention)."""
    cos, sin = jnp.cos(angle), jnp.sin(angle)
    cx, cy = (width - 1.0) / 2.0, (height - 1.0) / 2.0
    # translate center to origin, rotate, translate back (inverse warp)
    return jnp.asarray(
        [
            [cos, -sin, cx - cos * cx + sin * cy],
            [sin, cos, cy - sin * cx - cos * cy],
            [0.0, 0.0, 1.0],
        ],
        jnp.float32,
    )


def _translation(tx: jax.Array, ty: jax.Array) -> jax.Array:
    one = jnp.ones((), jnp.float32)
    zero = jnp.zeros((), jnp.float32)
    return jnp.stack(
        [
            jnp.stack([one, zero, tx]),
            jnp.stack([zero, one, ty]),
            jnp.stack([zero, zero, one]),
        ]
    )


def _zoom_crop(pct: jax.Array, off_x: jax.Array, off_y: jax.Array) -> jax.Array:
    one = jnp.ones((), jnp.float32)
    zero = jnp.zeros((), jnp.float32)
    return jnp.stack(
        [
            jnp.stack([pct, zero, off_x]),
            jnp.stack([zero, pct, off_y]),
            jnp.stack([zero, zero, one]),
        ]
    )


def _apply_warp(image: jax.Array, matrix: jax.Array, order: int) -> jax.Array:
    """Inverse-warp a [H, W, C] image by a 3x3 affine matrix. ``order=1`` bilinear
    (image), ``order=0`` nearest (mask) — reference: preprocessing.py:230-238. Out-of-
    bounds samples fill with 0, matching ``tf.contrib.image.transform``."""
    h, w, c = image.shape
    ys, xs = jnp.meshgrid(
        jnp.arange(h, dtype=jnp.float32), jnp.arange(w, dtype=jnp.float32), indexing="ij"
    )
    in_x = matrix[0, 0] * xs + matrix[0, 1] * ys + matrix[0, 2]
    in_y = matrix[1, 0] * xs + matrix[1, 1] * ys + matrix[1, 2]

    def warp_channel(ch: jax.Array) -> jax.Array:
        return jax.scipy.ndimage.map_coordinates(
            ch, [in_y, in_x], order=order, mode="constant", cval=0.0
        )

    return jnp.stack([warp_channel(image[..., i]) for i in range(c)], axis=-1)


def central_crop(x: jax.Array, out_hw: Tuple[int, int]) -> jax.Array:
    """Static central crop (the reference's ``tf.image.central_crop(x, 101/181)``,
    preprocessing/preprocessing.py:240-241)."""
    h, w = x.shape[-3], x.shape[-2]
    th, tw = out_hw
    top, left = (h - th) // 2, (w - tw) // 2
    return x[..., top : top + th, left : left + tw, :]


def _sample_affine(
    key: jax.Array, cfg: AugmentConfig, height: float, width: float
) -> jax.Array:
    """Sample the composed per-image affine (flips ∘ rotation ∘ shift ∘ crop), the
    reference's transform list (preprocessing/preprocessing.py:162-228)."""
    k_h, k_v, k_rot, k_tx, k_ty, k_crop, k_pct, k_ox, k_oy = jax.random.split(key, 9)
    m = _identity()
    if cfg.horizontal_flip:
        coin = jax.random.uniform(k_h) < 0.5
        m = m @ jnp.where(coin, _hflip(width), _identity())
    if cfg.vertical_flip:
        coin = jax.random.uniform(k_v) < 0.5
        m = m @ jnp.where(coin, _vflip(height), _identity())
    if cfg.rotate_range:
        max_rad = cfg.rotate_range / 180.0 * math.pi
        angle = jax.random.uniform(k_rot, minval=-max_rad, maxval=max_rad)
        m = m @ _rotation(angle, height, width)
    # per-image shifts — the fix for SURVEY §2.4.11; the reference also scaled BOTH
    # shifts by `height` (preprocessing/preprocessing.py:197-201), kept for parity
    # (all its inputs are square).
    tx = (
        jax.random.uniform(
            k_tx, minval=-cfg.width_shift_range, maxval=cfg.width_shift_range
        )
        * height
        if cfg.width_shift_range
        else jnp.zeros(())
    )
    ty = (
        jax.random.uniform(
            k_ty, minval=-cfg.height_shift_range, maxval=cfg.height_shift_range
        )
        * height
        if cfg.height_shift_range
        else jnp.zeros(())
    )
    m = m @ _translation(tx, ty)
    if cfg.crop_probability > 0:
        pct = jax.random.uniform(
            k_pct, minval=cfg.crop_min_percent, maxval=cfg.crop_max_percent
        )
        off_x = jax.random.uniform(k_ox, minval=0.0, maxval=width * jnp.abs(1.0 - pct))
        off_y = jax.random.uniform(k_oy, minval=0.0, maxval=height * jnp.abs(1.0 - pct))
        coin = jax.random.uniform(k_crop) < cfg.crop_probability
        m = m @ jnp.where(coin, _zoom_crop(pct, off_x, off_y), _identity())
    return m


def _augment_one(
    key: jax.Array,
    image: jax.Array,
    mask: jax.Array,
    cfg: AugmentConfig,
    out_hw: Tuple[int, int],
) -> Tuple[jax.Array, jax.Array]:
    """Augment a single [H, W, 1] image/mask pair. vmapped over the batch."""
    pad = cfg.pad
    pad_spec = [(pad, pad), (pad, pad), (0, 0)]
    image = jnp.pad(image, pad_spec, mode="reflect")
    mask = jnp.pad(mask, pad_spec, mode="reflect")

    k_transpose, k_bright, k_affine = jax.random.split(key, 3)

    # random transpose (reference: preprocessing/preprocessing.py:165-167)
    do_t = jax.random.uniform(k_transpose) < cfg.transpose_probability
    image = jnp.where(do_t, jnp.transpose(image, (1, 0, 2)), image)
    mask = jnp.where(do_t, jnp.transpose(mask, (1, 0, 2)), mask)

    # brightness jitter (reference: preprocessing/preprocessing.py:169-170)
    if cfg.brightness_range > 0:
        delta = jax.random.uniform(
            k_bright, minval=-cfg.brightness_range, maxval=cfg.brightness_range
        )
        image = image + delta

    h, w = image.shape[0], image.shape[1]
    matrix = _sample_affine(k_affine, cfg, float(h), float(w))
    image = _apply_warp(image, matrix, order=1)
    mask = _apply_warp(mask, matrix, order=0)

    image = central_crop(image, out_hw)
    mask = central_crop(mask, out_hw)
    return image, mask


def augment_batch(
    key: jax.Array,
    images: jax.Array,
    masks: jax.Array,
    cfg: AugmentConfig = AugmentConfig(),
    out_hw: Optional[Tuple[int, int]] = None,
) -> Dict[str, jax.Array]:
    """Jittable batched augmentation + Laplacian channel.

    ``images``/``masks``: [B, H, W, 1] normalized images and binary masks. Returns
    {'images': [B, h, w, 2], 'labels': [B, h, w, 1]} ready for the train step — the
    whole of the reference's augmenting input_fn map (model.py:315-317) as one fused
    XLA computation with per-image keys.
    """
    if out_hw is None:
        out_hw = (images.shape[1], images.shape[2])
    keys = jax.random.split(key, images.shape[0])
    aug_images, aug_masks = jax.vmap(
        lambda k, i, m: _augment_one(k, i, m, cfg, out_hw)
    )(keys, images, masks)
    return {"images": add_laplace_channel(aug_images), "labels": aug_masks}


def augment_classification_batch(
    key: jax.Array,
    images: jax.Array,
    crop_padding: int = 4,
    flip: bool = True,
) -> jax.Array:
    """Jittable standard classification augmentation: per-image random horizontal
    flip + reflect-padded random crop (the ImageNet/CIFAR recipe), on device.

    The classification twin of ``augment_batch``: geometry runs as one fused XLA
    computation on the accelerator, so the host feed never bottlenecks the MXU
    (the host pipeline only decodes and normalizes). ``flip=False`` drops the
    mirror for chirality-sensitive classes (text, digits, signage)."""
    b, h, w, _ = images.shape
    kf, ky, kx = jax.random.split(key, 3)
    if flip:
        flips = jax.random.bernoulli(kf, 0.5, (b,))
        images = jnp.where(
            flips[:, None, None, None], images[:, :, ::-1, :], images
        )
    if crop_padding > 0:
        p = crop_padding
        padded = jnp.pad(
            images, ((0, 0), (p, p), (p, p), (0, 0)), mode="reflect"
        )
        ys = jax.random.randint(ky, (b,), 0, 2 * p + 1)
        xs = jax.random.randint(kx, (b,), 0, 2 * p + 1)
        images = jax.vmap(
            lambda img, y, x: jax.lax.dynamic_slice(
                img, (y, x, 0), (h, w, img.shape[-1])
            )
        )(padded, ys, xs)
    return images


def mixup_batch(
    key: jax.Array,
    images: jax.Array,
    labels: jax.Array,
    alpha: float = 0.2,
) -> Dict[str, jax.Array]:
    """Mixup (arXiv:1710.09412): convex-combine each image with a permuted
    partner, lambda ~ Beta(alpha, alpha) per example. Returns the training
    batch with pairing info instead of materialized soft labels —
    ``labels``/``labels_b``/``lam`` — so the loss mixes per-example CE terms
    (algebraically identical to CE against the mixed one-hot target, without
    a [B, num_classes] buffer)."""
    kp, kl = jax.random.split(key)
    b = images.shape[0]
    perm = jax.random.permutation(kp, b)
    lam = jax.random.beta(kl, alpha, alpha, (b,)).astype(images.dtype)
    # fold toward the larger half so lam >= 0.5: keeps "labels" the majority
    # target (pure convention; CE mix is symmetric)
    lam = jnp.maximum(lam, 1.0 - lam)
    mixed = lam[:, None, None, None] * images + (
        1.0 - lam[:, None, None, None]
    ) * images[perm]
    return {
        "images": mixed,
        "labels": labels,
        "labels_b": labels[perm],
        "lam": lam.astype(jnp.float32),
    }


def cutmix_batch(
    key: jax.Array,
    images: jax.Array,
    labels: jax.Array,
    alpha: float = 1.0,
) -> Dict[str, jax.Array]:
    """CutMix (arXiv:1905.04899): paste a random rectangle from a permuted
    partner image; the label mixes by surviving area. Boxes are realized as
    iota-comparison masks (no dynamic slicing — XLA-friendly fixed shapes);
    ``lam`` is each example's ACTUAL surviving-area fraction after edge
    clamping, so the loss mix matches the pixels exactly."""
    kp, kl, ky, kx = jax.random.split(key, 4)
    b, h, w, _ = images.shape
    perm = jax.random.permutation(kp, b)
    lam0 = jax.random.beta(kl, alpha, alpha, (b,))
    cut = jnp.sqrt(1.0 - lam0)  # box side fraction
    bh = (cut * h).astype(jnp.int32)
    bw = (cut * w).astype(jnp.int32)
    cy = jax.random.randint(ky, (b,), 0, h)
    cx = jax.random.randint(kx, (b,), 0, w)
    y0 = jnp.clip(cy - bh // 2, 0, h)
    y1 = jnp.clip(cy + (bh + 1) // 2, 0, h)
    x0 = jnp.clip(cx - bw // 2, 0, w)
    x1 = jnp.clip(cx + (bw + 1) // 2, 0, w)
    rows = jnp.arange(h)[None, :, None]  # [1, H, 1]
    cols = jnp.arange(w)[None, None, :]  # [1, 1, W]
    in_box = (
        (rows >= y0[:, None, None])
        & (rows < y1[:, None, None])
        & (cols >= x0[:, None, None])
        & (cols < x1[:, None, None])
    )  # [B, H, W]
    mixed = jnp.where(in_box[..., None], images[perm], images)
    box_frac = jnp.mean(in_box.astype(jnp.float32), axis=(1, 2))
    return {
        "images": mixed,
        "labels": labels,
        "labels_b": labels[perm],
        "lam": 1.0 - box_frac,
    }


def prepare_eval_batch(images: jax.Array, masks: jax.Array) -> Dict[str, jax.Array]:
    """Eval-mode preparation: no geometry, just the Laplacian channel (the reference's
    non-augmenting input_fn path, preprocessing/preprocessing.py:243-246)."""
    return {"images": add_laplace_channel(images), "labels": masks}


# ---------------------------------------------------------------------------
# Test-time augmentation (reference: preprocessing/preprocessing.py:254-278 and the
# PREDICT-branch inversion, model.py:384-387). All four transforms are involutions, so
# each is its own inverse.
# ---------------------------------------------------------------------------

TTA_TRANSFORMS = ("vertical", "horizontal", "transpose", "none")


def tta_transform(x: jax.Array, transformation: str) -> jax.Array:
    """Apply a named TTA transform to a [B, H, W, C] batch."""
    if transformation == "vertical":
        return x[:, ::-1, :, :]
    if transformation == "horizontal":
        return x[:, :, ::-1, :]
    if transformation == "transpose":
        return jnp.transpose(x, (0, 2, 1, 3))
    if transformation == "none":
        return x
    raise ValueError(f"Unknown transformation {transformation}")


def tta_inverse(x: jax.Array, transformation: str) -> jax.Array:
    """Invert a named TTA transform (all are involutions)."""
    return tta_transform(x, transformation)
