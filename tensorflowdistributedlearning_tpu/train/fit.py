"""Single-run classification training loop for the ImageNet/CIFAR presets.

The reference's trainer was K-fold segmentation only (``Model.train``,
model.py:138-227); its backbone kept a classification path (``num_classes`` /
``global_pool``, reference: core/resnet.py:246-256) that nothing could train.
``fit`` is that missing driver, built on the same SPMD pieces as the K-fold
trainer — one jitted shard_map-ped train step, Orbax checkpoints with best-k
export, TensorBoard summaries — but with no folds, streaming on-disk input
(data/imagefolder.py), and top-1 as the model-selection metric:

- train/eval alternation with checkpoint cadence + throttled eval reproduces the
  ``train_and_evaluate`` loop shape (reference: model.py:219-223);
- multi-host correct by construction: per-process batch math, global batch
  assembly via ``multihost.global_shard_batch``, equal eval step counts.
"""

from __future__ import annotations

import dataclasses
import functools
import logging
import os
import time
from typing import Dict, Iterator, Optional

import jax
import numpy as np

from tensorflowdistributedlearning_tpu import obs as obs_lib
from tensorflowdistributedlearning_tpu.config import ModelConfig, TrainConfig
from tensorflowdistributedlearning_tpu.data import imagefolder
from tensorflowdistributedlearning_tpu.data import pipeline as pipeline_lib
from tensorflowdistributedlearning_tpu.data import synthetic as synthetic_lib
from tensorflowdistributedlearning_tpu.models import build_model
from tensorflowdistributedlearning_tpu.parallel import mesh as mesh_lib
from tensorflowdistributedlearning_tpu.parallel import multihost
from tensorflowdistributedlearning_tpu.resilience import faults as faults_lib
from tensorflowdistributedlearning_tpu.resilience import preempt as preempt_lib
from tensorflowdistributedlearning_tpu.train import async_loop
from tensorflowdistributedlearning_tpu.train import state as state_lib
from tensorflowdistributedlearning_tpu.train import step as step_lib
from tensorflowdistributedlearning_tpu.train.checkpoint import CheckpointManager
from tensorflowdistributedlearning_tpu.train.state import TrainState, create_train_state
from tensorflowdistributedlearning_tpu.utils.params import count_params
from tensorflowdistributedlearning_tpu.utils.summary import SummaryWriter

logger = logging.getLogger(__name__)


@functools.lru_cache(maxsize=None)
def _prepare_classification_cached(policy: str = "flip_crop"):
    from tensorflowdistributedlearning_tpu.data import augment as augment_lib

    @jax.jit
    def prepare(base_key, step, batch):
        key = jax.random.fold_in(base_key, step)
        kg, km = jax.random.split(key)
        # jitter scales with the input (h/8) up to the CIFAR-standard 4px —
        # a fixed 4 is a 25% displacement on a 16x16 input
        pad = min(4, max(batch["images"].shape[1] // 8, 1))
        images = augment_lib.augment_classification_batch(
            kg, batch["images"], crop_padding=pad,
            flip=policy in ("flip_crop", "mixup", "cutmix"),
        )
        if policy == "mixup":
            return augment_lib.mixup_batch(km, images, batch["labels"])
        if policy == "cutmix":
            return augment_lib.cutmix_batch(km, images, batch["labels"])
        return {"images": images, "labels": batch["labels"]}

    return prepare


@dataclasses.dataclass
class FitResult:
    final_metrics: Dict[str, float]
    n_params: int
    steps: int
    # set when fit_preset exported a serving artifact after training
    # (fit --export-serving): the directory the promotion pipeline takes
    serving_artifact: Optional[str] = None


class ClassifierTrainer:
    """Streaming classification trainer (one run, no folds).

    ``data_dir`` uses the ImageFolder layout: ``{data_dir}/train/{class}/*.png``
    and optionally ``{data_dir}/val/{class}/*.png`` (eval falls back to the train
    split when absent). ``data_dir=None`` trains on synthetic in-memory batches —
    every preset stays runnable with zero data on disk.
    """

    def __init__(
        self,
        model_dir: str,
        data_dir: Optional[str],
        model_config: ModelConfig,
        train_config: Optional[TrainConfig] = None,
        plan: Optional[Dict] = None,
    ):
        if model_config.num_classes is None:
            raise ValueError(
                "fit() trains classification models; model_config.num_classes is None "
                "(use train.trainer.Trainer for the segmentation task)"
            )
        multihost.initialize()
        self.model_dir = model_dir
        self.data_dir = data_dir
        self.model_config = model_config
        self.train_config = train_config or TrainConfig()
        if self.train_config.compile_cache_dir:
            # before anything compiles (state init, eval, the step): a second
            # same-shape run must LOAD its executables, not rebuild them
            from tensorflowdistributedlearning_tpu.utils import compile_cache

            compile_cache.configure(self.train_config.compile_cache_dir)
        if self.train_config.parallelism == "auto" and plan is None:
            # the mesh is built below from the config's explicit degrees, so
            # an unresolved 'auto' here would silently train explicit while
            # the ledger claims otherwise — auto must be resolved BEFORE the
            # trainer exists (fit_preset / the CLI do this; programmatic
            # callers use parallel.planner.plan() and apply overrides())
            raise ValueError(
                "parallelism='auto' must be resolved before constructing "
                "ClassifierTrainer: plan the layout first (fit_preset / the "
                "fit CLI do this automatically; programmatically, call "
                "parallel.planner.plan(model_config, train_config, "
                "global_batch), apply plan.overrides() onto the config, and "
                "pass plan=plan.header())"
            )
        self.task = step_lib.ClassificationTask(
            label_smoothing=self.train_config.label_smoothing
        )
        tcfg = self.train_config
        self.mesh = mesh_lib.make_mesh(
            tcfg.n_devices,
            # pipeline stages and experts ride the model axis (mutually
            # exclusive with tensor parallelism, enforced by TrainConfig)
            model_parallel=max(
                tcfg.model_parallel, tcfg.pipeline_parallel, tcfg.expert_parallel
            ),
            sequence_parallel=tcfg.sequence_parallel,
        )
        # tensor parallelism (GSPMD param/optimizer sharding, parallel/tensor.py);
        # multi-host works too: state placement assembles global arrays from
        # per-process shards, batches ride the same global_shard_batch path as DP
        self._tp = tcfg.model_parallel > 1
        # pipeline parallelism (GPipe stage runner over ViT blocks,
        # train/pipeline_step.py): params stay in the canonical replicated
        # tree (checkpoints/serving interchangeable); the step slices stages
        self._pp = tcfg.pipeline_parallel > 1
        if self._pp:
            from tensorflowdistributedlearning_tpu.train.pipeline_step import (
                validate_pipeline_config,
            )

            validate_pipeline_config(
                model_config, tcfg.pipeline_parallel, self._pp_microbatches
            )
        # expert parallelism: one MoE expert per model-axis shard, all-to-all
        # dispatch inside the STANDARD shard_map step (the model owns the
        # collective; params stay in the canonical replicated tree)
        self._ep = tcfg.expert_parallel > 1
        if self._ep and tcfg.expert_parallel != model_config.moe_experts:
            raise ValueError(
                f"expert_parallel={tcfg.expert_parallel} requires "
                f"moe_experts={tcfg.expert_parallel} (one expert per shard); "
                f"got moe_experts={model_config.moe_experts}"
            )
        # sequence_parallel > 1: H-sharded backbone (halo-exchange convs,
        # sequence-synced BN) exactly as in the K-fold Trainer
        from tensorflowdistributedlearning_tpu.parallel.spatial import (
            validate_spatial_config,
        )

        validate_spatial_config(model_config, tcfg.sequence_parallel)
        self._spatial = tcfg.sequence_parallel > 1
        axis = mesh_lib.SEQUENCE_AXIS if self._spatial else None
        # sync_batch_norm: BN statistics span the batch mesh axis too (and
        # the sequence axis when spatial) — cross-replica BN, the pod
        # standard for small per-shard batches (semantics and evidence:
        # config.py's field comment)
        bn_axis = axis
        if tcfg.sync_batch_norm:
            bn_axis = (
                (mesh_lib.BATCH_AXIS, axis) if axis else mesh_lib.BATCH_AXIS
            )
        self.model = build_model(
            model_config,
            bn_axis_name=bn_axis,
            spatial_axis_name=axis,
            expert_axis_name=mesh_lib.MODEL_AXIS if self._ep else None,
        )
        self._plain_model = (
            build_model(model_config)
            if (self._spatial or self._ep or tcfg.sync_batch_norm)
            else self.model
        )
        self._n_params: Optional[int] = None
        # the parallelism plan this run trains under (parallel/planner.py
        # header dict): handed in by fit_preset (auto or validated-explicit),
        # else derived best-effort at fit() time — it rides the run-header
        # ledger event either way (docs/LEDGER_SCHEMA.md `plan`)
        self._plan = plan
        # fit() swaps in a live Telemetry; the null instance keeps every other
        # entry point (serving restore, direct _evaluate) span-safe
        self._telemetry = obs_lib.NULL_TELEMETRY
        # streaming input service (data/service.py) for the record-sharded
        # train path; built by _train_stream, closed on run teardown. The
        # restored sidecar state (if resuming) is stashed before the stream
        # is built so the service can validate it.
        self._data_service = None
        self._restored_data_state = None
        os.makedirs(model_dir, exist_ok=True)

    @property
    def params(self) -> int:
        if self._n_params is None:
            raise AttributeError("fit() must build the model first")
        return self._n_params

    @property
    def _pp_microbatches(self) -> int:
        tcfg = self.train_config
        return tcfg.pipeline_microbatches or tcfg.pipeline_parallel

    # -- data -------------------------------------------------------------

    def _holdout_partition(self, paths):
        """(train_paths, heldout_paths) under ``eval_holdout_fraction``: the
        LAST ceil(frac*n) sorted shards (at least one) become the eval split —
        deterministic across processes, so every host agrees on the
        partition."""
        import math

        frac = self.train_config.eval_holdout_fraction
        if frac <= 0:
            return list(paths), []
        n_hold = max(1, math.ceil(frac * len(paths)))
        if n_hold >= len(paths):
            raise ValueError(
                f"eval_holdout_fraction={frac} would hold out {n_hold} of "
                f"{len(paths)} train record shard(s), leaving none to train "
                "on; write more shards or lower the fraction"
            )
        return list(paths[:-n_hold]), list(paths[-n_hold:])

    def _open_records(self, split: str, host_shard: bool = True):
        """Record-sharded source for ``split`` ({data_dir}/{split}-*.tfrecord),
        already reduced to this process's shard subset; None when absent.

        With ``eval_holdout_fraction`` set and no on-disk ``val`` shards, the
        train shards are deterministically partitioned: ``split='train'``
        excludes the held-out shards, ``split='val'`` serves them.

        ``host_shard=False`` keeps the FULL (holdout-filtered) shard list —
        the data-service train path assigns shards per epoch itself
        (``data.service.epoch_shard_assignment``), validating the
        shards-per-process floor at construction."""
        if self.data_dir is None:
            return None
        from tensorflowdistributedlearning_tpu.data import records as records_lib

        cfg = self.model_config

        def open_split(glob_split):
            try:
                return records_lib.ClassificationRecords(
                    self.data_dir,
                    split=glob_split,
                    image_shape=cfg.input_shape,
                    channels=cfg.input_channels,
                    num_classes=cfg.num_classes,
                )
            except ValueError:  # no shards for this split
                return None

        ds = open_split(split)
        holdout = self.train_config.eval_holdout_fraction > 0
        if holdout and open_split("val") is None:
            if split == "train" and ds is not None:
                ds.paths, _ = self._holdout_partition(ds.paths)
            elif split == "val":
                ds = open_split("train")
                if ds is not None:
                    _, ds.paths = self._holdout_partition(ds.paths)
        if ds is None:
            return None
        if not host_shard:
            return ds
        n_shards = len(ds.paths)
        ds.paths = records_lib.host_shard_paths(ds.paths)
        if not ds.paths:
            raise ValueError(
                f"{split} has {n_shards} record shard(s) for "
                f"{jax.process_count()} processes — every process needs at "
                "least one; re-shard the dataset (write_classification_shards"
                "(shards>=process_count))"
            )
        return ds

    def _open_split(self, split: str) -> Optional[imagefolder.ImageFolder]:
        if self.data_dir is None:
            return None
        root = os.path.join(self.data_dir, split)
        if not os.path.isdir(root):
            return None
        cfg = self.model_config
        ds = imagefolder.ImageFolder(
            root, cfg.input_shape, channels=cfg.input_channels
        )
        if ds.num_classes > cfg.num_classes:
            raise ValueError(
                f"{root} has {ds.num_classes} classes but the model has "
                f"num_classes={cfg.num_classes}"
            )
        return ds

    def _train_stream(
        self, batch_size: int, steps: int, start_step: int = 0
    ) -> Iterator[Dict[str, np.ndarray]]:
        tcfg = self.train_config
        local_bs = multihost.per_process_batch_size(batch_size)
        # fold the resume point into the shuffle seed: a restarted stream
        # would otherwise replay the SAME shuffled order from the beginning,
        # re-training on the earliest examples (the reference had exactly
        # this behavior — Estimator input_fns restart on resume — but there
        # is no reason to keep it). Every process shifts identically, so
        # multi-host batch assembly stays aligned.
        seed = tcfg.seed + jax.process_index() + 7919 * start_step
        # record-sharded source first: {data_dir}/train-*.tfrecord (the
        # ImageNet-scale on-disk form). Default: the streaming data service
        # (data/service.py) — N parallel read+decode workers over per-epoch
        # global-shuffle shard assignment, index-keyed so batch i is a pure
        # function of (seed, i) and a resumed run replays the exact remaining
        # stream (the sidecar state restored below is validated against it).
        # data_service_workers=0 keeps the legacy single-thread stream with
        # its seed-folded resume.
        use_service = tcfg.data_service_workers > 0
        records_ds = self._open_records("train", host_shard=not use_service)
        if records_ds is not None:
            if use_service:
                from tensorflowdistributedlearning_tpu.data import (
                    service as service_lib,
                )

                cfg = self.model_config
                source = service_lib.ClassificationRecordSource(
                    records_ds.paths,
                    image_shape=cfg.input_shape,
                    channels=cfg.input_channels,
                    num_classes=cfg.num_classes,
                )
                tel = self._telemetry
                svc = service_lib.StreamingDataService(
                    source,
                    batch_size=local_bs,
                    seed=tcfg.seed,
                    workers=tcfg.data_service_workers,
                    start_batch=start_step,
                    # same gating as device_prefetch: only a window-writing
                    # process drains these samples
                    registry=(
                        tel.registry
                        if tel.enabled and jax.process_index() == 0
                        else None
                    ),
                    resume_state=self._restored_data_state,
                )
                self._data_service = svc
                if svc.redeal is not None:
                    # resumed across a world resize (parallel/elastic.py):
                    # the validated re-deal is part of the run's durable
                    # story — telemetry-report lines it up with the
                    # coordinator's world_resize event
                    tel.event(
                        "data_redeal", step=start_step, **svc.redeal
                    )
                return svc.batches(steps=steps)
            if self._restored_data_state is not None:
                # the checkpoint was written by a service-fed run (sidecar
                # present): the legacy stream would silently replay/skip
                # records relative to the index-keyed plan — the exact
                # failure the sidecar validation exists to refuse
                raise ValueError(
                    "this checkpoint carries a data-service resume sidecar "
                    "but data_service_workers=0 selects the legacy stream — "
                    "resuming would silently replay or skip training data; "
                    "resume with --data-workers >= 1 (any count: batch "
                    "content is worker-invariant)"
                )
            return records_ds.batches(
                local_bs,
                seed=seed,
                steps=steps,
            )
        train_split = self._open_split("train")
        if train_split is None:
            cfg = self.model_config
            # index-keyed: batch i is a pure function of (seed, i), so a
            # resumed run replays the exact stream the uninterrupted run saw
            # from start_step on — the data-side half of the resilience
            # contract (resumed params must match bit-for-bit)
            return synthetic_lib.synthetic_batches(
                "classification",
                local_bs,
                seed=tcfg.seed + jax.process_index(),
                steps=steps,
                start_index=start_step,
                index_keyed=True,
                input_shape=cfg.input_shape,
                channels=cfg.input_channels,
                num_classes=cfg.num_classes,
            )
        # augment=False: geometry (flip + padded random crop) runs ON DEVICE in
        # the jitted prepare step (augment_classification_batch) — the host only
        # decodes and normalizes, mirroring the segmentation trainer's split
        return imagefolder.train_batches(
            train_split.host_shard(),
            local_bs,
            seed=seed,
            steps=steps,
            augment=False,
        )

    # -- training ---------------------------------------------------------

    def fit(
        self,
        batch_size: int = 64,
        steps: int = 10_000,
        eval_every_steps: Optional[int] = None,
    ) -> FitResult:
        """Train ``steps`` steps with periodic checkpoint + eval + best export.

        ``eval_every_steps`` decouples eval cadence from checkpoint cadence
        (defaults to ``checkpoint_every_steps``; the K-fold trainer's coupling of
        the two was a round-1 weak spot)."""
        from tensorflowdistributedlearning_tpu import config as config_lib

        tcfg = self.train_config
        config_lib.validate_training_data_format(tcfg)
        local_bs = mesh_lib.check_accum_divisibility(
            batch_size, self.mesh, tcfg.grad_accum_steps
        )
        if self._pp and local_bs % self._pp_microbatches:
            raise ValueError(
                f"per-replica batch {local_bs} not divisible into "
                f"{self._pp_microbatches} pipeline microbatches"
            )
        eval_every = (
            eval_every_steps or tcfg.eval_every_steps or tcfg.checkpoint_every_steps
        )
        # fail fast on data-layout problems EVERY split will hit, before any
        # training happens (e.g. fewer val record shards than processes would
        # otherwise only surface at the first eval, potentially hours in)
        self._open_records("val")

        if self._plan is None and tcfg.telemetry:
            # direct-construction path (no fit_preset): describe the explicit
            # layout through the planner so the run header carries the plan
            # (predicted bytes/chip) like every other run. Best-effort — the
            # mesh already validated divisibility in __init__, so a planner
            # hiccup here is telemetry loss, not a training error. Skipped
            # when telemetry is off: the plan's only consumer here is the
            # run header.
            try:
                from tensorflowdistributedlearning_tpu.parallel import (
                    planner as planner_lib,
                )

                self._plan = planner_lib.validate_config(
                    self.model_config, tcfg, batch_size
                ).header()
            except Exception as e:  # noqa: BLE001 — plan is telemetry here
                logger.warning("parallelism plan unavailable: %s", e)

        self._telemetry = obs_lib.Telemetry(
            self.model_dir,
            enabled=tcfg.telemetry,
            memory_every_windows=tcfg.telemetry_memory_every_windows,
            # sampled per-step/eval/checkpoint traces (obs/trace.py) and the
            # online health monitors (obs/health.py) ride the window stream
            trace_sample_rate=tcfg.trace_sample_rate,
            health=obs_lib.HealthMonitor.from_train_config(tcfg),
            run_info={
                "task": "classification",
                "steps": steps,
                "global_batch": batch_size,
                "mesh": {
                    name: int(size)
                    for name, size in zip(
                        self.mesh.axis_names, self.mesh.devices.shape
                    )
                },
                "model_config": dataclasses.asdict(self.model_config),
                "train_config": dataclasses.asdict(tcfg),
                # the parallelism plan (chosen layout + predicted bytes/chip):
                # telemetry-report renders it, obs/compare hashes its layout,
                # and the watermark events' measured-vs-predicted deltas are
                # judged against its prediction
                **({"plan": self._plan} if self._plan else {}),
            },
        )
        # time cross-process sync points as this run's barrier_wait span —
        # per-host barrier asymmetry is the fleet report's straggler signal
        multihost.instrument(self._telemetry)
        try:
            return self._fit_instrumented(batch_size, steps, eval_every)
        finally:
            # idempotent: the success path already closed with final metrics;
            # an exceptional exit reaches this close first and is recorded as
            # interrupted (and the compile listener never leaks either way)
            if self._data_service is not None:
                self._data_service.close()
                self._data_service = None
            self._restored_data_state = None
            multihost.uninstrument(self._telemetry)
            self._telemetry.close(interrupted=True)
            self._telemetry = obs_lib.NULL_TELEMETRY

    def _fit_instrumented(
        self, batch_size: int, steps: int, eval_every: int
    ) -> FitResult:
        """The training loop proper, running under ``self._telemetry``
        (constructed and torn down by ``fit``)."""
        tcfg = self.train_config
        tel = self._telemetry
        state = self._init_state()
        # post-init: the params/optimizer footprint, with exact per-device
        # opt-state accounting (1/dp of it under weight_update_sharding)
        tel.memory_event(
            params_bytes_per_device=state_lib.tree_bytes_per_device(state.params),
            opt_state_bytes_per_device=state_lib.tree_bytes_per_device(
                state.opt_state
            ),
            weight_update_sharding=tcfg.weight_update_sharding,
        )
        # MFU pricing + continuous profiling: the planner's analytic FLOP
        # model (6 * params * batch per step: fwd 2x + bwd 4x) against the
        # measured step time turns every step_window into an MFU point; the
        # profiler layers windowed/triggered jax.profiler captures on top and
        # ledgers the per-op roofline (obs/profiler.py)
        if tel.enabled:
            n_dev = self.mesh.devices.size
            tel.set_step_flops(
                6.0 * float(self.params) * float(batch_size),
                n_devices=n_dev,
                # dominant steady-state collective: the gradient all-reduce,
                # ~2x params bytes on-wire per step (ring); only priced when
                # there is a wire to cross
                collective_bytes_per_step=(
                    2.0 * float(
                        state_lib.tree_bytes_per_device(state.params)
                    ) if n_dev > 1 else None
                ),
            )
            profiler = obs_lib.ContinuousProfiler(
                tel, every_windows=tcfg.profile_every_windows
            )
            tel.set_profiler(profiler)
        ckpt = self._checkpointer()
        state = ckpt.restore_latest(state)
        start_step = int(jax.device_get(state.step))
        if start_step >= steps:
            logger.info("already trained to step %d", start_step)
            metrics = self._evaluate(state, batch_size, step_no=start_step)
            ckpt.close()
            tel.close(steps=start_step, already_trained=True)
            return FitResult(metrics, self.params, start_step)
        if start_step > 0:
            # resume verification: training actually CONTINUES from a prior
            # checkpoint (an already-trained rerun above is not a resume, and
            # must not fabricate a resilience story in the report); the ledger
            # records the resume point so telemetry-report can line restarts
            # up with recovered progress
            tel.event("resumed", step=start_step)
            # the input stream's sidecar state saved with this checkpoint:
            # _train_stream hands it to the data service, which validates it
            # against (seed, start_step) — the index-keyed resume contract
            self._restored_data_state = ckpt.restore_data_state(start_step)

        if self._tp:
            from tensorflowdistributedlearning_tpu.parallel import tensor as tp_lib

            train_step = tp_lib.make_train_step_gspmd(
                self.mesh,
                self.task,
                weight_update_sharding=tcfg.weight_update_sharding,
            )
        elif self._pp:
            from tensorflowdistributedlearning_tpu.train import pipeline_step as pp_lib

            train_step = pp_lib.make_train_step_pipeline(
                self.mesh, self.task, self.model_config, self._pp_microbatches,
                seed=self.train_config.seed,
            )
        else:
            train_step = step_lib.make_train_step(
                self.mesh,
                self.task,
                weight_decay=self.model_config.weight_decay,
                spatial=self._spatial,
                accum=self.train_config.grad_accum_steps,
                seed=self.train_config.seed,
                weight_update_sharding=tcfg.weight_update_sharding,
            )
        is_main = jax.process_index() == 0
        tb_train = SummaryWriter(os.path.join(self.model_dir, "train")) if is_main else None
        tb_eval = SummaryWriter(os.path.join(self.model_dir, "eval")) if is_main else None

        batches = pipeline_lib.device_prefetch(
            self._train_stream(batch_size, steps - start_step, start_step),
            self._place_batch,
            depth=tcfg.prefetch_depth,
            # the gauge is drained per log window; a run that never writes
            # windows (telemetry off, or a non-main host with no TB writer)
            # must not record into it — the samples would accumulate for the
            # life of the run with nothing reading them
            registry=(
                tel.registry if tel.enabled and tb_train is not None else None
            ),
        )
        step_no = start_step
        last_eval_step = -1
        final_metrics: Dict[str, float] = {}
        prepare = self._make_prepare_train()
        window_t0 = time.perf_counter()
        window_start = step_no
        # first window contains the compile; eval/save windows are not training
        # time either — dirty windows skip their throughput point
        window_dirty = True
        # host-side schedule mirror: the lr log line must not dispatch device
        # work (the whole point of the deferred-fetch loop is a full queue)
        lr_sched = step_lib.make_host_lr_schedule(tcfg)

        def emit_window(rec: async_loop.PendingWindow, scalars) -> None:
            if tb_train is not None:
                tb_train.scalars(scalars, rec.step)
            tel.window_event(
                rec.step,
                steps=rec.steps,
                images_per_sec=rec.images_per_sec,
                scalars=scalars,
                dirty=rec.dirty,
                samples=rec.samples,
                # cost accounting (obs/capacity.py): examples THIS PROCESS's
                # chips handled this window — the meter counts local devices,
                # so a multi-host run must price the per-process batch share,
                # not the global batch (which would inflate per-chip
                # throughput by the process count)
                examples=rec.steps * multihost.per_process_batch_size(batch_size),
            )

        # dispatch-ahead + deferred window fetch (train/async_loop.py);
        # dispatch_ahead_steps=0 is the synchronous legacy loop
        overlap = async_loop.HostOverlap(
            tel, dispatch_ahead=tcfg.dispatch_ahead_steps, emit=emit_window
        )

        def save_data_sidecar(step: int) -> None:
            # the input stream's resume state rides every checkpoint
            # (process 0 writes; the validated fields — seed, batch_index —
            # are identical on every host by construction)
            if self._data_service is not None and is_main:
                ckpt.save_data_state(
                    step, self._data_service.state(step).to_json()
                )

        batches_it = iter(batches)
        _end = object()
        while True:
            # host blocked on the loader (prefetch underrun) vs dispatching
            # compute: the split the ledger's step windows record
            with tel.span(obs_lib.SPAN_DATA_WAIT):
                raw = next(batches_it, _end)
            if raw is _end:
                break
            with tel.span(obs_lib.SPAN_STEP):
                batch = prepare(jax.numpy.asarray(step_no), raw)
                state, metrics = train_step(state, batch)
            step_no += 1
            # bounded dispatch-ahead: block (as fetch_wait) once more than
            # dispatch_ahead_steps steps are in flight
            overlap.track(metrics)
            # resilience boundary: injected faults fire here (a SIGTERM lands
            # in the preemption handler below within the same boundary), and a
            # pending preemption turns into a final checkpoint + distinct exit
            faults_lib.fire(faults_lib.SITE_STEP, step_no)
            if preempt_lib.requested():
                # the deferred window reaches the ledger BEFORE the preemption
                # checkpoint/events — resilience reporting stays complete.
                # Preemption outranks a health abort surfacing from this
                # flush: the alert is already ledgered, and the supervisor
                # contract (final checkpoint + EXIT_PREEMPTED) must hold.
                try:
                    overlap.flush()
                except obs_lib.HealthAbortError:
                    pass
                with tel.span(obs_lib.SPAN_CHECKPOINT):
                    ckpt.save(state, force=True)
                save_data_sidecar(step_no)
                tel.checkpoint_event(step_no, preempted=True)
                tel.event(
                    "preempted", step=step_no, reason=preempt_lib.reason()
                )
                raise preempt_lib.PreemptedError(step_no)
            if tb_train is not None and step_no % tcfg.train_log_every_steps == 0:
                now = time.perf_counter()
                images_per_sec = None
                if not window_dirty and step_no > window_start:
                    images_per_sec = (
                        (step_no - window_start) * batch_size / (now - window_t0)
                    )
                # sync mode fetches+emits here; async mode emits the PREVIOUS
                # window and defers this one while the device keeps running.
                # rec.lr is the lr the NEXT update will use — exact, the
                # schedule is step-driven (observability the reference's TB
                # summaries never had)
                overlap.window(
                    async_loop.PendingWindow(
                        step=step_no,
                        metrics=metrics,
                        steps=step_no - window_start,
                        lr=lr_sched(step_no),
                        images_per_sec=images_per_sec,
                        dirty=window_dirty,
                    )
                )
                window_t0, window_start, window_dirty = now, step_no, False
                # train-side executables exist now: further train compiles
                # are recompiles (the first eval marks its own phase warm)
                tel.mark_warm(obs_lib.SPAN_STEP, obs_lib.SPAN_DATA_WAIT)
            # the checkpoint span is a trace boundary (sampled runs show
            # checkpoint spans in --export-trace timelines), not a window
            # span; opened only on the manager's own save cadence so
            # off-cadence steps stay span-free
            saved = False
            if ckpt.is_save_step(step_no):
                with tel.span(obs_lib.SPAN_CHECKPOINT):
                    saved = ckpt.maybe_save(state, step=step_no)
            if saved:
                overlap.flush()
                window_dirty = True
                save_data_sidecar(step_no)
                tel.checkpoint_event(step_no)
            if step_no % eval_every == 0:
                overlap.flush()
                last_eval_step = step_no
                final_metrics = self._evaluate(state, batch_size, step_no=step_no)
                if tb_eval is not None:
                    tb_eval.scalars(final_metrics, step_no)
                    tb_eval.flush()
                # best-export stores the eval view: EMA params when tracked
                ckpt.export_best(
                    step_lib.with_ema_params(state), final_metrics
                )
                window_dirty = True
        # an abort surfacing from the end-of-run flush must not skip the
        # final checkpoint — write it, then re-raise (abort means "stop at a
        # recorded boundary", not "discard the run's last steps")
        abort_err: Optional[BaseException] = None
        try:
            overlap.flush()
        except obs_lib.HealthAbortError as e:
            abort_err = e
        with tel.span(obs_lib.SPAN_CHECKPOINT):
            ckpt.save(state, force=True)
        save_data_sidecar(step_no)
        tel.checkpoint_event(step_no, final=True)
        if abort_err is not None:
            raise abort_err
        if last_eval_step != step_no:
            final_metrics = self._evaluate(state, batch_size, step_no=step_no)
            if tb_eval is not None:
                tb_eval.scalars(final_metrics, step_no)
                tb_eval.flush()
            ckpt.export_best(step_lib.with_ema_params(state), final_metrics)
        if tb_train is not None:
            tb_train.close()
        if tb_eval is not None:
            tb_eval.close()
        ckpt.close()
        tel.memory_event(step=step_no)
        tel.close(
            steps=step_no,
            final_metrics={k: float(v) for k, v in final_metrics.items()},
        )
        return FitResult(final_metrics, self.params, step_no)

    def _make_prepare_train(self):
        """Jitted on-device classification augmentation keyed by (seed, step),
        under ``TrainConfig.augmentation`` ("flip_crop" | "crop" | "none" —
        data/augment.py:augment_classification_batch). The seed rides in through
        the traced base key so runs with different seeds share one executable."""
        policy = self.train_config.augmentation
        if policy == "none":
            return lambda step, batch: batch
        base_key = jax.random.PRNGKey(self.train_config.seed)
        prepare = _prepare_classification_cached(policy)

        def bound(step: jax.Array, batch):
            return prepare(base_key, step, batch)

        return bound

    def _init_state(self) -> TrainState:
        # init via the unsharded twin (identical param tree — SpatialConv is
        # nn.Conv-compatible, and MoEMlp's tree is the same dense or
        # expert-parallel); spatial/expert collectives cannot run outside
        # shard_map
        state = self._host_template()
        if self._spatial or self._ep or self.train_config.sync_batch_norm:
            # the train step calls state.apply_fn — it must be the AXIS-NAMED
            # model (spatial collectives, expert dispatch, or sync-BN pmean),
            # not the plain init twin
            state = state.replace(apply_fn=self.model.apply)
        self._n_params = count_params(state.params)
        if self.train_config.weight_update_sharding:
            from tensorflowdistributedlearning_tpu.parallel import zero as zero_lib

            # opt_state 1/dp over the data axis; params/batch_stats keep
            # their canonical layout (channel-sharded under TP, where the
            # optimizer leaves shard over (model, batch) jointly)
            return zero_lib.shard_state_weight_update(
                state, self.mesh, tensor_parallel=self._tp
            )
        if self._tp:
            from tensorflowdistributedlearning_tpu.parallel import tensor as tp_lib

            return tp_lib.shard_state_tensor_parallel(state, self.mesh)
        return mesh_lib.replicate(state, self.mesh)

    def _evaluate(
        self,
        state: TrainState,
        batch_size: int,
        step_no: Optional[int] = None,
    ) -> Dict[str, float]:
        """One eval pass: the ``val`` split when present (ImageFolder or record
        shards), else ``train`` (read in order, no augmentation), else one
        synthetic pass — EXCEPT when training came from record shards, where a
        synthetic fallback would drive best-checkpoint selection with accuracy
        on noise; that case evaluates one pass over the train records instead.

        ``step_no``: the host-known step the pass describes (the train loop
        always knows it); None falls back to a device fetch of ``state.step``
        — direct callers only, the loop path stays sync-free."""
        tcfg = self.train_config
        # evaluate the EMA view when one is tracked (TrainConfig.ema_decay>0) —
        # the same params best-export stores, so selection and serving agree —
        # then drop the optimizer state: eval reads params/batch_stats only,
        # and under weight_update_sharding the data-axis-sharded moments would
        # otherwise be all-gathered into the eval executable for nothing
        state = step_lib.with_ema_params(state).replace(opt_state=None)
        local_bs = multihost.per_process_batch_size(batch_size)
        val_folder = self._open_split("val")
        eval_records = self._open_records("val")
        if eval_records is None and val_folder is None:
            # no val split at all: records-trained runs eval on their train
            # records rather than silently on synthetic noise
            eval_records = self._open_records("train")
            if eval_records is not None:
                self._warn_eval_on_train("train record shards")
        if eval_records is not None:
            return self._evaluate_records(state, eval_records, local_bs, step_no)
        eval_split = val_folder
        if eval_split is None:
            eval_split = self._open_split("train")
            if eval_split is not None:
                self._warn_eval_on_train("the train ImageFolder split")
        if eval_split is None:
            cfg = self.model_config
            # uniform batch structure with the on-disk path (all rows valid)
            batches: Iterator[Dict[str, np.ndarray]] = (
                dict(b, valid=np.ones(local_bs, np.float32))
                for b in synthetic_lib.synthetic_batches(
                    "classification",
                    local_bs,
                    seed=tcfg.seed + 1,
                    steps=4,
                    input_shape=cfg.input_shape,
                    channels=cfg.input_channels,
                    num_classes=cfg.num_classes,
                )
            )
        else:
            num = multihost.eval_num_batches(len(eval_split), local_bs)
            batches = imagefolder.eval_batches(
                eval_split.host_shard(), local_bs, num_batches=num
            )
        return self._eval_pass(state, batches, step_no)

    def _eval_pass(
        self,
        state: TrainState,
        batches: Iterator[Dict[str, np.ndarray]],
        step_no: Optional[int] = None,
    ) -> Dict[str, float]:
        """The ONE streaming accumulate/compute/log eval loop (both the
        ImageFolder/synthetic and record-shard paths feed it), wrapped once in
        the telemetry eval span — eval wall time is not training time, and the
        ledger records each pass as an ``eval`` event.

        The metric accumulator stays DEVICE-RESIDENT (a tiny jitted merge per
        batch, train/async_loop.py): one host transfer per pass regardless of
        batch count, instead of a device-queue drain per batch."""
        tel = self._telemetry
        t0 = time.perf_counter()
        with tel.span(obs_lib.SPAN_EVAL):
            eval_step = self._eval_step
            # in-flight bound: without it, device-resident accumulation would
            # let the host enqueue EVERY eval batch's copy+step at once
            budget = async_loop.eval_budget(
                tel, self.train_config.dispatch_ahead_steps
            )
            acc = None
            for raw in batches:
                metrics = eval_step(state, self._place_batch(raw))
                acc = async_loop.merge_metrics_device(acc, metrics)
                budget.track(acc)
            result = async_loop.fetch_metrics(acc, telemetry=tel)
        if step_no is None:
            step_no = int(jax.device_get(state.step))
        logger.info("eval @ %d: %s", step_no, result)
        tel.eval_event(step_no, result, time.perf_counter() - t0)
        # this pass compiled whatever eval needed; later eval compiles are
        # recompiles
        tel.mark_warm(obs_lib.SPAN_EVAL)
        return result

    def _warn_eval_on_train(self, source: str) -> None:
        """Loud, once-per-trainer: model selection on train data overfits
        silently (round-2 VERDICT weak #6)."""
        if getattr(self, "_warned_eval_on_train", False):
            return
        self._warned_eval_on_train = True
        logger.warning(
            "no val split found — eval (and best-checkpoint selection) is "
            "running on %s; metrics/top1 will overestimate generalization. "
            "Provide val-*.tfrecord shards / a val/ folder, or set "
            "TrainConfig.eval_holdout_fraction to carve one out of the train "
            "record shards.",
            source,
        )

    def _evaluate_records(
        self, state: TrainState, ds, local_bs: int,
        step_no: Optional[int] = None,
    ) -> Dict[str, float]:
        """One streaming eval pass over record shards. Every process runs the
        same number of collective-bearing steps: batch counts are equalized to
        the cross-process MAXIMUM (counted from the record framing, cheap header
        scan), with wrap-around refill and `valid` masking excluding both the
        wrapped rows and the final batch's padding from the metrics."""
        from tensorflowdistributedlearning_tpu.data import records as records_lib

        my_n = records_lib.count_records(ds.paths)
        if jax.process_count() > 1:
            from tensorflowdistributedlearning_tpu.parallel import multihost as mh

            num = mh.all_processes_max_batches(my_n, local_bs)
        else:
            num = -(-my_n // local_bs) if my_n else 1
        return self._eval_pass(
            state, ds.batches(local_bs, repeat=False, pad_to_batches=num),
            step_no,
        )

    # -- serving ----------------------------------------------------------

    def _checkpointer(self) -> CheckpointManager:
        """The ONE manager configuration for this run directory — fit() and the
        serving restore must agree on cadence/best-metric or serving would
        silently select a different 'best' than training exported."""
        tcfg = self.train_config
        return CheckpointManager(
            self.model_dir,
            save_every_steps=tcfg.checkpoint_every_steps,
            save_best=tcfg.save_best,
            best_metric="metrics/top1",
            async_checkpointing=tcfg.async_checkpointing,
            # live during fit(), the null instance on serving restores —
            # checkpoint_retry/checkpoint_corrupt events reach the run ledger
            telemetry=self._telemetry,
        )

    def _host_template(self) -> TrainState:
        """Fresh unsharded state on the host template — the single recipe shared
        by _init_state and the serving restore."""
        cfg, tcfg = self.model_config, self.train_config
        return create_train_state(
            self._plain_model,
            step_lib.make_optimizer(tcfg),
            jax.random.PRNGKey(tcfg.seed),
            np.zeros((1, *cfg.input_shape, cfg.input_channels), np.float32),
        )

    def _restore_best_host(self) -> TrainState:
        """Best exported state (falling back to latest), restored UNSHARDED onto
        the host template. Single-process only: multi-process checkpoints are
        written as sharded jax.Arrays and serving wants one addressable copy —
        export from a single-process session instead."""
        if jax.process_count() > 1:
            raise RuntimeError(
                "serving_fn/export_serving run single-process (multi-process "
                "checkpoints restore into sharded layouts); load the model_dir "
                "from a single-process session to export"
            )
        ckpt = self._checkpointer()
        try:
            return ckpt.restore_best_or_raise(self._host_template(), hint="fit() first")
        finally:
            ckpt.close()

    def serving_fn(self, serving_dtype: str = "float32"):
        """Jitted single-model inference for deployment: ``serve(images) ->
        {'probabilities', 'class'}`` on the best state — the classification twin
        of the K-fold Trainer's serving_fn (reference exported SavedModels via
        BestExporter, model.py:190-204). Honors ``data_format='NCHW'`` at the
        boundary exactly like the segmentation path, and the same
        ``serving_dtype`` precision specs (train/quantize.py SERVING_SPECS,
        including ``int8-compute`` which traces dense/conv layers through the
        quantized-compute kernels): float32 wire contract either way,
        quantized constants inside; the closure carries its manifest section
        as ``serve.quantization``."""
        from tensorflowdistributedlearning_tpu.ops import quant_kernels
        from tensorflowdistributedlearning_tpu.train import quantize
        from tensorflowdistributedlearning_tpu.train.trainer import _forward_cached

        # EMA-trained models serve the averaged weights even when restore fell
        # back to a periodic (live-trajectory) checkpoint (identity otherwise);
        # then drop the optimizer moments — serving reads params/batch_stats only
        state = step_lib.with_ema_params(self._restore_best_host()).replace(
            opt_state=None
        )
        qparams, qstats, quant_section = quantize.quantize_state(
            state.params, state.batch_stats, serving_dtype
        )
        act_dtype = quantize.compute_dtype(serving_dtype)
        int8_compute = quant_section.get("compute_dtype") == "int8"
        task = self.task
        forward = _forward_cached(self._plain_model)
        nchw = self.train_config.data_format == "NCHW"

        def serve(images):
            if nchw:
                images = jax.numpy.transpose(images, (0, 2, 3, 1))
            st = state.replace(
                params=quantize.dequantize_pytree(qparams, act_dtype),
                batch_stats=quantize.dequantize_pytree(qstats, act_dtype),
            )
            x = images.astype(act_dtype)
            if int8_compute:
                # trace the forward under the interceptor: quantized layers
                # take the int8-compute kernels, the rest keep the
                # dequantized-float path (qparams records are shared with
                # dequantize_pytree above, so the int8 constants serialize once)
                with quant_kernels.int8_intercept(qparams, act_dtype):
                    logits = forward(st, x)
            else:
                logits = forward(st, x)
            out = task.serve_predictions(logits)
            return quantize.cast_outputs_float32(out)

        serve.quantization = quant_section
        return serve

    def export_serving(
        self,
        directory: Optional[str] = None,
        serving_dtype: str = "float32",
    ) -> str:
        """Standalone serialized-StableHLO serving artifact for the best state
        (see train/serving.py); default location ``{model_dir}/export/serving``
        (``serving-{dtype}`` for quantized exports, so the f32 reference and
        its quantize-check candidates coexist)."""
        from tensorflowdistributedlearning_tpu.train import serving as serving_lib

        suffix = "serving" if serving_dtype == "float32" else f"serving-{serving_dtype}"
        directory = directory or os.path.join(self.model_dir, "export", suffix)
        cfg = self.model_config
        h, w = cfg.input_shape
        shape = (
            (1, cfg.input_channels, h, w)
            if self.train_config.data_format == "NCHW"
            else (1, h, w, cfg.input_channels)
        )
        serve = self.serving_fn(serving_dtype=serving_dtype)
        return serving_lib.export_serving_artifact(
            serve,
            shape,
            directory,
            metadata={
                "task": "classification",
                "num_classes": cfg.num_classes,
                "backbone": cfg.backbone,
                "data_format": self.train_config.data_format,
            },
            quantization=serve.quantization,
        )

    @property
    def _eval_step(self):
        if self._pp:
            from tensorflowdistributedlearning_tpu.train import pipeline_step as pp_lib

            return pp_lib.make_eval_step_pipeline(
                self.mesh, self.task, self.model_config, self._pp_microbatches
            )
        if self._tp:
            from tensorflowdistributedlearning_tpu.parallel import tensor as tp_lib

            return tp_lib.make_eval_step_gspmd(self.mesh, self.task)
        return step_lib.make_eval_step(self.mesh, self.task, spatial=self._spatial)

    def _place_batch(self, raw):
        """Device placement for one host batch — shared by the train loop and
        both eval paths. One path for every strategy: per-process global
        assembly sharded on the batch axis (under tensor parallelism the model
        axis stays replicated for activations and GSPMD re-shards internally —
        the same layout place_batch_gspmd produces, but multi-host capable)."""
        return multihost.global_shard_batch(raw, self.mesh, spatial=self._spatial)


def fit_preset(
    preset_name: str,
    model_dir: str,
    data_dir: Optional[str] = None,
    steps: int = 100,
    batch_size: Optional[int] = None,
    eval_every_steps: Optional[int] = None,
    sequence_parallel: int = 1,
    sync_batch_norm: bool = False,
    model_parallel: int = 1,
    pipeline_parallel: int = 1,
    pipeline_microbatches: Optional[int] = None,
    expert_parallel: int = 1,
    weight_update_sharding: Optional[bool] = None,
    optimizer: Optional[str] = None,
    lr: Optional[float] = None,
    eval_holdout_fraction: Optional[float] = None,
    augmentation: Optional[str] = None,
    ema_decay: Optional[float] = None,
    grad_accum_steps: Optional[int] = None,
    grad_clip_norm: Optional[float] = None,
    prefetch_depth: Optional[int] = None,
    dispatch_ahead_steps: Optional[int] = None,
    data_service_workers: Optional[int] = None,
    trace_sample_rate: Optional[float] = None,
    nan_guard: Optional[str] = None,
    profile_every_windows: Optional[int] = None,
    parallelism: Optional[str] = None,
    hbm_budget_gb: Optional[float] = None,
    compile_cache_dir: Optional[str] = None,
    export_serving: Optional[str] = None,
    export_dir: Optional[str] = None,
) -> FitResult:
    """Train a named config preset end-to-end (the CLI `fit` entry point).

    ``parallelism='auto'`` derives the whole layout via the parallelism
    planner (``parallel/planner.py``) from the preset's model, the HBM
    budget, and the live topology — any parallelism flag explicitly set
    above its default stays pinned (explicit flags win). The default
    (explicit) path routes the preset's hardcoded layout through the SAME
    planner validator, so an indivisible or over-budget preset fails here,
    at parse time, with the named constraint instead of mid-compile."""
    from tensorflowdistributedlearning_tpu.configs import get_preset

    preset = get_preset(preset_name)
    if preset.model.num_classes is None:
        raise ValueError(
            f"Preset {preset_name!r} is a segmentation config; use the `train` "
            "command (K-fold Trainer) for it"
        )
    train_cfg = preset.train
    if optimizer is not None and optimizer != train_cfg.optimizer and lr is None:
        # preset learning rates are tuned FOR their optimizer (SGD presets run
        # linearly-scaled lr ~0.4-3.2; Adam wants ~1e-3): swapping one without
        # the other silently diverges
        raise ValueError(
            f"preset {preset_name!r} pairs optimizer={train_cfg.optimizer!r} "
            f"with lr={train_cfg.lr}; overriding --optimizer requires an "
            "explicit --lr tuned for it"
        )
    if (
        sequence_parallel != 1
        or sync_batch_norm
        or parallelism is not None
        or hbm_budget_gb is not None
        or model_parallel != 1
        or pipeline_parallel != 1
        or pipeline_microbatches is not None
        or expert_parallel != 1
        or weight_update_sharding is not None
        or optimizer is not None
        or lr is not None
        or eval_holdout_fraction is not None
        or augmentation is not None
        or ema_decay is not None
        or grad_accum_steps is not None
        or grad_clip_norm is not None
        or prefetch_depth is not None
        or dispatch_ahead_steps is not None
        or data_service_workers is not None
        or trace_sample_rate is not None
        or nan_guard is not None
        or profile_every_windows is not None
        or compile_cache_dir is not None
    ):
        train_cfg = dataclasses.replace(
            train_cfg,
            parallelism=parallelism or train_cfg.parallelism,
            hbm_budget_gb=(
                hbm_budget_gb
                if hbm_budget_gb is not None
                else train_cfg.hbm_budget_gb
            ),
            sequence_parallel=sequence_parallel,
            sync_batch_norm=sync_batch_norm or train_cfg.sync_batch_norm,
            model_parallel=model_parallel,
            pipeline_parallel=pipeline_parallel,
            pipeline_microbatches=(
                pipeline_microbatches
                if pipeline_microbatches is not None
                else train_cfg.pipeline_microbatches
            ),
            expert_parallel=expert_parallel,
            weight_update_sharding=(
                weight_update_sharding
                if weight_update_sharding is not None
                else train_cfg.weight_update_sharding
            ),
            optimizer=optimizer or train_cfg.optimizer,
            lr=lr if lr is not None else train_cfg.lr,
            eval_holdout_fraction=(
                eval_holdout_fraction
                if eval_holdout_fraction is not None
                else train_cfg.eval_holdout_fraction
            ),
            augmentation=augmentation or train_cfg.augmentation,
            ema_decay=(
                ema_decay if ema_decay is not None else train_cfg.ema_decay
            ),
            grad_accum_steps=(
                grad_accum_steps
                if grad_accum_steps is not None
                else train_cfg.grad_accum_steps
            ),
            grad_clip_norm=(
                grad_clip_norm
                if grad_clip_norm is not None
                else train_cfg.grad_clip_norm
            ),
            prefetch_depth=(
                prefetch_depth
                if prefetch_depth is not None
                else train_cfg.prefetch_depth
            ),
            dispatch_ahead_steps=(
                dispatch_ahead_steps
                if dispatch_ahead_steps is not None
                else train_cfg.dispatch_ahead_steps
            ),
            data_service_workers=(
                data_service_workers
                if data_service_workers is not None
                else train_cfg.data_service_workers
            ),
            trace_sample_rate=(
                trace_sample_rate
                if trace_sample_rate is not None
                else train_cfg.trace_sample_rate
            ),
            nan_guard=(
                nan_guard if nan_guard is not None else train_cfg.nan_guard
            ),
            profile_every_windows=(
                profile_every_windows
                if profile_every_windows is not None
                else train_cfg.profile_every_windows
            ),
            compile_cache_dir=(
                compile_cache_dir
                if compile_cache_dir is not None
                else train_cfg.compile_cache_dir
            ),
        )
    # route EVERY preset's layout through the parallelism planner before the
    # trainer is built: auto derives the layout (explicit flags pinned),
    # explicit validates the hand spec — either way an indivisible preset
    # fails HERE, at parse time, with the named constraint, and the plan's
    # predicted bytes/chip ride the run header
    from tensorflowdistributedlearning_tpu.parallel import multihost
    from tensorflowdistributedlearning_tpu.parallel import planner as planner_lib

    multihost.initialize()  # topology must see the full pod, like the mesh
    global_batch = batch_size or preset.global_batch
    if train_cfg.parallelism == "auto":
        # pin only what the CALLER explicitly asked for (explicit flags win);
        # the preset's own hardcoded layout is exactly what auto re-derives
        pinned = {}
        if model_parallel != 1:
            pinned["model_parallel"] = model_parallel
        if pipeline_parallel != 1:
            pinned["pipeline_parallel"] = pipeline_parallel
        if sequence_parallel != 1:
            pinned["sequence_parallel"] = sequence_parallel
        if expert_parallel != 1:
            pinned["expert_parallel"] = expert_parallel
        if weight_update_sharding is not None:
            pinned["weight_update_sharding"] = weight_update_sharding
        # prior runs in this workdir may have ledgered op_roofline captures
        # (--profile-every-windows): score candidates with the MEASURED
        # achieved rates when they exist — profile once, plan better forever
        # after. Falls back to the analytic constants (and stamps the
        # provenance in the run header) when none do.
        measured = None
        try:
            measured = planner_lib.measured_costs_from_workdir(model_dir)
        except Exception:  # noqa: BLE001 — a torn ledger must not block
            measured = None
        run_plan = planner_lib.plan(
            preset.model, train_cfg, global_batch, pinned=pinned,
            source="auto", measured_costs=measured,
        )
        train_cfg = dataclasses.replace(train_cfg, **run_plan.overrides())
    else:
        run_plan = planner_lib.validate_config(
            preset.model, train_cfg, global_batch
        )
    trainer = ClassifierTrainer(
        model_dir, data_dir, preset.model, train_cfg, plan=run_plan.header()
    )
    result = trainer.fit(
        batch_size=global_batch,
        steps=steps,
        eval_every_steps=eval_every_steps,
    )
    if export_serving is not None:
        # export rides the SAME trainer (best-checkpoint restore) so the
        # artifact is exactly the run that just finished — the flywheel's
        # `fit --export-serving --auto-promote` retrain path
        result.serving_artifact = trainer.export_serving(
            export_dir, serving_dtype=export_serving
        )
    return result
