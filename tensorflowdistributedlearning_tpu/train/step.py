"""Jitted SPMD train/eval/predict steps.

This module is where the reference's whole distribution machinery collapses: the
per-GPU towers, per-tower input_fns, NCCL gradient all-reduce, and UPDATE_OPS control
dependencies (reference: model.py:115-121, 326-505) become ONE function, shard_map-ped
over the device mesh:

- the batch arrives sharded on the `batch` mesh axis (each shard sees batch/n, the
  reference's per-tower split, model.py:156-159);
- BN statistics are computed per shard — matching the reference's per-tower slim BN
  under MirroredStrategy — then averaged across shards so the replicated-state
  invariant holds;
- gradients and metrics are reduced with `lax.pmean`/`lax.psum`, which XLA lowers to
  ICI all-reduces (the NCCL equivalent, emitted by the compiler);
- the optimizer update runs identically on every shard, keeping params replicated.

Everything is a pure function of (state, batch), so `jax.jit` with donated state gives
in-place buffer reuse on TPU.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, PartitionSpec as P

from tensorflowdistributedlearning_tpu.config import ModelConfig, TrainConfig
from tensorflowdistributedlearning_tpu.ops import losses as losses_lib
from tensorflowdistributedlearning_tpu.ops import metrics as metrics_lib
from tensorflowdistributedlearning_tpu.parallel.mesh import BATCH_AXIS, SEQUENCE_AXIS
from tensorflowdistributedlearning_tpu.train.state import TrainState

Metrics = Dict[str, metrics_lib.Mean]


def make_lr_schedule(cfg: TrainConfig) -> optax.Schedule:
    """The configured learning-rate schedule.

    ``exponential`` (default) reproduces the reference: continuous decay, lr
    halves every ``lr_decay_steps`` (reference: model.py:457-462,
    staircase=False). ``cosine`` is the standard ImageNet recipe — linear
    warmup over ``lr_warmup_steps`` then cosine decay to ~0 at
    ``lr_decay_steps``; with ``lr_warmup_steps=0`` it starts straight at the
    peak lr (a zero-lr first step would silently waste it)."""
    if cfg.lr_schedule == "cosine":
        if cfg.lr_warmup_steps == 0:
            return optax.cosine_decay_schedule(
                init_value=cfg.lr, decay_steps=max(cfg.lr_decay_steps, 1)
            )
        return optax.warmup_cosine_decay_schedule(
            init_value=0.0,
            peak_value=cfg.lr,
            warmup_steps=cfg.lr_warmup_steps,
            decay_steps=max(cfg.lr_decay_steps, cfg.lr_warmup_steps + 1),
        )
    return optax.exponential_decay(
        init_value=cfg.lr,
        transition_steps=cfg.lr_decay_steps,
        decay_rate=cfg.lr_decay_rate,
        staircase=False,
    )


def make_host_lr_schedule(cfg: TrainConfig) -> Callable[[int], float]:
    """Pure-host (math-library) mirror of ``make_lr_schedule``.

    The trainers log the next update's lr every window; evaluating the optax
    schedule for that dispatches a tiny device computation per log line — the
    logging path should add ZERO device work, especially under the async host
    loop where the device queue must stay full. Parity with the optax
    schedules is pinned by
    tests/test_async_loop.py::test_host_lr_schedule_matches_optax."""
    import math

    lr = float(cfg.lr)
    if cfg.lr_schedule == "cosine":
        warmup = cfg.lr_warmup_steps
        if warmup == 0:
            decay_steps = max(cfg.lr_decay_steps, 1)

            def sched(step: int) -> float:
                frac = min(max(step, 0), decay_steps) / decay_steps
                return lr * 0.5 * (1.0 + math.cos(math.pi * frac))

            return sched
        decay_steps = max(cfg.lr_decay_steps, warmup + 1)

        def sched(step: int) -> float:
            if step < warmup:
                return lr * max(step, 0) / warmup
            frac = min(step - warmup, decay_steps - warmup) / (
                decay_steps - warmup
            )
            return lr * 0.5 * (1.0 + math.cos(math.pi * frac))

        return sched
    transition, rate = cfg.lr_decay_steps, cfg.lr_decay_rate

    def sched(step: int) -> float:
        return lr * rate ** (step / transition)

    return sched


# weight-matrix leaf names: flax conv/dense "kernel", plus the MoE FFN's
# explicitly-declared expert matrices and router (models/vit.py:MoEMlp) —
# the direct replacements for the dense mlp kernels they stand in for
_DECAYED_LEAF_NAMES = frozenset({"kernel", "w_in", "w_out", "router"})


def kernel_decay_mask(params: Any) -> Any:
    """Weight-decay mask: True only for weight-matrix leaves (conv/dense
    kernels, MoE expert matrices + router). BN scale/bias, plain biases,
    LayerNorm params, ViT cls/position embeddings stay undecayed — the
    standard ImageNet recipe (arXiv:1706.02677 §5.3) and the same
    kernels-only scoping the reference's declared l2 used
    (reference: core/resnet.py:357-376, weights_regularizer on conv weights)."""
    paths_leaves, treedef = jax.tree_util.tree_flatten_with_path(params)
    mask_leaves = [
        any(getattr(k, "key", None) in _DECAYED_LEAF_NAMES for k in path)
        for path, _ in paths_leaves
    ]
    return jax.tree_util.tree_unflatten(treedef, mask_leaves)


class EmaTrackerState(NamedTuple):
    """State of ``ema_tracker``: the parameter EMA (same pytree as params)."""

    ema: Any


def ema_tracker(decay: float) -> optax.GradientTransformation:
    """Pass-through transformation that maintains an exponential moving average
    of the PARAMETERS (not the gradients) in its own state.

    Appended after the real optimizer in the chain, its ``update`` sees the
    final updates and the current params, so ``params + updates`` is exactly
    the post-step parameter value: ``ema <- decay * ema + (1 - decay) * new``.
    The EMA initializes AT the initial params (no zero-init debias needed) and
    rides ``opt_state`` — so checkpointing, donation, replication, and every
    execution strategy (shard_map, GSPMD tensor-parallel, pipeline) carry it
    with zero extra plumbing. Updates pass through UNCHANGED; evaluation opts
    in via ``with_ema_params``. Beyond-parity: the reference had no weight
    averaging (its slim arg_scope declared none); this is the standard modern
    ImageNet/ViT recipe component (e.g. arXiv:1706.02677-era baselines ship
    without it, RandAug/EffNet-era recipes with it)."""

    def init_fn(params):
        # a REAL copy, not jnp.asarray: the EMA must not alias the param
        # buffers, or donating TrainState would donate each buffer twice
        return EmaTrackerState(ema=jax.tree.map(jnp.copy, params))

    def update_fn(updates, state, params=None):
        if params is None:
            raise ValueError("ema_tracker needs params in tx.update()")
        new_ema = jax.tree.map(
            lambda e, p, u: e * decay + (p + u) * (1.0 - decay),
            state.ema,
            params,
            updates,
        )
        return updates, EmaTrackerState(ema=new_ema)

    return optax.GradientTransformation(init_fn, update_fn)


def find_ema_params(opt_state: Any) -> Optional[Any]:
    """The tracked parameter EMA inside ``opt_state``, or None when the
    optimizer chain has no ``ema_tracker``."""
    if isinstance(opt_state, EmaTrackerState):
        return opt_state.ema
    if isinstance(opt_state, (tuple, list)):
        for sub in opt_state:
            found = find_ema_params(sub)
            if found is not None:
                return found
    return None


def with_ema_params(state: TrainState) -> TrainState:
    """``state`` with params swapped for their EMA when one is tracked (the
    eval/export view); identity otherwise. The EMA tree matches the params
    tree exactly, so jitted eval/predict executables cache-hit either way."""
    ema = find_ema_params(state.opt_state)
    return state if ema is None else state.replace(params=ema)


def make_optimizer(cfg: TrainConfig) -> optax.GradientTransformation:
    """The configured optimizer under the configured lr schedule: ``adam``
    (the reference's choice, model.py:462), ``sgd`` (Nesterov momentum —
    the standard ImageNet recipe behind the 76%-top-1 north star), or
    ``lars`` (large-batch layer-wise scaling, arXiv:1708.03888).

    ``cfg.weight_decay > 0`` adds kernels-only decoupled decay to the chain:
    before momentum+lr scaling for sgd (classic l2-SGD, the Goyal recipe),
    as AdamW for adam, and through optax.lars' own decay/trust-ratio masks
    for lars. Living in the optimizer chain means every execution strategy —
    the shard_map step, the GSPMD tensor-parallel step, the pipeline runner —
    applies it identically through ``TrainState.tx``.

    Memoized on the optimizer-relevant fields only: optax transformations are
    pure function pairs, and ``TrainState.tx`` is a static pytree field compared
    by ``==`` inside jax.jit — returning the SAME object for equivalent
    configurations is what lets the jitted train step's cache hit across K-fold
    iterations, Trainer instances, and configs that differ only in
    orchestration knobs (checkpoint cadence, fold count, ...), instead of
    recompiling per fold."""
    return _make_optimizer_cached(
        cfg.optimizer,
        # momentum only shapes the sgd/lars transformations: normalize it for
        # adam so configs differing in an UNUSED knob still share one tx object
        cfg.sgd_momentum if cfg.optimizer in ("sgd", "lars") else 0.0,
        cfg.lr,
        cfg.lr_schedule,
        cfg.lr_decay_steps,
        cfg.lr_decay_rate,
        cfg.lr_warmup_steps,
        cfg.weight_decay,
        cfg.ema_decay,
        cfg.grad_clip_norm,
    )


@functools.lru_cache(maxsize=None)
def _make_optimizer_cached(
    optimizer: str,
    momentum: float,
    lr: float,
    schedule: str,
    decay_steps: int,
    decay_rate: float,
    warmup_steps: int,
    weight_decay: float,
    ema_decay: float = 0.0,
    grad_clip_norm: float = 0.0,
) -> optax.GradientTransformation:
    cfg = TrainConfig(
        lr=lr,
        lr_schedule=schedule,
        lr_decay_steps=decay_steps,
        lr_decay_rate=decay_rate,
        lr_warmup_steps=warmup_steps,
    )
    sched = make_lr_schedule(cfg)
    if optimizer == "lars":
        tx = optax.lars(
            sched,
            weight_decay=weight_decay,
            weight_decay_mask=kernel_decay_mask,
            trust_ratio_mask=kernel_decay_mask,
            momentum=momentum,
            nesterov=True,
        )
    elif optimizer == "sgd":
        if weight_decay:
            # decay BEFORE momentum+lr scaling == the classic coupled l2-SGD
            # update the 76%-top-1 recipe trains with (arXiv:1706.02677)
            tx = optax.chain(
                optax.add_decayed_weights(weight_decay, mask=kernel_decay_mask),
                optax.sgd(sched, momentum=momentum, nesterov=True),
            )
        else:
            tx = optax.sgd(sched, momentum=momentum, nesterov=True)
    elif weight_decay:
        tx = optax.adamw(sched, weight_decay=weight_decay, mask=kernel_decay_mask)
    else:
        tx = optax.adam(sched)
    if grad_clip_norm:
        # clip FIRST so decay/momentum/trust-ratio all see the clipped gradient
        # (the standard ViT/large-LR stabilizer placement)
        tx = optax.chain(optax.clip_by_global_norm(grad_clip_norm), tx)
    if ema_decay:
        tx = optax.chain(tx, ema_tracker(ema_decay))
    return tx


@dataclasses.dataclass(frozen=True)
class SegmentationTask:
    """Binary segmentation objective: per-image Lovász hinge on the logits, Kaggle
    thresholded mIOU + pixel accuracy on the thresholded sigmoid (reference:
    model.py:371-372, 391-398)."""

    threshold: float = 0.5

    def loss(self, logits: jax.Array, batch: Dict[str, jax.Array]) -> jax.Array:
        return losses_lib.lovasz_loss(batch["labels"], logits, "NHWC")

    def loss_per_example(
        self, logits: jax.Array, batch: Dict[str, jax.Array]
    ) -> jax.Array:
        return losses_lib.lovasz_hinge_per_image(
            jnp.squeeze(logits, -1).astype(jnp.float32),
            jnp.squeeze(batch["labels"], -1),
        )

    def metric_scores(
        self, logits: jax.Array, batch: Dict[str, jax.Array]
    ) -> Dict[str, jax.Array]:
        probs = jax.nn.sigmoid(logits)
        predicted = (probs > self.threshold).astype(jnp.float32)
        labels = batch["labels"]
        return {
            "metrics/mean_iou": metrics_lib.iou_scores(labels, predicted),
            "metrics/mean_acc": metrics_lib.mean_accuracy_scores(labels, predicted),
        }

    def predictions(self, logits: jax.Array) -> Dict[str, jax.Array]:
        probs = jax.nn.sigmoid(logits)
        return {
            "probabilities": probs,
            "mask": (probs > self.threshold).astype(jnp.float32),
        }

    def serve_predictions(self, logits: jax.Array) -> Dict[str, jax.Array]:
        """The serving-closure head: same outputs as :meth:`predictions` but
        through the fused sigmoid+threshold kernel — one HBM pass over the
        logits instead of three, bit-identical by contract
        (ops/pallas_kernels.py fused_sigmoid_mask). Only the serving export
        path calls this; train/eval keep the plain ops, which XLA already
        fuses into the surrounding step."""
        from tensorflowdistributedlearning_tpu.ops.pallas_kernels import (
            fused_sigmoid_mask,
        )

        probs, mask = fused_sigmoid_mask(logits, self.threshold)
        return {"probabilities": probs, "mask": mask}


@dataclasses.dataclass(frozen=True)
class ClassificationTask:
    """Softmax classification objective for the ImageNet/CIFAR configs (the
    classification path the reference kept in its backbone, core/resnet.py:246-256).
    ``label_smoothing`` (train loss only — eval stays plain CE so metrics remain
    comparable across smoothing settings) is the standard ImageNet regularizer."""

    label_smoothing: float = 0.0

    def loss(self, logits: jax.Array, batch: Dict[str, jax.Array]) -> jax.Array:
        if "lam" in batch:
            # mixup/cutmix pairing (data/augment.py:mixup_batch/cutmix_batch):
            # lam-weighted sum of the two per-example CE terms == CE against
            # the mixed target, without materializing soft labels. Label
            # smoothing applies to both terms (each target one-hot smooths
            # independently; the mix is linear).
            ce_a = losses_lib.softmax_cross_entropy_per_example(
                logits, batch["labels"], self.label_smoothing
            )
            ce_b = losses_lib.softmax_cross_entropy_per_example(
                logits, batch["labels_b"], self.label_smoothing
            )
            lam = batch["lam"]
            return jnp.mean(lam * ce_a + (1.0 - lam) * ce_b)
        return losses_lib.softmax_cross_entropy(
            logits, batch["labels"], self.label_smoothing
        )

    def loss_per_example(
        self, logits: jax.Array, batch: Dict[str, jax.Array]
    ) -> jax.Array:
        return losses_lib.softmax_cross_entropy_per_example(logits, batch["labels"])

    def metric_scores(
        self, logits: jax.Array, batch: Dict[str, jax.Array]
    ) -> Dict[str, jax.Array]:
        scores = {
            "metrics/top1": metrics_lib.top1_accuracy_scores(logits, batch["labels"])
        }
        # only meaningful with more than 5 classes (otherwise it would just
        # repeat top-1 under a misleading name — class count is trace-static)
        if logits.shape[-1] > 5:
            scores["metrics/top5"] = metrics_lib.topk_accuracy_scores(
                logits, batch["labels"], k=5
            )
        return scores

    def predictions(self, logits: jax.Array) -> Dict[str, jax.Array]:
        probs = jax.nn.softmax(logits, axis=-1)
        return {"probabilities": probs, "class": jnp.argmax(logits, axis=-1)}

    def serve_predictions(self, logits: jax.Array) -> Dict[str, jax.Array]:
        """Serving head — classification has no fused variant (softmax+argmax
        already fuse under XLA), so this is :meth:`predictions`; the method
        exists so serving closures can call one name for every task."""
        return self.predictions(logits)


def _l2_penalty(params: Any) -> jax.Array:
    """slim-style l2: scale * sum(w^2)/2 over conv/dense kernels only (reference:
    core/resnet.py:376 attached l2_regularizer to conv weights — though the reference
    never added the collected penalty to its minimized loss; see make_train_step)."""
    leaves = jax.tree_util.tree_leaves_with_path(params)
    total = jnp.zeros((), jnp.float32)
    for path, leaf in leaves:
        if any(getattr(k, "key", None) == "kernel" for k in path):
            total = total + 0.5 * jnp.sum(jnp.square(leaf.astype(jnp.float32)))
    return total


def _metric_deltas(
    scores: Dict[str, jax.Array],
    loss: jax.Array,
    weights: Optional[jax.Array] = None,
) -> Metrics:
    """Per-step metric contributions as psum-able Mean states. The loss is tracked the
    same way the reference tracked it in eval — as a streaming mean
    (reference: model.py:401-403). ``weights`` ([B] 0/1) excludes wrap-around-padded
    eval examples; ``loss`` must then be per-example [B]."""
    out: Metrics = {
        name: metrics_lib.Mean.empty().update(s, weights) for name, s in scores.items()
    }
    out["loss"] = metrics_lib.Mean.empty().update(
        loss if loss.ndim else loss[None], weights if loss.ndim else None
    )
    return out


def _mean_grads(grads: Any) -> Any:
    """Average gradients across the batch (and sequence) mesh axes, leaf-by-leaf
    vma-aware.

    Inside ``shard_map`` with varying-manual-axes checking, the gradient of a
    REPLICATED (unvarying) parameter is already psum'd by the automatic
    transposition, so the mean is ``leaf / axis_size``; a leaf that is still
    per-shard (varying on an axis) needs a real ``pmean``. The sequence axis
    matters under spatial parallelism: every sequence shard computes the same
    (gathered) loss, so the automatic psum over-counts by the axis size — the
    division below is what restores the true gradient. Axis size 1 (the
    non-spatial meshes) makes it a no-op.
    """
    from tensorflowdistributedlearning_tpu.parallel.collectives import vma_of
    from tensorflowdistributedlearning_tpu.utils import jaxcompat

    def mean_leaf(g):
        vma = vma_of(g)
        for axis in (BATCH_AXIS, SEQUENCE_AXIS):
            # legacy bridge (no vma tracking): nothing auto-psums, so every
            # inside-body gradient is per-shard varying — the divide branch
            # would halve/flip updates (proven by the cross-degree oracle)
            if axis in vma or jaxcompat.LEGACY_BRIDGE:
                g = jax.lax.pmean(g, axis)
            else:
                g = g / jax.lax.axis_size(axis)
        return g

    return jax.tree.map(mean_leaf, grads)


def _psum_metrics(metrics: Metrics) -> Metrics:
    """Total metric contributions across batch shards. The trailing pmean over the
    sequence axis is numerically an identity (every sequence shard computes
    identical metrics from the gathered outputs) but makes the result unvarying on
    that axis so it can leave the shard_map replicated."""

    def reduce(x):
        x = jax.lax.psum(x, BATCH_AXIS)
        return jax.lax.pmean(x, SEQUENCE_AXIS)

    return jax.tree.map(reduce, metrics)


def merge_metrics(acc: Optional[Metrics], new: Metrics) -> Metrics:
    """Host-side accumulation across steps (functional tf.metrics update_op)."""
    if acc is None:
        return new
    return {k: acc[k].merge(v) for k, v in new.items()}


def _merge_stacked_metrics(stacked: Metrics) -> Metrics:
    """Merge metric pytrees stacked on a leading axis (a scan's per-iteration
    outputs — the accumulation microbatch loop and the multi-step loop both
    produce one) into a single stream by summing over that axis.

    Summation IS the K-way merge only because every leaf is a ``Mean`` state
    (``Mean.merge`` is addition of total/count). A non-additive metric leaf
    slipping into a scanned step would be silently mis-merged by a blind
    ``jnp.sum`` — fail loudly instead, naming the offender, so whoever adds
    such a metric also adds its merge path here (the ONE place both scan
    paths share)."""
    for name, leaf in stacked.items():
        if not isinstance(leaf, metrics_lib.Mean):
            raise TypeError(
                f"stacked per-step metric {name!r} is a "
                f"{type(leaf).__name__}, not a Mean state — summing over the "
                "step axis is only a valid merge for Mean's (total, count); "
                "teach _merge_stacked_metrics this type before scanning it"
            )
    return jax.tree.map(lambda x: jnp.sum(x, axis=0), stacked)


def compute_metrics(acc: Metrics) -> Dict[str, float]:
    return {k: float(v.compute()) for k, v in acc.items()}


def _batch_in_specs(spatial: bool, keys: Tuple[str, ...]):
    """shard_map in_specs for a batch dict: everything sharded on the batch axis;
    under spatial (sequence) parallelism the images are additionally H-sharded
    over the sequence axis, while labels/valid stay whole per batch shard (they
    are 1-channel/scalar-sized, and the loss needs full images)."""
    if not spatial:
        return P(BATCH_AXIS)
    return {
        k: P(BATCH_AXIS, SEQUENCE_AXIS) if k == "images" else P(BATCH_AXIS)
        for k in keys
    }


def make_train_step(
    mesh: Mesh,
    task,
    *,
    weight_decay: float = 0.0,
    apply_weight_decay: bool = False,
    donate: bool = True,
    spatial: bool = False,
    accum: int = 1,
    seed: int = 0,
    auto_model: bool = False,
    weight_update_sharding: bool = False,
) -> Callable[[TrainState, Dict[str, jax.Array]], Tuple[TrainState, Metrics]]:
    """Build the jitted SPMD train step.

    Memoized on its (hashable) arguments: the reference rebuilt its graph per fold
    and per Estimator (model.py:164-172); here repeated calls — across K-fold
    iterations, Trainer instances, and tests — return the SAME jitted callable, so
    XLA compiles each (mesh, task, model, shapes) combination exactly once per
    process. jax.jit's own cache handles different models/shapes arriving through
    the returned callable (the model rides in as ``state.apply_fn``, a static
    pytree field; ``build_model`` is memoized so equal configs share one module
    instance and therefore one ``apply`` bound method).

    ``apply_weight_decay`` exists because the reference *declared* an l2 regularizer on
    every conv but minimized only the Lovász loss (reference: model.py:462-467 — the
    REGULARIZATION_LOSSES collection was never added). Default False reproduces the
    effective reference objective; True applies the declared one.

    ``spatial=True`` expects a model built with ``spatial_axis_name=SEQUENCE_AXIS``
    and a batch whose images are sharded (batch, sequence) — see
    ``mesh.shard_batch_spatial``. The model's forward runs H-sharded over the
    sequence mesh axis with halo exchanges; outputs are gathered inside the model,
    so loss/metrics math below is unchanged.

    ``accum > 1`` splits each shard's batch into that many equal microbatches,
    runs them sequentially under ``lax.scan`` (one microbatch's activation
    memory), and applies ONE optimizer update on the mean gradient — the
    effective global batch is ``accum`` times what the loop feeds, with the lr
    schedule advancing per update. BN statistics flow microbatch-to-microbatch
    sequentially, then average across shards as usual.

    ``seed`` roots the dropout PRNG stream (TrainConfig.seed in the drivers):
    runs configured with different seeds draw different dropout masks while the
    (step, shard, chunk) fold-in structure — which the cross-strategy parity
    tests rely on — is unchanged.

    ``auto_model=True`` runs the shard_map MANUAL over (batch, sequence) only,
    leaving the ``model`` mesh axis to XLA's SPMD partitioner (shard_map's
    hybrid ``axis_names`` mode). This composes the two execution strategies
    that otherwise exclude each other: the halo-exchange spatial convs need
    manual sequence-axis collectives, while GSPMD tensor parallelism
    (parallel/tensor.py — params channel-sharded over ``model``) needs the
    partitioner to derive its all-reduces. Pass state through
    ``shard_state_tensor_parallel`` and GSPMD partitions the channel math
    inside each manual shard — the dp x tp x sp layout real pods run.

    ``weight_update_sharding=True`` is the ZeRO-1 mode (arXiv:2004.13336,
    parallel/zero.py): the forward/backward still runs under the manual
    shard_map (per-tower BN, explicit collectives — semantics unchanged), but
    the shard_map returns (grads, batch_stats, metrics) and the OPTIMIZER
    UPDATE moves outside it, under GSPMD sharding constraints that keep every
    optimizer-state leaf sharded along the ``batch`` mesh axis on its largest
    divisible dimension. Each chip then stores and updates 1/dp of the
    Adam/LARS/EMA slots; the parameter all-gather falls out of constraining
    the updated params back to replicated. Pass state placed with
    ``parallel.zero.shard_state_weight_update``. Composes with ``accum``,
    ``spatial``, the multi-step scan, and ``auto_model`` tensor parallelism
    (slots shard over (model, batch) jointly).
    """
    return _make_train_step_cached(
        mesh, task, weight_decay, apply_weight_decay, donate, spatial, accum,
        seed, auto_model, weight_update_sharding,
    )


@functools.lru_cache(maxsize=None)
def _make_train_step_cached(
    mesh: Mesh,
    task,
    weight_decay: float,
    apply_weight_decay: bool,
    donate: bool,
    spatial: bool,
    accum: int = 1,
    seed: int = 0,
    auto_model: bool = False,
    weight_update_sharding: bool = False,
):
    def forward_backward(state: TrainState, batch: Dict[str, jax.Array]):
        """Per-shard forward/backward inside the manual region: returns the
        globally-meaned grads, the replicated new BN stats, and the psum'd
        metric deltas — everything the optimizer update needs, with the
        update itself left to the caller (inside the shard_map for the
        replicated update, outside under GSPMD for ZeRO-1)."""
        # Deterministic per-(step, batch-shard) dropout stream for the models
        # that have a stochastic layer (Xception41's pre-logits dropout — the
        # reference declared keep_prob but never used it; here it is live, so
        # train-mode apply needs a PRNG). Folding in the batch index gives each
        # tower its own masks; the sequence/spatial axis is deliberately NOT
        # folded in — spatially-sharded towers compute the same replicated
        # post-pool activations and must agree on one mask. Models without
        # dropout simply never draw from the stream.
        dropout_rng = jax.random.fold_in(
            jax.random.fold_in(jax.random.key(seed), state.step),
            jax.lax.axis_index(BATCH_AXIS),
        )

        def grads_of(batch_stats, chunk, chunk_idx):
            """value_and_grad of one microbatch against the CURRENT params,
            threading BN state in (not closed over) so scan can carry it."""

            def loss_fn(params):
                outputs, mutated = state.apply_fn(
                    {"params": params, "batch_stats": batch_stats},
                    chunk["images"],
                    train=True,
                    mutable=["batch_stats", "aux_loss"],
                    rngs={"dropout": jax.random.fold_in(dropout_rng, chunk_idx)},
                )
                loss = task.loss(outputs, chunk)
                # auxiliary losses sown by the model (MoE load balancing,
                # models/vit.py:MoEMlp) join the training objective; the
                # collection is empty for every non-MoE model
                for aux in jax.tree.leaves(mutated.get("aux_loss", {})):
                    loss = loss + aux
                if apply_weight_decay and weight_decay:
                    loss = loss + weight_decay * _l2_penalty(params)
                # BN-free models mutate nothing; keep the (empty) pytree structure
                new_stats = mutated.get("batch_stats", batch_stats)
                return loss, (outputs, new_stats)

            return jax.value_and_grad(loss_fn, has_aux=True)(state.params)

        if accum == 1:
            (loss, (outputs, new_batch_stats)), grads = grads_of(
                state.batch_stats, batch, 0
            )
            metrics = _metric_deltas(task.metric_scores(outputs, batch), loss)
        else:
            local = batch["images"].shape[0]
            if local % accum:
                raise ValueError(
                    f"grad accumulation needs the per-shard batch ({local}) "
                    f"divisible by grad_accum_steps ({accum})"
                )
            chunks = jax.tree.map(
                lambda x: x.reshape((accum, local // accum) + x.shape[1:]), batch
            )
            # scan carries must keep a stable varying-axes type: BN stats start
            # unvarying (replicated) but each microbatch's updated stats are
            # batch-shard varying — pre-varying the initial carry keeps the
            # types fixed across iterations. lax.pcast replaced the deprecated
            # lax.pvary; support both across jax versions (as
            # parallel/pipeline.py does).
            def pvary_leaf(x):
                axes = (BATCH_AXIS, SEQUENCE_AXIS)
                if hasattr(jax.lax, "pcast"):
                    return jax.lax.pcast(x, axes, to="varying")
                return jax.lax.pvary(x, axes)  # pragma: no cover - older jax

            def body(carry, chunk_with_idx):
                chunk, chunk_idx = chunk_with_idx
                stats, grads_acc = carry
                (loss, (outputs, new_stats)), grads = grads_of(
                    stats, chunk, chunk_idx
                )
                grads_acc = jax.tree.map(
                    lambda a, g: a + g / accum, grads_acc, grads
                )
                deltas = _metric_deltas(task.metric_scores(outputs, chunk), loss)
                return (new_stats, grads_acc), deltas

            # unfreeze so the carry's pytree TYPE matches what flax's mutable
            # apply returns (plain dict), keeping scan's carry structure stable
            from flax.core import unfreeze

            init = (
                jax.tree.map(pvary_leaf, unfreeze(state.batch_stats)),
                # grads of replicated params arrive cross-shard psum'd, i.e.
                # unvarying — the accumulator stays unvarying to match
                jax.tree.map(jnp.zeros_like, state.params),
            )
            (new_batch_stats, grads), stacked = jax.lax.scan(
                body, init, (chunks, jnp.arange(accum))
            )
            # stacked Mean states carry a leading [accum] dim on total/count
            metrics = _merge_stacked_metrics(stacked)

        # MirroredStrategy's gradient MEAN across towers. Under shard_map's
        # varying-manual-axes tracking, autodiff of replicated params already
        # inserts the cross-shard psum (the cotangent of an unvarying input must
        # be unvarying), so grads arrive as the SUM of per-shard local-mean
        # grads; _mean_grads turns that into the global mean — and still works
        # if a grad leaf arrives per-shard (varying), where an explicit pmean is
        # the right reduction.
        grads = _mean_grads(grads)
        # per-shard (per-tower) BN stats, averaged to keep state replicated (the
        # sequence pmean is an identity when BN already syncs over that axis, and
        # required either way so the stored stats leave the shard_map unvarying)
        new_batch_stats = jax.lax.pmean(new_batch_stats, BATCH_AXIS)
        new_batch_stats = jax.lax.pmean(new_batch_stats, SEQUENCE_AXIS)
        return grads, new_batch_stats, _psum_metrics(metrics)

    def step(state: TrainState, batch: Dict[str, jax.Array]):
        grads, new_batch_stats, metrics = forward_backward(state, batch)
        return state.apply_gradients(grads, new_batch_stats), metrics

    # hybrid mode: only (batch, sequence) are manual axes; the model axis is
    # left to the SPMD partitioner, so channel-sharded params (GSPMD tensor
    # parallelism) keep their sharding through the specs below, which describe
    # manual axes only
    batch_specs = _batch_in_specs(spatial, ("images", "labels"))
    if not weight_update_sharding:
        sharded = jax.shard_map(
            step,
            mesh=mesh,
            in_specs=(P(), batch_specs),
            out_specs=(P(), P()),
            **_hybrid_kwargs(auto_model),
        )
        return jax.jit(sharded, donate_argnums=(0,) if donate else ())

    # ZeRO-1: the manual region ends at (grads, stats, metrics) — all
    # unvarying, so they leave replicated — and the optimizer update runs in
    # the enclosing jit under GSPMD constraints that shard every slot (and
    # its 1/dp of the update math) along the batch axis. opt_state never
    # enters the shard_map (the gradient computation does not read it), so
    # its data-axis sharding is invisible to the manual region.
    from tensorflowdistributedlearning_tpu.parallel import zero as zero_lib

    sharded_grads = jax.shard_map(
        forward_backward,
        mesh=mesh,
        in_specs=(P(), batch_specs),
        out_specs=(P(), P(), P()),
        **_hybrid_kwargs(auto_model),
    )

    def zero_step(state: TrainState, batch: Dict[str, jax.Array]):
        grads, new_batch_stats, metrics = sharded_grads(
            state.replace(opt_state=None), batch
        )
        new_state = zero_lib.apply_gradients_sharded(
            state, grads, new_batch_stats, mesh, tensor_parallel=auto_model
        )
        return new_state, metrics

    return jax.jit(zero_step, donate_argnums=(0,) if donate else ())


def make_multi_train_step(
    mesh: Mesh,
    task,
    *,
    n_steps: int,
    weight_decay: float = 0.0,
    apply_weight_decay: bool = False,
    spatial: bool = False,
    accum: int = 1,
    seed: int = 0,
    auto_model: bool = False,
    weight_update_sharding: bool = False,
) -> Callable[[TrainState, Dict[str, jax.Array]], Any]:
    """Device-side training loop: ONE dispatch runs ``n_steps`` train steps
    under ``lax.scan``, the way the reference's Estimator ran many steps per
    ``session.run`` (model.py:164-172 — the host never re-entered the graph
    between steps). Measured honestly on the tunneled v5e (2026-08-01,
    bf16 flagship, K=8): 0.993x vs back-to-back single steps — jax's ASYNC
    DISPATCH already pipelines the single-step loop, so this buys nothing
    when the host keeps up; it exists for orchestration regimes where the
    host cannot (slow drivers, per-step callbacks, very short steps) and as
    the steps-per-loop parity point with the reference.

    Semantics are those of K sequential ``make_train_step`` calls — the scan
    body IS the single step (same builder, same PRNG fold-in on
    ``state.step``, same BN/metric math). Numerically equivalent, NOT
    bitwise: scan inlining lets XLA fuse differently (Lovász tie-order
    shifts bound the drift at ~1e-4 scale after 3 steps); pinned with a
    reversed-order discriminator by
    ``tests/test_train_step.py::test_multi_step_matches_sequential``.

    Input contract: every batch leaf carries a leading ``[n_steps]`` axis —
    place with ``mesh.shard_batch_stacked``. Returns ``(state, metrics)``
    where metrics are the merged streaming Means over all K steps (Mean
    merge = addition of total/count)."""
    if spatial:
        # shard_batch_stacked has no spatial variant yet: stacked images would
        # arrive sequence-replicated while the inner shard_map demands
        # (batch, sequence) sharding, so GSPMD would reshard around the scan —
        # exactly the overhead this function exists to avoid
        raise NotImplementedError(
            "spatial multi-step needs a stacked-spatial batch placement; "
            "use make_train_step per step under sequence parallelism"
        )
    single = make_train_step(
        mesh,
        task,
        weight_decay=weight_decay,
        apply_weight_decay=apply_weight_decay,
        donate=False,  # scan carries the state; donation happens at the outer jit
        spatial=spatial,
        accum=accum,
        seed=seed,
        auto_model=auto_model,
        # the zero step's sharding constraints ride inside the scan body, so
        # the carried opt_state stays data-axis sharded across all n_steps
        weight_update_sharding=weight_update_sharding,
    )
    return _make_multi_train_step_cached(single, n_steps)


@functools.lru_cache(maxsize=None)
def _make_multi_train_step_cached(single, n_steps: int):
    def multi(state: TrainState, batches: Dict[str, jax.Array]):
        # `single` already has scan's (carry, x) -> (carry, y) signature
        final, stacked = jax.lax.scan(single, state, batches, length=n_steps)
        # stacked Mean states carry a leading [n_steps] dim
        return final, _merge_stacked_metrics(stacked)

    return jax.jit(multi, donate_argnums=(0,))


def make_eval_step(
    mesh: Mesh,
    task,
    *,
    spatial: bool = False,
    with_valid: bool = True,
    auto_model: bool = False,
) -> Callable[[TrainState, Dict[str, jax.Array]], Metrics]:
    """Jitted SPMD eval step: forward in inference mode (BN running stats), streaming
    metric deltas (the reference's EVAL branch, model.py:391-403). Memoized — see
    ``make_train_step``; ``auto_model`` is the same hybrid mode (model axis left
    to GSPMD for channel-sharded params)."""
    return _make_eval_step_cached(mesh, task, spatial, with_valid, auto_model)


def _hybrid_kwargs(auto_model: bool) -> dict:
    """shard_map kwargs for hybrid mode: (batch, sequence) manual, model auto
    (see make_train_step's ``auto_model``)."""
    if not auto_model:
        return {}
    return {"axis_names": frozenset({BATCH_AXIS, SEQUENCE_AXIS})}


@functools.lru_cache(maxsize=None)
def _make_eval_step_cached(
    mesh: Mesh, task, spatial: bool, with_valid: bool, auto_model: bool = False
):
    def step(state: TrainState, batch: Dict[str, jax.Array]) -> Metrics:
        outputs = state.apply_fn(
            {"params": state.params, "batch_stats": state.batch_stats},
            batch["images"],
            train=False,
        )
        # per-example losses so the optional batch["valid"] mask (wrap-around padding
        # of the final eval batch — data/pipeline.py eval_batches) weights correctly
        loss = task.loss_per_example(outputs, batch)
        weights = batch.get("valid")
        return _psum_metrics(
            _metric_deltas(task.metric_scores(outputs, batch), loss, weights)
        )

    keys = ("images", "labels", "valid") if with_valid else ("images", "labels")
    sharded = jax.shard_map(
        step,
        mesh=mesh,
        in_specs=(P(), _batch_in_specs(spatial, keys)),
        out_specs=P(),
        **_hybrid_kwargs(auto_model),
    )
    return jax.jit(sharded)


def make_predict_step(
    mesh: Mesh, task, *, spatial: bool = False, auto_model: bool = False
) -> Callable[[TrainState, Dict[str, jax.Array]], Dict[str, jax.Array]]:
    """Jitted SPMD predict step (the reference's PREDICT branch, model.py:371-387);
    outputs stay sharded on the batch axis. Memoized — see ``make_train_step``;
    ``auto_model`` is the same hybrid mode."""
    return _make_predict_step_cached(mesh, task, spatial, auto_model)


@functools.lru_cache(maxsize=None)
def _make_predict_step_cached(
    mesh: Mesh, task, spatial: bool, auto_model: bool = False
):
    def step(state: TrainState, batch: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
        outputs = state.apply_fn(
            {"params": state.params, "batch_stats": state.batch_stats},
            batch["images"],
            train=False,
        )
        preds = task.predictions(outputs)
        if spatial:
            # every sequence shard holds the full gathered prediction; reduce to
            # clear the sequence-varying type (numerically an identity)
            preds = jax.tree.map(
                lambda v: jax.lax.pmax(v, SEQUENCE_AXIS)
                if jnp.issubdtype(v.dtype, jnp.integer)
                else jax.lax.pmean(v, SEQUENCE_AXIS),
                preds,
            )
        return preds

    sharded = jax.shard_map(
        step,
        mesh=mesh,
        in_specs=(P(), _batch_in_specs(spatial, ("images",))),
        out_specs=P(BATCH_AXIS),
        **_hybrid_kwargs(auto_model),
    )
    return jax.jit(sharded)
