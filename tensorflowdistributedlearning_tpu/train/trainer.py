"""K-fold trainer orchestration — the reference's ``Model`` class, TPU-native.

API parity with ``Model(model_dir, data_directory, ...)`` / ``.train(X, y, batch_size,
steps)`` / ``.predict(test_dir, batch_size, tta)`` / ``.params`` (reference:
model.py:27-512), redesigned around one jitted SPMD step per phase instead of
per-fold Estimators:

- folds are JSON index manifests, not symlink trees (data/folds.py; reference:
  preprocessing/preprocessing.py:33-88);
- the train/eval alternation of ``tf.estimator.train_and_evaluate`` (reference:
  model.py:219-223) becomes an explicit loop: train N steps → periodic checkpoint
  (every ``checkpoint_every_steps``, reference: model.py:118) → throttled eval
  (>= ``eval_throttle_secs`` apart, reference: model.py:214) → best-k export keyed on
  ``metrics/mean_iou`` with the comparison the right way around (reference:
  model.py:196-204, utils.py:23-28 — SURVEY §2.4.4);
- auto-resume per fold directory reproduces the Estimator restart contract
  (reference: model.py:164-167);
- TTA predict averages the fold x transform ensemble — finishing what the reference
  left TODO (reference: model.py:229, 255) — and fixes the inverted ``tti`` flag
  (reference: model.py:240-243, SURVEY §2.4.3);
- summaries go to ``fold{i}/train`` and ``fold{i}/eval`` event files with the
  reference's tag layout (reference: model.py:400, 447-448, 470-481).
"""

from __future__ import annotations

import dataclasses
import logging
import os
import time
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from tensorflowdistributedlearning_tpu.config import ModelConfig, TrainConfig
from tensorflowdistributedlearning_tpu.data import augment as augment_lib
from tensorflowdistributedlearning_tpu.data import folds as folds_lib
from tensorflowdistributedlearning_tpu.data import pipeline as pipeline_lib
from tensorflowdistributedlearning_tpu.models import build_model
from tensorflowdistributedlearning_tpu.parallel import mesh as mesh_lib
from tensorflowdistributedlearning_tpu.train import step as step_lib
from tensorflowdistributedlearning_tpu.train.checkpoint import CheckpointManager
from tensorflowdistributedlearning_tpu.train.state import TrainState, create_train_state
from tensorflowdistributedlearning_tpu.utils.params import count_params
from tensorflowdistributedlearning_tpu.utils.summary import SummaryWriter

logger = logging.getLogger(__name__)

_MODEL_FIELDS = {f.name for f in dataclasses.fields(ModelConfig)}


class Trainer:
    """K-fold cross-validated SPMD trainer for the segmentation task.

    ``**kwargs`` accepts every ``ModelConfig`` field, reproducing the reference's
    kwargs plumbing (reference: model.py:63-106) with typo-safety: unknown keys raise
    instead of being silently dropped.
    """

    def __init__(
        self,
        model_dir: str,
        data_directory: str,
        data_format: str = "NHWC",
        lr: float = 0.001,
        n_devices: Optional[int] = None,
        n_fold: int = 5,
        seed: int = 42,
        save_best: int = 5,
        train_config: Optional[TrainConfig] = None,
        augment_config: Optional[augment_lib.AugmentConfig] = None,
        **kwargs,
    ):
        unknown = set(kwargs) - _MODEL_FIELDS
        if unknown:
            raise ValueError(f"Unknown model config keys: {sorted(unknown)}")
        self.model_dir = model_dir
        self.data_directory = data_directory
        self.model_config = ModelConfig(**kwargs)
        self.train_config = train_config or TrainConfig(
            data_format=data_format,
            lr=lr,
            n_devices=n_devices,
            n_folds=n_fold,
            seed=seed,
            save_best=save_best,
        )
        # reference default: the trainer passed crop_probability=0 (model.py:316)
        self.augment_config = augment_config or augment_lib.AugmentConfig(
            crop_probability=0.0
        )
        self.task = step_lib.SegmentationTask()
        self.mesh = mesh_lib.make_mesh(self.train_config.n_devices)
        self.model = build_model(self.model_config)
        self._n_params: Optional[int] = None
        os.makedirs(model_dir, exist_ok=True)

    # -- state ------------------------------------------------------------

    @property
    def params(self) -> int:
        """Total trainable parameter count; available once a state has been built
        (the reference computed it inside model_fn and raised before first train,
        reference: model.py:444-445, 507-512)."""
        if self._n_params is None:
            raise AttributeError(
                "Parameter count unknown — train() or predict() must build the model "
                "first"
            )
        return self._n_params

    def _fold_dir(self, fold: int) -> str:
        return os.path.join(self.model_dir, f"fold{fold}")

    def _init_state(self) -> TrainState:
        cfg, tcfg = self.model_config, self.train_config
        tx = step_lib.make_optimizer(tcfg)
        h, w = cfg.input_shape
        sample = np.zeros((1, h, w, cfg.input_channels), np.float32)
        state = create_train_state(
            self.model, tx, jax.random.PRNGKey(tcfg.seed), sample
        )
        self._n_params = count_params(state.params)
        return mesh_lib.replicate(state, self.mesh)

    def _checkpointer(self, fold: int) -> CheckpointManager:
        tcfg = self.train_config
        return CheckpointManager(
            self._fold_dir(fold),
            save_every_steps=tcfg.checkpoint_every_steps,
            save_best=tcfg.save_best,
        )

    # -- training ---------------------------------------------------------

    def train(
        self,
        X: Sequence[str],
        y: Optional[Sequence[int]] = None,
        batch_size: int = 64,
        steps: int = 10_000,
    ) -> List[Dict[str, float]]:
        """Train every fold; returns each fold's final eval metrics.

        ``X``: example ids under ``{data_directory}/images``; ``y``: stratification
        classes (computed from mask coverage when omitted — the notebooks'
        ``cov_to_class``, Untitled.ipynb cell 4). ``batch_size`` is global and must
        divide the data-parallel degree (reference: model.py:156-159).
        """
        tcfg = self.train_config
        mesh_lib.local_batch_size(batch_size, self.mesh)  # divisibility check
        dataset = pipeline_lib.InMemoryDataset.from_directory(
            self.data_directory, ids=list(X)
        )
        if y is None:
            y = folds_lib.coverage_to_class(
                pipeline_lib.mask_coverage(dataset.masks)
            )
        manifests = folds_lib.write_fold_manifests(
            self.model_dir, list(X), list(np.asarray(y)), tcfg.n_folds, tcfg.seed
        )
        results = []
        for fold, manifest in enumerate(manifests):
            logger.info("Processing fold %d", fold)  # reference: model.py:162
            results.append(
                self._train_fold(fold, dataset, manifest, batch_size, steps)
            )
            logger.info("Finished training fold %d", fold)  # reference: model.py:225
        return results

    def _train_fold(
        self,
        fold: int,
        dataset: pipeline_lib.InMemoryDataset,
        manifest: Dict[str, List[str]],
        batch_size: int,
        steps: int,
    ) -> Dict[str, float]:
        tcfg = self.train_config
        train_ds = dataset.select(pipeline_lib.host_shard(manifest["train"]))
        eval_ds = dataset.select(pipeline_lib.host_shard(manifest["eval"]))

        ckpt = self._checkpointer(fold)
        state = ckpt.restore_latest(self._init_state())
        start_step = int(jax.device_get(state.step))
        if start_step >= steps:
            logger.info("fold %d already trained to step %d", fold, start_step)
            ckpt.close()
            return self._evaluate(state, eval_ds, batch_size, fold, writer=None)

        train_step = step_lib.make_train_step(
            self.mesh, self.task, weight_decay=self.model_config.weight_decay
        )
        prepare = self._make_prepare_train(fold)

        tb_train = SummaryWriter(os.path.join(self._fold_dir(fold), "train"))
        tb_eval = SummaryWriter(os.path.join(self._fold_dir(fold), "eval"))
        last_eval_time = 0.0
        final_metrics: Dict[str, float] = {}

        batches = pipeline_lib.train_batches(
            train_ds, batch_size, seed=tcfg.seed + fold, steps=steps - start_step
        )
        batches = pipeline_lib.device_prefetch(
            batches, lambda b: mesh_lib.shard_batch(b, self.mesh)
        )
        step_no = start_step
        last_eval_step = -1
        for raw in batches:
            batch = prepare(jnp.asarray(step_no), raw)
            state, metrics = train_step(state, batch)
            step_no += 1
            if step_no % tcfg.train_log_every_steps == 0:
                scalars = step_lib.compute_metrics(jax.device_get(metrics))
                tb_train.scalars(scalars, step_no)
            if ckpt.maybe_save(state) and (
                time.time() - last_eval_time >= tcfg.eval_throttle_secs
            ):
                last_eval_time = time.time()
                last_eval_step = step_no
                final_metrics = self._evaluate(
                    state, eval_ds, batch_size, fold, writer=tb_eval
                )
                ckpt.export_best(state, final_metrics)
        # end of training: final checkpoint + eval + export (train_and_evaluate's
        # final-eval contract) — skipped when the last loop iteration already
        # checkpointed and evaluated at this exact step
        ckpt.save(state, force=True)
        if last_eval_step != step_no:
            final_metrics = self._evaluate(
                state, eval_ds, batch_size, fold, writer=tb_eval
            )
            ckpt.export_best(state, final_metrics)
        tb_train.close()
        tb_eval.close()
        ckpt.close()
        return final_metrics

    def _make_prepare_train(self, fold: int):
        """Jitted on-device augmentation: {'images','masks'} -> {'images','labels'}
        with the Laplacian channel (the reference's augmenting input_fn map,
        model.py:315-317, run on TPU instead of the host)."""
        cfg = self.augment_config
        tcfg = self.train_config

        @jax.jit
        def prepare(step: jax.Array, batch: Dict[str, jax.Array]):
            key = jax.random.fold_in(
                jax.random.PRNGKey(tcfg.seed + fold), step
            )
            return augment_lib.augment_batch(
                key, batch["images"], batch["masks"], cfg
            )

        return prepare

    def _evaluate(
        self,
        state: TrainState,
        eval_ds: pipeline_lib.InMemoryDataset,
        batch_size: int,
        fold: int,
        writer: Optional[SummaryWriter],
    ) -> Dict[str, float]:
        """One full eval pass with streaming metrics (the EVAL branch + SummarySaverHook,
        reference: model.py:391-403, 475-481). Runs at the caller's ``batch_size``
        (the reference used 2x the train batch, model.py:207-211 — here the wrap-around
        padding makes eval batch size a pure throughput knob, so it is not doubled)."""
        eval_step = self._eval_step
        prepare = self._prepare_eval
        acc = None
        first_batch = None
        for raw in pipeline_lib.eval_batches(eval_ds, batch_size):
            sharded = mesh_lib.shard_batch(raw, self.mesh)
            batch = prepare(sharded)
            metrics = eval_step(state, batch)
            acc = step_lib.merge_metrics(acc, jax.device_get(metrics))
            if first_batch is None:
                first_batch = batch
        result = step_lib.compute_metrics(acc)
        step_no = int(jax.device_get(state.step))
        logger.info("fold %d eval @ %d: %s", fold, step_no, result)
        if writer is not None:
            writer.scalars(result, step_no)
            self._write_image_summaries(writer, state, first_batch, step_no)
            writer.flush()
        return result

    def _write_image_summaries(
        self, writer: SummaryWriter, state: TrainState, batch, step_no: int
    ) -> None:
        """input/label/probability/prediction image grids (reference:
        model.py:405-426 summarized the same four tensors)."""
        outputs = self._forward(state, batch["images"])
        probs = np.asarray(jax.device_get(jax.nn.sigmoid(outputs)))[..., 0]
        images = np.asarray(jax.device_get(batch["images"]))[..., 0]
        labels = np.asarray(jax.device_get(batch["labels"]))[..., 0]
        n = min(3, images.shape[0])
        for i in range(n):
            lo, hi = images[i].min(), images[i].max()
            writer.image(f"image/{i}", (images[i] - lo) / max(hi - lo, 1e-6), step_no)
            writer.image(f"label/{i}", labels[i], step_no)
            writer.image(f"probability/{i}", probs[i], step_no)
            writer.image(f"prediction/{i}", (probs[i] > 0.5).astype(np.float32), step_no)

    # -- cached jitted helpers --------------------------------------------

    @property
    def _eval_step(self):
        if not hasattr(self, "_eval_step_fn"):
            self._eval_step_fn = step_lib.make_eval_step(self.mesh, self.task)
        return self._eval_step_fn

    @property
    def _predict_step(self):
        if not hasattr(self, "_predict_step_fn"):
            self._predict_step_fn = step_lib.make_predict_step(self.mesh, self.task)
        return self._predict_step_fn

    @property
    def _prepare_eval(self):
        if not hasattr(self, "_prepare_eval_fn"):

            @jax.jit
            def prepare(batch):
                out = augment_lib.prepare_eval_batch(
                    batch["images"], batch["masks"]
                )
                if "valid" in batch:
                    out["valid"] = batch["valid"]
                return out

            self._prepare_eval_fn = prepare
        return self._prepare_eval_fn

    @property
    def _forward(self):
        if not hasattr(self, "_forward_fn"):

            @jax.jit
            def forward(state, images):
                return state.apply_fn(
                    {"params": state.params, "batch_stats": state.batch_stats},
                    images,
                    train=False,
                )

            self._forward_fn = forward
        return self._forward_fn

    # -- prediction -------------------------------------------------------

    def predict(
        self,
        test_dir: str,
        batch_size: int = 64,
        tta: bool = True,
        folds: Optional[Sequence[int]] = None,
    ) -> Dict[str, np.ndarray]:
        """Fold x TTA ensemble prediction.

        For every fold's best exported state and every TTA transform, forward the
        transformed images and inverse-transform the probabilities (reference:
        model.py:230-255, 384-387), then average the ensemble — the step the reference
        left unfinished (``# TODO: finish writing this method``, model.py:229).
        ``tta=True`` really enables all four transforms (the reference's ``tti`` flag
        was inverted, SURVEY §2.4.3).

        Returns ``{"ids", "probabilities" [N,H,W,1], "masks" [N,H,W,1]}``.
        """
        transforms = augment_lib.TTA_TRANSFORMS if tta else ("none",)
        folds = list(folds) if folds is not None else list(
            range(self.train_config.n_folds)
        )
        test_ds = pipeline_lib.InMemoryDataset.from_directory(
            test_dir, with_masks=False
        )
        template = self._init_state()
        total = None
        n_members = 0
        for fold in folds:
            state = self._restore_fold_or_raise(fold, template)
            for transformation in transforms:
                probs = self._predict_one(state, test_ds, batch_size, transformation)
                total = probs if total is None else total + probs
                n_members += 1
        mean_probs = total / n_members
        return {
            "ids": list(test_ds.ids),
            "probabilities": mean_probs,
            "masks": (mean_probs > self.task.threshold).astype(np.float32),
        }

    def _restore_fold_or_raise(self, fold: int, template: TrainState) -> TrainState:
        """Best exported state for ``fold`` (falling back to the latest periodic
        checkpoint); raises if the fold was never trained."""
        ckpt = self._checkpointer(fold)
        try:
            if ckpt.best_step() is None and ckpt.latest_step() is None:
                raise RuntimeError(
                    f"fold {fold} has no trained checkpoint under "
                    f"{self._fold_dir(fold)} — train it first or pass "
                    f"folds=[...] with only the trained folds"
                )
            return ckpt.restore_best(template)
        finally:
            ckpt.close()

    def serving_fn(self, fold: int):
        """Jitted single-model inference function for deployment — the JAX analogue
        of the reference's exported SavedModel with serving signature
        ``image: [None, H, W, input_channels] float32`` (reference: model.py:190-194).

        Loads the fold's best state and returns ``serve(images) ->
        {'probabilities', 'mask'}`` where ``images`` is the preprocessed input batch
        (normalized + Laplacian channel, exactly what the reference's serving
        placeholder received).
        """
        state = self._restore_fold_or_raise(fold, self._init_state())
        task = self.task
        forward = self._forward
        return lambda images: task.predictions(forward(state, images))

    def _predict_one(
        self,
        state: TrainState,
        test_ds: pipeline_lib.InMemoryDataset,
        batch_size: int,
        transformation: str,
    ) -> np.ndarray:
        """Probabilities [N, H, W, 1] for one (state, transform) ensemble member."""
        predict_step = self._predict_step
        chunks = []
        n = len(test_ds)
        for raw in pipeline_lib.eval_batches(test_ds, batch_size):
            images = augment_lib.tta_transform(jnp.asarray(raw["images"]), transformation)
            batch = {"images": augment_lib.add_laplace_channel(images)}
            batch = mesh_lib.shard_batch(batch, self.mesh)
            out = predict_step(state, batch)
            probs = augment_lib.tta_inverse(out["probabilities"], transformation)
            valid = raw["valid"].astype(bool)
            chunks.append(np.asarray(jax.device_get(probs))[valid])
        return np.concatenate(chunks)[:n]


# The reference exposed this as ``class Model`` (reference: model.py:27).
Model = Trainer
