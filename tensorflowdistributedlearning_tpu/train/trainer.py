"""K-fold trainer orchestration — the reference's ``Model`` class, TPU-native.

API parity with ``Model(model_dir, data_directory, ...)`` / ``.train(X, y, batch_size,
steps)`` / ``.predict(test_dir, batch_size, tta)`` / ``.params`` (reference:
model.py:27-512), redesigned around one jitted SPMD step per phase instead of
per-fold Estimators:

- folds are JSON index manifests, not symlink trees (data/folds.py; reference:
  preprocessing/preprocessing.py:33-88);
- the train/eval alternation of ``tf.estimator.train_and_evaluate`` (reference:
  model.py:219-223) becomes an explicit loop: train N steps → periodic checkpoint
  (every ``checkpoint_every_steps``, reference: model.py:118) → throttled eval
  (>= ``eval_throttle_secs`` apart, reference: model.py:214) → best-k export keyed on
  ``metrics/mean_iou`` with the comparison the right way around (reference:
  model.py:196-204, utils.py:23-28 — SURVEY §2.4.4);
- auto-resume per fold directory reproduces the Estimator restart contract
  (reference: model.py:164-167);
- TTA predict averages the fold x transform ensemble — finishing what the reference
  left TODO (reference: model.py:229, 255) — and fixes the inverted ``tti`` flag
  (reference: model.py:240-243, SURVEY §2.4.3);
- summaries go to ``fold{i}/train`` and ``fold{i}/eval`` event files with the
  reference's tag layout (reference: model.py:400, 447-448, 470-481).
"""

from __future__ import annotations

import dataclasses
import functools
import logging
import os
import time
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from tensorflowdistributedlearning_tpu import config as config_lib
from tensorflowdistributedlearning_tpu import obs as obs_lib
from tensorflowdistributedlearning_tpu.config import ModelConfig, TrainConfig
from tensorflowdistributedlearning_tpu.data import augment as augment_lib
from tensorflowdistributedlearning_tpu.data import folds as folds_lib
from tensorflowdistributedlearning_tpu.data import pipeline as pipeline_lib
from tensorflowdistributedlearning_tpu.models import build_model
from tensorflowdistributedlearning_tpu.parallel import mesh as mesh_lib
from tensorflowdistributedlearning_tpu.parallel import multihost
from tensorflowdistributedlearning_tpu.resilience import faults as faults_lib
from tensorflowdistributedlearning_tpu.resilience import preempt as preempt_lib
from tensorflowdistributedlearning_tpu.train import async_loop
from tensorflowdistributedlearning_tpu.train import state as state_lib
from tensorflowdistributedlearning_tpu.train import step as step_lib
from tensorflowdistributedlearning_tpu.train.checkpoint import CheckpointManager
from tensorflowdistributedlearning_tpu.train.state import TrainState, create_train_state
from tensorflowdistributedlearning_tpu.utils.params import count_params
from tensorflowdistributedlearning_tpu.utils.summary import SummaryWriter

logger = logging.getLogger(__name__)

_MODEL_FIELDS = {f.name for f in dataclasses.fields(ModelConfig)}


@functools.lru_cache(maxsize=None)
def _prepare_train_cached(cfg: augment_lib.AugmentConfig):
    """One compiled augmentation executable per AugmentConfig (shared across folds
    and Trainer instances — the per-fold randomness rides in through the key)."""

    @jax.jit
    def prepare(base_key, step, batch):
        key = jax.random.fold_in(base_key, step)
        return augment_lib.augment_batch(key, batch["images"], batch["masks"], cfg)

    return prepare


@functools.lru_cache(maxsize=None)
def _prepare_eval_cached():
    @jax.jit
    def prepare(batch):
        out = augment_lib.prepare_eval_batch(batch["images"], batch["masks"])
        if "valid" in batch:
            out["valid"] = batch["valid"]
        return out

    return prepare


@functools.lru_cache(maxsize=None)
def _forward_cached(model):
    """Single-device inference forward, one executable per model architecture
    (build_model returns a shared instance per config, so this caches across
    Trainer instances)."""

    @jax.jit
    def forward(state, images):
        return model.apply(
            {"params": state.params, "batch_stats": state.batch_stats},
            images,
            train=False,
        )

    return forward


class Trainer:
    """K-fold cross-validated SPMD trainer for the segmentation task.

    ``**kwargs`` accepts every ``ModelConfig`` field, reproducing the reference's
    kwargs plumbing (reference: model.py:63-106) with typo-safety: unknown keys raise
    instead of being silently dropped.
    """

    def __init__(
        self,
        model_dir: str,
        data_directory: str,
        data_format: str = "NHWC",
        lr: float = 0.001,
        n_devices: Optional[int] = None,
        n_fold: int = 5,
        seed: int = 42,
        save_best: int = 5,
        train_config: Optional[TrainConfig] = None,
        augment_config: Optional[augment_lib.AugmentConfig] = None,
        plan: Optional[Dict] = None,
        **kwargs,
    ):
        unknown = set(kwargs) - _MODEL_FIELDS
        if unknown:
            raise ValueError(f"Unknown model config keys: {sorted(unknown)}")
        # join the jax.distributed cluster (auto-discovery; quiet single-process
        # fallback) BEFORE the first device query below builds the mesh
        multihost.initialize()
        self.model_dir = model_dir
        self.data_directory = data_directory
        self.model_config = ModelConfig(**kwargs)
        self.train_config = train_config or TrainConfig(
            data_format=data_format,
            lr=lr,
            n_devices=n_devices,
            n_folds=n_fold,
            seed=seed,
            save_best=save_best,
        )
        # reference default: the trainer passed crop_probability=0 (model.py:316)
        self.augment_config = augment_config or augment_lib.AugmentConfig(
            crop_probability=0.0
        )
        if self.train_config.compile_cache_dir:
            # before the first compile (fold state init): a restarted run
            # loads its executables from the cache instead of rebuilding
            from tensorflowdistributedlearning_tpu.utils import compile_cache

            compile_cache.configure(self.train_config.compile_cache_dir)
        if self.train_config.parallelism == "auto" and plan is None:
            # same contract as ClassifierTrainer: the mesh is built below
            # from the explicit degrees, so 'auto' must be resolved (and its
            # plan handed in) before the trainer exists — the `train` CLI
            # does this; programmatic callers use parallel.planner.plan()
            raise ValueError(
                "parallelism='auto' must be resolved before constructing "
                "Trainer: plan the layout first (the train CLI does this "
                "automatically; programmatically, call parallel.planner."
                "plan(model_config, train_config, global_batch), apply "
                "plan.overrides() onto the config, and pass "
                "plan=plan.header())"
            )
        self.task = step_lib.SegmentationTask()
        tcfg = self.train_config
        # model_parallel > 1: tensor parallelism via shard_map's hybrid
        # ``axis_names`` mode — params/optimizer channel-sharded over the
        # model axis (parallel/tensor.py) while the step stays manual over
        # (batch, sequence), so GSPMD derives the tensor-parallel reductions
        # inside the K-fold segmentation loop's own step
        # (make_train_step(auto_model=True)). TrainConfig keeps tp and sp
        # mutually exclusive at the config level (fit()'s whole-step GSPMD tp
        # cannot compose with sp); the library-level 3-axis composition is
        # proven in tests/test_tensor_parallel.py + tests/test_multiprocess.py.
        self._tp = tcfg.model_parallel > 1
        self.mesh = mesh_lib.make_mesh(
            tcfg.n_devices,
            model_parallel=tcfg.model_parallel,
            sequence_parallel=tcfg.sequence_parallel,
        )
        # sequence_parallel > 1: H-sharded backbone with halo-exchange convs and
        # sequence-synced BN (parallel/spatial.py; a TPU-first capability — the
        # reference was data-parallel only, model.py:115-116)
        from tensorflowdistributedlearning_tpu.parallel.spatial import (
            validate_spatial_config,
        )

        validate_spatial_config(self.model_config, tcfg.sequence_parallel)
        self._spatial = tcfg.sequence_parallel > 1
        axis = mesh_lib.SEQUENCE_AXIS if self._spatial else None
        # sync_batch_norm: BN statistics span the batch mesh axis too —
        # cross-replica BN (semantics and evidence: config.py's field
        # comment)
        bn_axis = axis
        if tcfg.sync_batch_norm:
            bn_axis = (
                (mesh_lib.BATCH_AXIS, axis) if axis else mesh_lib.BATCH_AXIS
            )
        self._sync_bn = tcfg.sync_batch_norm
        self.model = build_model(
            self.model_config, bn_axis_name=bn_axis, spatial_axis_name=axis
        )
        self._n_params: Optional[int] = None
        # the parallelism plan this run trains under (planner header dict):
        # handed in by the CLI's --parallelism auto path, else derived
        # best-effort at train() time for the run-header ledger event
        self._plan = plan
        # train() swaps in a live Telemetry; the null instance keeps predict/
        # serving (which reuse _evaluate-adjacent paths) span-safe
        self._telemetry = obs_lib.NULL_TELEMETRY
        os.makedirs(model_dir, exist_ok=True)

    # -- state ------------------------------------------------------------

    @property
    def params(self) -> int:
        """Total trainable parameter count; available once a state has been built
        (the reference computed it inside model_fn and raised before first train,
        reference: model.py:444-445, 507-512)."""
        if self._n_params is None:
            raise AttributeError(
                "Parameter count unknown — train() or predict() must build the model "
                "first"
            )
        return self._n_params

    def _fold_dir(self, fold: int) -> str:
        return os.path.join(self.model_dir, f"fold{fold}")

    @property
    def _plain_model(self):
        """Unsharded twin of ``self.model`` (identical param tree — SpatialConv is
        nn.Conv-compatible): used for init and host-side single-device forwards,
        which cannot run the spatial collectives outside shard_map."""
        if not hasattr(self, "_plain_model_cache"):
            self._plain_model_cache = (
                build_model(self.model_config)
                if (self._spatial or self._sync_bn)
                else self.model
            )
        return self._plain_model_cache

    def _init_state(self) -> TrainState:
        cfg, tcfg = self.model_config, self.train_config
        tx = step_lib.make_optimizer(tcfg)
        h, w = cfg.input_shape
        sample = np.zeros((1, h, w, cfg.input_channels), np.float32)
        state = create_train_state(
            self._plain_model, tx, jax.random.PRNGKey(tcfg.seed), sample
        )
        if self._spatial or self._sync_bn:
            # state.apply_fn must be the axis-named model (halo-exchange
            # convs / sync-BN pmean), not the plain init twin
            state = state.replace(apply_fn=self.model.apply)
        self._n_params = count_params(state.params)
        if tcfg.weight_update_sharding:
            from tensorflowdistributedlearning_tpu.parallel import zero as zero_lib

            # opt_state 1/dp over the data axis; params/batch_stats keep
            # their canonical layout (channel-sharded under TP, where the
            # optimizer leaves shard over (model, batch) jointly and the
            # hybrid auto-model step constrains params back each step)
            return zero_lib.shard_state_weight_update(
                state, self.mesh, tensor_parallel=self._tp
            )
        if self._tp:
            from tensorflowdistributedlearning_tpu.parallel import tensor as tp_lib

            return tp_lib.shard_state_tensor_parallel(state, self.mesh)
        return mesh_lib.replicate(state, self.mesh)

    def _checkpointer(self, fold: int) -> CheckpointManager:
        tcfg = self.train_config
        return CheckpointManager(
            self._fold_dir(fold),
            save_every_steps=tcfg.checkpoint_every_steps,
            save_best=tcfg.save_best,
            async_checkpointing=tcfg.async_checkpointing,
            # live during train(), the null instance on predict/serving —
            # checkpoint_retry/checkpoint_corrupt events reach the run ledger
            telemetry=self._telemetry,
        )

    # -- training ---------------------------------------------------------

    def train(
        self,
        X: Sequence[str],
        y: Optional[Sequence[int]] = None,
        batch_size: int = 64,
        steps: int = 10_000,
    ) -> List[Dict[str, float]]:
        """Train every fold; returns each fold's final eval metrics.

        ``X``: example ids under ``{data_directory}/images``; ``y``: stratification
        classes (computed from mask coverage when omitted — the notebooks'
        ``cov_to_class``, Untitled.ipynb cell 4). ``batch_size`` is global and must
        divide the data-parallel degree (reference: model.py:156-159).
        """
        tcfg = self.train_config
        config_lib.validate_training_data_format(tcfg)
        mesh_lib.check_accum_divisibility(
            batch_size, self.mesh, tcfg.grad_accum_steps
        )
        dataset = pipeline_lib.InMemoryDataset.from_directory(
            self.data_directory, ids=list(X)
        )
        if y is None:
            y = folds_lib.coverage_to_class(
                pipeline_lib.mask_coverage(dataset.masks)
            )
        manifests = folds_lib.write_fold_manifests(
            self.model_dir, list(X), list(np.asarray(y)), tcfg.n_folds, tcfg.seed
        )
        # describe this run's layout through the parallelism planner so the
        # run header carries the plan (predicted bytes/chip); best-effort —
        # the mesh already validated divisibility in __init__, so a planner
        # hiccup here is telemetry loss, not a training error (the CLI's
        # --parallelism auto resolves its plan BEFORE this trainer exists)
        run_plan = self._plan
        if run_plan is None and tcfg.telemetry:
            # the plan's only consumer here is the run header
            try:
                from tensorflowdistributedlearning_tpu.parallel import (
                    planner as planner_lib,
                )

                run_plan = planner_lib.validate_config(
                    self.model_config, tcfg, batch_size
                ).header()
            except Exception as e:  # noqa: BLE001 — plan is telemetry here
                logger.warning("parallelism plan unavailable: %s", e)
        # one ledger for the whole K-fold run; events carry their fold
        self._telemetry = obs_lib.Telemetry(
            self.model_dir,
            enabled=tcfg.telemetry,
            memory_every_windows=tcfg.telemetry_memory_every_windows,
            # sampled per-step/eval/checkpoint traces (obs/trace.py) and the
            # online health monitors (obs/health.py) ride the window stream
            trace_sample_rate=tcfg.trace_sample_rate,
            health=obs_lib.HealthMonitor.from_train_config(tcfg),
            run_info={
                "task": "segmentation",
                "steps": steps,
                "global_batch": batch_size,
                "n_folds": tcfg.n_folds,
                "mesh": {
                    name: int(size)
                    for name, size in zip(
                        self.mesh.axis_names, self.mesh.devices.shape
                    )
                },
                "model_config": dataclasses.asdict(self.model_config),
                "train_config": dataclasses.asdict(tcfg),
                # chosen layout + predicted bytes/chip (parallel/planner.py):
                # rendered by telemetry-report, hashed by obs/compare
                **({"plan": run_plan} if run_plan else {}),
            },
        )
        # time cross-process sync points as this run's barrier_wait span —
        # per-host barrier asymmetry is the fleet report's straggler signal
        multihost.instrument(self._telemetry)
        try:
            results = []
            for fold, manifest in enumerate(manifests):
                logger.info("Processing fold %d", fold)  # reference: model.py:162
                results.append(
                    self._train_fold(fold, dataset, manifest, batch_size, steps)
                )
                logger.info("Finished training fold %d", fold)  # reference: model.py:225
            self._telemetry.close(
                folds=len(results),
                final_metrics={
                    k: float(v) for k, v in (results[-1] if results else {}).items()
                },
            )
            return results
        finally:
            # idempotent; an exceptional exit reaches this close first and is
            # recorded as interrupted
            multihost.uninstrument(self._telemetry)
            self._telemetry.close(interrupted=True)
            self._telemetry = obs_lib.NULL_TELEMETRY

    def _train_fold(
        self,
        fold: int,
        dataset: pipeline_lib.InMemoryDataset,
        manifest: Dict[str, List[str]],
        batch_size: int,
        steps: int,
    ) -> Dict[str, float]:
        tcfg = self.train_config
        # one telemetry (and one HealthMonitor) spans all K folds, but loss
        # history and step-time baselines are per-FOLD facts: a converged
        # fold's low-loss median would flag the next fold's fresh untrained
        # loss as a spike
        if self._telemetry.health is not None:
            self._telemetry.health.reset()
        # per-process data: each host loads only its round-robin shard of the fold
        # and draws batch/P examples per step; global_shard_batch assembles them
        # into one globally-sharded batch (the per-host generalization of the
        # reference's per-tower batch/n_gpus contract, model.py:156-159)
        local_bs = multihost.per_process_batch_size(batch_size)
        train_ds = dataset.select(pipeline_lib.host_shard(manifest["train"]))
        eval_ds = dataset.select(pipeline_lib.host_shard(manifest["eval"]))
        eval_global_n = len(manifest["eval"])

        ckpt = self._checkpointer(fold)
        state = ckpt.restore_latest(self._init_state())
        # post-init params/optimizer footprint, with exact per-device
        # opt-state accounting (1/dp of it under weight_update_sharding)
        self._telemetry.memory_event(
            params_bytes_per_device=state_lib.tree_bytes_per_device(state.params),
            opt_state_bytes_per_device=state_lib.tree_bytes_per_device(
                state.opt_state
            ),
            weight_update_sharding=tcfg.weight_update_sharding,
        )
        # MFU pricing + continuous profiling: analytic 6*params*batch FLOPs
        # against measured step time makes every step_window carry `mfu`; the
        # profiler adds windowed/triggered jax.profiler captures and ledgers
        # the per-op roofline (obs/profiler.py)
        if self._telemetry.enabled:
            n_dev = self.mesh.devices.size
            self._telemetry.set_step_flops(
                6.0 * float(self.params) * float(batch_size),
                n_devices=n_dev,
                collective_bytes_per_step=(
                    2.0 * float(
                        state_lib.tree_bytes_per_device(state.params)
                    ) if n_dev > 1 else None
                ),
            )
            if self._telemetry.profiler is None:
                self._telemetry.set_profiler(obs_lib.ContinuousProfiler(
                    self._telemetry,
                    every_windows=tcfg.profile_every_windows,
                ))
        start_step = int(jax.device_get(state.step))
        if start_step >= steps:
            logger.info("fold %d already trained to step %d", fold, start_step)
            ckpt.close()
            return self._evaluate(
                state, eval_ds, batch_size, fold, writer=None,
                global_n=eval_global_n, step_no=start_step,
            )
        if start_step > 0:
            # resume verification: training actually CONTINUES from a prior
            # checkpoint (an already-trained fold rerun above is not a resume);
            # telemetry-report lines restarts up with the recovered progress
            self._telemetry.event("resumed", step=start_step, fold=fold)

        train_step = step_lib.make_train_step(
            self.mesh,
            self.task,
            weight_decay=self.model_config.weight_decay,
            spatial=self._spatial,
            accum=self.train_config.grad_accum_steps,
            seed=self.train_config.seed,
            auto_model=self._tp,
            weight_update_sharding=tcfg.weight_update_sharding,
        )
        prepare = self._make_prepare_train(fold)

        is_main = jax.process_index() == 0
        tb_train = SummaryWriter(os.path.join(self._fold_dir(fold), "train")) if is_main else None
        tb_eval = SummaryWriter(os.path.join(self._fold_dir(fold), "eval")) if is_main else None
        last_eval_time = 0.0
        final_metrics: Dict[str, float] = {}

        data_service = None
        if tcfg.data_service_workers > 0:
            # streaming data service over the in-memory fold (data/service.py
            # ArrayBatchSource): batch assembly moves off the host loop onto
            # N workers, and the stream is INDEX-KEYED — batch i is a pure
            # function of (seed+fold, i), so a resumed fold replays the exact
            # remaining stream instead of approximating it by folding the
            # resume step into the seed
            from tensorflowdistributedlearning_tpu.data import (
                service as service_lib,
            )

            svc = service_lib.StreamingDataService(
                service_lib.ArrayBatchSource(
                    {"images": train_ds.images, "masks": train_ds.masks},
                    # the fold arrays were host-sharded for THIS world size:
                    # stamping it into the resume sidecar makes a resume that
                    # crossed a world resize an explicit, ledgered re-deal
                    # (the per-host rows change meaning) instead of a silent
                    # re-index — the same resize-aware contract as fit()'s
                    # record path
                    process_count=jax.process_count(),
                ),
                batch_size=local_bs,
                seed=tcfg.seed + fold,
                workers=tcfg.data_service_workers,
                start_batch=start_step,
                registry=(
                    self._telemetry.registry
                    if self._telemetry.enabled and tb_train is not None
                    else None
                ),
                resume_state=(
                    ckpt.restore_data_state(start_step)
                    if start_step > 0 else None
                ),
            )
            data_service = svc
            if svc.redeal is not None:
                self._telemetry.event(
                    "data_redeal", step=start_step, fold=fold, **svc.redeal
                )
            batches = svc.batches(steps=steps - start_step)
        else:
            batches = pipeline_lib.train_batches(
                train_ds,
                local_bs,
                # fold the resume point into the shuffle seed so a resumed
                # run does not replay the same shuffled order from the
                # beginning (see ClassifierTrainer._train_stream)
                seed=tcfg.seed + fold + 7919 * start_step,
                steps=steps - start_step,
            )
        batches = pipeline_lib.device_prefetch(
            batches,
            lambda b: multihost.global_shard_batch(
                b, self.mesh, spatial=self._spatial
            ),
            depth=tcfg.prefetch_depth,
            # the gauge is drained per log window; a run that never writes
            # windows (telemetry off, or a non-main host with no TB writer)
            # must not record into it — the samples would accumulate for the
            # life of the run with nothing reading them
            registry=(
                self._telemetry.registry
                if self._telemetry.enabled and tb_train is not None
                else None
            ),
        )
        step_no = start_step
        last_eval_step = -1
        window_t0 = time.perf_counter()
        window_start = step_no
        # the first window contains the train-step compile; windows containing
        # an eval pass or a synchronous checkpoint save are likewise not
        # training time — mark them dirty and skip their throughput point
        window_dirty = True
        # host-side schedule mirror: the lr log line adds zero device work
        lr_sched = step_lib.make_host_lr_schedule(tcfg)
        tel = self._telemetry

        def emit_window(rec: async_loop.PendingWindow, scalars) -> None:
            if tb_train is not None:
                tb_train.scalars(scalars, rec.step)
            tel.window_event(
                rec.step,
                steps=rec.steps,
                images_per_sec=rec.images_per_sec,
                scalars=scalars,
                dirty=rec.dirty,
                samples=rec.samples,
                # cost accounting (obs/capacity.py): examples THIS PROCESS's
                # chips handled this window — the meter counts local devices,
                # so a multi-host run must price the per-process batch share,
                # not the global batch
                examples=rec.steps * multihost.per_process_batch_size(batch_size),
                **rec.extra,
            )

        # dispatch-ahead + deferred window fetch (train/async_loop.py);
        # dispatch_ahead_steps=0 is the synchronous legacy loop
        overlap = async_loop.HostOverlap(
            tel, dispatch_ahead=tcfg.dispatch_ahead_steps, emit=emit_window
        )

        def save_data_sidecar(step: int) -> None:
            # the fold stream's resume state rides every checkpoint (process
            # 0 writes; seed/batch_index are identical on every host) — the
            # durable half of the service resume contract, like fit()'s
            if data_service is not None and is_main:
                ckpt.save_data_state(
                    step, data_service.state(step).to_json()
                )

        batches_it = iter(batches)
        _end = object()
        while True:
            # host blocked on the loader vs dispatching compute: the split
            # the ledger's step windows record
            with tel.span(obs_lib.SPAN_DATA_WAIT):
                raw = next(batches_it, _end)
            if raw is _end:
                break
            with tel.span(obs_lib.SPAN_STEP):
                batch = prepare(jnp.asarray(step_no), raw)
                state, metrics = train_step(state, batch)
            step_no += 1
            # bounded dispatch-ahead: block (as fetch_wait) once more than
            # dispatch_ahead_steps steps are in flight
            overlap.track(metrics)
            # resilience boundary: injected faults fire here (a SIGTERM lands
            # in the preemption handler below within the same boundary), and a
            # pending preemption turns into a final checkpoint + distinct exit
            faults_lib.fire(faults_lib.SITE_STEP, step_no)
            if preempt_lib.requested():
                # the deferred window reaches the ledger BEFORE the preemption
                # checkpoint/events — resilience reporting stays complete
                # preemption outranks a health abort surfacing from this
                # flush: the alert is already ledgered, and the supervisor
                # contract (final checkpoint + EXIT_PREEMPTED) must hold
                try:
                    overlap.flush()
                except obs_lib.HealthAbortError:
                    pass
                with tel.span(obs_lib.SPAN_CHECKPOINT):
                    ckpt.save(state, force=True)
                save_data_sidecar(step_no)
                tel.checkpoint_event(step_no, fold=fold, preempted=True)
                tel.event(
                    "preempted",
                    step=step_no,
                    fold=fold,
                    reason=preempt_lib.reason(),
                )
                raise preempt_lib.PreemptedError(step_no)
            if tb_train is not None and step_no % tcfg.train_log_every_steps == 0:
                now = time.perf_counter()
                images_per_sec = None
                if not window_dirty and step_no > window_start:
                    images_per_sec = (
                        (step_no - window_start) * batch_size / (now - window_t0)
                    )
                # sync mode fetches+emits here; async mode emits the PREVIOUS
                # window and defers this one while the device keeps running.
                # rec.lr is the exact lr of the next update (host-side
                # schedule eval)
                overlap.window(
                    async_loop.PendingWindow(
                        step=step_no,
                        metrics=metrics,
                        steps=step_no - window_start,
                        lr=lr_sched(step_no),
                        images_per_sec=images_per_sec,
                        dirty=window_dirty,
                        extra={"fold": fold},
                    )
                )
                window_t0, window_start, window_dirty = now, step_no, False
                tel.mark_warm(obs_lib.SPAN_STEP, obs_lib.SPAN_DATA_WAIT)
                # train-phase image grids every train_log_every_steps — the
                # reference's SummarySaverHook wrote input/label/probability/
                # prediction to fold{i}/train every 20 steps (model.py:470-481);
                # one extra inference-mode forward per log interval
                if jax.process_count() == 1:
                    self._write_image_summaries(tb_train, state, batch, step_no)
            # checkpoint span = trace boundary (obs/trace.py), opened only on
            # the manager's own save cadence so off-cadence steps stay
            # span-free
            saved = False
            if ckpt.is_save_step(step_no):
                with tel.span(obs_lib.SPAN_CHECKPOINT):
                    saved = ckpt.maybe_save(state, step=step_no)
            if saved:
                overlap.flush()
                window_dirty = True
                save_data_sidecar(step_no)
                tel.checkpoint_event(step_no, fold=fold)
            # eval cadence: an explicit eval_every_steps knob decouples eval from
            # checkpointing AND bypasses the time throttle (explicit user intent,
            # same semantics as fit()); the default preserves the reference's
            # train_and_evaluate shape — eval when a checkpoint lands and the
            # >=eval_throttle_secs window passed (reference: model.py:214)
            if tcfg.eval_every_steps:
                due = step_no % tcfg.eval_every_steps == 0
            else:
                due = saved and time.time() - last_eval_time >= tcfg.eval_throttle_secs
            if due:
                overlap.flush()
                last_eval_time = time.time()
                last_eval_step = step_no
                final_metrics = self._evaluate(
                    state, eval_ds, batch_size, fold, writer=tb_eval,
                    global_n=eval_global_n, step_no=step_no,
                )
                # best-export stores the eval view: EMA params when tracked
                ckpt.export_best(
                    step_lib.with_ema_params(state), final_metrics
                )
                window_dirty = True
        # end of training: final checkpoint + eval + export (train_and_evaluate's
        # final-eval contract) — skipped when the last loop iteration already
        # checkpointed and evaluated at this exact step
        # an abort from the end-of-fold flush must not skip the final
        # checkpoint — write it, then re-raise
        abort_err = None
        try:
            overlap.flush()
        except obs_lib.HealthAbortError as e:
            abort_err = e
        with tel.span(obs_lib.SPAN_CHECKPOINT):
            ckpt.save(state, force=True)
        save_data_sidecar(step_no)
        tel.checkpoint_event(step_no, fold=fold, final=True)
        if abort_err is not None:
            raise abort_err
        if last_eval_step != step_no:
            final_metrics = self._evaluate(
                state, eval_ds, batch_size, fold, writer=tb_eval,
                global_n=eval_global_n, step_no=step_no,
            )
            ckpt.export_best(step_lib.with_ema_params(state), final_metrics)
        if tb_train is not None:
            tb_train.close()
        if tb_eval is not None:
            tb_eval.close()
        ckpt.close()
        return final_metrics

    def _make_prepare_train(self, fold: int):
        """Jitted on-device augmentation: {'images','masks'} -> {'images','labels'}
        with the Laplacian channel (the reference's augmenting input_fn map,
        model.py:315-317, run on TPU instead of the host). The fold's base PRNG key
        is a traced argument, so every fold (and every Trainer with the same
        augment config) shares ONE compiled executable."""
        base_key = jax.random.PRNGKey(self.train_config.seed + fold)
        prepare = _prepare_train_cached(self.augment_config)

        def bound(step: jax.Array, batch: Dict[str, jax.Array]):
            return prepare(base_key, step, batch)

        return bound

    def _evaluate(
        self,
        state: TrainState,
        eval_ds: pipeline_lib.InMemoryDataset,
        batch_size: int,
        fold: int,
        writer: Optional[SummaryWriter],
        global_n: Optional[int] = None,
        step_no: Optional[int] = None,
    ) -> Dict[str, float]:
        """One full eval pass with streaming metrics (the EVAL branch + SummarySaverHook,
        reference: model.py:391-403, 475-481). Runs at the caller's ``batch_size``
        (the reference used 2x the train batch, model.py:207-211 — here the wrap-around
        padding makes eval batch size a pure throughput knob, so it is not doubled).

        ``eval_ds`` is this process's host shard; ``global_n`` (the fold's total eval
        size) pins the step count so every process runs the same number of
        collective-bearing steps. The metric accumulator stays DEVICE-RESIDENT
        (train/async_loop.py): one host transfer per pass regardless of batch
        count. ``step_no`` is the host-known step (None = fetch ``state.step``
        — direct callers only)."""
        mesh_lib.local_batch_size(batch_size, self.mesh)  # fail fast, clear message
        # evaluate the EMA view when one is tracked (TrainConfig.ema_decay>0),
        # then drop the optimizer state: eval reads params/batch_stats only,
        # and under weight_update_sharding the data-axis-sharded moments would
        # otherwise be all-gathered into the eval executable for nothing
        state = step_lib.with_ema_params(state).replace(opt_state=None)
        local_bs = multihost.per_process_batch_size(batch_size)
        num = multihost.eval_num_batches(
            global_n if global_n is not None else len(eval_ds), local_bs
        )
        tel = self._telemetry
        t0 = time.perf_counter()
        with tel.span(obs_lib.SPAN_EVAL):
            eval_step = self._eval_step
            prepare = self._prepare_eval
            # in-flight bound: without it, device-resident accumulation would
            # let the host enqueue EVERY eval batch's copy+step at once
            budget = async_loop.eval_budget(
                tel, self.train_config.dispatch_ahead_steps
            )
            acc = None
            first_batch = None
            for raw in pipeline_lib.eval_batches(eval_ds, local_bs, num_batches=num):
                sharded = multihost.global_shard_batch(
                    raw, self.mesh, spatial=self._spatial
                )
                batch = prepare(sharded)
                metrics = eval_step(state, batch)
                acc = async_loop.merge_metrics_device(acc, metrics)
                budget.track(acc)
                if first_batch is None:
                    first_batch = batch
            result = async_loop.fetch_metrics(acc, telemetry=tel)
        if step_no is None:
            step_no = int(jax.device_get(state.step))
        tel.eval_event(step_no, result, time.perf_counter() - t0, fold=fold)
        # this pass compiled whatever eval needed; later eval compiles are
        # recompiles
        tel.mark_warm(obs_lib.SPAN_EVAL)
        logger.info("fold %d eval @ %d: %s", fold, step_no, result)
        if writer is not None:
            writer.scalars(result, step_no)
            if jax.process_count() == 1:
                # image grids need fully-addressable batches; multi-host scalar
                # summaries still flow from process 0
                self._write_image_summaries(writer, state, first_batch, step_no)
            writer.flush()
        return result

    def _write_image_summaries(
        self, writer: SummaryWriter, state: TrainState, batch, step_no: int
    ) -> None:
        """input/label/probability/prediction image grids (reference:
        model.py:405-426 summarized the same four tensors)."""
        if self._tp:
            # the single-device forward cannot consume model-axis-sharded
            # params; pull one addressable copy of ONLY what it reads (the
            # Adam moments are ~2x the param bytes and _forward never
            # touches them)
            state = state.replace(
                params=jax.device_get(state.params),
                batch_stats=jax.device_get(state.batch_stats),
            )
        outputs = self._forward(state, batch["images"])
        probs = np.asarray(jax.device_get(jax.nn.sigmoid(outputs)))[..., 0]
        images = np.asarray(jax.device_get(batch["images"]))[..., 0]
        labels = np.asarray(jax.device_get(batch["labels"]))[..., 0]
        n = min(3, images.shape[0])
        for i in range(n):
            lo, hi = images[i].min(), images[i].max()
            writer.image(f"image/{i}", (images[i] - lo) / max(hi - lo, 1e-6), step_no)
            writer.image(f"label/{i}", labels[i], step_no)
            writer.image(f"probability/{i}", probs[i], step_no)
            writer.image(f"prediction/{i}", (probs[i] > 0.5).astype(np.float32), step_no)

    # -- cached jitted helpers --------------------------------------------

    @property
    def _eval_step(self):
        return step_lib.make_eval_step(
            self.mesh, self.task, spatial=self._spatial, auto_model=self._tp
        )

    @property
    def _predict_step(self):
        return step_lib.make_predict_step(
            self.mesh, self.task, spatial=self._spatial, auto_model=self._tp
        )

    @property
    def _prepare_eval(self):
        return _prepare_eval_cached()

    @property
    def _forward(self):
        return _forward_cached(self._plain_model)

    # -- prediction -------------------------------------------------------

    def predict(
        self,
        test_dir: str,
        batch_size: int = 64,
        tta: bool = True,
        folds: Optional[Sequence[int]] = None,
    ) -> Dict[str, np.ndarray]:
        """Fold x TTA ensemble prediction.

        For every fold's best exported state and every TTA transform, forward the
        transformed images and inverse-transform the probabilities (reference:
        model.py:230-255, 384-387), then average the ensemble — the step the reference
        left unfinished (``# TODO: finish writing this method``, model.py:229).
        ``tta=True`` really enables all four transforms (the reference's ``tti`` flag
        was inverted, SURVEY §2.4.3).

        Returns ``{"ids", "probabilities" [N,H,W,1], "masks" [N,H,W,1]}`` —
        ``[N,1,H,W]`` under ``data_format="NCHW"`` (prediction is a user-facing
        array boundary, honored like ``serving_fn``; the reference's NCHW mode
        produced NCHW predictions, model.py:344-351, 384-387).
        """
        transforms = augment_lib.TTA_TRANSFORMS if tta else ("none",)
        mesh_lib.local_batch_size(batch_size, self.mesh)  # fail fast, clear message
        folds = list(folds) if folds is not None else list(
            range(self.train_config.n_folds)
        )
        test_ds = pipeline_lib.InMemoryDataset.from_directory(
            test_dir, with_masks=False
        )
        template = self._init_state()
        total = None
        n_members = 0
        for fold in folds:
            # EMA-trained folds predict with the averaged weights even when the
            # restore fell back to a periodic checkpoint; identity otherwise
            state = step_lib.with_ema_params(
                self._restore_fold_or_raise(fold, template)
            )
            for transformation in transforms:
                probs = self._predict_one(state, test_ds, batch_size, transformation)
                total = probs if total is None else total + probs
                n_members += 1
        mean_probs = total / n_members
        if self.train_config.data_format == "NCHW":
            mean_probs = np.transpose(mean_probs, (0, 3, 1, 2))
        return {
            "ids": list(test_ds.ids),
            "probabilities": mean_probs,
            "masks": (mean_probs > self.task.threshold).astype(np.float32),
        }

    def _restore_fold_or_raise(self, fold: int, template: TrainState) -> TrainState:
        """Best exported state for ``fold`` (falling back to the latest periodic
        checkpoint); raises if the fold was never trained."""
        if jax.process_count() > 1:
            # multi-process checkpoints restore into sharded/global layouts;
            # serving and TTA prediction want one addressable copy (same
            # contract as ClassifierTrainer._restore_best_host)
            raise RuntimeError(
                "serving/predict restore runs single-process; load this "
                "model_dir from a single-process session"
            )
        ckpt = self._checkpointer(fold)
        try:
            return ckpt.restore_best_or_raise(
                template,
                hint=f"train fold {fold} first or pass folds=[...] with only "
                "the trained folds",
            )
        finally:
            ckpt.close()

    def serving_fn(self, fold: int, serving_dtype: str = "float32"):
        """Jitted single-model inference function for deployment — the JAX analogue
        of the reference's exported SavedModel with serving signature
        ``image: [None, H, W, input_channels] float32`` (reference: model.py:190-194).

        Loads the fold's best state and returns ``serve(images) ->
        {'probabilities', 'mask'}`` where ``images`` is the preprocessed input batch
        (normalized + Laplacian channel, exactly what the reference's serving
        placeholder received).

        ``serving_dtype`` selects the post-training precision spec
        (train/quantize.py SERVING_SPECS): ``float32`` is the training graph
        unchanged, ``bfloat16`` casts params/batch_stats and runs bf16
        activations, ``int8`` stores conv/dense kernels as int8 with
        per-channel scales (dequantized to bf16 inside the graph), and
        ``int8-compute`` stores the same bytes but traces dense/stride-1
        conv layers through the int8-arithmetic kernels
        (ops/quant_kernels.py). Wire contract is constant across specs:
        float32 in, float32 out. The returned closure carries its manifest
        ``quantization`` section as ``serve.quantization``.

        ``data_format="NCHW"`` is honored at this boundary: inputs arrive
        ``[B, C, H, W]`` and outputs return ``[B, 1, H, W]`` (the reference's NCHW
        mode transposed at the top of model_fn, model.py:344-351; on TPU, XLA owns
        the internal layout, so the transpose happens exactly once, here).
        """
        from tensorflowdistributedlearning_tpu.ops import quant_kernels
        from tensorflowdistributedlearning_tpu.train import quantize

        state = self._restore_fold_or_raise(fold, self._init_state())
        # EMA-trained models serve the averaged weights even when restore fell
        # back to a periodic (live-trajectory) checkpoint; identity otherwise
        state = step_lib.with_ema_params(state)
        # serving reads params/batch_stats only; dropping the Adam moments
        # frees ~2x parameter memory for the closure's lifetime
        state = state.replace(opt_state=None)
        qparams, qstats, quant_section = quantize.quantize_state(
            state.params, state.batch_stats, serving_dtype
        )
        act_dtype = quantize.compute_dtype(serving_dtype)
        int8_compute = quant_section.get("compute_dtype") == "int8"
        task = self.task
        forward = self._forward
        nchw = self.train_config.data_format == "NCHW"

        def serve(images):
            if nchw:
                images = jnp.transpose(images, (0, 2, 3, 1))
            st = state.replace(
                params=quantize.dequantize_pytree(qparams, act_dtype),
                batch_stats=quantize.dequantize_pytree(qstats, act_dtype),
            )
            x = images.astype(act_dtype)
            if int8_compute:
                # quantized layers take the int8-compute kernels; layers
                # outside the kernels' envelope keep the dequantized path
                with quant_kernels.int8_intercept(qparams, act_dtype):
                    logits = forward(st, x)
            else:
                logits = forward(st, x)
            out = task.serve_predictions(logits)
            out = quantize.cast_outputs_float32(out)
            if nchw:
                out = {k: jnp.transpose(v, (0, 3, 1, 2)) for k, v in out.items()}
            return out

        serve.quantization = quant_section
        return serve

    def export_serving(
        self,
        fold: int,
        directory: Optional[str] = None,
        serving_dtype: str = "float32",
    ) -> str:
        """Write a standalone serialized-StableHLO serving artifact for the fold's
        best state (the reference's SavedModel export, model.py:190-204, done the
        JAX-native way — see train/serving.py). Returns the artifact path; default
        location ``{fold_dir}/export/serving`` (``serving-{dtype}`` for quantized
        exports, so the f32 reference and its candidates coexist for
        quantize-check)."""
        from tensorflowdistributedlearning_tpu.train import serving as serving_lib

        suffix = "serving" if serving_dtype == "float32" else f"serving-{serving_dtype}"
        directory = directory or os.path.join(
            self._fold_dir(fold), "export", suffix
        )
        h, w = self.model_config.input_shape
        c = self.model_config.input_channels
        shape = (
            (1, c, h, w)
            if self.train_config.data_format == "NCHW"
            else (1, h, w, c)
        )
        serve = self.serving_fn(fold, serving_dtype=serving_dtype)
        return serving_lib.export_serving_artifact(
            serve,
            shape,
            directory,
            metadata={
                "fold": fold,
                "data_format": self.train_config.data_format,
                "backbone": self.model_config.backbone,
            },
            quantization=serve.quantization,
        )

    def _predict_one(
        self,
        state: TrainState,
        test_ds: pipeline_lib.InMemoryDataset,
        batch_size: int,
        transformation: str,
    ) -> np.ndarray:
        """Probabilities [N, H, W, 1] for one (state, transform) ensemble member.

        Every process holds the full test set, so batches are placed with
        ``shard_replicated_batch`` and outputs pulled with ``fetch`` (a cross-process
        allgather under multi-host; plain device_get single-process)."""
        predict_step = self._predict_step
        chunks = []
        n = len(test_ds)
        for raw in pipeline_lib.eval_batches(test_ds, batch_size):
            images = augment_lib.tta_transform(jnp.asarray(raw["images"]), transformation)
            batch = {"images": augment_lib.add_laplace_channel(images)}
            batch = multihost.shard_replicated_batch(
                batch, self.mesh, spatial=self._spatial
            )
            out = predict_step(state, batch)
            probs = augment_lib.tta_inverse(out["probabilities"], transformation)
            valid = raw["valid"].astype(bool)
            chunks.append(multihost.fetch(probs)[valid])
        return np.concatenate(chunks)[:n]


# The reference exposed this as ``class Model`` (reference: model.py:27).
Model = Trainer
