"""Checkpointing: periodic saves, auto-resume, best-k export.

The reference had three mechanisms (SURVEY §5.4): periodic variable checkpoints every
500 steps via RunConfig (reference: model.py:117-121), implicit resume-from-latest per
fold ``model_dir`` (reference: model.py:164-167), and a ``BestExporter`` keeping the
top-``save_best`` SavedModels ranked on ``metrics/mean_iou`` (reference:
model.py:189-204). All three map onto one Orbax ``CheckpointManager`` here:

- ``save_every_steps`` + ``max_to_keep`` reproduce the periodic-checkpoint cadence;
- ``restore_latest`` reproduces Estimator auto-resume;
- a second manager rooted at ``{dir}/export/best`` with ``best_fn=metrics/mean_iou``
  reproduces BestExporter — with the comparison the right way around: the reference's
  ``metric_comparisson`` returned ``best > current`` so it exported on *regressions*
  (reference: utils.py:23-28, SURVEY §2.4.4). ``best_mode='max'`` here is the fix.

Only the pytree part of ``TrainState`` (step/params/batch_stats/opt_state) is stored;
``apply_fn``/``tx`` are static and re-supplied from the template state on restore.

Resilience (resilience/): saves and restores retry transient I/O with backoff
(counted in obs metrics, ledgered as ``checkpoint_retry`` when a telemetry is
wired in); ``restore_latest`` skips a partially-written/corrupt latest
checkpoint and falls back to the previous step (``checkpoint_corrupt`` event)
— only a *structure mismatch* (``CheckpointStructureError``: the config
changed since the write) still raises; and every manager registers an
``atexit`` close so an uncaught exception mid-fold cannot leave orbax with
unflushed async state.
"""

from __future__ import annotations

import atexit
import logging
import os
from typing import Dict, Optional

import jax
import orbax.checkpoint as ocp

from tensorflowdistributedlearning_tpu.resilience import faults
import tensorflowdistributedlearning_tpu.resilience.retry as retry_lib
from tensorflowdistributedlearning_tpu.train.state import TrainState

logger = logging.getLogger(__name__)


class CheckpointStructureError(RuntimeError):
    """The checkpoint's pytree does not match the current training state —
    a configuration change, not corruption; the corrupt-checkpoint fallback
    must NOT swallow it (resuming an adam run as sgd deserves a crash)."""


def _state_pytree(state: TrainState) -> Dict:
    return {
        "step": state.step,
        "params": state.params,
        "batch_stats": state.batch_stats,
        "opt_state": state.opt_state,
    }


def _save_pytree(state: TrainState, *, to_host: bool) -> Dict:
    """The pytree handed to Orbax for SAVING.

    ``to_host=True`` materializes to host numpy first — one bulk ``device_get``
    is ~0.01s for small states, while Orbax's jax.Array path walks every leaf's
    sharding (measured ~20x slower for a small replicated state). Callers must
    keep jax.Arrays (``to_host=False``) when Orbax needs them: multi-process
    runs (coordinated per-host writes of sharded leaves) and async saves (a
    synchronous bulk copy here would stall the training thread for exactly the
    device-to-host transfer async checkpointing exists to overlap)."""
    tree = _state_pytree(state)
    if to_host and jax.process_count() == 1:
        # device_get assembles every leaf FULLY regardless of its sharding
        # (ZeRO-sharded opt_state leaves included), so the on-disk layout is
        # placement-independent — what makes a replicated checkpoint
        # restorable into weight_update_sharding mode and vice versa
        return jax.device_get(tree)
    return tree


class CheckpointManager:
    """Periodic + best-k checkpointing for one fold directory.

    ``{directory}/checkpoints/{step}`` — rolling recent checkpoints (auto-resume);
    ``{directory}/export/best/{step}`` — top-``save_best`` by ``best_metric``
    (the reference's SavedModel exports, model.py:196-202).
    """

    def __init__(
        self,
        directory: str,
        *,
        save_every_steps: int = 500,
        max_to_keep: int = 5,
        save_best: int = 5,
        best_metric: str = "metrics/mean_iou",
        greater_is_better: bool = True,
        async_checkpointing: bool = False,
        telemetry=None,
    ):
        self.directory = os.path.abspath(directory)
        self.save_every_steps = save_every_steps
        self.best_metric = best_metric
        # ledger sink for checkpoint_retry/checkpoint_corrupt events; the
        # trainers pass their live Telemetry, everything else stays silent
        if telemetry is None:
            from tensorflowdistributedlearning_tpu.obs import NULL_TELEMETRY

            telemetry = NULL_TELEMETRY
        self._telemetry = telemetry
        self._closed = False
        # async: periodic saves overlap the next train steps (device->host copy
        # happens synchronously, serialization in a background thread — the knob
        # the large-batch pod configs want); best exports stay synchronous since
        # they follow an eval anyway.
        self._async = async_checkpointing
        self._ckpt = ocp.CheckpointManager(
            os.path.join(self.directory, "checkpoints"),
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=1,  # cadence enforced by maybe_save
                enable_async_checkpointing=async_checkpointing,
            ),
        )
        self._best = ocp.CheckpointManager(
            os.path.join(self.directory, "export", "best"),
            options=ocp.CheckpointManagerOptions(
                max_to_keep=save_best,
                best_fn=lambda m: m[best_metric],
                best_mode="max" if greater_is_better else "min",
                enable_async_checkpointing=False,
            ),
        )
        # an uncaught exception mid-fold must not leave orbax's async machinery
        # with unflushed state; close() unregisters, so a normal close is not
        # re-run at interpreter exit
        atexit.register(self.close)

    # -- periodic ---------------------------------------------------------

    def save(self, state: TrainState, *, force: bool = False) -> bool:
        """Save now (used at step boundaries and end-of-training); idempotent per
        step — re-offering an already-saved step is a no-op. Transient I/O
        failures retry with backoff (resilience/retry.py; the injectable
        ``io-ckpt`` fault site lives inside the attempt)."""
        step = int(jax.device_get(state.step))
        if step in self._ckpt.all_steps():
            return False

        def attempt() -> bool:
            faults.fire(faults.SITE_CHECKPOINT)
            return self._ckpt.save(
                step,
                args=ocp.args.StandardSave(
                    _save_pytree(state, to_host=not self._async)
                ),
                force=force,
            )

        saved = retry_lib.call_with_retry(
            attempt,
            name="checkpoint_save",
            exceptions=(OSError,),
            on_retry=lambda a, e: self._telemetry.event(
                "checkpoint_retry", step=step, attempt=a, error=str(e)[:200]
            ),
        )
        if not self._async:
            self._ckpt.wait_until_finished()
        return saved

    def is_save_step(self, step: int) -> bool:
        """Whether ``step`` is on the periodic save cadence — THE cadence
        rule, exposed so callers that wrap saves (the trainers' checkpoint
        trace spans) gate on the manager's own decision instead of
        re-deriving it from config."""
        return step % self.save_every_steps == 0

    def maybe_save(self, state: TrainState, step: Optional[int] = None) -> bool:
        """Save iff ``step`` is on the periodic cadence (reference:
        ``save_checkpoints_steps=500``, model.py:118).

        Pass the host-side ``step`` counter when available: the cadence check then
        never touches ``state.step``, so it does not force a host-device sync on the
        just-dispatched train step (which would defeat async dispatch pipelining)."""
        if step is None:
            step = int(jax.device_get(state.step))
        if not self.is_save_step(step):
            return False
        return self.save(state)

    def latest_step(self) -> Optional[int]:
        return self._ckpt.latest_step()

    # -- data-service resume state (sidecar) -------------------------------

    def _data_state_path(self, step: int) -> str:
        return os.path.join(
            self.directory, "checkpoints", f"data_state-{step}.json"
        )

    def save_data_state(self, step: int, state: Dict) -> None:
        """Persist the input stream's resume state (a ``DataServiceState``
        json dict, data/service.py) NEXT TO the step's checkpoint — the
        index-keyed stream contract's durable half: restore reads it back and
        the service validates it against (seed, resume step), so a mid-epoch
        preemption provably resumes the exact remaining stream. Written
        atomically; stale sidecars beyond the newest ``max_to_keep``-ish
        window are pruned opportunistically (they are a few bytes — pruning
        is hygiene, not correctness)."""
        import glob
        import json

        path = self._data_state_path(step)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"step": int(step), **state}, f)
        os.replace(tmp, path)
        kept = set(self._ckpt.all_steps())
        for old in glob.glob(
            os.path.join(self.directory, "checkpoints", "data_state-*.json")
        ):
            try:
                old_step = int(
                    os.path.basename(old)[len("data_state-"):-len(".json")]
                )
            except ValueError:
                continue
            if old_step != step and old_step not in kept:
                try:
                    os.remove(old)
                except OSError:
                    pass

    def restore_data_state(self, step: int) -> Optional[Dict]:
        """The data-service resume state saved with ``step``, or None (no
        sidecar — a pre-service checkpoint, or a non-service run; the stream
        state is then derived purely from the step, which the index-keyed
        contract makes exact anyway). Corrupt sidecars warn and return None
        rather than kill a resume the derivation can complete."""
        import json

        path = self._data_state_path(step)
        try:
            with open(path, encoding="utf-8") as f:
                state = json.load(f)
            if not isinstance(state, dict) or not {
                "seed", "batch_index"
            } <= state.keys():
                # parseable but not a sidecar: same stance as unreadable
                raise ValueError(f"not a data_state sidecar: {state!r:.120}")
            return state
        except FileNotFoundError:
            return None
        except (OSError, ValueError) as e:
            logger.warning(
                "data-state sidecar for step %d is unreadable (%s) — "
                "deriving the stream state from the step instead", step, e,
            )
            return None

    def restore_latest(self, template: TrainState) -> TrainState:
        """Estimator-style auto-resume: if a checkpoint exists, restore it into the
        template's shardings; else return the template unchanged (reference: implicit
        in per-fold Estimator construction, model.py:164-167).

        A partially-written/corrupt latest checkpoint (the signature of a run
        killed mid-write) is skipped — deleted, so later saves can re-write
        its step — with a ``checkpoint_corrupt`` ledger event, and the
        previous step restored instead; if every retained step is genuinely
        corrupt the template (fresh init) is returned — for a supervised run,
        retraining beats a permanent crash loop. Two failure classes still
        raise: structure mismatches (``CheckpointStructureError``: the
        *configuration* changed, and silently restarting from scratch would
        hide it) and TRANSIENT exhaustion on every step (a filesystem blip —
        the kept checkpoints will likely restore after the supervisor's
        backoff, and fresh-initing next to retained old-lineage steps would
        build a mixed history)."""
        self._ckpt.wait_until_finished()  # async saves must land before reading
        steps = sorted(self._ckpt.all_steps(), reverse=True)
        last_error: Optional[BaseException] = None
        any_transient = False
        for step in steps:
            try:
                return self._restore(self._ckpt, step, template)
            except CheckpointStructureError:
                raise
            except Exception as e:  # noqa: BLE001 — corrupt/truncated step dir
                last_error = e
                # a transiently-failing filesystem (RetryExhaustedError: the
                # short backoff window expired) is NOT corruption — fall back
                # for this resume but KEEP the step; it may restore fine once
                # the blip passes, and deleting good checkpoints on a blip
                # could walk the whole history into a fresh init
                transient = isinstance(e, retry_lib.RetryExhaustedError)
                logger.warning(
                    "checkpoint at step %d under %s is unrestorable (%s: %s) "
                    "— falling back to the previous step",
                    step, self.directory, type(e).__name__, str(e)[:200],
                )
                self._telemetry.event(
                    "checkpoint_corrupt",
                    step=step,
                    transient=transient,
                    error=f"{type(e).__name__}: {str(e)[:200]}",
                )
                if transient:
                    any_transient = True
                    continue
                # drop the genuinely-corrupt step: otherwise every restart
                # re-walks it, and save()'s per-step idempotence guard
                # (`step in all_steps()`) would refuse to ever RE-write this
                # step after the run retrains through it — capping
                # recoverable progress at the corruption point forever
                try:
                    self._ckpt.delete(step)
                except Exception as delete_error:  # noqa: BLE001
                    logger.warning(
                        "could not delete corrupt checkpoint step %d: %s",
                        step, delete_error,
                    )
        if last_error is not None:
            if any_transient:
                # at least one step failed only TRANSIENTLY and was kept: a
                # fresh init here would retrain a new lineage next to retained
                # old-lineage step dirs (whose steps save() would then refuse
                # to re-write — a mixed history later resumes could pick up).
                # Raise instead; the supervisor's backoff retries the whole
                # launch after the blip.
                raise last_error
            logger.error(
                "no restorable checkpoint under %s (%d candidate(s), all "
                "corrupt and removed) — starting from a fresh init",
                self.directory, len(steps),
            )
            self._telemetry.event(
                "checkpoint_corrupt", fallback="fresh_init", candidates=len(steps)
            )
        return template

    # -- best export ------------------------------------------------------

    def export_best(self, state: TrainState, metrics: Dict[str, float]) -> bool:
        """Offer ``state`` with its eval metrics; kept only if it ranks in the
        top-``save_best`` on ``best_metric``."""
        step = int(jax.device_get(state.step))
        if step in self._best.all_steps():
            return False
        saved = self._best.save(
            step,
            args=ocp.args.StandardSave(_save_pytree(state, to_host=True)),
            metrics={self.best_metric: float(metrics[self.best_metric])},
            force=True,
        )
        self._best.wait_until_finished()
        return saved

    def best_step(self) -> Optional[int]:
        return self._best.best_step()

    def restore_best(self, template: TrainState) -> TrainState:
        """Load the best exported state; falls back to latest periodic checkpoint,
        then to the template (fresh init)."""
        step = self._best.best_step()
        if step is None:
            return self.restore_latest(template)
        return self._restore(self._best, step, template)

    def restore_best_or_raise(self, template: TrainState, hint: str = "") -> TrainState:
        """``restore_best`` that refuses to hand back a fresh init: raises with
        ``hint`` when neither a best export nor a periodic checkpoint exists
        (the shared guard of every serving/predict path)."""
        if self.best_step() is None and self.latest_step() is None:
            raise RuntimeError(
                f"no trained checkpoint under {self.directory}"
                + (f" — {hint}" if hint else "")
            )
        return self.restore_best(template)

    # -- shared -----------------------------------------------------------

    def _restore(self, manager: ocp.CheckpointManager, step: int, template: TrainState) -> TrainState:
        # the abstract tree keeps each template leaf's SHARDING (not just
        # shape/dtype), so orbax places every restored leaf straight into the
        # template's layout — a checkpoint written replicated restores into a
        # ZeRO-sharded template (opt_state landing 1/dp per chip) and a
        # sharded-run checkpoint restores into a replicated template, the
        # cross-mode resume contract tests/test_zero1.py pins both ways
        abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, _state_pytree(template))
        try:
            # transient filesystem faults retry; persistent corruption
            # surfaces as RetryExhaustedError for restore_latest's fallback
            restored = retry_lib.call_with_retry(
                lambda: manager.restore(
                    step, args=ocp.args.StandardRestore(abstract)
                ),
                name="checkpoint_restore",
                exceptions=(OSError,),
            )
        except Exception as e:  # noqa: BLE001 — surface structure mismatches clearly
            msg = str(e)
            # orbax raises KeyError both for a tree-key mismatch (config
            # changed) and for a MISSING SAVE UNIT ('Item "default" was not
            # found...') — the latter is the signature of a step dir a killed
            # run left partially written, i.e. corruption, not a mismatch
            missing_item = "was not found in the checkpoint" in msg
            mismatch = (isinstance(e, KeyError) and not missing_item) or any(
                marker in msg.lower()
                for marker in ("pytree", "tree structure", "key mismatch")
            )
            if mismatch:
                raise CheckpointStructureError(
                    f"checkpoint at step {step} under {self.directory} does not "
                    "match the current training state structure — most often "
                    "the optimizer or model configuration changed since the "
                    "checkpoint was written (e.g. --optimizer adam -> sgd "
                    "changes the opt_state pytree). Use a fresh model_dir or "
                    f"restore with the original configuration. ({msg[:300]})"
                ) from e
            raise
        return template.replace(
            step=restored["step"],
            params=restored["params"],
            batch_stats=restored["batch_stats"],
            opt_state=restored["opt_state"],
        )

    def close(self) -> None:
        """Idempotent: also runs via ``atexit`` when a fold dies with this
        manager open, so async orbax state is always flushed."""
        if self._closed:
            return
        self._closed = True
        atexit.unregister(self.close)
        self._ckpt.close()
        self._best.close()
