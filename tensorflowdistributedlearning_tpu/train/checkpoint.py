"""Checkpointing: periodic saves, auto-resume, best-k export.

The reference had three mechanisms (SURVEY §5.4): periodic variable checkpoints every
500 steps via RunConfig (reference: model.py:117-121), implicit resume-from-latest per
fold ``model_dir`` (reference: model.py:164-167), and a ``BestExporter`` keeping the
top-``save_best`` SavedModels ranked on ``metrics/mean_iou`` (reference:
model.py:189-204). All three map onto one Orbax ``CheckpointManager`` here:

- ``save_every_steps`` + ``max_to_keep`` reproduce the periodic-checkpoint cadence;
- ``restore_latest`` reproduces Estimator auto-resume;
- a second manager rooted at ``{dir}/export/best`` with ``best_fn=metrics/mean_iou``
  reproduces BestExporter — with the comparison the right way around: the reference's
  ``metric_comparisson`` returned ``best > current`` so it exported on *regressions*
  (reference: utils.py:23-28, SURVEY §2.4.4). ``best_mode='max'`` here is the fix.

Only the pytree part of ``TrainState`` (step/params/batch_stats/opt_state) is stored;
``apply_fn``/``tx`` are static and re-supplied from the template state on restore.
"""

from __future__ import annotations

import os
from typing import Dict, Optional

import jax
import orbax.checkpoint as ocp

from tensorflowdistributedlearning_tpu.train.state import TrainState


def _state_pytree(state: TrainState) -> Dict:
    return {
        "step": state.step,
        "params": state.params,
        "batch_stats": state.batch_stats,
        "opt_state": state.opt_state,
    }


def _save_pytree(state: TrainState, *, to_host: bool) -> Dict:
    """The pytree handed to Orbax for SAVING.

    ``to_host=True`` materializes to host numpy first — one bulk ``device_get``
    is ~0.01s for small states, while Orbax's jax.Array path walks every leaf's
    sharding (measured ~20x slower for a small replicated state). Callers must
    keep jax.Arrays (``to_host=False``) when Orbax needs them: multi-process
    runs (coordinated per-host writes of sharded leaves) and async saves (a
    synchronous bulk copy here would stall the training thread for exactly the
    device-to-host transfer async checkpointing exists to overlap)."""
    tree = _state_pytree(state)
    if to_host and jax.process_count() == 1:
        return jax.device_get(tree)
    return tree


class CheckpointManager:
    """Periodic + best-k checkpointing for one fold directory.

    ``{directory}/checkpoints/{step}`` — rolling recent checkpoints (auto-resume);
    ``{directory}/export/best/{step}`` — top-``save_best`` by ``best_metric``
    (the reference's SavedModel exports, model.py:196-202).
    """

    def __init__(
        self,
        directory: str,
        *,
        save_every_steps: int = 500,
        max_to_keep: int = 5,
        save_best: int = 5,
        best_metric: str = "metrics/mean_iou",
        greater_is_better: bool = True,
        async_checkpointing: bool = False,
    ):
        self.directory = os.path.abspath(directory)
        self.save_every_steps = save_every_steps
        self.best_metric = best_metric
        # async: periodic saves overlap the next train steps (device->host copy
        # happens synchronously, serialization in a background thread — the knob
        # the large-batch pod configs want); best exports stay synchronous since
        # they follow an eval anyway.
        self._async = async_checkpointing
        self._ckpt = ocp.CheckpointManager(
            os.path.join(self.directory, "checkpoints"),
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=1,  # cadence enforced by maybe_save
                enable_async_checkpointing=async_checkpointing,
            ),
        )
        self._best = ocp.CheckpointManager(
            os.path.join(self.directory, "export", "best"),
            options=ocp.CheckpointManagerOptions(
                max_to_keep=save_best,
                best_fn=lambda m: m[best_metric],
                best_mode="max" if greater_is_better else "min",
                enable_async_checkpointing=False,
            ),
        )

    # -- periodic ---------------------------------------------------------

    def save(self, state: TrainState, *, force: bool = False) -> bool:
        """Save now (used at step boundaries and end-of-training); idempotent per
        step — re-offering an already-saved step is a no-op."""
        step = int(jax.device_get(state.step))
        if step in self._ckpt.all_steps():
            return False
        saved = self._ckpt.save(
            step,
            args=ocp.args.StandardSave(_save_pytree(state, to_host=not self._async)),
            force=force,
        )
        if not self._async:
            self._ckpt.wait_until_finished()
        return saved

    def maybe_save(self, state: TrainState, step: Optional[int] = None) -> bool:
        """Save iff ``step`` is on the periodic cadence (reference:
        ``save_checkpoints_steps=500``, model.py:118).

        Pass the host-side ``step`` counter when available: the cadence check then
        never touches ``state.step``, so it does not force a host-device sync on the
        just-dispatched train step (which would defeat async dispatch pipelining)."""
        if step is None:
            step = int(jax.device_get(state.step))
        if step % self.save_every_steps != 0:
            return False
        return self.save(state)

    def latest_step(self) -> Optional[int]:
        return self._ckpt.latest_step()

    def restore_latest(self, template: TrainState) -> TrainState:
        """Estimator-style auto-resume: if a checkpoint exists, restore it into the
        template's shardings; else return the template unchanged (reference: implicit
        in per-fold Estimator construction, model.py:164-167)."""
        self._ckpt.wait_until_finished()  # async saves must land before reading
        step = self._ckpt.latest_step()
        if step is None:
            return template
        return self._restore(self._ckpt, step, template)

    # -- best export ------------------------------------------------------

    def export_best(self, state: TrainState, metrics: Dict[str, float]) -> bool:
        """Offer ``state`` with its eval metrics; kept only if it ranks in the
        top-``save_best`` on ``best_metric``."""
        step = int(jax.device_get(state.step))
        if step in self._best.all_steps():
            return False
        saved = self._best.save(
            step,
            args=ocp.args.StandardSave(_save_pytree(state, to_host=True)),
            metrics={self.best_metric: float(metrics[self.best_metric])},
            force=True,
        )
        self._best.wait_until_finished()
        return saved

    def best_step(self) -> Optional[int]:
        return self._best.best_step()

    def restore_best(self, template: TrainState) -> TrainState:
        """Load the best exported state; falls back to latest periodic checkpoint,
        then to the template (fresh init)."""
        step = self._best.best_step()
        if step is None:
            return self.restore_latest(template)
        return self._restore(self._best, step, template)

    def restore_best_or_raise(self, template: TrainState, hint: str = "") -> TrainState:
        """``restore_best`` that refuses to hand back a fresh init: raises with
        ``hint`` when neither a best export nor a periodic checkpoint exists
        (the shared guard of every serving/predict path)."""
        if self.best_step() is None and self.latest_step() is None:
            raise RuntimeError(
                f"no trained checkpoint under {self.directory}"
                + (f" — {hint}" if hint else "")
            )
        return self.restore_best(template)

    # -- shared -----------------------------------------------------------

    def _restore(self, manager: ocp.CheckpointManager, step: int, template: TrainState) -> TrainState:
        abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, _state_pytree(template))
        try:
            restored = manager.restore(step, args=ocp.args.StandardRestore(abstract))
        except Exception as e:  # noqa: BLE001 — surface structure mismatches clearly
            msg = str(e)
            mismatch = isinstance(e, KeyError) or (
                "pytree" in msg.lower() or "tree structure" in msg.lower()
            )
            if mismatch:
                raise RuntimeError(
                    f"checkpoint at step {step} under {self.directory} does not "
                    "match the current training state structure — most often "
                    "the optimizer or model configuration changed since the "
                    "checkpoint was written (e.g. --optimizer adam -> sgd "
                    "changes the opt_state pytree). Use a fresh model_dir or "
                    f"restore with the original configuration. ({msg[:300]})"
                ) from e
            raise
        return template.replace(
            step=restored["step"],
            params=restored["params"],
            batch_stats=restored["batch_stats"],
            opt_state=restored["opt_state"],
        )

    def close(self) -> None:
        self._ckpt.close()
        self._best.close()
