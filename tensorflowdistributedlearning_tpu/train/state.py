"""Functional training state.

The reference's training state was implicit TF1 graph collections — GLOBAL_VARIABLES,
UPDATE_OPS for the BN moving stats, the optimizer's slots, and the global step
(reference: model.py:457-467). Here it is one explicit pytree, which is what makes
donation, sharding, and Orbax checkpointing trivial.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import core, struct


class TrainState(struct.PyTreeNode):
    step: jax.Array
    params: core.FrozenDict
    # BN moving statistics — the explicit form of the reference's UPDATE_OPS dance
    # (reference: model.py:465-467)
    batch_stats: core.FrozenDict
    opt_state: optax.OptState
    apply_fn: Callable = struct.field(pytree_node=False)
    tx: optax.GradientTransformation = struct.field(pytree_node=False)

    def apply_gradients(self, grads: Any, new_batch_stats: Any) -> "TrainState":
        updates, new_opt_state = self.tx.update(grads, self.opt_state, self.params)
        new_params = optax.apply_updates(self.params, updates)
        return self.replace(
            step=self.step + 1,
            params=new_params,
            batch_stats=new_batch_stats,
            opt_state=new_opt_state,
        )


def create_train_state(
    model, tx: optax.GradientTransformation, rng: jax.Array, sample_input: jax.Array
) -> TrainState:
    """Initialize parameters/BN stats from a sample input and wrap them with the
    optimizer state.

    Init runs EAGERLY on purpose: op-by-op dispatch hits jax's process-wide
    primitive cache (shared across all architectures), whereas a jitted init
    compiles a fresh ~10s executable per architecture — the wrong trade for
    K-fold loops and test suites that build many small model variants."""
    variables = model.init(rng, sample_input, train=False)
    params = variables["params"]
    batch_stats = variables.get("batch_stats", core.FrozenDict())
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        batch_stats=batch_stats,
        opt_state=tx.init(params),
        apply_fn=model.apply,
        tx=tx,
    )


def tree_bytes_per_device(tree: Any) -> int:
    """Bytes ONE device holds for a placed pytree: each leaf counts its shard
    (``sharding.shard_shape``), so a replicated leaf counts full size and a
    ZeRO-sharded optimizer moment counts 1/dp — the number the weight-update
    sharding mode exists to shrink, reported by the trainers' memory events
    and bench.py so the saving is measured, not asserted. Host numpy leaves
    (and ShapeDtypeStructs without a sharding) count full size."""
    total = 0
    for leaf in jax.tree.leaves(tree):
        shape = getattr(leaf, "shape", None)
        if shape is None:
            continue
        sharding = getattr(leaf, "sharding", None)
        if sharding is not None and hasattr(sharding, "shard_shape"):
            shape = sharding.shard_shape(tuple(shape))
        total += int(np.prod(shape)) * np.dtype(leaf.dtype).itemsize
    return total
