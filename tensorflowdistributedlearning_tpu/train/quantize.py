"""Post-training quantization for serving export: bf16 casts and int8 weights.

The training stack already runs bf16 recipes; serving exported the float32
training graph and paid full-precision HBM bandwidth on every request even
though the step profile is dominated by bandwidth-bound elementwise/BN fusions
(PROFILE_SEG_r05.json: 53.2%). This module is the export-side half of the
quantized serving path: it transforms a restored state's pytrees ONCE at
export time, so the serialized StableHLO artifact carries low-precision
constants — the weights are genuinely small at rest and in HBM, and the
engine (serve/engine.py) needs nothing but the manifest to execute them.

Precision recipes (the standard PTQ-for-serving ladder, Gemma-on-TPU
serving, arXiv:2605.25645):

- ``bfloat16``: every floating leaf casts to bf16; compute runs bf16.
- ``int8``: weight-only quantization — conv/dense **kernels** (floating
  leaves named ``kernel`` with >= 2 dims) store as int8 with per-channel
  symmetric scales over the output-channel axis (-1); everything else
  (biases, BN scale/bias, batch_stats) casts to bf16, and activations stay
  bf16. The serve closure dequantizes inside the traced graph, so the
  artifact reads int8 from HBM and upcasts in registers.
- ``float32``: identity — the pre-quantization graph, bit-for-bit. Still
  stamped with a manifest section so every artifact is self-describing.

Every artifact's manifest ``quantization`` section carries the serving dtype,
per-tensor scale metadata, and a **source fingerprint** (sha256 over the
float32 params) so the accuracy gate (serve/quant_check.py) can verify an
f32/quantized pair really came from the same checkpoint before comparing
outputs — the promotion-pipeline pairing contract (ROADMAP item 4).
"""

from __future__ import annotations

import hashlib
from collections.abc import Mapping
from typing import Any, Dict, Tuple

import numpy as np

SERVING_DTYPES = ("float32", "bfloat16", "int8")

# Serving SPECS are what the export CLI accepts: the storage dtypes plus
# "int8-compute", which stores int8 LIKE "int8" but additionally declares
# int8 *arithmetic* — the serving closure routes dense/conv layers through
# the quantized-compute kernels (ops/quant_kernels.py) instead of
# dequantizing to bf16 and paying floating-point matmuls. The manifest
# section records the split as (dtype="int8", compute_dtype="int8"): storage
# and compute are separate axes, storage names the bytes at rest, compute
# names the matmul arithmetic.
SERVING_SPECS = SERVING_DTYPES + ("int8-compute",)

# manifest compute_dtype per storage dtype when the spec doesn't say
# otherwise — the pre-compute_dtype behaviour, which legacy manifests
# (no compute_dtype field) get by default
_DEFAULT_COMPUTE = {
    "float32": "float32",
    "bfloat16": "bfloat16",
    "int8": "bfloat16",  # PR-6 dequantize-in-graph: int8 bytes, bf16 math
}

# the int8 recipe quantizes exactly the matmul/conv weights; the leaf name is
# the flax convention shared by nn.Conv / nn.Dense / DepthwiseConv2D
_KERNEL_LEAF = "kernel"
_INT8_AXIS = -1  # output channels: the last dim of conv [kh,kw,cin,cout]
# and dense [in,out] kernels

# marker key for a quantized leaf's record dict — chosen to be impossible as
# a flax module name, so tree traversal can tell records from submodules
_QKEY = "__int8__"


def check_serving_dtype(serving_dtype: str) -> str:
    if serving_dtype not in SERVING_DTYPES:
        raise ValueError(
            f"serving_dtype {serving_dtype!r} not in {SERVING_DTYPES}"
        )
    return serving_dtype


def check_serving_spec(spec: str) -> str:
    if spec not in SERVING_SPECS:
        raise ValueError(f"serving spec {spec!r} not in {SERVING_SPECS}")
    return spec


def parse_serving_spec(spec: str) -> Tuple[str, str]:
    """Split a serving spec into its two axes: ``(storage_dtype,
    compute_dtype)``. ``"int8-compute"`` -> ``("int8", "int8")``; the plain
    dtypes keep their historical compute (f32/bf16/bf16-dequantized)."""
    check_serving_spec(spec)
    if spec == "int8-compute":
        return "int8", "int8"
    return spec, _DEFAULT_COMPUTE[spec]


def default_compute_dtype(storage_dtype: str) -> str:
    """What a manifest without a ``compute_dtype`` field means — the ONE
    legacy-default site ``read_manifest`` applies."""
    check_serving_dtype(storage_dtype)
    return _DEFAULT_COMPUTE[storage_dtype]


def compute_dtype(serving_spec: str):
    """The ACTIVATION dtype a serving graph runs in for a given spec. Note
    int8-compute still answers bf16: activations between layers stay bf16 —
    the int8 part is the matmul/conv arithmetic inside the quant kernels,
    which dynamically quantize their own inputs and hand back bf16."""
    import jax.numpy as jnp

    check_serving_spec(serving_spec)
    return jnp.float32 if serving_spec == "float32" else jnp.bfloat16


def fingerprint_tree(tree) -> str:
    """sha256 over (path, dtype, shape, bytes) of every leaf — the identity
    of a params pytree, stable across export runs and serving dtypes (always
    computed on the SOURCE tree, before any cast/quantize)."""
    import jax

    h = hashlib.sha256()
    for path, leaf in sorted(
        jax.tree_util.tree_flatten_with_path(tree)[0],
        key=lambda kv: jax.tree_util.keystr(kv[0]),
    ):
        arr = np.asarray(leaf)
        h.update(jax.tree_util.keystr(path).encode())
        h.update(str(arr.dtype).encode())
        h.update(str(arr.shape).encode())
        h.update(np.ascontiguousarray(arr).tobytes())
    return "sha256:" + h.hexdigest()


def _is_quant_record(node) -> bool:
    return isinstance(node, Mapping) and _QKEY in node


def _quantize_leaf_int8(arr: np.ndarray) -> Dict[str, Any]:
    """Per-channel symmetric int8 over the last axis: scale = max|w|/127,
    q = round(w/scale) in [-127, 127]. All-zero channels keep scale 1.0 so
    dequantization never divides by (or multiplies garbage with) zero."""
    a = np.asarray(arr, np.float32)
    max_abs = np.max(np.abs(a), axis=tuple(range(a.ndim - 1)))
    scale = np.where(max_abs > 0, max_abs / 127.0, 1.0).astype(np.float32)
    q = np.clip(np.round(a / scale), -127, 127).astype(np.int8)
    return {_QKEY: True, "q": q, "scale": scale}


def _walk(tree, path, fn):
    # Mapping, not dict: flax FrozenDict params must recurse too — matching
    # dict alone would pass a frozen tree through as one opaque "leaf" and
    # export a full-precision artifact whose manifest claims it is quantized
    if isinstance(tree, Mapping):
        return {k: _walk(v, path + (k,), fn) for k, v in tree.items()}
    return fn(path, tree)


def quantize_pytree(tree, serving_spec: str) -> Tuple[Any, Dict]:
    """Transform a (nested-dict) params/batch_stats pytree for export.

    Returns ``(qtree, section)`` where ``section`` is the manifest
    ``quantization`` dict (dtype, compute_dtype, per-tensor scale metadata,
    source fingerprint). ``float32`` returns the tree untouched;
    ``bfloat16`` casts floating leaves; ``int8`` and ``int8-compute``
    replace kernel leaves with ``{__int8__, q, scale}`` records and cast the
    rest to bf16 — the two int8 specs produce IDENTICAL bytes; the
    compute_dtype stamp is what tells the serving closure to trace through
    the quant kernels instead of ``dequantize_pytree``'s bf16 upcast.
    """
    import jax.numpy as jnp

    storage_dtype, compute = parse_serving_spec(serving_spec)
    section: Dict[str, Any] = {
        "dtype": storage_dtype,
        "compute_dtype": compute,
        "source_fingerprint": fingerprint_tree(tree),
    }
    if storage_dtype == "float32":
        return tree, section

    scales: Dict[str, Dict] = {}

    def convert(path, leaf):
        arr = np.asarray(leaf)
        if not np.issubdtype(arr.dtype, np.floating):
            return leaf  # int leaves (counters, ids) pass through untouched
        if (
            storage_dtype == "int8"
            and path
            and path[-1] == _KERNEL_LEAF
            and arr.ndim >= 2
        ):
            rec = _quantize_leaf_int8(arr)
            scales["/".join(path)] = {
                "shape": list(rec["scale"].shape),
                "axis": _INT8_AXIS,
                "scale_min": float(rec["scale"].min()),
                "scale_max": float(rec["scale"].max()),
            }
            return rec
        return jnp.asarray(arr, jnp.bfloat16)

    qtree = _walk(tree, (), convert)
    if storage_dtype == "int8":
        section["scheme"] = "per-channel-symmetric"
        section["scales"] = scales
    return qtree, section


def dequantize_pytree(qtree, dtype=None):
    """Rebuild a float tree from ``quantize_pytree``'s output — jit-traceable,
    so calling it inside a serve closure bakes the low-precision constants
    (and the cheap upcast) into the exported graph. ``dtype`` is the target
    activation dtype for int8 records (default bf16); already-cast bf16 / f32
    leaves pass through untouched."""
    import jax.numpy as jnp

    dtype = jnp.bfloat16 if dtype is None else dtype

    def restore(node):
        if _is_quant_record(node):
            # jnp.asarray FIRST: the int8 values must enter the trace as an
            # int8 constant with a traced convert op after it — numpy's
            # eager .astype would upcast at trace time and the artifact
            # would serialize bf16 constants, silently doubling its weight
            # bytes at rest (caught by the artifact-size assertion in
            # tests/test_quant_serve.py)
            q = jnp.asarray(node["q"])
            return q.astype(dtype) * jnp.asarray(node["scale"], dtype)
        if isinstance(node, Mapping):
            return {k: restore(v) for k, v in node.items()}
        return node

    return restore(qtree)


def quantize_state(params, batch_stats, serving_spec: str):
    """The trainers' one-call entry: quantize params and batch_stats with a
    single manifest section whose fingerprint covers the PARAMS tree (the
    identity a checkpoint is selected by). Accepts any SERVING_SPECS value
    including ``int8-compute``."""
    qparams, section = quantize_pytree(params, serving_spec)
    if batch_stats is not None:
        qstats, _ = quantize_pytree(batch_stats, serving_spec)
        # batch_stats never holds kernels: drop the redundant empty scale map
    else:
        qstats = None
    return qparams, qstats, section


def cast_outputs_float32(out: Dict):
    """Serving boundary contract: float outputs leave as float32 regardless
    of the internal compute dtype (clients, the accuracy gate, and the HTTP
    JSON encoder all see one stable dtype); integer outputs (class ids,
    binary masks already cast by the task) pass through."""
    import jax.numpy as jnp

    def cast(v):
        if jnp.issubdtype(v.dtype, jnp.floating) and v.dtype != jnp.float32:
            return v.astype(jnp.float32)
        return v

    return {k: cast(v) for k, v in out.items()}


def validate_quantization(section) -> Dict:
    """Manifest ``quantization`` section validation — the corrupt-artifact
    gate ``read_manifest`` applies. Raises ``ValueError`` with a pointed
    message; returns the section for chaining."""
    if not isinstance(section, dict):
        raise ValueError(
            f"manifest quantization section must be a dict, got "
            f"{type(section).__name__}"
        )
    dtype = section.get("dtype")
    if dtype not in SERVING_DTYPES:
        raise ValueError(
            f"manifest quantization.dtype {dtype!r} not in {SERVING_DTYPES}"
        )
    compute = section.get("compute_dtype")
    if compute is not None:
        # storage and compute are separate axes, but not every pairing is a
        # thing that can be exported: f32/bf16 storage computes in its own
        # dtype; int8 storage computes bf16 (dequantize-in-graph) or int8
        # (quant kernels). Anything else is a corrupt or forged manifest.
        allowed = ("bfloat16", "int8") if dtype == "int8" else (dtype,)
        if compute not in allowed:
            raise ValueError(
                f"manifest quantization.compute_dtype {compute!r} invalid "
                f"for storage dtype {dtype!r} (allowed: {allowed})"
            )
    scales = section.get("scales")
    if dtype == "int8":
        if not isinstance(scales, dict) or not scales:
            raise ValueError(
                "int8 manifest must carry non-empty quantization.scales "
                "metadata — an int8 recipe that quantized zero tensors is a "
                "broken export, not a precision"
            )
        for name, meta in scales.items():
            if not isinstance(meta, dict):
                raise ValueError(
                    f"quantization.scales[{name!r}] must be a dict"
                )
            shape = meta.get("shape")
            if not (
                isinstance(shape, list)
                and all(isinstance(d, int) and d > 0 for d in shape)
            ):
                raise ValueError(
                    f"quantization.scales[{name!r}].shape corrupt: {shape!r}"
                )
            for key in ("scale_min", "scale_max"):
                v = meta.get(key)
                if not isinstance(v, (int, float)) or not np.isfinite(v) or v <= 0:
                    raise ValueError(
                        f"quantization.scales[{name!r}].{key} corrupt: {v!r} "
                        "(scales are strictly positive finite floats)"
                    )
            if meta["scale_min"] > meta["scale_max"]:
                raise ValueError(
                    f"quantization.scales[{name!r}] corrupt: scale_min "
                    f"{meta['scale_min']} > scale_max {meta['scale_max']}"
                )
    elif scales:
        raise ValueError(
            f"quantization.scales present on a {dtype} manifest — only int8 "
            "artifacts carry scale metadata"
        )
    return section
