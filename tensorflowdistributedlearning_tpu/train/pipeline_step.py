"""Jitted SPMD train/eval steps for PIPELINE parallelism over ViT blocks.

The reference had no pipeline parallelism (SURVEY §2.3 — data parallel was its
only strategy); this is the trainable form of the beyond-parity GPipe runner
(parallel/pipeline.py). The mesh is (batch=dp, model=K): each data-parallel
replica is a K-stage pipeline whose stages each hold ``vit_layers/K``
consecutive transformer blocks. One train step:

- patch-embed + position-embed run replicated on every stage (token-local,
  cheap — the heavy per-layer compute is what pipelines);
- the local batch splits into M microbatches and flows through the
  ``lax.scan``-scheduled GPipe fill/drain with one ``ppermute`` hop per tick;
  autodiff derives the reversed-pipeline backward automatically;
- the head (final LN + pool + logits) runs on the gathered output, loss and
  metrics exactly as the plain classification step.

Parameters stay in the canonical ``ViTClassifier`` tree, REPLICATED across the
mesh — checkpoints, serving export, and eval are interchangeable with every
other execution strategy; inside the step each stage dynamically slices its own
block group. Gradient assembly rides shard_map's varying-manual-axes-aware
transposition (verified empirically: raw cotangents arrive at exactly
``dp x`` the single-device global-mean gradient for EVERY leaf):

- block params: stage k's cotangent is nonzero only in slot k; the model-axis
  reduction assembles the slots without over-counting;
- shared params (embed/head): the forward is unvarying on the model axis, so
  the cotangent is taken once, not K times — vma tracking knows an unvarying
  primal has an unvarying cotangent.

What remains is the per-tower mean over data-parallel shards — the same
``_mean_grads`` normalization as the plain step.
"""

from __future__ import annotations

import functools
from typing import Callable, Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tensorflowdistributedlearning_tpu.config import ModelConfig
from tensorflowdistributedlearning_tpu.models import vit as vit_lib
from tensorflowdistributedlearning_tpu.ops import metrics as metrics_lib
from tensorflowdistributedlearning_tpu.parallel.mesh import BATCH_AXIS, MODEL_AXIS
from tensorflowdistributedlearning_tpu.parallel.pipeline import pipeline_apply
from tensorflowdistributedlearning_tpu.train.state import TrainState
from tensorflowdistributedlearning_tpu.train import step as step_lib
from tensorflowdistributedlearning_tpu.train.step import Metrics, _metric_deltas


def validate_pipeline_config(
    config: ModelConfig, pipeline_parallel: int, microbatches: int
) -> None:
    """Config-time checks so misconfiguration fails before any compile."""
    if config.backbone != "vit":
        raise ValueError(
            "pipeline_parallel requires backbone='vit' (homogeneous "
            "transformer blocks are the GPipe runner's stage regime); got "
            f"backbone={config.backbone!r}"
        )
    if config.moe_experts:
        raise ValueError(
            "pipeline_parallel and moe_experts cannot combine: MoE blocks "
            "break the homogeneous-stage regime the GPipe runner requires "
            "(dense and MoE blocks have different param shapes)"
        )
    if config.vit_layers % pipeline_parallel:
        raise ValueError(
            f"vit_layers={config.vit_layers} not divisible by "
            f"pipeline_parallel={pipeline_parallel}: stages must hold equal "
            "block groups"
        )
    if microbatches < pipeline_parallel:
        raise ValueError(
            f"pipeline_microbatches={microbatches} < pipeline stages "
            f"{pipeline_parallel}: the fill/drain schedule needs at least one "
            "microbatch per stage (and wants many more — bubble fraction is "
            "(K-1)/(M+K-1))"
        )


def _pipelined_forward(
    config: ModelConfig, stage_fn, microbatches: int, params, images: jax.Array
) -> jax.Array:
    """Full ViT forward with the block stack routed through the GPipe runner.
    Runs inside shard_map; ``images`` is the local batch shard."""
    k = lax.axis_size(MODEL_AXIS)
    tokens = vit_lib.embed_tokens(config, params, images)
    b, t, d = tokens.shape
    if b % microbatches:
        raise ValueError(
            f"local batch {b} not divisible into {microbatches} microbatches"
        )
    x = tokens.reshape(microbatches, b // microbatches, t, d)
    stacked = vit_lib.stack_vit_block_params(params, config.vit_layers, n_stages=k)
    my_stage = jax.tree.map(
        lambda p: lax.dynamic_index_in_dim(
            p, lax.axis_index(MODEL_AXIS), 0, keepdims=False
        ),
        stacked,
    )
    out = pipeline_apply(stage_fn, my_stage, x)
    return vit_lib.head_logits(config, params, out.reshape(b, t, d))


def _reduce_metrics(metrics: Metrics) -> Metrics:
    """Sum metric contributions over batch shards; the model-axis pmean is
    numerically an identity (every stage computes identical metrics from the
    replicated pipeline output) but clears the varying type."""

    def reduce(x):
        x = lax.psum(x, BATCH_AXIS)
        return lax.pmean(x, MODEL_AXIS)

    return jax.tree.map(reduce, metrics)


def make_train_step_pipeline(
    mesh: Mesh,
    task,
    config: ModelConfig,
    microbatches: int,
    *,
    donate: bool = True,
) -> Callable[[TrainState, Dict[str, jax.Array]], Tuple[TrainState, Metrics]]:
    """Build the jitted pipeline-parallel train step. Memoized like the
    builders in train/step.py so K-fold loops / evals / tests share one
    executable per configuration."""
    return _make_train_step_pipeline_cached(mesh, task, config, microbatches, donate)


@functools.lru_cache(maxsize=None)
def _make_train_step_pipeline_cached(
    mesh: Mesh, task, config: ModelConfig, microbatches: int, donate: bool
):
    k = mesh.shape[MODEL_AXIS]
    stage_fn = vit_lib.grouped_pipeline_stage_fn(config, config.vit_layers // k)

    def step(state: TrainState, batch: Dict[str, jax.Array]):
        def loss_fn(params):
            logits = _pipelined_forward(
                config, stage_fn, microbatches, params, batch["images"]
            )
            return task.loss(logits, batch), logits

        (loss, logits), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params
        )
        # raw cotangents are dp x the global-mean gradient (module docstring);
        # the vma-aware division in _mean_grads restores the tower mean
        grads = step_lib._mean_grads(grads)
        # ViT has no BatchNorm: batch_stats is an empty pytree, passed through
        new_state = state.apply_gradients(grads, state.batch_stats)
        metrics = _reduce_metrics(
            _metric_deltas(task.metric_scores(logits, batch), loss)
        )
        return new_state, metrics

    sharded = jax.shard_map(
        step,
        mesh=mesh,
        in_specs=(P(), P(BATCH_AXIS)),
        out_specs=(P(), P()),
    )
    return jax.jit(sharded, donate_argnums=(0,) if donate else ())


def make_eval_step_pipeline(
    mesh: Mesh, task, config: ModelConfig, microbatches: int
) -> Callable[[TrainState, Dict[str, jax.Array]], Metrics]:
    """Jitted pipeline-parallel eval step: the pipelined forward in inference
    mode, per-example loss so the ``valid`` wrap-around mask weights correctly
    (same contract as train/step.py:make_eval_step)."""
    return _make_eval_step_pipeline_cached(mesh, task, config, microbatches)


@functools.lru_cache(maxsize=None)
def _make_eval_step_pipeline_cached(
    mesh: Mesh, task, config: ModelConfig, microbatches: int
):
    k = mesh.shape[MODEL_AXIS]
    stage_fn = vit_lib.grouped_pipeline_stage_fn(config, config.vit_layers // k)

    def step(state: TrainState, batch: Dict[str, jax.Array]) -> Metrics:
        logits = _pipelined_forward(
            config, stage_fn, microbatches, state.params, batch["images"]
        )
        loss = task.loss_per_example(logits, batch)
        weights = batch.get("valid")
        return _reduce_metrics(
            _metric_deltas(task.metric_scores(logits, batch), loss, weights)
        )

    sharded = jax.shard_map(
        step,
        mesh=mesh,
        in_specs=(P(), P(BATCH_AXIS)),
        out_specs=P(),
    )
    return jax.jit(sharded)
