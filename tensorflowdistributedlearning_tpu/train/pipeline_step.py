"""Jitted SPMD train/eval steps for PIPELINE parallelism over ViT blocks.

The reference had no pipeline parallelism (SURVEY §2.3 — data parallel was its
only strategy); this is the trainable form of the beyond-parity GPipe runner
(parallel/pipeline.py). The mesh is (batch=dp, model=K): each data-parallel
replica is a K-stage pipeline whose stages each hold ``vit_layers/K``
consecutive transformer blocks. One train step:

- patch-embed + position-embed run replicated on every stage (token-local,
  cheap — the heavy per-layer compute is what pipelines);
- the local batch splits into M microbatches and flows through the
  ``lax.scan``-scheduled GPipe fill/drain with one ``ppermute`` hop per tick;
  autodiff derives the reversed-pipeline backward automatically;
- the head (final LN + pool + logits) runs on the gathered output, loss and
  metrics exactly as the plain classification step.

Parameters stay in the canonical ``ViTClassifier`` tree, REPLICATED across the
mesh — checkpoints, serving export, and eval are interchangeable with every
other execution strategy; inside the step each stage dynamically slices its own
block group. Gradient assembly rides shard_map's varying-manual-axes-aware
transposition (verified empirically: raw cotangents arrive at exactly
``dp x`` the single-device global-mean gradient for EVERY leaf):

- block params: stage k's cotangent is nonzero only in slot k; the model-axis
  reduction assembles the slots without over-counting;
- shared params (embed/head): the forward is unvarying on the model axis, so
  the cotangent is taken once, not K times — vma tracking knows an unvarying
  primal has an unvarying cotangent.

What remains is the per-tower mean over data-parallel shards — the same
``_mean_grads`` normalization as the plain step.
"""

from __future__ import annotations

import functools
from typing import Callable, Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tensorflowdistributedlearning_tpu.config import ModelConfig
from tensorflowdistributedlearning_tpu.models import vit as vit_lib
from tensorflowdistributedlearning_tpu.ops import metrics as metrics_lib
from tensorflowdistributedlearning_tpu.parallel.mesh import BATCH_AXIS, MODEL_AXIS
from tensorflowdistributedlearning_tpu.parallel.pipeline import (
    pipeline_apply,
    pipeline_apply_aux,
)
from tensorflowdistributedlearning_tpu.train.state import TrainState
from tensorflowdistributedlearning_tpu.train import step as step_lib
from tensorflowdistributedlearning_tpu.train.step import Metrics, _metric_deltas


def validate_pipeline_config(
    config: ModelConfig, pipeline_parallel: int, microbatches: int
) -> None:
    """Config-time checks so misconfiguration fails before any compile."""
    if config.backbone not in ("vit", "xception"):
        # whitelist, not a resnet blacklist: a backbone added later must opt
        # in explicitly rather than silently falling through to the ViT
        # divisibility branch below and being built as a ViT pipeline
        raise ValueError(
            f"pipeline_parallel does not support backbone={config.backbone!r}: "
            "it requires homogeneous stages (the GPipe runner's regime) — "
            "backbone='vit' (transformer blocks) or backbone='xception' (the "
            "8 identical 728-wide middle-flow units). ResNet's bottleneck "
            "stages change width/stride and cannot pipeline"
        )
    if config.moe_experts:
        raise ValueError(
            "pipeline_parallel and moe_experts cannot combine: MoE blocks "
            "break the homogeneous-stage regime the GPipe runner requires "
            "(dense and MoE blocks have different param shapes)"
        )
    if config.backbone == "xception":
        from tensorflowdistributedlearning_tpu.models.xception import (
            MIDDLE_FLOW_UNITS,
        )

        if config.num_classes is None:
            raise ValueError(
                "pipeline_parallel with backbone='xception' supports the "
                "classifier layout only (the segmentation head needs the "
                "atrous end-point dict, which the stage split does not "
                "thread through)"
            )
        if MIDDLE_FLOW_UNITS % pipeline_parallel:
            raise ValueError(
                f"{MIDDLE_FLOW_UNITS} Xception middle-flow units not "
                f"divisible by pipeline_parallel={pipeline_parallel}: stages "
                "must hold equal unit groups (use 2, 4, or 8)"
            )
    elif config.vit_layers % pipeline_parallel:
        raise ValueError(
            f"vit_layers={config.vit_layers} not divisible by "
            f"pipeline_parallel={pipeline_parallel}: stages must hold equal "
            "block groups"
        )
    if microbatches < pipeline_parallel:
        raise ValueError(
            f"pipeline_microbatches={microbatches} < pipeline stages "
            f"{pipeline_parallel}: the fill/drain schedule needs at least one "
            "microbatch per stage (and wants many more — bubble fraction is "
            "(K-1)/(M+K-1))"
        )


def _pipelined_forward(
    config: ModelConfig, stage_fn, microbatches: int, params, images: jax.Array
) -> jax.Array:
    """Full ViT forward with the block stack routed through the GPipe runner.
    Runs inside shard_map; ``images`` is the local batch shard."""
    k = lax.axis_size(MODEL_AXIS)
    # named scopes thread the obs span taxonomy into the lowered HLO, so an
    # xplane capture attributes device time to embed / fill-drain / head the
    # same way the host-side ledger names its phases (obs/telemetry.py)
    with jax.named_scope("obs/pipeline_embed"):
        tokens = vit_lib.embed_tokens(config, params, images)
    b, t, d = tokens.shape
    if b % microbatches:
        raise ValueError(
            f"local batch {b} not divisible into {microbatches} microbatches"
        )
    x = tokens.reshape(microbatches, b // microbatches, t, d)
    stacked = vit_lib.stack_vit_block_params(params, config.vit_layers, n_stages=k)
    my_stage = jax.tree.map(
        lambda p: lax.dynamic_index_in_dim(
            p, lax.axis_index(MODEL_AXIS), 0, keepdims=False
        ),
        stacked,
    )
    with jax.named_scope("obs/pipeline_fill_drain"):
        out = pipeline_apply(stage_fn, my_stage, x)
    with jax.named_scope("obs/pipeline_head"):
        return vit_lib.head_logits(config, params, out.reshape(b, t, d))


def _reduce_metrics(metrics: Metrics) -> Metrics:
    """Sum metric contributions over batch shards; the model-axis pmean is
    numerically an identity (every stage computes identical metrics from the
    replicated pipeline output) but clears the varying type."""

    def reduce(x):
        x = lax.psum(x, BATCH_AXIS)
        return lax.pmean(x, MODEL_AXIS)

    return jax.tree.map(reduce, metrics)


def make_train_step_pipeline(
    mesh: Mesh,
    task,
    config: ModelConfig,
    microbatches: int,
    *,
    donate: bool = True,
    seed: int = 0,
) -> Callable[[TrainState, Dict[str, jax.Array]], Tuple[TrainState, Metrics]]:
    """Build the jitted pipeline-parallel train step. Memoized like the
    builders in train/step.py so K-fold loops / evals / tests share one
    executable per configuration. Dispatches on the backbone family: ViT
    pipelines its transformer blocks; Xception pipelines the middle flow
    (8 identical 728-wide sum-skip units) with the entry/exit flows
    replicated, BN normalizing per microbatch (the standard GPipe regime).
    ``seed`` roots the xception head's dropout PRNG stream exactly as in
    train/step.py:make_train_step — the same value must be passed to both
    builders for the cross-strategy mask parity the tests pin. The ViT
    branch deliberately ignores it (no stochastic layer anywhere in its
    pipelined forward, so keying its cache on seed would only force
    pointless recompiles per seed); a future dropout-bearing ViT pipeline
    must thread it into _make_train_step_pipeline_cached too."""
    if config.backbone == "xception":
        return _make_train_step_pipeline_xception_cached(
            mesh, task, config, microbatches, donate, seed
        )
    return _make_train_step_pipeline_cached(mesh, task, config, microbatches, donate)


@functools.lru_cache(maxsize=None)
def _make_train_step_pipeline_cached(
    mesh: Mesh, task, config: ModelConfig, microbatches: int, donate: bool
):
    k = mesh.shape[MODEL_AXIS]
    stage_fn = vit_lib.grouped_pipeline_stage_fn(config, config.vit_layers // k)

    def step(state: TrainState, batch: Dict[str, jax.Array]):
        def loss_fn(params):
            logits = _pipelined_forward(
                config, stage_fn, microbatches, params, batch["images"]
            )
            return task.loss(logits, batch), logits

        (loss, logits), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params
        )
        # raw cotangents are dp x the global-mean gradient (module docstring);
        # the vma-aware division in _mean_grads restores the tower mean
        grads = step_lib._mean_grads(grads)
        # ViT has no BatchNorm: batch_stats is an empty pytree, passed through
        new_state = state.apply_gradients(grads, state.batch_stats)
        metrics = _reduce_metrics(
            _metric_deltas(task.metric_scores(logits, batch), loss)
        )
        return new_state, metrics

    sharded = jax.shard_map(
        step,
        mesh=mesh,
        in_specs=(P(), P(BATCH_AXIS)),
        out_specs=(P(), P()),
    )
    return jax.jit(sharded, donate_argnums=(0,) if donate else ())


def _xception_stage_bundle(params, batch_stats, k):
    """This stage's (param, stat) groups: stack the 8 middle-unit subtrees
    into [K, G, ...] and dynamic-index the model-axis slot. Differentiable —
    the transpose of stack+index routes each stage's cotangent back to its own
    units' slots."""
    from tensorflowdistributedlearning_tpu.models import xception as xc

    idx = lax.axis_index(MODEL_AXIS)
    take = lambda tree: jax.tree.map(  # noqa: E731
        lambda l: lax.dynamic_index_in_dim(l, idx, 0, keepdims=False),
        xc.stack_middle_unit_tree(tree, k),
    )
    return take(params["backbone"]), take(batch_stats["backbone"])


# canonical-tree key split for the replicated (non-pipelined) flows
_XC_ENTRY_KEYS = (
    "conv1_1",
    "conv1_2",
    "entry_block1_unit1",
    "entry_block2_unit1",
    "entry_block3_unit1",
)
_XC_EXIT_KEYS = ("exit_block1_unit1", "exit_block2_unit1")


@functools.lru_cache(maxsize=None)
def _make_train_step_pipeline_xception_cached(
    mesh: Mesh, task, config: ModelConfig, microbatches: int, donate: bool,
    seed: int = 0,
):
    from tensorflowdistributedlearning_tpu.models import xception as xc

    k = mesh.shape[MODEL_AXIS]
    entry = xc.XceptionEntryFlow(config)
    exit_head = xc.XceptionExitHead(config)
    stage_fn = xc.grouped_middle_stage_fn(
        config, xc.MIDDLE_FLOW_UNITS // k, train=True
    )

    def step(state: TrainState, batch: Dict[str, jax.Array]):
        # per-(step, batch-shard) dropout stream for the pre-logits dropout;
        # the model axis is NOT folded in — every stage computes the same
        # replicated head and must agree on one mask. The trailing fold_in(0)
        # mirrors the plain step's accum-chunk fold (train/step.py) so the
        # two strategies draw the IDENTICAL mask for a given (step, shard) —
        # the parity tests rely on it.
        dropout_rng = jax.random.fold_in(
            jax.random.fold_in(
                jax.random.fold_in(jax.random.key(seed), state.step),
                lax.axis_index(BATCH_AXIS),
            ),
            0,
        )

        def loss_fn(params):
            backbone_p = params["backbone"]
            stats = state.batch_stats
            backbone_s = stats["backbone"]
            with jax.named_scope("obs/pipeline_entry"):
                feats, entry_mut = entry.apply(
                    {
                        "params": {
                            key: backbone_p[key] for key in _XC_ENTRY_KEYS
                        },
                        "batch_stats": {
                            key: backbone_s[key] for key in _XC_ENTRY_KEYS
                        },
                    },
                    batch["images"],
                    True,
                    mutable=["batch_stats"],
                )
            b = feats.shape[0]
            if b % microbatches:
                raise ValueError(
                    f"local batch {b} not divisible into {microbatches} "
                    "microbatches"
                )
            x = feats.reshape(
                (microbatches, b // microbatches) + feats.shape[1:]
            )
            my_p, my_s = _xception_stage_bundle(params, stats, k)
            with jax.named_scope("obs/pipeline_fill_drain"):
                out, my_new_stats = pipeline_apply_aux(
                    stage_fn, (my_p, my_s), x
                )
            logits, exit_mut = exit_head.apply(
                {
                    "params": {
                        **{key: backbone_p[key] for key in _XC_EXIT_KEYS},
                        "logits": params["logits"],
                    },
                    "batch_stats": {
                        key: backbone_s[key] for key in _XC_EXIT_KEYS
                    },
                },
                out.reshape((b,) + out.shape[2:]),
                True,
                mutable=["batch_stats"],
                rngs={"dropout": dropout_rng},
            )
            loss = task.loss(logits, batch)
            # assemble the full new batch_stats tree: each stage scatters its
            # group's microbatch-averaged stats into its [K, G, ...] slot; the
            # model-axis psum fills the other slots (zeros elsewhere — a copy,
            # not a reduction)
            idx = lax.axis_index(MODEL_AXIS)
            scattered = jax.tree.map(
                lambda s: jnp.zeros((k,) + s.shape, s.dtype).at[idx].set(s),
                my_new_stats,
            )
            middle_new = xc.unstack_middle_unit_tree(
                lax.psum(scattered, MODEL_AXIS)
            )
            new_backbone = dict(entry_mut["batch_stats"])
            new_backbone.update(middle_new)
            new_backbone.update(exit_mut["batch_stats"])
            return loss, (logits, {"backbone": new_backbone})

        (loss, (logits, new_stats)), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(state.params)
        grads = step_lib._mean_grads(grads)
        # per-tower BN stats -> replicated (same normalization as the plain
        # step); the stats are already model-axis unvarying: entry/exit ran
        # replicated, the middle slots were psum-assembled above
        new_stats = lax.pmean(new_stats, BATCH_AXIS)
        new_state = state.apply_gradients(grads, new_stats)
        metrics = _reduce_metrics(
            _metric_deltas(task.metric_scores(logits, batch), loss)
        )
        return new_state, metrics

    sharded = jax.shard_map(
        step,
        mesh=mesh,
        in_specs=(P(), P(BATCH_AXIS)),
        out_specs=(P(), P()),
    )
    return jax.jit(sharded, donate_argnums=(0,) if donate else ())


@functools.lru_cache(maxsize=None)
def _make_eval_step_pipeline_xception_cached(
    mesh: Mesh, task, config: ModelConfig, microbatches: int
):
    from tensorflowdistributedlearning_tpu.models import xception as xc

    k = mesh.shape[MODEL_AXIS]
    entry = xc.XceptionEntryFlow(config)
    exit_head = xc.XceptionExitHead(config)
    stage_fn = xc.grouped_middle_stage_fn(
        config, xc.MIDDLE_FLOW_UNITS // k, train=False
    )

    def step(state: TrainState, batch: Dict[str, jax.Array]) -> Metrics:
        backbone_p = state.params["backbone"]
        backbone_s = state.batch_stats["backbone"]
        feats = entry.apply(
            {
                "params": {key: backbone_p[key] for key in _XC_ENTRY_KEYS},
                "batch_stats": {key: backbone_s[key] for key in _XC_ENTRY_KEYS},
            },
            batch["images"],
            False,
        )
        b = feats.shape[0]
        if b % microbatches:
            raise ValueError(
                f"local batch {b} not divisible into {microbatches} "
                "microbatches"
            )
        x = feats.reshape((microbatches, b // microbatches) + feats.shape[1:])
        bundle = _xception_stage_bundle(state.params, state.batch_stats, k)
        out = pipeline_apply(stage_fn, bundle, x)
        logits = exit_head.apply(
            {
                "params": {
                    **{key: backbone_p[key] for key in _XC_EXIT_KEYS},
                    "logits": state.params["logits"],
                },
                "batch_stats": {key: backbone_s[key] for key in _XC_EXIT_KEYS},
            },
            out.reshape((b,) + out.shape[2:]),
            False,
        )
        loss = task.loss_per_example(logits, batch)
        weights = batch.get("valid")
        return _reduce_metrics(
            _metric_deltas(task.metric_scores(logits, batch), loss, weights)
        )

    sharded = jax.shard_map(
        step,
        mesh=mesh,
        in_specs=(P(), P(BATCH_AXIS)),
        out_specs=P(),
    )
    return jax.jit(sharded)


def make_eval_step_pipeline(
    mesh: Mesh, task, config: ModelConfig, microbatches: int
) -> Callable[[TrainState, Dict[str, jax.Array]], Metrics]:
    """Jitted pipeline-parallel eval step: the pipelined forward in inference
    mode, per-example loss so the ``valid`` wrap-around mask weights correctly
    (same contract as train/step.py:make_eval_step). Dispatches on backbone
    like ``make_train_step_pipeline``."""
    if config.backbone == "xception":
        return _make_eval_step_pipeline_xception_cached(
            mesh, task, config, microbatches
        )
    return _make_eval_step_pipeline_cached(mesh, task, config, microbatches)


@functools.lru_cache(maxsize=None)
def _make_eval_step_pipeline_cached(
    mesh: Mesh, task, config: ModelConfig, microbatches: int
):
    k = mesh.shape[MODEL_AXIS]
    stage_fn = vit_lib.grouped_pipeline_stage_fn(config, config.vit_layers // k)

    def step(state: TrainState, batch: Dict[str, jax.Array]) -> Metrics:
        logits = _pipelined_forward(
            config, stage_fn, microbatches, state.params, batch["images"]
        )
        loss = task.loss_per_example(logits, batch)
        weights = batch.get("valid")
        return _reduce_metrics(
            _metric_deltas(task.metric_scores(logits, batch), loss, weights)
        )

    sharded = jax.shard_map(
        step,
        mesh=mesh,
        in_specs=(P(), P(BATCH_AXIS)),
        out_specs=P(),
    )
    return jax.jit(sharded)
