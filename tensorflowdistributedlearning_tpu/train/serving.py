"""Standalone serving artifacts: serialized StableHLO via ``jax.export``.

The reference's BestExporter wrote SavedModel bundles an external TF-Serving
process could load without the training code (reference: model.py:190-204). The
JAX-native equivalent is ``jax.export``: the jitted inference function (with the
fold's best params baked in as constants) lowers to StableHLO and serializes to a
self-contained byte artifact; any process with jax installed — no framework code,
no checkpoint plumbing — can deserialize and call it.

Layout of an artifact directory:
    {dir}/serving.stablehlo   — the serialized Exported function
    {dir}/manifest.json       — input signature + metadata for humans/tools
"""

from __future__ import annotations

import json
import os
from typing import Callable, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

ARTIFACT_NAME = "serving.stablehlo"
MANIFEST_NAME = "manifest.json"


def export_serving_artifact(
    serve_fn: Callable,
    input_shape: Tuple[int, ...],
    directory: str,
    *,
    batch_polymorphic: bool = True,
    metadata: Dict | None = None,
) -> str:
    """Serialize ``serve_fn`` (a jittable ``images -> {...}`` closure with params
    baked in) for the given input signature; returns the artifact path.

    ``input_shape`` is the full input shape including the batch dimension;
    ``batch_polymorphic=True`` replaces the batch dim with a symbolic size so one
    artifact serves any batch size (the reference's ``[None, 101, 101, 2]``
    placeholder semantics, model.py:192).
    """
    from jax import export as jax_export

    if batch_polymorphic:
        (b,) = jax_export.symbolic_shape("b")
        spec_shape: Tuple = (b, *input_shape[1:])
    else:
        spec_shape = tuple(input_shape)
    spec = jax.ShapeDtypeStruct(spec_shape, jnp.float32)
    exported = jax_export.export(jax.jit(serve_fn))(spec)
    payload = exported.serialize()

    os.makedirs(directory, exist_ok=True)
    artifact = os.path.join(directory, ARTIFACT_NAME)
    with open(artifact, "wb") as f:
        f.write(bytes(payload))
    manifest = {
        "input_shape": [None if batch_polymorphic else input_shape[0]]
        + list(input_shape[1:]),
        "input_dtype": "float32",
        "format": "jax.export serialized StableHLO",
        "platforms": list(getattr(exported, "platforms", ())),
        **(metadata or {}),
    }
    with open(os.path.join(directory, MANIFEST_NAME), "w") as f:
        json.dump(manifest, f, indent=2)
    return artifact


def load_serving_artifact(directory: str) -> Callable:
    """Deserialize an exported artifact; returns ``serve(images) -> outputs``.
    Needs only jax — none of this framework's modules or checkpoints."""
    from jax import export as jax_export

    with open(os.path.join(directory, ARTIFACT_NAME), "rb") as f:
        payload = f.read()
    exported = jax_export.deserialize(bytearray(payload))

    def serve(images) -> Dict:
        return exported.call(jnp.asarray(images, jnp.float32))

    return serve


def read_manifest(directory: str) -> Dict:
    with open(os.path.join(directory, MANIFEST_NAME)) as f:
        return json.load(f)
