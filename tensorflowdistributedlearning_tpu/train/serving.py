"""Standalone serving artifacts: serialized StableHLO via ``jax.export``.

The reference's BestExporter wrote SavedModel bundles an external TF-Serving
process could load without the training code (reference: model.py:190-204). The
JAX-native equivalent is ``jax.export``: the jitted inference function (with the
fold's best params baked in as constants) lowers to StableHLO and serializes to a
self-contained byte artifact; any process with jax installed — no framework code,
no checkpoint plumbing — can deserialize and call it.

Layout of an artifact directory:
    {dir}/serving.stablehlo   — the serialized Exported function
    {dir}/manifest.json       — input signature + metadata for humans/tools
"""

from __future__ import annotations

import json
import os
from typing import Callable, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

ARTIFACT_NAME = "serving.stablehlo"
MANIFEST_NAME = "manifest.json"


def _manifest_dims(shape) -> list:
    """Manifest encoding of a shape: ints stay, symbolic dims (the polymorphic
    batch) become None — the same placeholder convention as the input spec."""
    return [int(d) if isinstance(d, int) else None for d in shape]


def _output_signature(out_tree) -> Dict[str, Dict]:
    """Flatten an output pytree of avals into ``{name: {shape, dtype}}``
    manifest entries, so clients can validate responses without calling the
    artifact. Dict outputs (both tasks' ``predictions``) name entries by key;
    other containers fall back to the jax key-path string."""
    import jax

    sig: Dict[str, Dict] = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(out_tree)[0]:
        parts = []
        for p in path:
            for attr in ("key", "idx", "name"):
                if hasattr(p, attr):
                    parts.append(str(getattr(p, attr)))
                    break
            else:
                parts.append(str(p))
        name = "/".join(parts) if parts else "output"
        sig[name] = {
            "shape": _manifest_dims(leaf.shape),
            "dtype": str(leaf.dtype),
        }
    return sig


def export_serving_artifact(
    serve_fn: Callable,
    input_shape: Tuple[int, ...],
    directory: str,
    *,
    batch_polymorphic: bool = True,
    input_dtype: str = "float32",
    metadata: Dict | None = None,
    quantization: Dict | None = None,
) -> str:
    """Serialize ``serve_fn`` (a jittable ``images -> {...}`` closure with params
    baked in) for the given input signature; returns the artifact path.

    ``input_shape`` is the full input shape including the batch dimension;
    ``batch_polymorphic=True`` replaces the batch dim with a symbolic size so one
    artifact serves any batch size (the reference's ``[None, 101, 101, 2]``
    placeholder semantics, model.py:192).

    ``quantization`` is the manifest section ``train/quantize.py`` produced
    alongside the (possibly quantized) ``serve_fn`` — serving dtype, per-tensor
    scale metadata, source fingerprint. Validated before writing, so a corrupt
    section fails the EXPORT, not some later load.
    """
    from jax import export as jax_export

    if batch_polymorphic:
        (b,) = jax_export.symbolic_shape("b")
        spec_shape: Tuple = (b, *input_shape[1:])
    else:
        spec_shape = tuple(input_shape)
    spec = jax.ShapeDtypeStruct(spec_shape, jnp.dtype(input_dtype))
    exported = jax_export.export(jax.jit(serve_fn))(spec)
    payload = exported.serialize()

    os.makedirs(directory, exist_ok=True)
    artifact = os.path.join(directory, ARTIFACT_NAME)
    with open(artifact, "wb") as f:
        f.write(bytes(payload))
    manifest = {
        "input_shape": [None if batch_polymorphic else input_shape[0]]
        + list(input_shape[1:]),
        "input_dtype": str(jnp.dtype(input_dtype)),
        # the OUTPUT signature too: without it clients can't validate
        # responses (or pre-allocate) from the manifest alone. Read from what
        # export already traced (re-tracing via eval_shape trips shape-poly
        # restrictions the export lowering itself handles).
        "outputs": _output_signature(
            jax.tree_util.tree_unflatten(
                exported.out_tree, list(exported.out_avals)
            )
        ),
        "format": "jax.export serialized StableHLO",
        "platforms": list(getattr(exported, "platforms", ())),
        **(metadata or {}),
    }
    if quantization is not None:
        from tensorflowdistributedlearning_tpu.train import quantize

        manifest["quantization"] = quantize.validate_quantization(quantization)
    with open(os.path.join(directory, MANIFEST_NAME), "w") as f:
        json.dump(manifest, f, indent=2)
    return artifact


def load_serving_artifact(directory: str) -> Callable:
    """Deserialize an exported artifact; returns ``serve(images) -> outputs``.
    Needs only jax — none of this framework's modules or checkpoints. The
    input dtype comes from the manifest (an artifact exported for bfloat16
    inputs used to be silently fed float32); a MISSING manifest falls back to
    float32, the historical contract — a present-but-corrupt one (bad dtype
    string, invalid quantization section) raises, because executing an
    artifact whose self-description cannot be trusted is how silently-wrong
    answers ship."""
    from jax import export as jax_export

    with open(os.path.join(directory, ARTIFACT_NAME), "rb") as f:
        payload = f.read()
    exported = jax_export.deserialize(bytearray(payload))
    try:
        manifest = read_manifest(directory)
    except OSError:
        manifest = {"input_dtype": "float32"}
    dtype = jnp.dtype(manifest["input_dtype"])

    def serve(images) -> Dict:
        return exported.call(jnp.asarray(images, dtype))

    return serve


_ATTACH_SCRIPT = """
import json, sys
directory, cache_dir, raw = sys.argv[1], sys.argv[2], sys.argv[3]
from tensorflowdistributedlearning_tpu.utils import compile_cache
if not compile_cache.configure(cache_dir):
    sys.exit(3)
from tensorflowdistributedlearning_tpu.serve.engine import InferenceEngine
buckets = json.loads(raw)
kwargs = {"buckets": tuple(buckets)} if buckets else {}
engine = InferenceEngine.from_artifact(directory, **kwargs)
engine.warmup()
print(json.dumps([int(b) for b in engine.buckets]))
"""


def attach_compile_cache(
    directory: str, *, buckets=None, timeout_s: float = 600.0
) -> Dict:
    """Populate ``{directory}/compile_cache`` with the artifact's compiled
    bucket-ladder executables and stamp the subdir's fingerprint into the
    manifest — the load-not-compile serving contract.

    The exporter pays the ladder compile ONCE, here; every replica that
    later loads the artifact (fleet scale-up surge, promotion flip) merges
    the shipped entries into its own persistent cache and goes ready on
    load. The manifest section::

        "compile_cache": {"subdir": "compile_cache",
                          "buckets": [...], "entries": N,
                          "fingerprint": "sha256..."}

    lets consumers detect a torn/mixed copy before trusting the entries
    (serve/engine.py consume_artifact_cache).

    The ladder is compiled in a SUBPROCESS pinned to the serving topology
    (one forced host device): cache keys hash the process-local backend
    topology, so entries compiled in the training process — typically many
    emulated devices, maybe a distributed world — would never match what a
    single-process serve replica looks up. A replica on different hardware
    or topology simply misses and compiles; shipped entries are an
    optimization, never a correctness dependency. Returns the manifest
    section ({} when the cache could not be populated — the export is
    already on disk and unaffected)."""
    import subprocess
    import sys

    from tensorflowdistributedlearning_tpu.utils import compile_cache

    sub = os.path.join(directory, "compile_cache")
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    # make the package importable even when running from a source tree
    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
    argv = [
        sys.executable, "-c", _ATTACH_SCRIPT,
        directory, sub,
        json.dumps([int(b) for b in buckets] if buckets else None),
    ]
    try:
        proc = subprocess.run(
            argv, env=env, capture_output=True, text=True, timeout=timeout_s
        )
    except (OSError, subprocess.TimeoutExpired) as e:
        import logging

        logging.getLogger(__name__).warning(
            "compile-cache attach subprocess failed (%s) — artifact ships "
            "without a cache; replicas compile cold", e,
        )
        return {}
    if proc.returncode != 0:
        import logging

        logging.getLogger(__name__).warning(
            "compile-cache attach exited rc=%d — artifact ships without a "
            "cache; replicas compile cold. stderr tail: %s",
            proc.returncode, proc.stderr[-500:],
        )
        return {}
    bucket_list = json.loads(proc.stdout.strip().splitlines()[-1])
    section = {
        "subdir": "compile_cache",
        "buckets": bucket_list,
        **compile_cache.fingerprint(sub),
    }
    manifest = read_manifest(directory)
    manifest["compile_cache"] = section
    with open(os.path.join(directory, MANIFEST_NAME), "w") as f:
        json.dump(manifest, f, indent=2)
    return section


def read_manifest(directory: str) -> Dict:
    """Read + validate an artifact manifest. The ONE site that applies the
    legacy defaults (pre-input_dtype manifests mean float32; no
    ``quantization`` section means an unquantized float32 graph; a
    quantization section without ``compute_dtype`` means the storage dtype's
    historical arithmetic — f32/bf16/bf16-dequantized) and the one gate that
    rejects corrupt quantization metadata — every consumer (engine, loader,
    quantize-check, CLI) reads through here."""
    from tensorflowdistributedlearning_tpu.train import quantize

    with open(os.path.join(directory, MANIFEST_NAME)) as f:
        manifest = json.load(f)
    manifest.setdefault("input_dtype", "float32")
    if "quantization" in manifest:
        quantize.validate_quantization(manifest["quantization"])
        q = manifest["quantization"]
        if "compute_dtype" not in q and q.get("dtype") in quantize.SERVING_DTYPES:
            q["compute_dtype"] = quantize.default_compute_dtype(q["dtype"])
    return manifest
