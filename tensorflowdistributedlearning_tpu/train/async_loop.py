"""Host–device overlap for the training loops: dispatch-ahead, deferred fetch.

jax dispatches jitted computations asynchronously, but the trainers used to
defeat that twice per run phase: every log window blocked on a synchronous
``jax.device_get(metrics)`` (draining the device queue before the next step
could be fed), and every eval BATCH pulled its metric deltas to the host.
Keeping the accelerator queue full with asynchronous dispatch and deferred
host fetches is the standard overlap discipline of pjit-era TPU stacks
(arXiv:2204.06514) and generalizes the reference's ``prefetch(2×n_gpus)``
host-overlap idea (arXiv:1605.08695, reference: model.py:319-320) from input
copies to the whole host loop. This module owns the three pieces:

- **bounded dispatch-ahead** (``HostOverlap.track``): the host may run at most
  ``TrainConfig.dispatch_ahead_steps`` dispatched-but-unretired steps past the
  device; beyond the budget it blocks on the oldest in-flight step under the
  ``fetch_wait`` telemetry span, so backpressure is bounded AND measured
  (surfaced per window and in ``telemetry-report``'s goodput split);
- **deferred window metrics** (``HostOverlap.window``/``flush``): a log
  window's scalars start a ``copy_to_host_async`` at the boundary and are
  fetched/emitted at the NEXT boundary, while the device is already running
  window N+1 — TB/ledger events carry the step they describe, arriving one
  window late. Span samples are snapshotted at the boundary so a late-written
  window event still describes its own interval. ``flush()`` runs at every
  eval/checkpoint/preemption/end boundary, so resilience semantics
  (``faults.fire``/``preempt.requested`` ordering, ledger completeness at a
  preemption checkpoint) are unchanged;
- **device-resident eval accumulation** (``merge_metrics_device`` +
  ``fetch_metrics``): the eval accumulator stays a device ``Mean`` pytree,
  merged by a tiny jitted add per batch, with ONE host transfer per eval pass
  (counted in the registry under ``EVAL_FETCH_COUNTER`` — pinned by
  tests/test_async_loop.py) instead of one per batch.

``dispatch_ahead_steps=0`` is the synchronous legacy loop: the window fetch
blocks in place (under the ``step`` span, as before) and nothing is tracked —
the bit-for-bit A/B the bench (``bench.py --async-loop``) and the parity tests
compare against.

All blocked-on-device time here flows through the telemetry span API, so with
tracing enabled (``TrainConfig.trace_sample_rate``) the ``fetch_wait`` waits
appear as sampled spans in ``telemetry-report --export-trace`` timelines
alongside step/eval/checkpoint — the per-unit view of dispatch-ahead
backpressure.
"""

from __future__ import annotations

import dataclasses
import functools
from collections import deque
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp

from tensorflowdistributedlearning_tpu import obs as obs_lib
from tensorflowdistributedlearning_tpu.ops import metrics as metrics_lib

# registry counter: one increment per eval-pass metric transfer — the
# "exactly one host transfer per eval pass" contract is asserted against it
EVAL_FETCH_COUNTER = "fetch/eval_metrics"


class DispatchBudget:
    """Bounded dispatch-ahead over any loop of device computations.

    ``track(tree)`` once per dispatched step with one of its device outputs;
    past ``budget`` in-flight steps it blocks on the OLDEST one (recorded
    under ``span`` — default the ``fetch_wait`` window span; None records
    nothing) so the host never runs unboundedly ahead of the device.
    ``block_until_ready`` waits for completion without transferring —
    tracking adds no host copies. ``budget <= 0`` disables tracking entirely
    (the caller owns its own sync points)."""

    def __init__(
        self,
        telemetry,
        budget: int,
        span: Optional[str] = obs_lib.SPAN_FETCH_WAIT,
    ):
        self._tel = telemetry
        self._budget = int(budget)
        self._span = span
        self._inflight: deque = deque()

    @property
    def budget(self) -> int:
        return self._budget

    def track(self, tree: Any) -> None:
        if self._budget <= 0:
            return
        leaf = next(iter(jax.tree.leaves(tree)), None)
        if leaf is None:
            return
        self._inflight.append(leaf)
        if len(self._inflight) > self._budget:
            oldest = self._inflight.popleft()
            if self._span is None:
                jax.block_until_ready(oldest)
            else:
                with self._tel.span(self._span):
                    jax.block_until_ready(oldest)


def eval_budget(telemetry, dispatch_ahead: int) -> DispatchBudget:
    """The eval pass's in-flight bound: the legacy loop's per-batch
    ``device_get`` throttled eval to ~1 batch in flight as a side effect;
    device-resident accumulation removes that sync, so WITHOUT a bound the
    host would enqueue every eval batch's H2D copy + step at once and a large
    val split could hold its whole input set in HBM. Track the accumulator
    each batch with at least a budget of 1 (even in sync mode — bounded
    memory is not optional), at most the train loop's dispatch-ahead knob.

    ``span=None``: these waits happen INSIDE the eval span, whose wall time
    the eval event already records — a ``fetch_wait`` sample here would sit
    in the histogram until the NEXT train window drained it, double-counting
    eval time as dispatch-ahead backpressure in the goodput split."""
    return DispatchBudget(telemetry, max(1, int(dispatch_ahead)), span=None)


@dataclasses.dataclass
class PendingWindow:
    """One log window's deferred payload: the device metric pytree plus every
    host-side fact the emit needs, captured AT the boundary (wall-clock
    throughput, host-computed lr, the span samples of the window's own
    interval) so nothing is recomputed when the event is written late."""

    step: int
    metrics: Any  # device Metrics pytree (Dict[str, ops.metrics.Mean])
    steps: int
    lr: float
    images_per_sec: Optional[float] = None
    dirty: bool = False
    extra: Dict[str, Any] = dataclasses.field(default_factory=dict)
    samples: Optional[Dict[str, List[float]]] = None


class HostOverlap:
    """The trainers' host–device overlap state machine (one per run phase).

    ``emit(record, scalars)`` is the trainer's write-out (TB scalars + ledger
    window event); it fires immediately in sync mode and one boundary late in
    async mode. ``telemetry`` provides the span API the blocked-on-fetch time
    is recorded through (``NULL_TELEMETRY`` works: spans no-op).
    """

    def __init__(
        self,
        telemetry,
        *,
        dispatch_ahead: int = 2,
        emit: Callable[[PendingWindow, Dict[str, float]], None],
    ):
        self._tel = telemetry
        self._emit = emit
        self._tracker = DispatchBudget(telemetry, max(0, int(dispatch_ahead)))
        self._pending: Optional[PendingWindow] = None

    @property
    def async_mode(self) -> bool:
        return self._tracker.budget > 0

    def track(self, metrics: Any) -> None:
        """Bounded dispatch-ahead: call once per dispatched train step with its
        metric output. Past the budget, blocks on the OLDEST in-flight step
        (recorded as ``fetch_wait``) so the host never runs unboundedly ahead
        of the device. Sync mode (budget 0) is a no-op — the legacy loop's
        only sync point is the window ``device_get``."""
        self._tracker.track(metrics)

    def window(self, record: PendingWindow) -> None:
        """Log-window boundary. Sync mode fetches and emits in place (the
        ``device_get`` synchronizes on this step, so window span totals are
        real wall time — it counts as step time, exactly the legacy
        accounting). Async mode emits the PREVIOUS window, snapshots this
        window's span samples, starts the host copy, and defers."""
        if not self.async_mode:
            with self._tel.span(obs_lib.SPAN_STEP):
                host = jax.device_get(record.metrics)
            self._emit(record, self._scalars(record, host))
            return
        self.flush()
        record.samples = self._tel.drain_window_samples()
        for leaf in jax.tree.leaves(record.metrics):
            if hasattr(leaf, "copy_to_host_async"):
                leaf.copy_to_host_async()
        self._pending = record

    def flush(self) -> None:
        """Fetch and emit the deferred window, if any. The trainers call this
        at every eval/checkpoint/preemption/end boundary so the ledger is
        complete before any resilience-relevant event is written. Idempotent
        and cheap when nothing is pending."""
        record, self._pending = self._pending, None
        if record is None:
            return
        with self._tel.span(obs_lib.SPAN_FETCH_WAIT):
            host = jax.device_get(record.metrics)
        self._emit(record, self._scalars(record, host))

    @staticmethod
    def _scalars(record: PendingWindow, host_metrics: Any) -> Dict[str, float]:
        from tensorflowdistributedlearning_tpu.train import step as step_lib

        scalars = step_lib.compute_metrics(host_metrics)
        if record.images_per_sec is not None:
            scalars["throughput/images_per_sec"] = record.images_per_sec
        scalars["lr"] = record.lr
        return scalars


@functools.lru_cache(maxsize=None)
def _merge_jit():
    # Mean.merge is addition of (total, count); a leafwise add over two Mean
    # pytrees IS the K-way streaming merge, and jitting it keeps the eval
    # accumulator device-resident (dispatch only, no host sync per batch)
    return jax.jit(lambda acc, new: jax.tree.map(jnp.add, acc, new))


def merge_metrics_device(acc: Optional[Any], new: Any) -> Any:
    """Device-side streaming metric merge for eval passes: ``None`` starts the
    stream (validating every leaf is a ``Mean`` — the addition-is-merge
    contract ``train.step._merge_stacked_metrics`` enforces for the scan
    paths), subsequent calls add on device."""
    if acc is None:
        for name, leaf in new.items():
            if not isinstance(leaf, metrics_lib.Mean):
                raise TypeError(
                    f"eval metric {name!r} is a {type(leaf).__name__}, not a "
                    "Mean state — the device-resident accumulator merges by "
                    "addition, which is only a valid merge for Mean's "
                    "(total, count); teach merge_metrics_device this type "
                    "before streaming it"
                )
        return new
    return _merge_jit()(acc, new)


def fetch_metrics(acc: Any, telemetry=None) -> Dict[str, float]:
    """THE one host transfer of an eval pass: pull the accumulated device
    metrics and reduce them to floats. Counts the transfer in the telemetry
    registry (``EVAL_FETCH_COUNTER``) so the single-transfer contract is
    testable from ledger-side accounting."""
    if acc is None:
        raise ValueError("fetch_metrics: no eval batches were accumulated")
    if telemetry is not None:
        telemetry.registry.counter(EVAL_FETCH_COUNTER).inc()
    from tensorflowdistributedlearning_tpu.train import step as step_lib

    return step_lib.compute_metrics(jax.device_get(acc))
