from tensorflowdistributedlearning_tpu.train.state import TrainState, create_train_state
from tensorflowdistributedlearning_tpu.train.step import (
    ClassificationTask,
    SegmentationTask,
    make_eval_step,
    make_optimizer,
    make_predict_step,
    make_multi_train_step,
    make_train_step,
)

__all__ = [
    "TrainState",
    "create_train_state",
    "ClassificationTask",
    "SegmentationTask",
    "make_eval_step",
    "make_optimizer",
    "make_predict_step",
    "make_multi_train_step",
    "make_train_step",
]
