"""Lovász hinge loss, TPU-native.

Re-design of the reference's loss stack (reference: core/losses.py:5-92). Differences by
design, not accident:

- The reference looped over images with ``tf.map_fn`` (core/losses.py:27-34) and pinned
  the whole loss to CPU:0 (model.py:391-394), forcing a device->host round trip every
  step. Here the per-image loss is ``vmap``-ed and the descending sort is
  ``lax.top_k`` — everything stays on the TPU and fuses into the step.
- The reference handled void pixels with dynamic-shape ``boolean_mask`` + ``tf.cond``
  (core/losses.py:59-64, 77-80), which cannot be jitted with static shapes. Here void
  pixels are handled with fixed-shape mask arithmetic: invalid errors are pushed to the
  end of the sort and contribute exactly zero to both the hinge terms and the Jaccard
  deltas, so an all-void image yields loss 0 just like the reference's ``tf.cond`` arm.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

# Errors of void pixels are set to this so they sort strictly last. relu() of it is 0,
# so they contribute nothing to the hinge dot product.
_VOID_ERROR = -1e9


def lovasz_grad(gt_sorted: jax.Array, valid_sorted: Optional[jax.Array] = None) -> jax.Array:
    """Gradient of the Lovász extension w.r.t. sorted errors (reference:
    core/losses.py:5-15; Alg. 1 of Berman et al. 2018).

    ``gt_sorted``: [P] float 0/1 ground truth, ordered by descending error.
    ``valid_sorted``: optional [P] float 0/1 mask in the same order; void positions are
    weighted out of the cumulative sums so the Jaccard sequence is constant across them
    (delta 0), which is exactly what removing them (as the reference's boolean_mask did)
    produces.
    """
    if valid_sorted is None:
        valid_sorted = jnp.ones_like(gt_sorted)
    gt_sorted = gt_sorted * valid_sorted
    gts = jnp.sum(gt_sorted)
    intersection = gts - jnp.cumsum(gt_sorted)
    union = gts + jnp.cumsum((1.0 - gt_sorted) * valid_sorted)
    jaccard = 1.0 - intersection / jnp.maximum(union, 1e-12)
    return jnp.concatenate([jaccard[:1], jaccard[1:] - jaccard[:-1]])


def lovasz_hinge_flat(
    logits: jax.Array, labels: jax.Array, valid: Optional[jax.Array] = None
) -> jax.Array:
    """Binary Lovász hinge over a flat pixel vector (reference: core/losses.py:40-65).

    ``logits``: [P] float; ``labels``: [P] 0/1; ``valid``: optional [P] 0/1 mask
    (fixed-shape replacement for the reference's ignore-label boolean_mask).
    """
    labels = labels.astype(logits.dtype)
    signs = 2.0 * labels - 1.0
    errors = 1.0 - logits * lax.stop_gradient(signs)
    if valid is not None:
        valid = valid.astype(logits.dtype)
        errors = jnp.where(valid > 0, errors, _VOID_ERROR)
    errors_sorted, perm = lax.top_k(errors, errors.shape[0])
    gt_sorted = jnp.take(labels, perm)
    valid_sorted = None if valid is None else jnp.take(valid, perm)
    grad = lovasz_grad(gt_sorted, valid_sorted)
    return jnp.dot(jax.nn.relu(errors_sorted), lax.stop_gradient(grad))


def lovasz_hinge(
    logits: jax.Array,
    labels: jax.Array,
    per_image: bool = True,
    ignore: Optional[int] = None,
) -> jax.Array:
    """Binary Lovász hinge loss (reference: core/losses.py:18-37).

    ``logits``: [B, H, W] scores; ``labels``: [B, H, W] binary masks.
    ``per_image=True`` computes the loss per image and averages (the reference's
    ``map_fn`` path); ``False`` flattens the whole batch first.
    """
    valid = None if ignore is None else (labels != ignore)

    if per_image:
        return jnp.mean(lovasz_hinge_per_image(logits, labels, ignore))

    return lovasz_hinge_flat(
        logits.reshape(-1),
        labels.reshape(-1),
        None if valid is None else valid.reshape(-1),
    )


def lovasz_hinge_per_image(
    logits: jax.Array, labels: jax.Array, ignore: Optional[int] = None
) -> jax.Array:
    """Per-image Lovász hinge losses, shape [B] — the un-averaged form of the
    reference's ``map_fn`` path (core/losses.py:27-34); used by eval to weight out
    wrap-around-padded examples."""
    valid = None if ignore is None else (labels != ignore)
    flat_logits = logits.reshape(logits.shape[0], -1)
    flat_labels = labels.reshape(labels.shape[0], -1)
    if valid is None:
        return jax.vmap(lovasz_hinge_flat)(flat_logits, flat_labels)
    return jax.vmap(lovasz_hinge_flat)(
        flat_logits, flat_labels, valid.reshape(valid.shape[0], -1)
    )


def lovasz_loss(y_true: jax.Array, y_pred: jax.Array, data_format: str = "NHWC") -> jax.Array:
    """Layout-aware wrapper (reference: core/losses.py:83-92): squeezes the channel axis
    and runs the per-image hinge. ``y_pred`` are raw logits."""
    if data_format == "NHWC":
        labels = jnp.squeeze(y_true, -1)
        logits = jnp.squeeze(y_pred, -1)
    else:
        labels = jnp.squeeze(y_true, 1)
        logits = jnp.squeeze(y_pred, 1)
    return lovasz_hinge(logits.astype(jnp.float32), labels, per_image=True, ignore=None)


def sigmoid_cross_entropy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Numerically-stable BCE-with-logits; auxiliary loss for classification configs
    (no direct reference analogue — the reference only trains the Lovász objective)."""
    labels = labels.astype(logits.dtype)
    return jnp.mean(jnp.maximum(logits, 0) - logits * labels + jnp.log1p(jnp.exp(-jnp.abs(logits))))


def softmax_cross_entropy_per_example(
    logits: jax.Array, labels: jax.Array, label_smoothing: float = 0.0
) -> jax.Array:
    """Per-example softmax cross entropy with integer labels, shape [B].

    ``label_smoothing`` mixes the one-hot target with the uniform distribution
    (Szegedy et al., arXiv:1512.00567) — the standard ImageNet regularizer
    (0.1 in the 76%-top-1 recipe); 0.0 is plain cross entropy."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    true_logp = jnp.take_along_axis(
        logp, labels[:, None].astype(jnp.int32), axis=-1
    )[:, 0]
    if label_smoothing:
        k = logits.shape[-1]
        # target = (1-s)*onehot + s/k: CE = -(1-s)*logp_true - s/k*sum(logp)
        return -(1.0 - label_smoothing) * true_logp - (
            label_smoothing / k
        ) * jnp.sum(logp, axis=-1)
    return -true_logp


def softmax_cross_entropy(
    logits: jax.Array, labels: jax.Array, label_smoothing: float = 0.0
) -> jax.Array:
    """Mean softmax cross entropy with integer labels, for the classification path the
    reference kept alongside segmentation (reference: core/resnet.py:246-256)."""
    return jnp.mean(
        softmax_cross_entropy_per_example(logits, labels, label_smoothing)
    )
