"""Quantized-compute kernels: real int8 arithmetic on the serve hot path.

PR 6 shipped int8 *storage* — kernels quantize to int8 at export, the traced
graph dequantizes them back to bf16, and every matmul still runs in floating
point. This module closes the gap ROADMAP item 2 names: the arithmetic
itself. An ``int8-compute`` artifact (train/quantize.py spec) routes its
dense/conv layers through these kernels, which

1. **dynamically quantize activations** per-tensor symmetric (scale =
   max|x|/127, zero-point 0 — so the engine's zero-padded bucket rows stay
   exact: padding can never change the max or the quantized zeros),
2. run the matmul/conv as **int8 x int8 -> int32** on the MXU
   (``jnp.dot(..., preferred_element_type=jnp.int32)`` inside a Pallas
   kernel), and
3. fuse the epilogue — ``acc.f32 * (x_scale * w_scale[channel]) + bias``
   then the activation — into the same VMEM-resident pass, reusing
   :func:`ops.pallas_kernels.bias_act_epilogue` so the tail math has one
   home shared with the fused elementwise kernels.

Dispatch policy (same shape as the other Pallas ops): compiled kernels on
TPU behind :func:`pallas_platform_ok`; off-TPU the public wrappers take the
**exact dequantize-f32 XLA fallback** — the same dynamic activation
quantization followed by f32 dequantize-and-matmul. That fallback is also
the parity oracle (`*_reference`): integer accumulation is exact, so the
kernel and the oracle differ only by f32 accumulation rounding, which the
parity tests pin (tests/test_quant_kernels.py). XLA's own int8 dot is
measured ~12x slower than f32 on this CPU backend, so the honest CPU path
is the f32-arithmetic twin, not interpreted integer math; ``interpret=True``
still runs the real integer kernel body for tests, and
:func:`int8_matmul_xla` exposes XLA's genuine int8->int32 arithmetic for
bitwise accumulator-equivalence checks.

The serving integration is :func:`int8_intercept`: a
``flax.linen.intercept_methods`` context that, at serving-closure trace
time, replaces ``nn.Dense`` / stride-1 undilated ``nn.Conv`` calls whose
kernel is an ``{__int8__, q, scale}`` record (train/quantize.py) with the
quantized-compute path. Layers outside that envelope (strided/dilated
convs, grouped convs, custom modules) fall through to the dequantized
float path untouched — partial coverage is explicit, not silent: the
quantize-check gate compares the *composed* artifact against the f32
reference, whatever mix of paths it traced.
"""

from __future__ import annotations

import functools
from collections.abc import Mapping
from contextlib import contextmanager
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tensorflowdistributedlearning_tpu.ops.pallas_kernels import (
    _VMEM_BLOCK_LIMIT_BYTES,
    bias_act_epilogue,
    pallas_platform_ok,
)
from tensorflowdistributedlearning_tpu.parallel.collectives import vma_of

__all__ = [
    "quantize_activations",
    "int8_matmul",
    "int8_matmul_reference",
    "int8_matmul_xla",
    "int8_conv2d",
    "int8_conv2d_reference",
    "int8_intercept",
    "make_int8_interceptor",
]


def quantize_activations(x: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Per-tensor symmetric dynamic quantization: ``(q_int8, scale_f32)``
    with ``scale = max|x|/127`` (1.0 when the tensor is all-zero so nothing
    ever divides by zero) and ``q = clip(round(x/scale), -127, 127)``.

    Zero-point is 0 by construction, which is the property the serving
    engine's bucket padding relies on: appended zero rows quantize to zero,
    contribute exactly zero to every dot product, and cannot move the
    per-tensor max, so a padded batch computes bit-identical results for
    the real rows."""
    xf = x.astype(jnp.float32)
    m = jnp.max(jnp.abs(xf))
    scale = jnp.where(m > 0, m / 127.0, 1.0).astype(jnp.float32)
    q = jnp.clip(jnp.round(xf / scale), -127, 127).astype(jnp.int8)
    return q, scale


def _epilogue(acc_f32, scale_vec, bias, act, out_dtype):
    """int32 accumulator -> output: per-channel scale, then the shared
    bias+act tail. ``scale_vec`` broadcasts over leading dims."""
    return bias_act_epilogue(acc_f32 * scale_vec, bias, act).astype(out_dtype)


# -- int8 matmul --------------------------------------------------------------


def int8_matmul_reference(
    x: jax.Array,
    wq: jax.Array,
    w_scale: jax.Array,
    *,
    bias: Optional[jax.Array] = None,
    act: str = "none",
    out_dtype=None,
) -> jax.Array:
    """Exact dequantize-f32 oracle AND the off-TPU serving fallback: the
    same dynamic activation quantization as the kernel, then f32
    dequantize-and-matmul. Mathematically ``(xq*xs) @ (wq*ws)`` — identical
    to the kernel's ``(xq @ wq) * (xs*ws)`` up to f32 accumulation rounding
    (the integer path is the exact one)."""
    out_dtype = x.dtype if out_dtype is None else out_dtype
    # jnp.asarray FIRST (same contract as dequantize_pytree): a numpy wq
    # would upcast EAGERLY and the exported graph would embed f32 weight
    # constants — 4x the bytes at rest the int8 manifest promises
    wq = jnp.asarray(wq)
    xq, xs = quantize_activations(x)
    xf = xq.astype(jnp.float32) * xs
    wf = wq.astype(jnp.float32) * jnp.asarray(w_scale, jnp.float32)
    acc = xf @ wf
    b32 = None if bias is None else jnp.asarray(bias, jnp.float32)
    return bias_act_epilogue(acc, b32, act).astype(out_dtype)


def int8_matmul_xla(
    x: jax.Array,
    wq: jax.Array,
    w_scale: jax.Array,
    *,
    bias: Optional[jax.Array] = None,
    act: str = "none",
    out_dtype=None,
) -> jax.Array:
    """XLA's genuine int8 x int8 -> int32 arithmetic with the identical
    epilogue — the integer accumulator is bitwise-equal to the Pallas
    kernel's (both are exact), and the f32 tail matches up to FMA fusion
    (last-ulp), used by tests to prove fallback-path equivalence. NOT
    the serving fallback: XLA CPU has no vectorized int8 GEMM (~12x slower
    than f32 here), so the hot path's off-TPU twin is the f32 reference."""
    out_dtype = x.dtype if out_dtype is None else out_dtype
    wq = jnp.asarray(wq)
    xq, xs = quantize_activations(x)
    acc = lax.dot_general(
        xq, wq, (((1,), (0,)), ((), ())), preferred_element_type=jnp.int32
    )
    scale_vec = xs * jnp.asarray(w_scale, jnp.float32)
    b32 = None if bias is None else jnp.asarray(bias, jnp.float32)
    return _epilogue(acc.astype(jnp.float32), scale_vec, b32, act, out_dtype)


def _qmm_kernel(x_ref, w_ref, s_ref, b_ref, o_ref, *, act: str):
    acc = jnp.dot(x_ref[...], w_ref[...], preferred_element_type=jnp.int32)
    y = bias_act_epilogue(acc.astype(jnp.float32) * s_ref[...], b_ref[...], act)
    o_ref[...] = y.astype(o_ref.dtype)


def _n_tile(n: int, fixed_bytes: int, per_n_bytes: int, limit: int) -> int:
    """Largest divisor-of-n output-feature tile whose block set fits VMEM.
    Features are independent columns, so tiling N is free."""
    nt = n
    while nt > 1 and nt % 2 == 0 and fixed_bytes + nt * per_n_bytes > limit:
        nt //= 2
    return nt


def int8_matmul(
    x: jax.Array,
    wq: jax.Array,
    w_scale: jax.Array,
    *,
    bias: Optional[jax.Array] = None,
    act: str = "none",
    out_dtype=None,
    interpret: Optional[bool] = None,
    vmem_limit_bytes: int = _VMEM_BLOCK_LIMIT_BYTES,
) -> jax.Array:
    """Quantized-compute dense layer: dynamic-quantize ``x``, int8 matmul
    against the pre-quantized ``wq`` with per-output-channel ``w_scale``,
    fused scale+bias+act epilogue.

    ``x``: [..., K] float (leading dims flattened for the kernel and
    restored); ``wq``: [K, N] int8; ``w_scale``: [N] f32; ``bias``: [N] or
    ``None``; output [..., N] in ``out_dtype`` (default ``x.dtype``).

    Dispatch: compiled Pallas on TPU (N-tiled when a whole-array block
    overflows the VMEM budget, whole-K always resident); the exact
    dequantize-f32 XLA reference off-TPU, on VMEM overflow, and under
    shard_map's interpreter restriction. ``interpret=True`` runs the real
    integer kernel body interpreted (tests only — slow)."""
    if wq.dtype != jnp.int8:
        raise ValueError(f"wq must be int8, got {wq.dtype}")
    k, n = wq.shape
    if x.shape[-1] != k:
        raise ValueError(f"x last dim {x.shape[-1]} != wq rows {k}")
    if w_scale.shape != (n,):
        raise ValueError(f"w_scale must be [{n}], got {w_scale.shape}")
    if bias is not None and bias.shape != (n,):
        raise ValueError(f"bias must be [{n}], got {bias.shape}")
    out_dtype = x.dtype if out_dtype is None else out_dtype
    if interpret is None:
        interpret = not pallas_platform_ok()
        if interpret:
            return int8_matmul_reference(
                x, wq, w_scale, bias=bias, act=act, out_dtype=out_dtype
            )
    if interpret and vma_of(x):
        return int8_matmul_reference(
            x, wq, w_scale, bias=bias, act=act, out_dtype=out_dtype
        )
    lead = x.shape[:-1]
    m = 1
    for d in lead:
        m *= d
    # block budget: xq [m,k]i8 + wq [k,nt]i8 + acc/out [m,nt]f32 + vectors
    fixed = m * k
    per_n = k + m * 4 + 8
    nt = _n_tile(n, fixed, per_n, vmem_limit_bytes)
    if fixed + nt * per_n > vmem_limit_bytes:
        return int8_matmul_reference(
            x, wq, w_scale, bias=bias, act=act, out_dtype=out_dtype
        )
    wq = jnp.asarray(wq)
    xq, xs = quantize_activations(x)
    xq2 = xq.reshape(m, k)
    scale_vec = (xs * jnp.asarray(w_scale, jnp.float32)).reshape(1, n)
    b32 = (
        jnp.zeros((1, n), jnp.float32)
        if bias is None
        else jnp.asarray(bias, jnp.float32).reshape(1, n)
    )
    vma = vma_of(x)
    out_shape = (
        jax.ShapeDtypeStruct((m, n), out_dtype, vma=vma)
        if vma
        else jax.ShapeDtypeStruct((m, n), out_dtype)
    )
    out = pl.pallas_call(
        functools.partial(_qmm_kernel, act=act),
        grid=(n // nt,),
        in_specs=[
            pl.BlockSpec((m, k), lambda j: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((k, nt), lambda j: (0, j), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, nt), lambda j: (0, j), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, nt), lambda j: (0, j), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((m, nt), lambda j: (0, j), memory_space=pltpu.VMEM),
        out_shape=out_shape,
        interpret=interpret,
    )(xq2, wq, scale_vec, b32)
    return out.reshape(*lead, n)


# -- int8 conv2d (stride-1, undilated) ----------------------------------------


def _conv_pads(padding, kh: int, kw: int) -> Optional[Tuple]:
    """Normalize a SAME/VALID/explicit padding spec to ((lo,hi),(lo,hi)) for
    a stride-1 undilated conv; None = unsupported (caller falls back)."""
    if isinstance(padding, str):
        p = padding.upper()
        if p == "VALID":
            return ((0, 0), (0, 0))
        if p == "SAME":
            # stride-1 SAME: total pad k-1, split low-first like XLA
            return (
                ((kh - 1) // 2, kh // 2),
                ((kw - 1) // 2, kw // 2),
            )
        return None
    try:
        (a, b), (c, d) = ((p[0], p[1]) for p in padding)
    except (TypeError, ValueError, IndexError):
        return None
    if min(a, b, c, d) < 0:
        return None
    return ((int(a), int(b)), (int(c), int(d)))


def int8_conv2d_reference(
    x: jax.Array,
    wq: jax.Array,
    w_scale: jax.Array,
    *,
    padding="SAME",
    bias: Optional[jax.Array] = None,
    act: str = "none",
    out_dtype=None,
) -> jax.Array:
    """Exact dequantize-f32 oracle/fallback for the stride-1 undilated conv:
    same dynamic activation quantization, f32 dequantize, XLA conv.
    ``x``: [B, H, W, Cin]; ``wq``: [kh, kw, Cin, Cout] int8; ``w_scale``:
    [Cout]."""
    kh, kw, _, _ = wq.shape
    pads = _conv_pads(padding, kh, kw)
    if pads is None:
        raise ValueError(f"unsupported padding spec {padding!r}")
    out_dtype = x.dtype if out_dtype is None else out_dtype
    # jnp.asarray FIRST — see int8_matmul_reference: numpy weights would
    # constant-fold the dequantize and serialize f32 bytes
    wq = jnp.asarray(wq)
    xq, xs = quantize_activations(x)
    xf = xq.astype(jnp.float32) * xs
    wf = wq.astype(jnp.float32) * jnp.asarray(w_scale, jnp.float32)
    acc = lax.conv_general_dilated(
        xf,
        wf,
        window_strides=(1, 1),
        padding=list(pads),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    b32 = None if bias is None else jnp.asarray(bias, jnp.float32)
    return bias_act_epilogue(acc, b32, act).astype(out_dtype)


def _qconv_kernel(
    x_ref, w_ref, s_ref, b_ref, o_ref, *, kh: int, kw: int, act: str
):
    """One image per grid step: shift-and-matmul over the kh*kw taps, int32
    accumulation on the MXU, fused epilogue. ``x_ref``: pre-padded
    [1, H+ph, W+pw, Cin] int8; ``o_ref``: [1, H, W, Cout]."""
    xp = x_ref[0]
    cin = xp.shape[-1]
    _, h, wd, cout = o_ref.shape
    acc = jnp.zeros((h * wd, cout), jnp.int32)
    for i in range(kh):
        for j in range(kw):
            tap = lax.slice(xp, (i, j, 0), (i + h, j + wd, cin))
            acc = acc + jnp.dot(
                tap.reshape(h * wd, cin),
                w_ref[i, j],
                preferred_element_type=jnp.int32,
            )
    y = bias_act_epilogue(acc.astype(jnp.float32) * s_ref[...], b_ref[...], act)
    o_ref[0] = y.reshape(h, wd, cout).astype(o_ref.dtype)


def int8_conv2d(
    x: jax.Array,
    wq: jax.Array,
    w_scale: jax.Array,
    *,
    padding="SAME",
    bias: Optional[jax.Array] = None,
    act: str = "none",
    out_dtype=None,
    interpret: Optional[bool] = None,
    vmem_limit_bytes: int = _VMEM_BLOCK_LIMIT_BYTES,
) -> jax.Array:
    """Quantized-compute stride-1 undilated conv: dynamic-quantize ``x``,
    int8 direct convolution (shift-and-matmul over the kh*kw taps, the same
    decomposition the depthwise kernel uses, but with an MXU contraction
    over Cin), fused scale+bias+act epilogue.

    ``x``: [B, H, W, Cin] float; ``wq``: [kh, kw, Cin, Cout] int8;
    ``w_scale``: [Cout] f32; ``padding``: SAME/VALID/explicit pairs.
    Strided or dilated convs are out of envelope by design — the
    interceptor routes those layers through the dequantized float path.

    Dispatch: compiled Pallas on TPU; the exact dequantize-f32 XLA
    reference off-TPU, on VMEM overflow, and under shard_map's interpreter
    restriction. The zero-padding the conv itself applies is exact under
    symmetric quantization (zero-point 0), so padding before or after
    quantizing is the same arithmetic."""
    if wq.dtype != jnp.int8:
        raise ValueError(f"wq must be int8, got {wq.dtype}")
    if x.ndim != 4 or wq.ndim != 4:
        raise ValueError(
            f"int8_conv2d expects x [B,H,W,Cin] and wq [kh,kw,Cin,Cout], "
            f"got {x.shape} and {wq.shape}"
        )
    kh, kw, cin, cout = wq.shape
    if x.shape[-1] != cin:
        raise ValueError(f"x channels {x.shape[-1]} != wq Cin {cin}")
    if w_scale.shape != (cout,):
        raise ValueError(f"w_scale must be [{cout}], got {w_scale.shape}")
    if bias is not None and bias.shape != (cout,):
        raise ValueError(f"bias must be [{cout}], got {bias.shape}")
    pads = _conv_pads(padding, kh, kw)
    if pads is None:
        raise ValueError(f"unsupported padding spec {padding!r}")
    out_dtype = x.dtype if out_dtype is None else out_dtype
    if interpret is None:
        interpret = not pallas_platform_ok()
        if interpret:
            return int8_conv2d_reference(
                x, wq, w_scale, padding=pads, bias=bias, act=act,
                out_dtype=out_dtype,
            )
    if interpret and vma_of(x):
        return int8_conv2d_reference(
            x, wq, w_scale, padding=pads, bias=bias, act=act,
            out_dtype=out_dtype,
        )
    b, h, wd, _ = x.shape
    (pt, pb), (pl_, pr) = pads
    ho = h + pt + pb - (kh - 1)
    wo = wd + pl_ + pr - (kw - 1)
    if ho <= 0 or wo <= 0:
        return int8_conv2d_reference(
            x, wq, w_scale, padding=pads, bias=bias, act=act,
            out_dtype=out_dtype,
        )
    hp, wp = h + pt + pb, wd + pl_ + pr
    # block budget: padded image i8 + filter i8 + int32 acc + f32 out
    block_bytes = (
        hp * wp * cin + kh * kw * cin * cout + ho * wo * cout * 8
    )
    if block_bytes > vmem_limit_bytes:
        return int8_conv2d_reference(
            x, wq, w_scale, padding=pads, bias=bias, act=act,
            out_dtype=out_dtype,
        )
    wq = jnp.asarray(wq)
    xq, xs = quantize_activations(x)
    xqp = jnp.pad(xq, ((0, 0), (pt, pb), (pl_, pr), (0, 0)))
    scale_vec = (xs * jnp.asarray(w_scale, jnp.float32)).reshape(1, cout)
    b32 = (
        jnp.zeros((1, cout), jnp.float32)
        if bias is None
        else jnp.asarray(bias, jnp.float32).reshape(1, cout)
    )
    vma = vma_of(x)
    out_shape = (
        jax.ShapeDtypeStruct((b, ho, wo, cout), out_dtype, vma=vma)
        if vma
        else jax.ShapeDtypeStruct((b, ho, wo, cout), out_dtype)
    )
    return pl.pallas_call(
        functools.partial(_qconv_kernel, kh=kh, kw=kw, act=act),
        grid=(b,),
        in_specs=[
            pl.BlockSpec(
                (1, hp, wp, cin), lambda i: (i, 0, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (kh, kw, cin, cout), lambda i: (0, 0, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec((1, cout), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, cout), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (1, ho, wo, cout), lambda i: (i, 0, 0, 0), memory_space=pltpu.VMEM
        ),
        out_shape=out_shape,
        interpret=interpret,
    )(xqp, wq, scale_vec, b32)


# -- the serving-closure interceptor ------------------------------------------

# train/quantize.py's record marker, duplicated here (not imported) so this
# module never imports train/ — ops stays a leaf package
_QKEY = "__int8__"


def _is_quant_record(node) -> bool:
    return isinstance(node, Mapping) and _QKEY in node


def _lookup(tree, path) -> Optional[Any]:
    node = tree
    for key in path:
        if not isinstance(node, Mapping) or key not in node:
            return None
        node = node[key]
    return node


def _norm_pair(v, default=1) -> Optional[Tuple[int, int]]:
    if v is None:
        v = default
    if isinstance(v, int):
        return (v, v)
    try:
        t = tuple(int(e) for e in v)
    except (TypeError, ValueError):
        return None
    return t if len(t) == 2 else None


def make_int8_interceptor(qparams, act_dtype=jnp.bfloat16):
    """Build the ``nn.intercept_methods`` interceptor that routes quantized
    layers through the int8-compute kernels.

    ``qparams`` is the quantize_pytree output (records still in place, leaves
    already jnp arrays so the int8 constants are SHARED with any
    dequantize_pytree call on the same tree — one constant in the exported
    graph, not two). For each ``nn.Dense`` / supported ``nn.Conv`` whose
    params-tree path holds an ``{__int8__, q, scale}`` kernel record, the
    module's ``__call__`` is replaced by the quantized-compute path (bias
    fused into the kernel epilogue). Everything else — including convs
    outside the stride-1 undilated feature_group_count=1 envelope — falls
    through to ``next_fun`` untouched, i.e. the PR-6 dequantized float path.
    """
    from flax import linen as nn

    def intercept(next_fun, args, kwargs, context):
        mod = context.module
        if context.method_name != "__call__" or not args:
            return next_fun(*args, **kwargs)
        if not isinstance(mod, (nn.Dense, nn.Conv)):
            return next_fun(*args, **kwargs)
        node = _lookup(qparams, tuple(mod.path))
        if not isinstance(node, Mapping):
            return next_fun(*args, **kwargs)
        rec = node.get("kernel")
        if not _is_quant_record(rec):
            return next_fun(*args, **kwargs)
        x = args[0]
        wq, w_scale = rec["q"], rec["scale"]
        bias = node.get("bias") if mod.use_bias else None
        if isinstance(mod, nn.Dense):
            return int8_matmul(
                x, wq, w_scale, bias=bias, act="none", out_dtype=act_dtype
            )
        # nn.Conv: only the 2-D stride-1 undilated ungrouped case
        if wq.ndim != 4 or x.ndim != 4 or mod.feature_group_count != 1:
            return next_fun(*args, **kwargs)
        if _norm_pair(mod.strides) != (1, 1):
            return next_fun(*args, **kwargs)
        if _norm_pair(mod.kernel_dilation) != (1, 1):
            return next_fun(*args, **kwargs)
        if _norm_pair(getattr(mod, "input_dilation", None)) != (1, 1):
            return next_fun(*args, **kwargs)
        kh, kw = wq.shape[0], wq.shape[1]
        if _conv_pads(mod.padding, kh, kw) is None:
            return next_fun(*args, **kwargs)
        return int8_conv2d(
            x,
            wq,
            w_scale,
            padding=mod.padding,
            bias=bias,
            act="none",
            out_dtype=act_dtype,
        )

    return intercept


@contextmanager
def int8_intercept(qparams, act_dtype=jnp.bfloat16):
    """Context manager the serving closures trace under: inside it, flax
    module applications route quantized dense/conv layers through the
    int8-compute kernels. Tracing under jit is exactly the intended use —
    the kernels (or their fallback) are baked into the exported graph."""
    from flax import linen as nn

    with nn.intercept_methods(make_int8_interceptor(qparams, act_dtype)):
        yield
