"""Pallas TPU kernels for the framework's hot custom ops.

Second kernel: **fused inference BatchNorm + activation (+ residual add)** —
the serving-side answer to the step profile's dominant bucket
(PROFILE_SEG_r05.json: 53.2% of serialized device time in bandwidth-bound
elementwise/BN fusions). At inference BN is an affine per-channel transform
(running statistics are constants), so the whole
``BN -> (+residual) -> activation`` chain is one read and one write of the
activation tensor at the HBM roofline. :func:`fused_bn_act` folds the four BN
vectors into a per-channel multiplier/offset in XLA (a [C]-sized epsilon of
work) and runs the memory-bound part as a single VMEM-resident Pallas pass;
:func:`fused_bn_act_reference` is the XLA oracle and the off-TPU/VMEM-overflow
fallback. Inference-only by design — training BN needs batch statistics and a
VJP, which the flax path already owns.

First kernel: **depthwise (per-channel) 2-D convolution**, the core of the
split-separable convolutions the ASPP head runs at atrous rates 2/4/8 and the
decoder runs at rate 1 (reference: core/layers.py:7-49 built these from
``slim.separable_conv2d``; SURVEY §3.3). On TPU the depthwise stage is VPU-bound —
XLA lowers it as a grouped convolution, while this kernel computes it directly as
``kh*kw`` shifted multiply-accumulates over a VMEM-resident block with channels on
the 128-wide lane dimension, the natural TPU layout.

The kernel is stride-1 SAME with dilation (atrous) support — exactly the shapes the
models use. Gradients are provided by a ``jax.custom_vjp``: dx is the same kernel
applied with a spatially-flipped filter; dw is nine cheap XLA reductions. A pure-XLA
reference (`depthwise_conv2d_reference`) doubles as the numerical oracle in tests
and the fallback when the image block exceeds the VMEM budget.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tensorflowdistributedlearning_tpu.parallel.collectives import vma_of

# One image block (padded H x W x C fp32) must fit comfortably in the ~16 MB VMEM
# alongside double-buffering; beyond this the public wrapper falls back to XLA.
_VMEM_BLOCK_LIMIT_BYTES = 4 * 1024 * 1024

# Measured on a v5e chip under the DEVICE-DOMINATED protocol
# (bench_kernels.py `_chained` + interleaved median-of-ratios, 2026-08-01,
# ASPP shape [32, 13, 13, 1024]): Pallas vs XLA grouped conv — rate 1:
# 1.51x, rate 2: 1.46x, rate 4: 1.56x, rate 8: 1.61x. The shift-accumulate
# VMEM kernel is rate-independent (~4.6 ms/chained-kernel) while XLA's
# grouped-conv lowering sits at ~7.3 ms at every rate. The old threshold of
# 4 came from per-call windows that were 97%+ tunnel dispatch latency for
# sub-ms device work — those "XLA wins below rate 4" columns (0.71-0.90x)
# were dispatch noise, later swinging to 2.8x in other windows; the chained
# protocol cancels it. Models gate their Pallas dispatch on this threshold
# (models/layers.py:DepthwiseConv2D); 1 = every rate takes the kernel.
PALLAS_DEPTHWISE_MIN_RATE = 1


def pallas_platform_ok() -> bool:
    """True where the Pallas kernels run COMPILED (TPU); elsewhere they only
    have the slow interpreter. The ONE copy of this decision — the layer
    dispatch gate (models/layers.py:DepthwiseConv2D) and the interpret
    auto-selects of BOTH kernels (this module and ops/flash_attention.py)
    consult it, so they can never disagree."""
    return jax.default_backend() == "tpu"


def depthwise_conv2d_reference(
    x: jax.Array, w: jax.Array, rate: int = 1
) -> jax.Array:
    """XLA oracle/fallback: stride-1 SAME depthwise conv via grouped convolution.

    ``x``: [B, H, W, C]; ``w``: [kh, kw, C] per-channel filters.
    """
    kh, kw, c = w.shape
    kernel = w.reshape(kh, kw, 1, c)  # HWIO with I=1, feature_group_count=C
    pad_h = rate * (kh - 1) // 2
    pad_w = rate * (kw - 1) // 2
    return lax.conv_general_dilated(
        x,
        kernel.astype(x.dtype),
        window_strides=(1, 1),
        padding=[(pad_h, pad_h), (pad_w, pad_w)],
        rhs_dilation=(rate, rate),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=c,
    )


def _dw_kernel(x_ref, w_ref, o_ref, *, kh: int, kw: int, rate: int):
    """One image per grid step: out = sum_ij w[i,j] * shift(x, (i,j))."""
    x = x_ref[0]  # [H, W, C]
    h, wdt, _ = x.shape
    ph = rate * (kh - 1) // 2
    pw = rate * (kw - 1) // 2
    xp = jnp.pad(x, ((ph, ph), (pw, pw), (0, 0)))
    acc = jnp.zeros(x.shape, jnp.float32)
    for i in range(kh):
        for j in range(kw):
            tap = lax.slice(
                xp, (i * rate, j * rate, 0), (i * rate + h, j * rate + wdt, xp.shape[2])
            )
            acc = acc + tap.astype(jnp.float32) * w_ref[i, j].astype(jnp.float32)
    o_ref[0] = acc.astype(o_ref.dtype)


def _channel_tile(c: int, block_elems: int, limit_bytes: int, itemsize: int) -> int:
    """Largest lane-aligned channel tile whose padded image block fits the VMEM
    budget. Channels are independent in a depthwise conv, so tiling C is free."""
    if c % 128 != 0:
        return c  # Mosaic pads the lane dim; only whole-C blocks possible
    ct = c
    while ct > 128 and block_elems * ct * itemsize > limit_bytes:
        ct //= 2
        while c % ct != 0 and ct > 128:
            ct -= 128
    return max(ct, 128)


def _dw_pallas(
    x: jax.Array, w: jax.Array, rate: int, interpret: bool, channel_tile: int
) -> jax.Array:
    b, h, wdt, c = x.shape
    kh, kw, _ = w.shape
    ct = channel_tile
    kernel = functools.partial(_dw_kernel, kh=kh, kw=kw, rate=rate)
    # Inside shard_map with check_vma, the out aval must declare how it varies
    # across mesh axes — the output varies exactly like the input block.
    vma = vma_of(x)
    out_shape = (
        jax.ShapeDtypeStruct(x.shape, x.dtype, vma=vma)
        if vma
        else jax.ShapeDtypeStruct(x.shape, x.dtype)
    )
    return pl.pallas_call(
        kernel,
        grid=(b, c // ct),
        in_specs=[
            pl.BlockSpec(
                (1, h, wdt, ct), lambda i, j: (i, 0, 0, j), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec((kh, kw, ct), lambda i, j: (0, 0, j), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (1, h, wdt, ct), lambda i, j: (i, 0, 0, j), memory_space=pltpu.VMEM
        ),
        out_shape=out_shape,
        interpret=interpret,
    )(x, w)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _dw_with_grad(
    x: jax.Array, w: jax.Array, rate: int, interpret: bool, channel_tile: int
) -> jax.Array:
    return _dw_pallas(x, w, rate, interpret, channel_tile)


def _dw_fwd(x, w, rate, interpret, channel_tile):
    return _dw_pallas(x, w, rate, interpret, channel_tile), (x, w)


def _dw_bwd(rate, interpret, channel_tile, res, g):
    x, w = res
    # dx: correlate the cotangent with the spatially flipped filter — for stride-1
    # SAME with symmetric padding this is again a depthwise conv (same kernel).
    dx = _dw_pallas(g, w[::-1, ::-1, :], rate, interpret, channel_tile).astype(x.dtype)
    # dw[i, j, c] = sum_{b,y,x} g * shift(x): nine reductions, left to XLA.
    kh, kw, _ = w.shape
    h, wdt = x.shape[1], x.shape[2]
    ph = rate * (kh - 1) // 2
    pw = rate * (kw - 1) // 2
    xp = jnp.pad(x, ((0, 0), (ph, ph), (pw, pw), (0, 0)))
    g32 = g.astype(jnp.float32)
    taps = []
    for i in range(kh):
        row = []
        for j in range(kw):
            tap = lax.slice(
                xp,
                (0, i * rate, j * rate, 0),
                (x.shape[0], i * rate + h, j * rate + wdt, x.shape[3]),
            )
            row.append(jnp.sum(tap.astype(jnp.float32) * g32, axis=(0, 1, 2)))
        taps.append(jnp.stack(row))
    dw = jnp.stack(taps).astype(w.dtype)
    # Inside shard_map, custom_vjp must hand back cotangents whose varying manual
    # axes match the primal inputs. dw is built from varying activations, so when
    # the weight itself is replicated it needs the cross-shard psum the automatic
    # transposition would have inserted for a standard primitive.
    extra = tuple(sorted(vma_of(g) - vma_of(w)))
    if extra:
        dw = lax.psum(dw, extra)
    return dx, dw


_dw_with_grad.defvjp(_dw_fwd, _dw_bwd)


def depthwise_conv2d(
    x: jax.Array,
    w: jax.Array,
    rate: int = 1,
    *,
    interpret: Optional[bool] = None,
    vmem_limit_bytes: int = _VMEM_BLOCK_LIMIT_BYTES,
) -> jax.Array:
    """Stride-1 SAME depthwise conv, Pallas-accelerated where it fits.

    ``x``: [B, H, W, C]; ``w``: [kh, kw, C]; ``rate``: atrous dilation. Odd kernel
    dims required. Differentiable (custom VJP). ``interpret=None`` auto-selects:
    the Pallas path on TPU, the interpreter off-TPU (so tests exercise the same
    kernel code on the CPU mesh). Falls back to the XLA grouped-conv reference when
    one padded image block would not fit the VMEM budget.
    """
    kh, kw, c = w.shape
    if kh % 2 != 1 or kw % 2 != 1:
        raise ValueError(f"depthwise_conv2d requires odd kernel dims, got {kh}x{kw}")
    if x.shape[-1] != c:
        raise ValueError(f"channel mismatch: x has {x.shape[-1]}, w has {c}")
    ph = rate * (kh - 1)
    pw = rate * (kw - 1)
    block_elems = (x.shape[1] + ph) * (x.shape[2] + pw)
    itemsize = jnp.dtype(x.dtype).itemsize
    ct = _channel_tile(c, block_elems, vmem_limit_bytes, itemsize)
    if block_elems * ct * itemsize > vmem_limit_bytes:
        # even a single 128-lane tile (or an unsplittable C) is too large spatially
        return depthwise_conv2d_reference(x, w, rate)
    if interpret is None:
        interpret = not pallas_platform_ok()
    if interpret and vma_of(x):
        # Pallas's HLO interpreter cannot run under shard_map's varying-manual-axes
        # tracking (its internal dynamic_slice mixes varying/unvarying operands and
        # jax rejects it). Only the off-TPU debug path is affected — on TPU the
        # kernel lowers through Mosaic, not the interpreter.
        return depthwise_conv2d_reference(x, w, rate)
    return _dw_with_grad(x, w, rate, interpret, ct)


# -- fused inference BN + activation (+ residual) ----------------------------

# the activations the models' BN chains end in; "none" covers the pre-residual
# projection case where the add itself is the last op
_BN_ACTIVATIONS = {
    "none": lambda y: y,
    "relu": lambda y: jnp.maximum(y, 0.0),
    "relu6": lambda y: jnp.clip(y, 0.0, 6.0),
    "sigmoid": jax.nn.sigmoid,
    "gelu": jax.nn.gelu,
}


def bias_act_epilogue(y, bias=None, act: str = "none"):
    """The shared f32 epilogue: ``act(y + bias)``. ONE copy of the
    bias-then-activate tail used by :func:`fused_bias_act`'s kernel body,
    both quant-kernel epilogues (ops/quant_kernels.py fuses it after the
    int32->f32 scale application), and their XLA references — so a kernel
    and its oracle can never disagree about the tail math. ``y`` is f32;
    ``bias`` broadcasts over the leading dims (``None`` skips the add)."""
    if act not in _BN_ACTIVATIONS:
        raise ValueError(f"act {act!r} not in {sorted(_BN_ACTIVATIONS)}")
    if bias is not None:
        y = y + bias
    return _BN_ACTIVATIONS[act](y)


def _fold_bn(scale, bias, mean, var, eps):
    """Inference BN as per-channel affine: ``y = x*m + b`` with
    ``m = scale*rsqrt(var+eps)``, ``b = bias - mean*m``. Folded in float32 —
    a [C]-sized computation, numerically the safest place to spend f32."""
    inv = lax.rsqrt(var.astype(jnp.float32) + jnp.float32(eps))
    m = scale.astype(jnp.float32) * inv
    b = bias.astype(jnp.float32) - mean.astype(jnp.float32) * m
    return m, b


def fused_bn_act_reference(
    x: jax.Array,
    scale: jax.Array,
    bias: jax.Array,
    mean: jax.Array,
    var: jax.Array,
    *,
    eps: float = 1e-3,
    act: str = "relu",
    residual: Optional[jax.Array] = None,
) -> jax.Array:
    """XLA oracle/fallback: ``act((x - mean)/sqrt(var+eps)*scale + bias
    [+ residual])`` with f32 internal math, output in ``x``'s dtype."""
    if act not in _BN_ACTIVATIONS:
        raise ValueError(
            f"act {act!r} not in {sorted(_BN_ACTIVATIONS)}"
        )
    m, b = _fold_bn(scale, bias, mean, var, eps)
    y = x.astype(jnp.float32) * m + b
    if residual is not None:
        y = y + residual.astype(jnp.float32)
    return _BN_ACTIVATIONS[act](y).astype(x.dtype)


def _bn_act_kernel(x_ref, m_ref, b_ref, o_ref, *, act: str):
    y = x_ref[0].astype(jnp.float32) * m_ref[0] + b_ref[0]
    o_ref[0] = _BN_ACTIVATIONS[act](y).astype(o_ref.dtype)


def _bn_act_res_kernel(x_ref, m_ref, b_ref, r_ref, o_ref, *, act: str):
    y = x_ref[0].astype(jnp.float32) * m_ref[0] + b_ref[0]
    y = y + r_ref[0].astype(jnp.float32)
    o_ref[0] = _BN_ACTIVATIONS[act](y).astype(o_ref.dtype)


def fused_bn_act(
    x: jax.Array,
    scale: jax.Array,
    bias: jax.Array,
    mean: jax.Array,
    var: jax.Array,
    *,
    eps: float = 1e-3,
    act: str = "relu",
    residual: Optional[jax.Array] = None,
    interpret: Optional[bool] = None,
    vmem_limit_bytes: int = _VMEM_BLOCK_LIMIT_BYTES,
) -> jax.Array:
    """Fused inference BN + activation (+ residual add), Pallas where it fits.

    ``x``: [B, H, W, C] activations (channels on the 128-lane dim, the
    natural TPU layout); ``scale``/``bias``/``mean``/``var``: [C] running BN
    parameters; ``residual``: optional [B, H, W, C] skip input added before
    the activation. One grid step handles one image (channel-tiled like the
    depthwise kernel when an image block overflows the VMEM budget); the BN
    fold happens once in XLA outside the kernel, so the kernel body is
    exactly the HBM-roofline pass: read x (+residual), multiply-add,
    activate, write.

    INFERENCE-ONLY: no custom VJP — serving graphs never differentiate it.
    ``interpret=None`` auto-selects compiled Pallas on TPU and the
    interpreter off-TPU (tests); falls back to the XLA reference when the
    image block exceeds the VMEM budget or under shard_map's interpreter
    restriction (same policy as ``depthwise_conv2d``).
    """
    if act not in _BN_ACTIVATIONS:
        raise ValueError(f"act {act!r} not in {sorted(_BN_ACTIVATIONS)}")
    if x.ndim != 4:
        raise ValueError(f"fused_bn_act expects [B, H, W, C], got {x.shape}")
    c = x.shape[-1]
    for name, v in (("scale", scale), ("bias", bias), ("mean", mean), ("var", var)):
        if v.shape != (c,):
            raise ValueError(
                f"{name} must be [{c}] to match x's channels, got {v.shape}"
            )
    if residual is not None and residual.shape != x.shape:
        raise ValueError(
            f"residual shape {residual.shape} != x shape {x.shape}"
        )
    b_, h, wdt, _ = x.shape
    itemsize = jnp.dtype(x.dtype).itemsize
    # the block must hold x (and the residual, when present) simultaneously
    block_elems = h * wdt * (2 if residual is not None else 1)
    ct = _channel_tile(c, block_elems, vmem_limit_bytes, itemsize)
    if block_elems * ct * itemsize > vmem_limit_bytes:
        return fused_bn_act_reference(
            x, scale, bias, mean, var, eps=eps, act=act, residual=residual
        )
    if interpret is None:
        interpret = not pallas_platform_ok()
    if interpret and vma_of(x):
        # same interpreter-under-shard_map restriction as the depthwise kernel
        return fused_bn_act_reference(
            x, scale, bias, mean, var, eps=eps, act=act, residual=residual
        )
    m, b = _fold_bn(scale, bias, mean, var, eps)
    # 2-D [1, C] so the per-channel vectors land on the lane dimension
    m2, b2 = m.reshape(1, c), b.reshape(1, c)
    vma = vma_of(x)
    out_shape = (
        jax.ShapeDtypeStruct(x.shape, x.dtype, vma=vma)
        if vma
        else jax.ShapeDtypeStruct(x.shape, x.dtype)
    )
    x_spec = pl.BlockSpec(
        (1, h, wdt, ct), lambda i, j: (i, 0, 0, j), memory_space=pltpu.VMEM
    )
    chan_spec = pl.BlockSpec(
        (1, ct), lambda i, j: (0, j), memory_space=pltpu.VMEM
    )
    if residual is None:
        kernel = functools.partial(_bn_act_kernel, act=act)
        in_specs = [x_spec, chan_spec, chan_spec]
        operands = (x, m2, b2)
    else:
        kernel = functools.partial(_bn_act_res_kernel, act=act)
        in_specs = [x_spec, chan_spec, chan_spec, x_spec]
        operands = (x, m2, b2, residual)
    return pl.pallas_call(
        kernel,
        grid=(b_, c // ct),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, h, wdt, ct), lambda i, j: (i, 0, 0, j), memory_space=pltpu.VMEM
        ),
        out_shape=out_shape,
        interpret=interpret,
    )(*operands)


# -- fused bias + activation (the reusable epilogue) --------------------------


def fused_bias_act_reference(
    x: jax.Array, bias: Optional[jax.Array] = None, *, act: str = "none"
) -> jax.Array:
    """XLA oracle/fallback: ``act(x + bias)`` with f32 internal math, output
    in ``x``'s dtype. ``bias``: [C] over the last axis (or ``None``)."""
    y = bias_act_epilogue(
        x.astype(jnp.float32),
        None if bias is None else bias.astype(jnp.float32),
        act,
    )
    return y.astype(x.dtype)


def _fused_bias_act_kernel(x_ref, b_ref, o_ref, *, act: str):
    y = bias_act_epilogue(x_ref[...].astype(jnp.float32), b_ref[...], act)
    o_ref[...] = y.astype(o_ref.dtype)


def fused_sigmoid_mask_reference(
    logits: jax.Array, threshold: float
) -> tuple:
    """XLA oracle/fallback — literally the unfused segmentation head
    (train/step.py SegmentationTask.predictions): probabilities in the
    logits dtype, binary mask as float32. The fused kernel must stay
    BIT-IDENTICAL to this, so the ops here are the contract."""
    probs = jax.nn.sigmoid(logits)
    return probs, (probs > threshold).astype(jnp.float32)


def _sigmoid_mask_kernel(x_ref, p_ref, m_ref, *, threshold: float):
    # the same two ops as the reference, in the same dtype — one HBM read
    # feeding BOTH outputs is the entire win; any "optimization" of the
    # math here would break the bit-identity contract
    p = jax.nn.sigmoid(x_ref[...])
    p_ref[...] = p
    m_ref[...] = (p > threshold).astype(jnp.float32)


def fused_bias_act(
    x: jax.Array,
    bias: Optional[jax.Array] = None,
    *,
    act: str = "none",
    interpret: Optional[bool] = None,
    vmem_limit_bytes: int = _VMEM_BLOCK_LIMIT_BYTES,
) -> jax.Array:
    """Fused per-channel bias + activation over the last axis, Pallas where
    it fits: one read and one write of ``x`` instead of XLA's
    add-then-activate pair when the fusion boundary splits them. This is the
    standalone face of :func:`bias_act_epilogue` — the quantized matmul/conv
    kernels (ops/quant_kernels.py) fuse the identical tail after their
    int32->f32 scale application, so the epilogue math has exactly one home.

    ``x``: [..., C]; ``bias``: [C] or ``None``. INFERENCE-ONLY (no VJP).
    ``interpret=None`` auto-selects compiled Pallas on TPU and the XLA
    reference off-TPU (the interpreter is for tests, not the hot path);
    falls back to the reference when a row block exceeds the VMEM budget or
    under shard_map's interpreter restriction.
    """
    if act not in _BN_ACTIVATIONS:
        raise ValueError(f"act {act!r} not in {sorted(_BN_ACTIVATIONS)}")
    c = x.shape[-1]
    if bias is not None and bias.shape != (c,):
        raise ValueError(f"bias must be [{c}] to match x's last axis, got {bias.shape}")
    if x.ndim < 2:
        return fused_bias_act_reference(x, bias, act=act)
    if interpret is None:
        interpret = not pallas_platform_ok()
        if interpret:
            return fused_bias_act_reference(x, bias, act=act)
    if interpret and vma_of(x):
        return fused_bias_act_reference(x, bias, act=act)
    x2 = x.reshape(-1, c)
    r = x2.shape[0]
    itemsize = jnp.dtype(x.dtype).itemsize
    rt = r
    while rt > 1 and rt % 2 == 0 and rt * c * (itemsize + 4) > vmem_limit_bytes:
        rt //= 2
    if rt * c * (itemsize + 4) > vmem_limit_bytes:
        return fused_bias_act_reference(x, bias, act=act)
    b32 = (
        jnp.zeros((1, c), jnp.float32)
        if bias is None
        else bias.astype(jnp.float32).reshape(1, c)
    )
    vma = vma_of(x)
    out_shape = (
        jax.ShapeDtypeStruct(x2.shape, x.dtype, vma=vma)
        if vma
        else jax.ShapeDtypeStruct(x2.shape, x.dtype)
    )
    out = pl.pallas_call(
        functools.partial(_fused_bias_act_kernel, act=act),
        grid=(r // rt,),
        in_specs=[
            pl.BlockSpec((rt, c), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, c), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((rt, c), lambda i: (i, 0), memory_space=pltpu.VMEM),
        out_shape=out_shape,
        interpret=interpret,
    )(x2, b32)
    return out.reshape(x.shape)


# -- fused sigmoid + threshold mask head --------------------------------------


def fused_sigmoid_mask(
    logits: jax.Array,
    threshold: float,
    *,
    interpret: Optional[bool] = None,
    vmem_limit_bytes: int = _VMEM_BLOCK_LIMIT_BYTES,
) -> tuple:
    """Fused segmentation serve head: ``(sigmoid(logits),
    (sigmoid(logits) > threshold).float32)`` from ONE pass over the logits.

    The unfused head reads the logits to build probs, writes probs, then
    reads probs again to build the mask — three HBM traversals of an
    [B, H, W, 1] tensor for two elementwise ops. The kernel reads each
    logits block once and emits both outputs while it is VMEM-resident.

    BIT-IDENTITY CONTRACT: outputs are bitwise equal to
    :func:`fused_sigmoid_mask_reference` (the literal unfused ops, which is
    what SegmentationTask.predictions computes) — the kernel runs the same
    sigmoid in the same dtype, so fusing is a memory-traffic change, not a
    numerics change. Enforced by tests/test_pallas_kernels.py.

    INFERENCE-ONLY (no VJP). ``interpret=None`` auto-selects compiled
    Pallas on TPU and the XLA reference off-TPU; ``interpret=True`` runs
    the kernel body interpreted (tests). Falls back to the reference when
    an image block exceeds the VMEM budget, for rank<2 inputs, or under
    shard_map's interpreter restriction.
    """
    if logits.ndim < 2:
        return fused_sigmoid_mask_reference(logits, threshold)
    if interpret is None:
        interpret = not pallas_platform_ok()
        if interpret:
            return fused_sigmoid_mask_reference(logits, threshold)
    if interpret and vma_of(logits):
        return fused_sigmoid_mask_reference(logits, threshold)
    b = logits.shape[0]
    rest = 1
    for d in logits.shape[1:]:
        rest *= d
    itemsize = jnp.dtype(logits.dtype).itemsize
    # in-block + probs-block + f32 mask-block resident together
    if rest * (2 * itemsize + 4) > vmem_limit_bytes:
        return fused_sigmoid_mask_reference(logits, threshold)
    x2 = logits.reshape(b, rest)
    vma = vma_of(logits)
    def _sds(shape, dtype):
        return (
            jax.ShapeDtypeStruct(shape, dtype, vma=vma)
            if vma
            else jax.ShapeDtypeStruct(shape, dtype)
        )
    spec = pl.BlockSpec((1, rest), lambda i: (i, 0), memory_space=pltpu.VMEM)
    probs, mask = pl.pallas_call(
        functools.partial(_sigmoid_mask_kernel, threshold=threshold),
        grid=(b,),
        in_specs=[spec],
        out_specs=[spec, spec],
        out_shape=[_sds((b, rest), logits.dtype), _sds((b, rest), jnp.float32)],
        interpret=interpret,
    )(x2)
    return probs.reshape(logits.shape), mask.reshape(logits.shape)
