from tensorflowdistributedlearning_tpu.ops.losses import (
    lovasz_grad,
    lovasz_hinge,
    lovasz_hinge_flat,
    lovasz_loss,
    sigmoid_cross_entropy,
    softmax_cross_entropy,
)
from tensorflowdistributedlearning_tpu.ops.metrics import (
    IOU_THRESHOLDS,
    Mean,
    iou_scores,
    mean_accuracy_scores,
    miou,
    mean_accuracy,
)

__all__ = [
    "lovasz_grad",
    "lovasz_hinge",
    "lovasz_hinge_flat",
    "lovasz_loss",
    "sigmoid_cross_entropy",
    "softmax_cross_entropy",
    "IOU_THRESHOLDS",
    "Mean",
    "iou_scores",
    "mean_accuracy_scores",
    "miou",
    "mean_accuracy",
]
