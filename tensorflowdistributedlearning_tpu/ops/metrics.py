"""Streaming metrics, TPU-native.

Re-design of the reference's metric stack (reference: core/metric.py:1-71). The
reference returned TF1 ``(value_op, update_op)`` streaming pairs backed by hidden local
variables (core/metric.py:42, 63); here the streaming state is an explicit ``Mean``
pytree — a (total, count) pair that is functional, checkpointable, and reducible across
the device mesh with a single ``psum`` (the cross-replica story the reference delegated
to tf.metrics' implicit variable aggregation).

Semantics preserved exactly:
- per-image IoU from the binary confusion matrix, with the empty-mask rule: if
  TP+FP+FN == 0 the score is 1.0 (reference: core/metric.py:16-30);
- Kaggle-style thresholding over IOU_THRESHOLDS 0.50..0.95, in the reference's
  (deliberate-looking, nonstandard) ``mean(score * (score > t))`` form — NOT the Kaggle
  ``mean(score > t)`` (reference: core/metric.py:32-33; SURVEY §2.4.14);
- per-image pixel accuracy averaged over all non-batch axes (reference:
  core/metric.py:60-63).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from flax import struct

# Reference: core/metric.py:3
IOU_THRESHOLDS = (0.5, 0.55, 0.6, 0.65, 0.7, 0.75, 0.8, 0.85, 0.9, 0.95)


@struct.dataclass
class Mean:
    """Functional streaming mean — the explicit form of ``tf.metrics.mean``'s hidden
    (total, count) locals (reference: core/metric.py:42)."""

    total: jax.Array
    count: jax.Array

    @classmethod
    def empty(cls) -> "Mean":
        return cls(total=jnp.zeros((), jnp.float32), count=jnp.zeros((), jnp.float32))

    def update(self, values: jax.Array, weights: jax.Array | None = None) -> "Mean":
        """Add ``values`` to the stream; optional per-value ``weights`` (0 excludes a
        value — used to mask wrap-around padding in the final eval batch)."""
        values = values.astype(jnp.float32)
        if weights is None:
            return Mean(
                total=self.total + jnp.sum(values), count=self.count + values.size
            )
        weights = jnp.broadcast_to(weights.astype(jnp.float32), values.shape)
        return Mean(
            total=self.total + jnp.sum(values * weights),
            count=self.count + jnp.sum(weights),
        )

    def merge(self, other: "Mean") -> "Mean":
        return Mean(total=self.total + other.total, count=self.count + other.count)

    def compute(self) -> jax.Array:
        return self.total / jnp.maximum(self.count, 1.0)


def _flatten_per_image(x: jax.Array) -> jax.Array:
    return x.reshape(x.shape[0], -1)


def iou_scores(y_true: jax.Array, y_pred: jax.Array) -> jax.Array:
    """Per-image thresholded IoU scores, shape [B].

    ``y_true``/``y_pred`` are binary masks of shape [B, ...]. Equivalent to the
    reference's per-image confusion-matrix walk (core/metric.py:16-37) but expressed as
    three reductions — the 2x2 confusion matrix of a binary problem collapses to
    TP/FP/FN sums, which XLA fuses into one pass.
    """
    t = _flatten_per_image(y_true).astype(jnp.float32)
    p = _flatten_per_image(y_pred).astype(jnp.float32)
    tp = jnp.sum(t * p, axis=1)
    fp = jnp.sum((1.0 - t) * p, axis=1)
    fn = jnp.sum(t * (1.0 - p), axis=1)
    denominator = tp + fp + fn
    # empty-mask rule (reference: core/metric.py:27-30)
    score = jnp.where(denominator > 0, tp / jnp.maximum(denominator, 1e-12), 1.0)
    thresholds = jnp.asarray(IOU_THRESHOLDS, jnp.float32)
    # nonstandard score*(score>t) form preserved (reference: core/metric.py:32-33)
    return jnp.mean(
        score[:, None] * (score[:, None] > thresholds[None, :]).astype(jnp.float32),
        axis=1,
    )


def mean_accuracy_scores(y_true: jax.Array, y_pred: jax.Array) -> jax.Array:
    """Per-image pixel accuracy, shape [B] (reference: core/metric.py:60-63)."""
    t = _flatten_per_image(y_true)
    p = _flatten_per_image(y_pred)
    return jnp.mean((t == p).astype(jnp.float32), axis=1)


def miou(
    y_true: jax.Array, y_pred: jax.Array, state: Mean | None = None
) -> Tuple[jax.Array, Mean]:
    """Streaming thresholded mIOU (reference: core/metric.py:6-50).

    Returns ``(value, new_state)`` — the functional analogue of the reference's
    ``(value_op, update_op)`` pair.
    """
    state = Mean.empty() if state is None else state
    new_state = state.update(iou_scores(y_true, y_pred))
    return new_state.compute(), new_state


def mean_accuracy(
    y_true: jax.Array, y_pred: jax.Array, state: Mean | None = None
) -> Tuple[jax.Array, Mean]:
    """Streaming pixel accuracy (reference: core/metric.py:53-71)."""
    state = Mean.empty() if state is None else state
    new_state = state.update(mean_accuracy_scores(y_true, y_pred))
    return new_state.compute(), new_state


def top1_accuracy_scores(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Per-example top-1 hits for the classification path, shape [B]."""
    return (jnp.argmax(logits, axis=-1) == labels.astype(jnp.int32)).astype(jnp.float32)


def topk_accuracy_scores(
    logits: jax.Array, labels: jax.Array, k: int = 5
) -> jax.Array:
    """Per-example top-k hits (ImageNet's standard companion metric), shape [B].

    Degrades to TOP-1 when the class count is <= k — clamping k to the class
    count instead would make the metric a constant 1.0 (every class in the top
    set), a perfect-looking but vacuous number."""
    if k >= logits.shape[-1]:
        return top1_accuracy_scores(logits, labels)
    _, top = jax.lax.top_k(logits, k)
    return jnp.any(top == labels.astype(jnp.int32)[:, None], axis=-1).astype(
        jnp.float32
    )
