"""Pallas TPU kernel: fused (flash-style) block attention.

The framework's attention hot path is the per-device block attention inside
``parallel/ring_attention.py`` and ``models/vit.py`` — sequence LENGTH scaling is
handled by the ring (each chip holds S/n tokens), so the kernel's job is making
one device's block attention fast: QK^T -> online softmax -> PV fused in VMEM,
never materializing the [T, T] score matrix in HBM (XLA's unfused lowering
writes scores + softmax out to HBM twice at fp32 — pure bandwidth waste).

Shape strategy: grid over (batch*heads, query blocks); each step holds one
``block_q x D`` query tile plus the full K/V block ``[T, D]`` in VMEM, computes
the ``[block_q, T]`` score tile in one shot (softmax over the full row — no
inner K scan; the VMEM budget check below bounds the score tile, and longer
blocks fall back to the XLA oracle), accumulating in float32 on the MXU
(``preferred_element_type``). Causal masking compares global row/column indices
via ``broadcasted_iota`` (TPU requires >=2-D iota).

Gradients come from a ``jax.custom_vjp`` whose backward REBUILDS the scores
with plain XLA einsums from the saved residuals (q, k, v only — nothing
O(T^2) is saved across the forward). Note the backward itself still
materializes [B*H, T, T] score/weight tensors transiently in HBM; the flash
memory win applies to the forward pass and to saved activations, which is the
regime that matters here because ``parallel/ring_attention.py`` bounds T to one
device's block. The XLA oracle (`attention_reference`) is the numerical
fallback for shapes that exceed the VMEM budget and the test oracle; off-TPU
the kernel runs in interpreter mode so CPU CI exercises the identical code
path.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tensorflowdistributedlearning_tpu.parallel.collectives import vma_of
from tensorflowdistributedlearning_tpu.parallel.ring_attention import (
    _MASK_VALUE,
    attention_reference,
)

# Per-grid-step VMEM estimate must fit well under the ~16 MB/core budget
# (double-buffering included); above it the public wrapper falls back to the
# XLA oracle instead of failing Mosaic compilation.
_VMEM_KV_LIMIT_BYTES = 8 * 1024 * 1024
_BLOCK_Q = 256


def _vmem_estimate_bytes(t: int, d: int, block_q: int) -> int:
    """float32 working set of one grid step: K + V blocks, the q tile and the
    output tile, and the [block_q, T] scores twice (raw + exp)."""
    return 4 * (2 * t * d + 2 * block_q * d + 2 * block_q * t)


def _attn_kernel(q_ref, k_ref, v_ref, o_ref, *, scale: float, causal: bool, block_q: int):
    """One (batch*head, q-block) grid step: one-shot softmax over the full K row."""
    j = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)  # [block_q, D]
    k = k_ref[0].astype(jnp.float32)  # [T, D]
    v = v_ref[0].astype(jnp.float32)  # [T, D]

    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale  # [block_q, T]
    if causal:
        q_pos = j * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        k_pos = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(q_pos >= k_pos, s, _MASK_VALUE)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jax.lax.dot_general(
        p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    o_ref[0] = (o / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _flash_forward(
    q: jax.Array, k: jax.Array, v: jax.Array, causal: bool, interpret: bool
) -> jax.Array:
    """[BH, T, D] fused attention via pallas_call."""
    bh, t, d = q.shape
    block_q = min(_BLOCK_Q, t)
    n_q = pl.cdiv(t, block_q)
    scale = 1.0 / (d ** 0.5)
    kernel = functools.partial(
        _attn_kernel, scale=scale, causal=causal, block_q=block_q
    )
    # inside shard_map the output inherits the inputs' varying-manual-axes type
    # (the batch axis of the SPMD train step); outside, vma is empty
    out_vma = vma_of(q) | vma_of(k) | vma_of(v)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((bh, t, d), q.dtype, vma=out_vma),
        grid=(bh, n_q),
        in_specs=[
            pl.BlockSpec(
                (1, block_q, d), lambda i, j: (i, j, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec((1, t, d), lambda i, j: (i, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, t, d), lambda i, j: (i, 0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (1, block_q, d), lambda i, j: (i, j, 0), memory_space=pltpu.VMEM
        ),
        interpret=interpret,
    )(q, k, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash_with_grad(q, k, v, causal: bool, interpret: bool):
    return _flash_forward(q, k, v, causal, interpret)


def _flash_fwd(q, k, v, causal, interpret):
    o = _flash_forward(q, k, v, causal, interpret)
    return o, (q, k, v)


def _flash_bwd(causal, interpret, res, g):
    """Flash-style recompute backward in plain XLA (scores rebuilt, never saved).

    With p the post-softmax weights and o = p @ v:
      dv = p^T @ g
      dp = g @ v^T
      ds = p * (dp - rowsum(dp * p))       (softmax JVP transpose)
      dq = ds @ k * scale ; dk = ds^T @ q * scale
    """
    q, k, v = res
    orig_dtype = q.dtype
    q32, k32, v32, g32 = (x.astype(jnp.float32) for x in (q, k, v, g))
    d = q.shape[-1]
    scale = 1.0 / (d ** 0.5)
    s = jnp.einsum("btd,bsd->bts", q32, k32) * scale
    if causal:
        t, t_k = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((t, t_k), bool))
        s = jnp.where(mask, s, _MASK_VALUE)
    p = jax.nn.softmax(s, axis=-1)
    dv = jnp.einsum("bts,btd->bsd", p, g32)
    dp = jnp.einsum("btd,bsd->bts", g32, v32)
    ds = p * (dp - jnp.sum(dp * p, axis=-1, keepdims=True))
    dq = jnp.einsum("bts,bsd->btd", ds, k32) * scale
    dk = jnp.einsum("bts,btd->bsd", ds, q32) * scale
    return (
        dq.astype(orig_dtype),
        dk.astype(orig_dtype),
        dv.astype(orig_dtype),
    )


_flash_with_grad.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Fused block attention, [B, T, H, D] -> [B, T, H, D] (same contract as
    ``attention_reference``). Differentiable (custom VJP with flash-style
    recompute). ``interpret=None`` auto-selects: the Mosaic kernel on TPU, the
    Pallas interpreter off-TPU (so CPU CI runs the identical kernel code).
    Falls back to the XLA oracle when the per-step working set (K/V blocks plus
    the [block_q, T] score tile) would not fit the VMEM budget."""
    b, t, h, d = q.shape
    block_q = min(_BLOCK_Q, t)
    if _vmem_estimate_bytes(t, d, block_q) > _VMEM_KV_LIMIT_BYTES:
        return attention_reference(q, k, v, causal=causal)
    if interpret is None:
        from tensorflowdistributedlearning_tpu.ops.pallas_kernels import (
            pallas_platform_ok,
        )

        interpret = not pallas_platform_ok()
    if interpret and (vma_of(q) | vma_of(k) | vma_of(v)):
        # the Pallas interpreter's block slicing trips shard_map's varying-axes
        # checks (same limitation as ops/pallas_kernels.py): inside shard_map
        # off-TPU, take the XLA oracle; the Mosaic path owns this case on TPU
        return attention_reference(q, k, v, causal=causal)
    # [B, T, H, D] -> [B*H, T, D]: heads become independent grid rows
    qh, kh, vh = (
        x.transpose(0, 2, 1, 3).reshape(b * h, t, d) for x in (q, k, v)
    )
    out = _flash_with_grad(qh, kh, vh, causal, interpret)
    return out.reshape(b, h, t, d).transpose(0, 2, 1, 3)
