"""Stdlib HTTP front-end: ``/v1/predict``, ``/healthz``, ``/metrics``.

A ``ThreadingHTTPServer`` (one thread per connection — the handler threads
block in ``Request.result()``, the single batcher worker does the compute, so
concurrency costs threads-waiting-on-events, not parallel TPU dispatch) in
front of the engine/batcher pair. Wire protocol, TF-Serving-shaped:

    POST /v1/predict   {"instances": [[...], ...], "deadline_ms": 250}
                    -> {"predictions": {...}, "n": k}
    GET  /healthz      {"ok": true, "status": "ok|degraded|draining",
                        "artifact": {...}, "uptime_s": ...}
    GET  /metrics      live registry snapshot + bucket hits + queue depth
                       (JSON by default; Prometheus text exposition under
                       ``Accept: text/plain`` or ``?format=prometheus``)

Every ``/v1/predict`` response — success and error alike, 429s and timeouts
included — echoes the request id as ``x-request-id`` (honoring a
client-supplied header, minting one otherwise); the id doubles as the
request's trace id, so a shed request is correlatable with server-side
telemetry from the client's copy of the id alone. Errors are structured,
never silent: 400 malformed input, 413 over the largest bucket, 429 queue
full (backpressure), 503 draining, 504 deadline — each body carries
``{"error": {"code", "message", "request_id"}}`` (``code`` is the
machine-readable kind) and bumps the matching registry counter.

SLO: with a p99 target configured (``--slo-p99-ms``), answered-request
latency feeds an ``obs.health.SloTracker`` (deadline expiries count as
violations); each ledger window evaluates the error budget, breaches write
``health_alert`` events, and ``/healthz`` reports ``status: "degraded"`` —
the signal a fleet router drains on.

Request-path telemetry: alongside the live ``/metrics`` view, the server
appends ``serve_window`` events to the workdir's ``telemetry.jsonl`` every
``window_secs`` (cumulative counters + that window's queue-wait/pad/compute
latency percentiles + post-warmup recompile count), and ``shutdown()`` drains
gracefully — intake stops, accepted requests finish, a final window and
``run_end`` land in the ledger. ``obs.report`` renders these as the ``serving``
section of the goodput report.
"""

from __future__ import annotations

import collections
import json
import logging
import math
import socket
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Deque, Dict, Optional, Tuple

import numpy as np

from tensorflowdistributedlearning_tpu.obs import health as health_lib
from tensorflowdistributedlearning_tpu.obs import trace as trace_lib
from tensorflowdistributedlearning_tpu.resilience import faults as faults_lib
from tensorflowdistributedlearning_tpu.obs.metrics import (
    time_summary,
    window_count,
)
from tensorflowdistributedlearning_tpu.obs.telemetry import NULL_TELEMETRY
from tensorflowdistributedlearning_tpu.serve.batcher import (
    DeadlineExceededError,
    MicroBatcher,
    QueueFullError,
    RequestTooLargeError,
    ServerClosedError,
)
from tensorflowdistributedlearning_tpu.serve.engine import InferenceEngine
from tensorflowdistributedlearning_tpu.serve.registry import DEFAULT_MODEL

logger = logging.getLogger(__name__)

# counters a serve_window snapshot carries (cumulative since server start)
_WINDOW_COUNTERS = (
    "requests",
    "completed",
    "rejected_queue_full",
    "deadline_exceeded",
    "errors",
    "batches",
    "batched_examples",
)
# per-window latency histograms, drained each window so a long-lived server
# holds at most one window's samples (same boundedness stance as the
# trainers' span histograms, obs/telemetry.py); "request" is end-to-end
# handler latency — what the SLO tracker budgets against
_WINDOW_HISTOGRAMS = ("queue_wait", "pad", "compute", "request")

# Retry-After bounds (seconds): a rejected client must neither hot-loop (<1s)
# nor give up on a replica that drains its queue in a few seconds (cap 30)
_RETRY_AFTER_MIN_S = 1
_RETRY_AFTER_MAX_S = 30
# with no observed drain yet (cold or fully stalled server) advertise a
# middle-of-the-road backoff rather than pretending to know the drain rate
_RETRY_AFTER_DEFAULT_S = 5


def bind_ephemeral(host: str = "127.0.0.1", port: int = 0) -> socket.socket:
    """Bind (without listening) a TCP socket — ``port=0`` picks a free
    ephemeral port the caller can read back via ``getsockname()`` BEFORE
    constructing the server around it. This is how ``serve --port 0`` knows
    its real port early enough to stamp it into the telemetry run header
    (written at ``Telemetry`` construction, before ``ServingServer`` exists),
    and how N replicas spawn into one test without port races."""
    sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    try:
        sock.bind((host, port))
    except OSError:
        sock.close()
        raise
    return sock


class _ModelRuntime:
    """One tenant inside a replica: its engine, batcher, version, and SLO.

    Each model owns a *separate* ``MetricsRegistry`` (the engine's), so
    tenant counters and latency histograms never cross-contaminate — the
    server sums across runtimes for its aggregate views and reports each
    runtime under a ``models`` sub-dict for per-tenant ones."""

    def __init__(
        self,
        name: str,
        engine: InferenceEngine,
        batcher: MicroBatcher,
        *,
        version: int = 1,
        slo: Optional[health_lib.SloTracker] = None,
    ):
        self.name = name
        self.engine = engine
        self.batcher = batcher
        self.version = int(version)
        self.slo = slo

    @property
    def status(self) -> str:
        if self.slo is not None and not self.slo.healthy:
            return "degraded"
        return "ok"


class ServingServer:
    """Engine + batcher behind a ThreadingHTTPServer, with ledger windows.

    Multi-tenant: the constructor's engine/batcher pair becomes the
    *primary* model (named ``model``, default :data:`DEFAULT_MODEL` — which
    is also what requests that don't name a model resolve to), and
    :meth:`add_model` mounts further tenants before :meth:`start`. Requests
    select a tenant with a ``"model"`` key in the predict payload."""

    def __init__(
        self,
        engine: InferenceEngine,
        batcher: MicroBatcher,
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        telemetry=None,
        window_secs: float = 30.0,
        result_timeout_s: float = 60.0,
        slo_p99_ms: Optional[float] = None,
        slo_error_budget: float = 0.01,
        replica_id: int = 0,
        sock: Optional[socket.socket] = None,
        model: str = DEFAULT_MODEL,
        registry_version: Optional[int] = None,
        capture=None,
        drift_monitor=None,
    ):
        self.engine = engine
        self.batcher = batcher
        # continuous-learning tees (loop/capture.py, obs/health.py
        # DriftMonitor): both observe the PRIMARY model's accepted requests
        # only — foreign tenants' traffic is skipped, same rule as the
        # promotion shadow tee
        self.capture = capture
        self.drift = drift_monitor
        self.telemetry = telemetry if telemetry is not None else NULL_TELEMETRY
        self.window_secs = float(window_secs)
        self.result_timeout_s = float(result_timeout_s)
        # which fleet replica this server is: stamped on every serve_window
        # (and serve_start) ledger event so the multi-ledger merge
        # (obs/fleet.py) can attribute request-path telemetry per replica —
        # same role process_index plays for trainer ledgers
        self.replica_id = int(replica_id)
        # serving SLO (obs/health.py): p99 target as a windowed error budget;
        # None = no SLO tracking (healthz never degrades on latency)
        self.slo = (
            health_lib.SloTracker(slo_p99_ms, error_budget=slo_error_budget)
            if slo_p99_ms is not None
            else None
        )
        # tenant table: the constructor pair is the primary model; add_model
        # mounts more. Ordered so windows/metrics render deterministically.
        self._primary = _ModelRuntime(
            model,
            engine,
            batcher,
            version=registry_version if registry_version is not None else 1,
            slo=self.slo,
        )
        self.models: Dict[str, _ModelRuntime] = collections.OrderedDict(
            {model: self._primary}
        )
        # spawned from a registry entry (vs the legacy --artifact-dir path):
        # only then does /healthz artifact identity carry model + version —
        # legacy probes keep seeing exactly the shape they always did
        self._versioned = registry_version is not None
        # HBM headroom monitor (obs/health.py): fed by the per-window
        # watermark sample below; a replica running out of device memory
        # degrades /healthz BEFORE it OOMs, so the fleet router drains it
        # while it can still answer. Inert on backends with no allocator
        # query (CPU builds never report a limit).
        self.headroom = health_lib.HeadroomMonitor()
        # per-request chip-seconds attribution (obs/capacity.py): the batcher
        # worker feeds the meter as batches dispatch; emit_window drains it
        # into `cost` ledger events and the rps-per-chip gauges. A server on
        # DISABLED telemetry gets its own meter: the default telemetry is the
        # process-global NULL_TELEMETRY singleton, and wiring two servers'
        # batchers into its one meter would cross-contaminate their windows
        from tensorflowdistributedlearning_tpu.obs import (
            capacity as capacity_lib,
        )

        self.cost_meter = (
            self.telemetry.cost
            if self.telemetry.enabled
            else capacity_lib.CostMeter()
        )
        self.batcher.cost_meter = self.cost_meter
        # same ownership rule for the watermark tracker: without live
        # telemetry nothing ledgers, but the /healthz OOM-drain protection
        # and the hbm gauges must still work — the server samples its own
        # tracker directly in that case (_emit_capacity_window)
        self.watermarks = (
            self.telemetry.watermarks
            if self.telemetry.enabled
            else capacity_lib.WatermarkTracker()
        )
        self._last_cost: Dict = {}
        # continuous profiling (obs/profiler.py): /admin/profile?seconds=N
        # on-demand captures, plus ONE rate-limited postmortem capture when
        # the SLO budget blows (emit_window). Timed captures only — the
        # serving tier has no train-step spans to count.
        from tensorflowdistributedlearning_tpu.obs.profiler import (
            ContinuousProfiler,
        )

        self.profiler = ContinuousProfiler(self.telemetry, phase="infer")
        if self.telemetry.enabled:
            self.telemetry.set_profiler(self.profiler)
        if self.slo is not None and self.window_secs <= 0:
            # the budget evaluates at window boundaries; with periodic windows
            # off only shutdown's final window (or a manual emit_window) runs
            # it — a breach would go unalerted for the server's lifetime
            logger.warning(
                "SLO tracking with window_secs=0: the error budget is only "
                "evaluated at shutdown; set a positive --window-secs for "
                "live health_alert events and /healthz degradation"
            )
        self.draining = False
        self._started_t = time.time()
        self._stop = threading.Event()
        self._shutdown_lock = threading.Lock()
        self._shut_down = False
        # drain-rate samples (monotonic_t, cumulative completed): what the
        # Retry-After header on 429/503 is derived from — how fast THIS
        # window's queue is actually emptying, not a fixed constant.
        # Locked: handler threads append AND expire concurrently (a 429
        # burst hits retry_after_s from dozens of threads at once)
        self._drain_samples: Deque[Tuple[float, int]] = collections.deque(
            maxlen=64
        )
        self._drain_lock = threading.Lock()
        handler = type("Handler", (_Handler,), {"ctx": self})
        self._httpd = ThreadingHTTPServer((host, port), handler, bind_and_activate=False)
        # stdlib default listen backlog is 5: a burst of concurrent connects
        # overflows it and the overflow retransmits SYNs for seconds — size it
        # like the request queue, and let quick restarts rebind the port
        self._httpd.request_queue_size = max(128, batcher.max_queue)
        if sock is not None:
            # adopt a pre-bound socket (bind_ephemeral): the caller learned
            # the real port before building Telemetry around this server
            self._httpd.socket.close()
            self._httpd.socket = sock
            bound_host, bound_port = sock.getsockname()[:2]
            self._httpd.server_address = (bound_host, bound_port)
            # what HTTPServer.server_bind would have set
            self._httpd.server_name = socket.getfqdn(bound_host)
            self._httpd.server_port = bound_port
        else:
            self._httpd.allow_reuse_address = True
            self._httpd.server_bind()
        self._httpd.server_activate()
        self._httpd.daemon_threads = True
        self._serve_thread: Optional[threading.Thread] = None
        self._ticker: Optional[threading.Thread] = None

    @property
    def host(self) -> str:
        return self._httpd.server_address[0]

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> "ServingServer":
        self._serve_thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="serve-http",
            daemon=True,
        )
        self._serve_thread.start()
        start_fields: Dict = {}
        if len(self.models) > 1 or self._versioned:
            start_fields["models"] = {
                name: rt.version for name, rt in self.models.items()
            }
        self.telemetry.event(
            "serve_start",
            endpoint=self.url,
            replica=self.replica_id,
            buckets=list(self.engine.buckets),
            max_batch_size=self.batcher.max_batch_size,
            max_wait_ms=self.batcher.max_wait_s * 1000,
            max_queue=self.batcher.max_queue,
            **start_fields,
        )
        if self.window_secs > 0:
            self._ticker = threading.Thread(
                target=self._tick, name="serve-window-ticker", daemon=True
            )
            self._ticker.start()
        logger.info("serving on %s (buckets %s)", self.url, self.engine.buckets)
        return self

    def wait(self) -> None:
        """Block the calling thread until ``shutdown()`` (the CLI foreground)."""
        self._stop.wait()

    def install_signal_handlers(self, signals=None) -> None:
        """SIGTERM/SIGINT trigger the graceful drain — the serving tier's
        preemption contract (resilience/): intake stops, accepted requests
        finish, the final ledger window and ``run_end`` land. Main thread
        only (the CPython signal rule)."""
        import signal as signal_lib

        for sig in signals or (signal_lib.SIGINT, signal_lib.SIGTERM):
            signal_lib.signal(sig, lambda *_: self.shutdown())

    def add_model(
        self,
        name: str,
        engine: InferenceEngine,
        batcher: MicroBatcher,
        *,
        version: int = 1,
        slo_p99_ms: Optional[float] = None,
        slo_error_budget: float = 0.01,
    ) -> _ModelRuntime:
        """Mount another tenant on this replica (before :meth:`start`).

        The engine must carry its own ``MetricsRegistry`` — tenants sharing
        instruments would cross-contaminate every per-model window."""
        if name in self.models:
            raise ValueError(f"model {name!r} already mounted")
        if engine.registry is self.engine.registry:
            raise ValueError(
                f"model {name!r}: each tenant needs its own MetricsRegistry "
                "(shared instruments cross-contaminate per-model windows)"
            )
        slo = (
            health_lib.SloTracker(slo_p99_ms, error_budget=slo_error_budget)
            if slo_p99_ms is not None
            else None
        )
        runtime = _ModelRuntime(
            name, engine, batcher, version=version, slo=slo
        )
        # one cost meter per replica: chip-seconds are a property of the
        # chips, not the tenant — per-model cost splits happen upstream
        # (router/bench) from per-model request rates
        batcher.cost_meter = self.cost_meter
        self.models[name] = runtime
        return runtime

    def model_runtime(self, name: Optional[str]) -> Optional[_ModelRuntime]:
        """Resolve a request's model name: absent -> primary, unknown -> None."""
        if name is None:
            return self._primary
        return self.models.get(name)

    def queue_depth_total(self) -> int:
        return sum(
            rt.engine.registry.gauge("serve/queue_depth").value or 0
            for rt in self.models.values()
        )

    def _counter_total(self, name: str) -> int:
        return sum(
            rt.engine.registry.counter(f"serve/{name}").value
            for rt in self.models.values()
        )

    @property
    def health_status(self) -> str:
        """The replica's live state a fleet router routes on: "draining" >
        "degraded" (any tenant's SLO budget blown, or HBM headroom at OOM
        risk) > "ok"."""
        if self.draining:
            return "draining"
        if any(
            rt.slo is not None and not rt.slo.healthy
            for rt in self.models.values()
        ):
            return "degraded"
        if self.headroom.degraded:
            return "degraded"
        return "ok"

    def artifact_identity(self) -> Optional[Dict]:
        """What this replica is actually serving — manifest dtype + source
        fingerprint (train/quantize.py), plus the registry version when the
        replica was spawned from a registry entry — so a readiness probe can
        tell replicas serving different artifacts (or different *versions*
        of one model) apart. None for raw-closure / legacy engines whose
        manifest carries no quantization section and no registry entry."""
        q = self.engine.quantization
        identity: Dict = {}
        if q is not None:
            identity = {
                "dtype": q.get("dtype"),
                "source_fingerprint": q.get("source_fingerprint"),
            }
        if self._versioned:
            identity["model"] = self._primary.name
            identity["registry_version"] = self._primary.version
        return identity or None

    def note_drain_progress(self) -> None:
        """Sample the cumulative completed counter (throttled to ~5Hz) so
        ``retry_after_s`` can estimate the live drain rate. Called from the
        request path — one deque append per answered request at most."""
        now = time.monotonic()
        with self._drain_lock:
            if self._drain_samples and now - self._drain_samples[-1][0] < 0.2:
                return
            completed = self._counter_total("completed")
            self._drain_samples.append((now, completed))

    def retry_after_s(self) -> int:
        """Seconds a rejected (429 queue-full / 503 draining) client should
        back off: current queue depth / the window's observed drain rate,
        clamped to [1, 30]. With no drain observed yet the estimate is a
        conservative default — better than hot-looping clients either way."""
        depth = self.queue_depth_total()
        now = time.monotonic()
        completed = self._counter_total("completed")
        rate = 0.0
        with self._drain_lock:
            # rate over the recent past only: drop samples older than ~10s
            # so a long-idle server does not average its burst rate into
            # oblivion
            while (
                self._drain_samples
                and now - self._drain_samples[0][0] > 10.0
            ):
                self._drain_samples.popleft()
            if self._drain_samples:
                t0, c0 = self._drain_samples[0]
                if now - t0 >= 0.05 and completed > c0:
                    rate = (completed - c0) / (now - t0)
        if rate <= 0.0:
            return _RETRY_AFTER_DEFAULT_S
        return int(
            min(
                max(math.ceil(depth / rate), _RETRY_AFTER_MIN_S),
                _RETRY_AFTER_MAX_S,
            )
        )

    def models_snapshot(self) -> Dict[str, Dict]:
        """Per-tenant live view: what the fleet router's poll routes on —
        version, backlog, windowed p99 — plus counters and SLO state."""
        out: Dict[str, Dict] = {}
        for name, rt in self.models.items():
            reg = rt.engine.registry
            row: Dict = {
                "version": rt.version,
                "status": rt.status,
                "queue_depth": reg.gauge("serve/queue_depth").value or 0,
                "requests": reg.counter("serve/requests").value,
                "completed": reg.counter("serve/completed").value,
                "rejected_queue_full": reg.counter(
                    "serve/rejected_queue_full"
                ).value,
            }
            hist = reg.histogram("serve/request")
            if len(hist):
                row["p99_ms"] = round(
                    hist.summary().get("p99_s", 0.0) * 1000, 3
                )
            if rt.slo is not None:
                row["slo"] = rt.slo.snapshot()
            if rt.engine.quantization is not None:
                row["serving_dtype"] = rt.engine.quantization.get("dtype")
            out[name] = row
        return out

    def metrics_snapshot(self) -> Dict:
        """The ``/metrics`` body: live registry view + serving identity."""
        reg = self.engine.registry
        snapshot = {
            "uptime_s": round(time.time() - self._started_t, 3),
            "draining": self.draining,
            "status": self.health_status,
            "buckets": {str(b): n for b, n in self.engine.bucket_hits.items()},
            "padding_waste": {
                str(b): w for b, w in self.engine.padding_waste.items()
            },
            "queue_depth": self.queue_depth_total(),
            # histograms here are "since the last ledger window" — the window
            # drain keeps a long-lived server's sample memory bounded
            "registry": reg.snapshot(),
            # per-tenant view (one entry even single-tenant: the fleet
            # router's per-model routing state comes from here)
            "models": self.models_snapshot(),
        }
        if self.slo is not None:
            snapshot["slo"] = self.slo.snapshot()
        if self.engine.quantization is not None:
            snapshot["serving_dtype"] = self.engine.quantization.get("dtype")
        # the /healthz artifact identity, mirrored here so the fleet
        # router's poll captures what this replica serves in one request
        snapshot["artifact"] = self.artifact_identity()
        # capacity/cost views (obs/capacity.py): per-phase HBM peaks +
        # headroom estimate, cumulative chip-seconds + last window's rates —
        # what a scraper needs to see cost and OOM risk without the ledger
        snapshot["cost"] = self.cost_meter.snapshot()
        if self._last_cost:
            snapshot["cost"]["last_window"] = self._last_cost
        memory = self.watermarks.snapshot()
        if memory.get("peak_bytes"):
            snapshot["memory"] = memory
        return snapshot

    def prometheus_text(self) -> str:
        """The ``/metrics`` Prometheus exposition body (``text/plain;
        version=0.0.4``): the shared registry rendered by
        ``MetricsRegistry.render_prometheus``, with server-level state
        refreshed into gauges first so scrapers see uptime/drain/health
        without a second endpoint."""
        reg = self.engine.registry
        reg.gauge("serve/uptime_s").set(time.time() - self._started_t)
        reg.gauge("serve/draining").set(1.0 if self.draining else 0.0)
        reg.gauge("serve/healthy").set(
            1.0 if self.health_status == "ok" else 0.0
        )
        if self.slo is not None:
            reg.gauge("serve/slo_p99_target_ms").set(self.slo.p99_target_ms)
        # device-memory and cost series (obs/capacity.py): external scrapers
        # see headroom and chip-seconds without parsing ledgers
        cost = self.cost_meter.snapshot()
        reg.gauge("serve/chip_seconds_total").set(
            cost.get("chip_seconds_total", 0.0)
        )
        # unconditional: gauges persist in the registry, so an idle window
        # must overwrite the last busy window's rates with zero
        reg.gauge("serve/rps_per_chip").set(
            self._last_cost.get("rps_per_chip", 0.0)
        )
        reg.gauge("serve/cost_duty_cycle").set(
            self._last_cost.get("duty_cycle", 0.0)
        )
        per_req = self._last_cost.get("chip_seconds_per_request") or {}
        reg.gauge("serve/chip_seconds_per_request_p99").set(
            per_req.get("p99", 0.0)
        )
        memory = self.watermarks.snapshot()
        if memory.get("peak_bytes"):
            reg.gauge("serve/hbm_peak_bytes").set(memory["peak_bytes"])
            headroom = memory.get("headroom") or {}
            if headroom.get("headroom_frac") is not None:
                reg.gauge("serve/hbm_headroom_frac").set(
                    headroom["headroom_frac"]
                )
            if memory.get("bytes_limit"):
                reg.gauge("serve/hbm_bytes_limit").set(memory["bytes_limit"])
        return reg.render_prometheus() + self._prometheus_model_text()

    # per-model series exposed with {model=,version=} labels so ONE scrape
    # distinguishes tenants; names live under tfdl_serve_model_* beside the
    # unlabeled per-replica aggregates render_prometheus produces
    _MODEL_PROM_COUNTERS = (
        "requests",
        "completed",
        "rejected_queue_full",
        "deadline_exceeded",
        "errors",
    )

    def _prometheus_model_text(self) -> str:
        lines = []
        labeled = []
        for name, rt in self.models.items():
            labeled.append(
                (f'model="{name}",version="{rt.version}"', rt)
            )
        for metric in self._MODEL_PROM_COUNTERS:
            pname = f"tfdl_serve_model_{metric}_total"
            lines.append(f"# TYPE {pname} counter")
            for labels, rt in labeled:
                value = rt.engine.registry.counter(f"serve/{metric}").value
                lines.append(f"{pname}{{{labels}}} {value}")
        lines.append("# TYPE tfdl_serve_model_queue_depth gauge")
        for labels, rt in labeled:
            depth = (
                rt.engine.registry.gauge("serve/queue_depth").value or 0
            )
            lines.append(f"tfdl_serve_model_queue_depth{{{labels}}} {depth}")
        lines.append("# TYPE tfdl_serve_model_request_seconds summary")
        for labels, rt in labeled:
            hist = rt.engine.registry.histogram("serve/request")
            if not len(hist):
                continue
            summary = hist.summary()
            for q, key in ((0.5, "p50_s"), (0.9, "p90_s"), (0.99, "p99_s")):
                if key in summary:
                    lines.append(
                        f'tfdl_serve_model_request_seconds'
                        f'{{{labels},quantile="{q}"}} {summary[key]:.10g}'
                    )
        return "\n".join(lines) + "\n"

    @staticmethod
    def _latency_row(samples) -> Dict:
        summary = time_summary(samples)
        row = {
            k[:-2] + "_ms": round(v * 1000, 3)
            for k, v in summary.items()
            if k.endswith("_s") and k != "total_s"
        }
        # exact even when the histogram ring capped the raw samples
        row["count"] = float(window_count(samples))
        return row

    def emit_window(self, final: bool = False) -> Dict:
        """One ``serve_window`` ledger event: cumulative counters, this
        window's latency split (ms percentiles), post-warmup recompiles.

        Multi-tenant: top-level counters/latency are the sum across the
        replica's models (identical to the old single-model fields when one
        model is mounted — no ledger flag-day), and a ``models`` sub-dict
        carries the same shape per tenant. Each tenant's SLO budget is
        evaluated on its own window; breaches ledger ``health_alert`` events
        stamped with the model name."""
        fields: Dict = {
            k: self._counter_total(k) for k in _WINDOW_COUNTERS
        }
        fields["replica"] = self.replica_id
        fields["queue_depth"] = self.queue_depth_total()
        fields["bucket_hits"] = {
            str(b): n for b, n in self.engine.bucket_hits.items()
        }
        # ladder utilization: fraction of compiled batch slots filled with
        # padding, per bucket that saw traffic (cumulative, like the hits)
        waste = self.engine.padding_waste
        if waste:
            fields["padding_waste"] = {str(b): w for b, w in waste.items()}
        if self.engine.quantization is not None:
            fields["serving_dtype"] = self.engine.quantization.get("dtype")
        # drain every tenant's histograms once; aggregate windows are the
        # concatenation (exact counts/totals summed via SampleWindow)
        combined: Dict[str, list] = {}
        models_field: Dict[str, Dict] = {}
        multi = len(self.models) > 1
        for name, rt in self.models.items():
            reg = rt.engine.registry
            mrow: Dict = {
                "version": rt.version,
                **{
                    k: reg.counter(f"serve/{k}").value
                    for k in _WINDOW_COUNTERS
                },
            }
            mrow["queue_depth"] = (
                reg.gauge("serve/queue_depth").value or 0
            )
            mlat: Dict = {}
            for hname in _WINDOW_HISTOGRAMS:
                samples = reg.histogram(f"serve/{hname}").drain()
                if samples:
                    combined.setdefault(hname, []).append(samples)
                    mlat[hname] = self._latency_row(samples)
            if mlat:
                mrow["latency_ms"] = mlat
            if rt.slo is not None:
                verdict = rt.slo.evaluate()
                if verdict is not None:
                    verdict.setdefault("alert_id", trace_lib.new_id())
                    if multi:
                        verdict.setdefault("model", name)
                    self.telemetry.event(
                        health_lib.HEALTH_ALERT_EVENT, **verdict
                    )
                    if not verdict.get("resolved"):
                        # SLO budget blown: capture ONE rate-limited
                        # postmortem profile stamped with the triggering
                        # alert id — the evidence an on-call wants is the
                        # trace from the bad minutes, not a capture
                        # requested after the fact
                        self.profiler.trigger(verdict, seconds=2.0)
                mrow["slo"] = rt.slo.snapshot()
            if rt.engine.quantization is not None:
                mrow["serving_dtype"] = rt.engine.quantization.get("dtype")
            models_field[name] = mrow
        latency: Dict = {}
        for hname, windows in combined.items():
            if len(windows) == 1:
                merged = windows[0]
            else:
                from tensorflowdistributedlearning_tpu.obs.metrics import (
                    SampleWindow,
                )

                merged = SampleWindow(
                    [s for w in windows for s in w],
                    sum(window_count(w) for w in windows),
                    sum(getattr(w, "total_s", 0.0) for w in windows),
                )
            latency[hname] = self._latency_row(merged)
        if latency:
            fields["latency_ms"] = latency
        detector = self.telemetry.detector
        if detector is not None:
            fields["recompiles_post_warmup"] = detector.post_warmup_count
        if self.slo is not None:
            # the primary model's live SLO state rides at top level for the
            # report's health section, exactly as before
            fields["slo"] = self.slo.snapshot()
        if self.capture is not None:
            # capture-loss is never silent: the cumulative drop count rides
            # every serve_window, and windows with tee activity ledger a
            # full capture_window record
            fields["tee_dropped"] = self.capture.total_dropped
            if self.capture.active() or final:
                from tensorflowdistributedlearning_tpu.loop.capture import (
                    CAPTURE_WINDOW_EVENT,
                )

                snap = self.capture.window_snapshot()
                if final:
                    snap["final"] = True
                self.telemetry.event(
                    CAPTURE_WINDOW_EVENT, replica=self.replica_id, **snap
                )
        if self.drift is not None:
            verdict = self.drift.evaluate()
            if verdict is not None:
                verdict.setdefault("alert_id", trace_lib.new_id())
                verdict["replica"] = self.replica_id
                self.telemetry.event(health_lib.DRIFT_ALERT_EVENT, **verdict)
            fields["drift"] = self.drift.snapshot()
        if multi:
            fields["models"] = models_field
        elif self._versioned:
            # one model-aware tenant on this replica (fleet spawn with
            # --model): name it at top level so the fleet merge can
            # attribute the replica's whole window to that tenant
            fields["model"] = self._primary.name
            fields["model_version"] = self._primary.version
        if final:
            fields["final"] = True
        self.telemetry.event("serve_window", **fields)
        self._emit_capacity_window()
        return fields

    def _emit_capacity_window(self) -> None:
        """The capacity/cost half of a window boundary (obs/capacity.py):
        one allocator watermark sample attributed to the infer phase (fed to
        the headroom monitor — low headroom degrades /healthz), and one
        ``cost`` ledger event draining the window's per-request chip-second
        attribution. Both are no-ops on an idle window / statless backend."""
        from tensorflowdistributedlearning_tpu.obs import (
            capacity as capacity_lib,
        )

        if self.telemetry.enabled:
            self.telemetry.sample_watermark(capacity_lib.PHASE_INFER)
        else:
            # no ledger, but the tracker still samples so /healthz and the
            # hbm gauges keep their OOM-drain protection
            self.watermarks.sample(capacity_lib.PHASE_INFER)
        # the monitor runs on the tracker's LIVE headroom every window — not
        # only when the peak advanced — so a trend-triggered degraded state
        # resolves once the peak plateaus instead of sticking forever
        headroom = self.watermarks.headroom()
        if headroom and headroom.get("bytes_limit"):
            alert = self.headroom.check(
                None,
                headroom["peak_bytes"],
                headroom["bytes_limit"],
                samples_to_limit=headroom.get("samples_to_limit"),
            )
            if alert:
                alert["replica"] = self.replica_id
                self.telemetry.event(health_lib.HEALTH_ALERT_EVENT, **alert)
        cost = self.cost_meter.serve_window()
        if cost:
            cost["replica"] = self.replica_id
            self._last_cost = cost
            self.telemetry.event(capacity_lib.COST_EVENT, **cost)
        else:
            # idle window: the last busy window's RATES are stale the moment
            # a new window closes without traffic — scrapers and the router
            # must see zero, not phantom throughput
            self._last_cost = {}

    def _tick(self) -> None:
        while not self._stop.wait(self.window_secs):
            try:
                self.emit_window()
            except Exception:  # noqa: BLE001 — telemetry never kills serving
                logger.exception("serve window emission failed")

    def shutdown(self) -> None:
        """Graceful drain: refuse new work, finish accepted requests, write
        the final ledger window, stop the listener. Idempotent."""
        with self._shutdown_lock:
            if self._shut_down:
                return
            self._shut_down = True
        self.draining = True
        self._stop.set()
        if self._ticker is not None:
            self._ticker.join(timeout=5)
        for rt in self.models.values():
            rt.batcher.close(drain=True)
        if self.capture is not None:
            try:
                # seal the partial shard BEFORE the final window so the
                # closing capture_window reports everything on disk
                self.capture.close()
            except Exception:  # noqa: BLE001
                logger.warning("capture tee close failed", exc_info=True)
        try:
            final = self.emit_window(final=True)
        except Exception:  # noqa: BLE001
            logger.exception("final serve window emission failed")
            final = {}
        try:
            # stop any in-flight timed capture and ledger what it got;
            # telemetry.close would do this too, but only when it owns the
            # profiler (enabled telemetry) — close is idempotent either way
            self.profiler.close()
        except Exception:  # noqa: BLE001
            logger.warning("profiler close failed", exc_info=True)
        self.telemetry.close(
            kind="serve",
            requests=final.get("requests"),
            completed=final.get("completed"),
            rejected_queue_full=final.get("rejected_queue_full"),
            deadline_exceeded=final.get("deadline_exceeded"),
        )
        # only break serve_forever if it ever ran: BaseServer.shutdown()
        # waits on an event that ONLY serve_forever sets, so calling it on a
        # constructed-but-never-started server deadlocks forever
        if self._serve_thread is not None:
            self._httpd.shutdown()
        self._httpd.server_close()
        if self._serve_thread is not None:
            self._serve_thread.join(timeout=5)
        logger.info("serving stopped (drained)")


class _Handler(BaseHTTPRequestHandler):
    ctx: ServingServer  # bound by ServingServer via a subclass attribute
    # HTTP/1.1 keep-alive: every response sets Content-Length below
    protocol_version = "HTTP/1.1"
    # small request/response bodies in separate writes + Nagle + delayed ACK
    # = ~200ms per round trip on loopback; inference RPCs always disable it
    disable_nagle_algorithm = True

    def log_message(self, fmt, *args):  # route access logs to logging, quiet
        logger.debug("%s - %s", self.address_string(), fmt % args)

    # set per request by do_POST; echoed on every response it produces
    _request_id: Optional[str] = None

    def _json(
        self,
        status: int,
        payload: Dict,
        extra_headers: Optional[Dict[str, str]] = None,
    ) -> None:
        body = json.dumps(payload).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        if self._request_id:
            self.send_header("x-request-id", self._request_id)
        for k, v in (extra_headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _text(self, status: int, body: str, content_type: str) -> None:
        raw = body.encode()
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(raw)))
        self.end_headers()
        self.wfile.write(raw)

    def _error(
        self,
        status: int,
        code: str,
        message: str,
        retry_after: Optional[int] = None,
    ) -> int:
        """Structured error: ``code`` is the machine-readable kind, and the
        request id (when one exists — every /v1/predict error has one, 429s
        and timeouts included) rides in the body AND the x-request-id header
        so a shed request is correlatable with server-side telemetry.
        Backpressure statuses (429 queue-full, 503 draining) carry a
        ``Retry-After`` header derived from the window's drain rate
        (``ServingServer.retry_after_s``) so clients — the fleet router
        included — back off intelligently instead of hot-looping. Returns
        ``status`` so the predict path can hand it back in one expression."""
        error: Dict = {"code": code, "message": message}
        if self._request_id:
            error["request_id"] = self._request_id
        headers = None
        if retry_after is not None:
            error["retry_after_s"] = int(retry_after)
            headers = {"Retry-After": str(int(retry_after))}
        self._json(status, {"error": error}, extra_headers=headers)
        return status

    def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler contract
        # keep-alive reuses handler instances: a GET after a POST on the same
        # connection must not echo the previous request's id
        self._request_id = None
        parsed = urllib.parse.urlparse(self.path)
        if parsed.path == "/healthz":
            server_status = self.ctx.health_status
            status = 503 if self.ctx.draining else 200
            body = {
                # ok = "answers traffic within contract": draining AND
                # SLO-degraded replicas both report false, with `status`
                # naming which; only draining refuses traffic (503)
                "ok": server_status == "ok",
                "status": server_status,
                "replica": self.ctx.replica_id,
                "draining": self.ctx.draining,
                "uptime_s": round(time.time() - self.ctx._started_t, 3),
                "buckets": list(self.ctx.engine.buckets),
                # artifact identity: which export this replica answers from
                "artifact": self.ctx.artifact_identity(),
            }
            if self.ctx.slo is not None:
                body["slo"] = self.ctx.slo.snapshot()
            if len(self.ctx.models) > 1 or self.ctx._versioned:
                # which tenants (and which registry versions) this replica
                # answers for — the multi-tenant readiness contract
                body["models"] = {
                    name: {"version": rt.version, "status": rt.status}
                    for name, rt in self.ctx.models.items()
                }
            if self.ctx.headroom.last is not None:
                # the OOM-risk view a fleet controller drains on (None until
                # a device watermark sample exists — CPU builds stay silent)
                body["memory"] = dict(
                    self.ctx.headroom.last,
                    degraded=self.ctx.headroom.degraded,
                )
            self._json(status, body)
        elif parsed.path == "/metrics":
            query = urllib.parse.parse_qs(parsed.query)
            accept = self.headers.get("Accept", "")
            if (
                query.get("format", [""])[0] == "prometheus"
                or "text/plain" in accept
                or "openmetrics" in accept
            ):
                self._text(
                    200,
                    self.ctx.prometheus_text(),
                    "text/plain; version=0.0.4; charset=utf-8",
                )
            else:
                self._json(200, self.ctx.metrics_snapshot())
        elif parsed.path == "/admin/profile":
            # on-demand capture: kick a timed jax.profiler capture in the
            # background and answer immediately (202); the parsed roofline
            # lands in the ledger when the capture window closes. 409 while
            # another capture is in flight — the running one wins.
            query = urllib.parse.parse_qs(parsed.query)
            try:
                seconds = float(query.get("seconds", ["1"])[0])
            except ValueError:
                self._error(400, "bad_request", "seconds must be a number")
                return
            if not (0 < seconds <= 60):
                self._error(
                    400, "bad_request", "seconds must be in (0, 60]"
                )
                return
            if self.ctx.profiler.logdir is None:
                self._error(
                    503, "profiling_unavailable",
                    "no telemetry workdir to write captures into",
                )
                return
            started = self.ctx.profiler.capture_timed(seconds, reason="admin")
            if started is None:
                self._error(
                    409, "capture_in_flight",
                    "a profile capture is already running on this replica",
                )
                return
            started["replica"] = self.ctx.replica_id
            self._json(202, started)
        else:
            self._error(404, "not_found", f"no route for GET {self.path}")

    def do_POST(self):  # noqa: N802
        # request identity FIRST — before any routing answer, so a 404 on a
        # reused keep-alive connection cannot echo the previous request's id:
        # honor a client-supplied x-request-id, mint one otherwise; it
        # doubles as the trace id, so the header clients get back IS the key
        # into the sampled trace ledger
        self._request_id = (
            self.headers.get("x-request-id") or trace_lib.new_id()
        )
        if self.path != "/v1/predict":
            self._error(404, "not_found", f"no route for POST {self.path}")
            return
        tracer = self.ctx.telemetry.tracer
        t0 = time.perf_counter()
        if tracer.enabled:
            with tracer.span(
                trace_lib.SPAN_REQUEST, trace_id=self._request_id
            ) as span:
                status = self._predict(span)
                span.attrs["status"] = status
        else:
            status = self._predict(None)
        self._account_latency(status, time.perf_counter() - t0)
        self.ctx.note_drain_progress()
        # drill seam (resilience/faults.py): `serve --inject-fault
        # sigkill@N` hard-kills this replica after its Nth answered request —
        # the deterministic mid-soak replica death the fleet failover tests
        # and the bench's kill soak drive. Fired AFTER the response so the
        # triggering request itself is answered; in-flight requests on other
        # handler threads die with the process, which is the point.
        faults_lib.fire(faults_lib.SITE_REQUEST)

    # the tenant the in-flight POST resolved to; _predict sets it before
    # dispatch so _account_latency attributes the request histogram and SLO
    # sample to the right model (handlers are per-connection and a
    # connection's requests are sequential, so an instance attribute is safe)
    _runtime = None

    def _account_latency(self, status: int, dt: float) -> None:
        """End-to-end handler latency: answered requests feed the `request`
        histogram (and the SLO budget) of the model that answered; deadline
        expiries count as SLO violations even though they produce no latency
        sample."""
        runtime = self._runtime or self.ctx._primary
        slo = runtime.slo
        if status == 200:
            runtime.engine.registry.histogram("serve/request").record(dt)
            if slo is not None:
                slo.observe(dt)
        elif status == 504 and slo is not None:
            slo.observe_violation()

    def _predict(self, span) -> int:
        """The /v1/predict body; returns the HTTP status it answered with.
        ``span`` is the open request trace span (None when tracing is off):
        its context rides the batcher Request so the worker can emit this
        request's queue/pad/compute child spans."""
        self._runtime = None
        if self.ctx.draining:
            return self._error(
                503,
                "draining",
                "server is draining; retry elsewhere",
                retry_after=self.ctx.retry_after_s(),
            )
        try:
            length = int(self.headers.get("Content-Length", 0))
            payload = json.loads(self.rfile.read(length) or b"{}")
            instances = payload["instances"]
        except (ValueError, KeyError) as e:
            return self._error(
                400, "bad_request", f"expected JSON {{'instances': [...]}}: {e}"
            )
        # tenant selection: {"model": NAME} routes to that model's
        # engine/batcher; absent -> the primary model (the only one on a
        # legacy single-artifact replica); unknown -> structured 404
        model_name = payload.get("model")
        if model_name is not None and not isinstance(model_name, str):
            return self._error(
                400, "bad_request", "'model' must be a string"
            )
        runtime = self.ctx.model_runtime(model_name)
        if runtime is None:
            return self._error(
                404,
                "model_unknown",
                f"model {model_name!r} is not served here; "
                f"available: {sorted(self.ctx.models)}",
            )
        self._runtime = runtime
        try:
            x = np.asarray(instances, runtime.engine.input_dtype)
        except (ValueError, TypeError) as e:
            return self._error(400, "bad_request", f"instances not array-like: {e}")
        deadline_ms = payload.get("deadline_ms")
        try:
            request = runtime.batcher.submit(
                x,
                deadline_ms=deadline_ms,
                trace=span.context if span is not None else None,
            )
            out = request.result(timeout=self.ctx.result_timeout_s)
        except QueueFullError as e:
            return self._error(
                429, "queue_full", str(e),
                retry_after=self.ctx.retry_after_s(),
            )
        except RequestTooLargeError as e:
            return self._error(413, "request_too_large", str(e))
        except ServerClosedError as e:
            return self._error(
                503, "draining", str(e),
                retry_after=self.ctx.retry_after_s(),
            )
        except DeadlineExceededError as e:
            return self._error(504, "deadline_exceeded", str(e))
        except TimeoutError as e:
            return self._error(504, "result_timeout", str(e))
        except ValueError as e:  # wrong example shape
            return self._error(400, "bad_request", str(e))
        except Exception as e:  # noqa: BLE001 — engine failures surfaced by
            # the batcher must still answer structurally, never drop the socket
            logger.exception("inference failed")
            return self._error(500, "internal", f"{type(e).__name__}: {e}")
        import jax

        predictions = jax.tree_util.tree_map(
            lambda a: np.asarray(a).tolist(), out
        )
        self._json(200, {"predictions": predictions, "n": request.n})
        ctx = self.ctx
        if (
            (ctx.capture is not None or ctx.drift is not None)
            and runtime is ctx._primary
        ):
            # continuous-learning tees, AFTER the client was answered: the
            # capture enqueue is non-blocking and the drift fold is a
            # bincount, but neither may turn a served 200 into anything else
            try:
                raw = (
                    {k: np.asarray(v) for k, v in out.items()}
                    if isinstance(out, dict)
                    else {"output": np.asarray(out)}
                )
                if ctx.drift is not None:
                    ctx.drift.observe(raw)
                if ctx.capture is not None:
                    ctx.capture.maybe_capture(x, raw)
            except Exception:  # noqa: BLE001
                logger.exception("capture/drift tee failed")
        return 200
