"""Stdlib HTTP front-end: ``/v1/predict``, ``/healthz``, ``/metrics``.

A ``ThreadingHTTPServer`` (one thread per connection — the handler threads
block in ``Request.result()``, the single batcher worker does the compute, so
concurrency costs threads-waiting-on-events, not parallel TPU dispatch) in
front of the engine/batcher pair. Wire protocol, TF-Serving-shaped:

    POST /v1/predict   {"instances": [[...], ...], "deadline_ms": 250}
                    -> {"predictions": {...}, "n": k}
    GET  /healthz      {"ok": true, "status": "ok|degraded|draining",
                        "artifact": {...}, "uptime_s": ...}
    GET  /metrics      live registry snapshot + bucket hits + queue depth
                       (JSON by default; Prometheus text exposition under
                       ``Accept: text/plain`` or ``?format=prometheus``)

Every ``/v1/predict`` response — success and error alike, 429s and timeouts
included — echoes the request id as ``x-request-id`` (honoring a
client-supplied header, minting one otherwise); the id doubles as the
request's trace id, so a shed request is correlatable with server-side
telemetry from the client's copy of the id alone. Errors are structured,
never silent: 400 malformed input, 413 over the largest bucket, 429 queue
full (backpressure), 503 draining, 504 deadline — each body carries
``{"error": {"code", "message", "request_id"}}`` (``code`` is the
machine-readable kind) and bumps the matching registry counter.

SLO: with a p99 target configured (``--slo-p99-ms``), answered-request
latency feeds an ``obs.health.SloTracker`` (deadline expiries count as
violations); each ledger window evaluates the error budget, breaches write
``health_alert`` events, and ``/healthz`` reports ``status: "degraded"`` —
the signal a fleet router drains on.

Request-path telemetry: alongside the live ``/metrics`` view, the server
appends ``serve_window`` events to the workdir's ``telemetry.jsonl`` every
``window_secs`` (cumulative counters + that window's queue-wait/pad/compute
latency percentiles + post-warmup recompile count), and ``shutdown()`` drains
gracefully — intake stops, accepted requests finish, a final window and
``run_end`` land in the ledger. ``obs.report`` renders these as the ``serving``
section of the goodput report.
"""

from __future__ import annotations

import collections
import json
import logging
import math
import socket
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Deque, Dict, Optional, Tuple

import numpy as np

from tensorflowdistributedlearning_tpu.obs import health as health_lib
from tensorflowdistributedlearning_tpu.obs import trace as trace_lib
from tensorflowdistributedlearning_tpu.resilience import faults as faults_lib
from tensorflowdistributedlearning_tpu.obs.metrics import (
    time_summary,
    window_count,
)
from tensorflowdistributedlearning_tpu.obs.telemetry import NULL_TELEMETRY
from tensorflowdistributedlearning_tpu.serve.batcher import (
    DeadlineExceededError,
    MicroBatcher,
    QueueFullError,
    RequestTooLargeError,
    ServerClosedError,
)
from tensorflowdistributedlearning_tpu.serve.engine import InferenceEngine

logger = logging.getLogger(__name__)

# counters a serve_window snapshot carries (cumulative since server start)
_WINDOW_COUNTERS = (
    "requests",
    "completed",
    "rejected_queue_full",
    "deadline_exceeded",
    "errors",
    "batches",
    "batched_examples",
)
# per-window latency histograms, drained each window so a long-lived server
# holds at most one window's samples (same boundedness stance as the
# trainers' span histograms, obs/telemetry.py); "request" is end-to-end
# handler latency — what the SLO tracker budgets against
_WINDOW_HISTOGRAMS = ("queue_wait", "pad", "compute", "request")

# Retry-After bounds (seconds): a rejected client must neither hot-loop (<1s)
# nor give up on a replica that drains its queue in a few seconds (cap 30)
_RETRY_AFTER_MIN_S = 1
_RETRY_AFTER_MAX_S = 30
# with no observed drain yet (cold or fully stalled server) advertise a
# middle-of-the-road backoff rather than pretending to know the drain rate
_RETRY_AFTER_DEFAULT_S = 5


def bind_ephemeral(host: str = "127.0.0.1", port: int = 0) -> socket.socket:
    """Bind (without listening) a TCP socket — ``port=0`` picks a free
    ephemeral port the caller can read back via ``getsockname()`` BEFORE
    constructing the server around it. This is how ``serve --port 0`` knows
    its real port early enough to stamp it into the telemetry run header
    (written at ``Telemetry`` construction, before ``ServingServer`` exists),
    and how N replicas spawn into one test without port races."""
    sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    try:
        sock.bind((host, port))
    except OSError:
        sock.close()
        raise
    return sock


class ServingServer:
    """Engine + batcher behind a ThreadingHTTPServer, with ledger windows."""

    def __init__(
        self,
        engine: InferenceEngine,
        batcher: MicroBatcher,
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        telemetry=None,
        window_secs: float = 30.0,
        result_timeout_s: float = 60.0,
        slo_p99_ms: Optional[float] = None,
        slo_error_budget: float = 0.01,
        replica_id: int = 0,
        sock: Optional[socket.socket] = None,
    ):
        self.engine = engine
        self.batcher = batcher
        self.telemetry = telemetry if telemetry is not None else NULL_TELEMETRY
        self.window_secs = float(window_secs)
        self.result_timeout_s = float(result_timeout_s)
        # which fleet replica this server is: stamped on every serve_window
        # (and serve_start) ledger event so the multi-ledger merge
        # (obs/fleet.py) can attribute request-path telemetry per replica —
        # same role process_index plays for trainer ledgers
        self.replica_id = int(replica_id)
        # serving SLO (obs/health.py): p99 target as a windowed error budget;
        # None = no SLO tracking (healthz never degrades on latency)
        self.slo = (
            health_lib.SloTracker(slo_p99_ms, error_budget=slo_error_budget)
            if slo_p99_ms is not None
            else None
        )
        # HBM headroom monitor (obs/health.py): fed by the per-window
        # watermark sample below; a replica running out of device memory
        # degrades /healthz BEFORE it OOMs, so the fleet router drains it
        # while it can still answer. Inert on backends with no allocator
        # query (CPU builds never report a limit).
        self.headroom = health_lib.HeadroomMonitor()
        # per-request chip-seconds attribution (obs/capacity.py): the batcher
        # worker feeds the meter as batches dispatch; emit_window drains it
        # into `cost` ledger events and the rps-per-chip gauges. A server on
        # DISABLED telemetry gets its own meter: the default telemetry is the
        # process-global NULL_TELEMETRY singleton, and wiring two servers'
        # batchers into its one meter would cross-contaminate their windows
        from tensorflowdistributedlearning_tpu.obs import (
            capacity as capacity_lib,
        )

        self.cost_meter = (
            self.telemetry.cost
            if self.telemetry.enabled
            else capacity_lib.CostMeter()
        )
        self.batcher.cost_meter = self.cost_meter
        # same ownership rule for the watermark tracker: without live
        # telemetry nothing ledgers, but the /healthz OOM-drain protection
        # and the hbm gauges must still work — the server samples its own
        # tracker directly in that case (_emit_capacity_window)
        self.watermarks = (
            self.telemetry.watermarks
            if self.telemetry.enabled
            else capacity_lib.WatermarkTracker()
        )
        self._last_cost: Dict = {}
        # continuous profiling (obs/profiler.py): /admin/profile?seconds=N
        # on-demand captures, plus ONE rate-limited postmortem capture when
        # the SLO budget blows (emit_window). Timed captures only — the
        # serving tier has no train-step spans to count.
        from tensorflowdistributedlearning_tpu.obs.profiler import (
            ContinuousProfiler,
        )

        self.profiler = ContinuousProfiler(self.telemetry, phase="infer")
        if self.telemetry.enabled:
            self.telemetry.set_profiler(self.profiler)
        if self.slo is not None and self.window_secs <= 0:
            # the budget evaluates at window boundaries; with periodic windows
            # off only shutdown's final window (or a manual emit_window) runs
            # it — a breach would go unalerted for the server's lifetime
            logger.warning(
                "SLO tracking with window_secs=0: the error budget is only "
                "evaluated at shutdown; set a positive --window-secs for "
                "live health_alert events and /healthz degradation"
            )
        self.draining = False
        self._started_t = time.time()
        self._stop = threading.Event()
        self._shutdown_lock = threading.Lock()
        self._shut_down = False
        # drain-rate samples (monotonic_t, cumulative completed): what the
        # Retry-After header on 429/503 is derived from — how fast THIS
        # window's queue is actually emptying, not a fixed constant.
        # Locked: handler threads append AND expire concurrently (a 429
        # burst hits retry_after_s from dozens of threads at once)
        self._drain_samples: Deque[Tuple[float, int]] = collections.deque(
            maxlen=64
        )
        self._drain_lock = threading.Lock()
        handler = type("Handler", (_Handler,), {"ctx": self})
        self._httpd = ThreadingHTTPServer((host, port), handler, bind_and_activate=False)
        # stdlib default listen backlog is 5: a burst of concurrent connects
        # overflows it and the overflow retransmits SYNs for seconds — size it
        # like the request queue, and let quick restarts rebind the port
        self._httpd.request_queue_size = max(128, batcher.max_queue)
        if sock is not None:
            # adopt a pre-bound socket (bind_ephemeral): the caller learned
            # the real port before building Telemetry around this server
            self._httpd.socket.close()
            self._httpd.socket = sock
            bound_host, bound_port = sock.getsockname()[:2]
            self._httpd.server_address = (bound_host, bound_port)
            # what HTTPServer.server_bind would have set
            self._httpd.server_name = socket.getfqdn(bound_host)
            self._httpd.server_port = bound_port
        else:
            self._httpd.allow_reuse_address = True
            self._httpd.server_bind()
        self._httpd.server_activate()
        self._httpd.daemon_threads = True
        self._serve_thread: Optional[threading.Thread] = None
        self._ticker: Optional[threading.Thread] = None

    @property
    def host(self) -> str:
        return self._httpd.server_address[0]

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> "ServingServer":
        self._serve_thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="serve-http",
            daemon=True,
        )
        self._serve_thread.start()
        self.telemetry.event(
            "serve_start",
            endpoint=self.url,
            replica=self.replica_id,
            buckets=list(self.engine.buckets),
            max_batch_size=self.batcher.max_batch_size,
            max_wait_ms=self.batcher.max_wait_s * 1000,
            max_queue=self.batcher.max_queue,
        )
        if self.window_secs > 0:
            self._ticker = threading.Thread(
                target=self._tick, name="serve-window-ticker", daemon=True
            )
            self._ticker.start()
        logger.info("serving on %s (buckets %s)", self.url, self.engine.buckets)
        return self

    def wait(self) -> None:
        """Block the calling thread until ``shutdown()`` (the CLI foreground)."""
        self._stop.wait()

    def install_signal_handlers(self, signals=None) -> None:
        """SIGTERM/SIGINT trigger the graceful drain — the serving tier's
        preemption contract (resilience/): intake stops, accepted requests
        finish, the final ledger window and ``run_end`` land. Main thread
        only (the CPython signal rule)."""
        import signal as signal_lib

        for sig in signals or (signal_lib.SIGINT, signal_lib.SIGTERM):
            signal_lib.signal(sig, lambda *_: self.shutdown())

    @property
    def health_status(self) -> str:
        """The replica's live state a fleet router routes on: "draining" >
        "degraded" (SLO budget blown, or HBM headroom at OOM risk) > "ok"."""
        if self.draining:
            return "draining"
        if self.slo is not None and not self.slo.healthy:
            return "degraded"
        if self.headroom.degraded:
            return "degraded"
        return "ok"

    def artifact_identity(self) -> Optional[Dict]:
        """What this replica is actually serving — manifest dtype + source
        fingerprint (train/quantize.py) — so a readiness probe can tell
        replicas serving different artifacts apart. None for raw-closure /
        legacy engines whose manifest carries no quantization section."""
        q = self.engine.quantization
        if q is None:
            return None
        return {
            "dtype": q.get("dtype"),
            "source_fingerprint": q.get("source_fingerprint"),
        }

    def note_drain_progress(self) -> None:
        """Sample the cumulative completed counter (throttled to ~5Hz) so
        ``retry_after_s`` can estimate the live drain rate. Called from the
        request path — one deque append per answered request at most."""
        now = time.monotonic()
        with self._drain_lock:
            if self._drain_samples and now - self._drain_samples[-1][0] < 0.2:
                return
            completed = self.engine.registry.counter("serve/completed").value
            self._drain_samples.append((now, completed))

    def retry_after_s(self) -> int:
        """Seconds a rejected (429 queue-full / 503 draining) client should
        back off: current queue depth / the window's observed drain rate,
        clamped to [1, 30]. With no drain observed yet the estimate is a
        conservative default — better than hot-looping clients either way."""
        reg = self.engine.registry
        depth = reg.gauge("serve/queue_depth").value or 0
        now = time.monotonic()
        completed = reg.counter("serve/completed").value
        rate = 0.0
        with self._drain_lock:
            # rate over the recent past only: drop samples older than ~10s
            # so a long-idle server does not average its burst rate into
            # oblivion
            while (
                self._drain_samples
                and now - self._drain_samples[0][0] > 10.0
            ):
                self._drain_samples.popleft()
            if self._drain_samples:
                t0, c0 = self._drain_samples[0]
                if now - t0 >= 0.05 and completed > c0:
                    rate = (completed - c0) / (now - t0)
        if rate <= 0.0:
            return _RETRY_AFTER_DEFAULT_S
        return int(
            min(
                max(math.ceil(depth / rate), _RETRY_AFTER_MIN_S),
                _RETRY_AFTER_MAX_S,
            )
        )

    def metrics_snapshot(self) -> Dict:
        """The ``/metrics`` body: live registry view + serving identity."""
        reg = self.engine.registry
        snapshot = {
            "uptime_s": round(time.time() - self._started_t, 3),
            "draining": self.draining,
            "status": self.health_status,
            "buckets": {str(b): n for b, n in self.engine.bucket_hits.items()},
            "padding_waste": {
                str(b): w for b, w in self.engine.padding_waste.items()
            },
            "queue_depth": reg.gauge("serve/queue_depth").value or 0,
            # histograms here are "since the last ledger window" — the window
            # drain keeps a long-lived server's sample memory bounded
            "registry": reg.snapshot(),
        }
        if self.slo is not None:
            snapshot["slo"] = self.slo.snapshot()
        if self.engine.quantization is not None:
            snapshot["serving_dtype"] = self.engine.quantization.get("dtype")
        # the /healthz artifact identity, mirrored here so the fleet
        # router's poll captures what this replica serves in one request
        snapshot["artifact"] = self.artifact_identity()
        # capacity/cost views (obs/capacity.py): per-phase HBM peaks +
        # headroom estimate, cumulative chip-seconds + last window's rates —
        # what a scraper needs to see cost and OOM risk without the ledger
        snapshot["cost"] = self.cost_meter.snapshot()
        if self._last_cost:
            snapshot["cost"]["last_window"] = self._last_cost
        memory = self.watermarks.snapshot()
        if memory.get("peak_bytes"):
            snapshot["memory"] = memory
        return snapshot

    def prometheus_text(self) -> str:
        """The ``/metrics`` Prometheus exposition body (``text/plain;
        version=0.0.4``): the shared registry rendered by
        ``MetricsRegistry.render_prometheus``, with server-level state
        refreshed into gauges first so scrapers see uptime/drain/health
        without a second endpoint."""
        reg = self.engine.registry
        reg.gauge("serve/uptime_s").set(time.time() - self._started_t)
        reg.gauge("serve/draining").set(1.0 if self.draining else 0.0)
        reg.gauge("serve/healthy").set(
            1.0 if self.health_status == "ok" else 0.0
        )
        if self.slo is not None:
            reg.gauge("serve/slo_p99_target_ms").set(self.slo.p99_target_ms)
        # device-memory and cost series (obs/capacity.py): external scrapers
        # see headroom and chip-seconds without parsing ledgers
        cost = self.cost_meter.snapshot()
        reg.gauge("serve/chip_seconds_total").set(
            cost.get("chip_seconds_total", 0.0)
        )
        # unconditional: gauges persist in the registry, so an idle window
        # must overwrite the last busy window's rates with zero
        reg.gauge("serve/rps_per_chip").set(
            self._last_cost.get("rps_per_chip", 0.0)
        )
        reg.gauge("serve/cost_duty_cycle").set(
            self._last_cost.get("duty_cycle", 0.0)
        )
        per_req = self._last_cost.get("chip_seconds_per_request") or {}
        reg.gauge("serve/chip_seconds_per_request_p99").set(
            per_req.get("p99", 0.0)
        )
        memory = self.watermarks.snapshot()
        if memory.get("peak_bytes"):
            reg.gauge("serve/hbm_peak_bytes").set(memory["peak_bytes"])
            headroom = memory.get("headroom") or {}
            if headroom.get("headroom_frac") is not None:
                reg.gauge("serve/hbm_headroom_frac").set(
                    headroom["headroom_frac"]
                )
            if memory.get("bytes_limit"):
                reg.gauge("serve/hbm_bytes_limit").set(memory["bytes_limit"])
        return reg.render_prometheus()

    def emit_window(self, final: bool = False) -> Dict:
        """One ``serve_window`` ledger event: cumulative counters, this
        window's latency split (ms percentiles), post-warmup recompiles."""
        reg = self.engine.registry
        fields: Dict = {
            k: reg.counter(f"serve/{k}").value for k in _WINDOW_COUNTERS
        }
        fields["replica"] = self.replica_id
        fields["queue_depth"] = reg.gauge("serve/queue_depth").value or 0
        fields["bucket_hits"] = {
            str(b): n for b, n in self.engine.bucket_hits.items()
        }
        # ladder utilization: fraction of compiled batch slots filled with
        # padding, per bucket that saw traffic (cumulative, like the hits)
        waste = self.engine.padding_waste
        if waste:
            fields["padding_waste"] = {str(b): w for b, w in waste.items()}
        if self.engine.quantization is not None:
            fields["serving_dtype"] = self.engine.quantization.get("dtype")
        latency: Dict = {}
        for name in _WINDOW_HISTOGRAMS:
            samples = reg.histogram(f"serve/{name}").drain()
            if samples:
                summary = time_summary(samples)
                latency[name] = {
                    k[:-2] + "_ms": round(v * 1000, 3)
                    for k, v in summary.items()
                    if k.endswith("_s") and k != "total_s"
                }
                # exact even when the histogram ring capped the raw samples
                latency[name]["count"] = float(window_count(samples))
        if latency:
            fields["latency_ms"] = latency
        detector = self.telemetry.detector
        if detector is not None:
            fields["recompiles_post_warmup"] = detector.post_warmup_count
        if self.slo is not None:
            # evaluate the error budget on the window boundary: breaches /
            # recoveries become health_alert events, and the live state rides
            # in the window for the report's health section
            verdict = self.slo.evaluate()
            if verdict is not None:
                verdict.setdefault("alert_id", trace_lib.new_id())
                self.telemetry.event(health_lib.HEALTH_ALERT_EVENT, **verdict)
                if not verdict.get("resolved"):
                    # SLO budget blown: capture ONE rate-limited postmortem
                    # profile stamped with the triggering alert id — the
                    # evidence an on-call wants is the trace from the bad
                    # minutes, not a capture requested after the fact
                    self.profiler.trigger(verdict, seconds=2.0)
            fields["slo"] = self.slo.snapshot()
        if final:
            fields["final"] = True
        self.telemetry.event("serve_window", **fields)
        self._emit_capacity_window()
        return fields

    def _emit_capacity_window(self) -> None:
        """The capacity/cost half of a window boundary (obs/capacity.py):
        one allocator watermark sample attributed to the infer phase (fed to
        the headroom monitor — low headroom degrades /healthz), and one
        ``cost`` ledger event draining the window's per-request chip-second
        attribution. Both are no-ops on an idle window / statless backend."""
        from tensorflowdistributedlearning_tpu.obs import (
            capacity as capacity_lib,
        )

        if self.telemetry.enabled:
            self.telemetry.sample_watermark(capacity_lib.PHASE_INFER)
        else:
            # no ledger, but the tracker still samples so /healthz and the
            # hbm gauges keep their OOM-drain protection
            self.watermarks.sample(capacity_lib.PHASE_INFER)
        # the monitor runs on the tracker's LIVE headroom every window — not
        # only when the peak advanced — so a trend-triggered degraded state
        # resolves once the peak plateaus instead of sticking forever
        headroom = self.watermarks.headroom()
        if headroom and headroom.get("bytes_limit"):
            alert = self.headroom.check(
                None,
                headroom["peak_bytes"],
                headroom["bytes_limit"],
                samples_to_limit=headroom.get("samples_to_limit"),
            )
            if alert:
                alert["replica"] = self.replica_id
                self.telemetry.event(health_lib.HEALTH_ALERT_EVENT, **alert)
        cost = self.cost_meter.serve_window()
        if cost:
            cost["replica"] = self.replica_id
            self._last_cost = cost
            self.telemetry.event(capacity_lib.COST_EVENT, **cost)
        else:
            # idle window: the last busy window's RATES are stale the moment
            # a new window closes without traffic — scrapers and the router
            # must see zero, not phantom throughput
            self._last_cost = {}

    def _tick(self) -> None:
        while not self._stop.wait(self.window_secs):
            try:
                self.emit_window()
            except Exception:  # noqa: BLE001 — telemetry never kills serving
                logger.exception("serve window emission failed")

    def shutdown(self) -> None:
        """Graceful drain: refuse new work, finish accepted requests, write
        the final ledger window, stop the listener. Idempotent."""
        with self._shutdown_lock:
            if self._shut_down:
                return
            self._shut_down = True
        self.draining = True
        self._stop.set()
        if self._ticker is not None:
            self._ticker.join(timeout=5)
        self.batcher.close(drain=True)
        try:
            final = self.emit_window(final=True)
        except Exception:  # noqa: BLE001
            logger.exception("final serve window emission failed")
            final = {}
        try:
            # stop any in-flight timed capture and ledger what it got;
            # telemetry.close would do this too, but only when it owns the
            # profiler (enabled telemetry) — close is idempotent either way
            self.profiler.close()
        except Exception:  # noqa: BLE001
            logger.warning("profiler close failed", exc_info=True)
        self.telemetry.close(
            kind="serve",
            requests=final.get("requests"),
            completed=final.get("completed"),
            rejected_queue_full=final.get("rejected_queue_full"),
            deadline_exceeded=final.get("deadline_exceeded"),
        )
        # only break serve_forever if it ever ran: BaseServer.shutdown()
        # waits on an event that ONLY serve_forever sets, so calling it on a
        # constructed-but-never-started server deadlocks forever
        if self._serve_thread is not None:
            self._httpd.shutdown()
        self._httpd.server_close()
        if self._serve_thread is not None:
            self._serve_thread.join(timeout=5)
        logger.info("serving stopped (drained)")


class _Handler(BaseHTTPRequestHandler):
    ctx: ServingServer  # bound by ServingServer via a subclass attribute
    # HTTP/1.1 keep-alive: every response sets Content-Length below
    protocol_version = "HTTP/1.1"
    # small request/response bodies in separate writes + Nagle + delayed ACK
    # = ~200ms per round trip on loopback; inference RPCs always disable it
    disable_nagle_algorithm = True

    def log_message(self, fmt, *args):  # route access logs to logging, quiet
        logger.debug("%s - %s", self.address_string(), fmt % args)

    # set per request by do_POST; echoed on every response it produces
    _request_id: Optional[str] = None

    def _json(
        self,
        status: int,
        payload: Dict,
        extra_headers: Optional[Dict[str, str]] = None,
    ) -> None:
        body = json.dumps(payload).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        if self._request_id:
            self.send_header("x-request-id", self._request_id)
        for k, v in (extra_headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _text(self, status: int, body: str, content_type: str) -> None:
        raw = body.encode()
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(raw)))
        self.end_headers()
        self.wfile.write(raw)

    def _error(
        self,
        status: int,
        code: str,
        message: str,
        retry_after: Optional[int] = None,
    ) -> int:
        """Structured error: ``code`` is the machine-readable kind, and the
        request id (when one exists — every /v1/predict error has one, 429s
        and timeouts included) rides in the body AND the x-request-id header
        so a shed request is correlatable with server-side telemetry.
        Backpressure statuses (429 queue-full, 503 draining) carry a
        ``Retry-After`` header derived from the window's drain rate
        (``ServingServer.retry_after_s``) so clients — the fleet router
        included — back off intelligently instead of hot-looping. Returns
        ``status`` so the predict path can hand it back in one expression."""
        error: Dict = {"code": code, "message": message}
        if self._request_id:
            error["request_id"] = self._request_id
        headers = None
        if retry_after is not None:
            error["retry_after_s"] = int(retry_after)
            headers = {"Retry-After": str(int(retry_after))}
        self._json(status, {"error": error}, extra_headers=headers)
        return status

    def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler contract
        # keep-alive reuses handler instances: a GET after a POST on the same
        # connection must not echo the previous request's id
        self._request_id = None
        parsed = urllib.parse.urlparse(self.path)
        if parsed.path == "/healthz":
            server_status = self.ctx.health_status
            status = 503 if self.ctx.draining else 200
            body = {
                # ok = "answers traffic within contract": draining AND
                # SLO-degraded replicas both report false, with `status`
                # naming which; only draining refuses traffic (503)
                "ok": server_status == "ok",
                "status": server_status,
                "replica": self.ctx.replica_id,
                "draining": self.ctx.draining,
                "uptime_s": round(time.time() - self.ctx._started_t, 3),
                "buckets": list(self.ctx.engine.buckets),
                # artifact identity: which export this replica answers from
                "artifact": self.ctx.artifact_identity(),
            }
            if self.ctx.slo is not None:
                body["slo"] = self.ctx.slo.snapshot()
            if self.ctx.headroom.last is not None:
                # the OOM-risk view a fleet controller drains on (None until
                # a device watermark sample exists — CPU builds stay silent)
                body["memory"] = dict(
                    self.ctx.headroom.last,
                    degraded=self.ctx.headroom.degraded,
                )
            self._json(status, body)
        elif parsed.path == "/metrics":
            query = urllib.parse.parse_qs(parsed.query)
            accept = self.headers.get("Accept", "")
            if (
                query.get("format", [""])[0] == "prometheus"
                or "text/plain" in accept
                or "openmetrics" in accept
            ):
                self._text(
                    200,
                    self.ctx.prometheus_text(),
                    "text/plain; version=0.0.4; charset=utf-8",
                )
            else:
                self._json(200, self.ctx.metrics_snapshot())
        elif parsed.path == "/admin/profile":
            # on-demand capture: kick a timed jax.profiler capture in the
            # background and answer immediately (202); the parsed roofline
            # lands in the ledger when the capture window closes. 409 while
            # another capture is in flight — the running one wins.
            query = urllib.parse.parse_qs(parsed.query)
            try:
                seconds = float(query.get("seconds", ["1"])[0])
            except ValueError:
                self._error(400, "bad_request", "seconds must be a number")
                return
            if not (0 < seconds <= 60):
                self._error(
                    400, "bad_request", "seconds must be in (0, 60]"
                )
                return
            if self.ctx.profiler.logdir is None:
                self._error(
                    503, "profiling_unavailable",
                    "no telemetry workdir to write captures into",
                )
                return
            started = self.ctx.profiler.capture_timed(seconds, reason="admin")
            if started is None:
                self._error(
                    409, "capture_in_flight",
                    "a profile capture is already running on this replica",
                )
                return
            started["replica"] = self.ctx.replica_id
            self._json(202, started)
        else:
            self._error(404, "not_found", f"no route for GET {self.path}")

    def do_POST(self):  # noqa: N802
        # request identity FIRST — before any routing answer, so a 404 on a
        # reused keep-alive connection cannot echo the previous request's id:
        # honor a client-supplied x-request-id, mint one otherwise; it
        # doubles as the trace id, so the header clients get back IS the key
        # into the sampled trace ledger
        self._request_id = (
            self.headers.get("x-request-id") or trace_lib.new_id()
        )
        if self.path != "/v1/predict":
            self._error(404, "not_found", f"no route for POST {self.path}")
            return
        tracer = self.ctx.telemetry.tracer
        t0 = time.perf_counter()
        if tracer.enabled:
            with tracer.span(
                trace_lib.SPAN_REQUEST, trace_id=self._request_id
            ) as span:
                status = self._predict(span)
                span.attrs["status"] = status
        else:
            status = self._predict(None)
        self._account_latency(status, time.perf_counter() - t0)
        self.ctx.note_drain_progress()
        # drill seam (resilience/faults.py): `serve --inject-fault
        # sigkill@N` hard-kills this replica after its Nth answered request —
        # the deterministic mid-soak replica death the fleet failover tests
        # and the bench's kill soak drive. Fired AFTER the response so the
        # triggering request itself is answered; in-flight requests on other
        # handler threads die with the process, which is the point.
        faults_lib.fire(faults_lib.SITE_REQUEST)

    def _account_latency(self, status: int, dt: float) -> None:
        """End-to-end handler latency: answered requests feed the `request`
        histogram (and the SLO budget); deadline expiries count as SLO
        violations even though they produce no latency sample."""
        slo = self.ctx.slo
        if status == 200:
            self.ctx.engine.registry.histogram("serve/request").record(dt)
            if slo is not None:
                slo.observe(dt)
        elif status == 504 and slo is not None:
            slo.observe_violation()

    def _predict(self, span) -> int:
        """The /v1/predict body; returns the HTTP status it answered with.
        ``span`` is the open request trace span (None when tracing is off):
        its context rides the batcher Request so the worker can emit this
        request's queue/pad/compute child spans."""
        if self.ctx.draining:
            return self._error(
                503,
                "draining",
                "server is draining; retry elsewhere",
                retry_after=self.ctx.retry_after_s(),
            )
        try:
            length = int(self.headers.get("Content-Length", 0))
            payload = json.loads(self.rfile.read(length) or b"{}")
            instances = payload["instances"]
        except (ValueError, KeyError) as e:
            return self._error(
                400, "bad_request", f"expected JSON {{'instances': [...]}}: {e}"
            )
        try:
            x = np.asarray(instances, self.ctx.engine.input_dtype)
        except (ValueError, TypeError) as e:
            return self._error(400, "bad_request", f"instances not array-like: {e}")
        deadline_ms = payload.get("deadline_ms")
        try:
            request = self.ctx.batcher.submit(
                x,
                deadline_ms=deadline_ms,
                trace=span.context if span is not None else None,
            )
            out = request.result(timeout=self.ctx.result_timeout_s)
        except QueueFullError as e:
            return self._error(
                429, "queue_full", str(e),
                retry_after=self.ctx.retry_after_s(),
            )
        except RequestTooLargeError as e:
            return self._error(413, "request_too_large", str(e))
        except ServerClosedError as e:
            return self._error(
                503, "draining", str(e),
                retry_after=self.ctx.retry_after_s(),
            )
        except DeadlineExceededError as e:
            return self._error(504, "deadline_exceeded", str(e))
        except TimeoutError as e:
            return self._error(504, "result_timeout", str(e))
        except ValueError as e:  # wrong example shape
            return self._error(400, "bad_request", str(e))
        except Exception as e:  # noqa: BLE001 — engine failures surfaced by
            # the batcher must still answer structurally, never drop the socket
            logger.exception("inference failed")
            return self._error(500, "internal", f"{type(e).__name__}: {e}")
        import jax

        predictions = jax.tree_util.tree_map(
            lambda a: np.asarray(a).tolist(), out
        )
        self._json(200, {"predictions": predictions, "n": request.n})
        return 200
