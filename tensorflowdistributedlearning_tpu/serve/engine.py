"""Bucketed-compilation inference engine: the shape-discipline layer of serving.

A TPU serving path lives or dies on two things the training stack already
learned the hard way (obs/recompile.py): every distinct input shape is its own
XLA executable, and a post-warmup compile stalls every chip for seconds. A
naive server that forwards whatever batch size arrives compiles once per
observed size — and production traffic observes *every* size. The standard
discipline (Gemma-on-TPU serving, arXiv:2605.25645 §4; TF-Serving's batching
contract) is a fixed ladder of batch **buckets**: requests are zero-padded up
to the smallest bucket that fits, so steady state touches only
``len(buckets)`` executables, all of them compiled during warmup.

``InferenceEngine`` wraps either a loaded ``jax.export`` artifact
(:meth:`from_artifact`) or any params-baked ``x -> pytree`` closure, owns the
pad → compute → slice round-trip, pre-warms every bucket, and records the
pad/compute latency split plus per-bucket hit counts into an
``obs.metrics.MetricsRegistry`` so ``/metrics`` and the serve ledger windows
report from the same instruments the trainers use.
"""

from __future__ import annotations

import bisect
import contextlib
import logging
import os
import tempfile
import threading
import time
from typing import Callable, Dict, Optional, Sequence, Tuple

import numpy as np

from tensorflowdistributedlearning_tpu.obs import trace as trace_lib
from tensorflowdistributedlearning_tpu.obs.metrics import MetricsRegistry

logger = logging.getLogger(__name__)

# the ladder production TPU servers converge on: fine steps at the small end
# (latency-sensitive singletons), coarse at the top (throughput batches)
DEFAULT_BUCKETS: Tuple[int, ...] = (1, 4, 16, 64)

# reusable no-op context: the untraced request path must not pay even the
# generator-contextmanager entry of a disabled tracer span
_NULL_CTX = contextlib.nullcontext()


class RequestTooLargeError(ValueError):
    """A request carries more examples than the largest compiled bucket —
    the caller must chunk it; silently splitting here would reorder the
    batcher's fairness guarantees."""


# manifest-adjacent cache subdir an exporter may ship beside the artifact
# (train/serving.py attach_compile_cache); warmup LOADS these executables
# instead of compiling them — the load-not-compile replica path
ARTIFACT_CACHE_SUBDIR = "compile_cache"


def consume_artifact_cache(directory: str, manifest: Optional[Dict]) -> int:
    """Fold an artifact's shipped compile-cache subdir into this process's
    active persistent cache so the subsequent warmup loads, not compiles.

    The manifest's ``compile_cache`` section records the subdir's
    fingerprint at export time; a mismatch (truncated copy, mixed artifact)
    warns and skips the shipped entries — a stale cache entry is harmless
    (keys are content-addressed) but a torn one is not worth the risk. When
    no cache dir is configured yet, the entries land in a throwaway temp
    cache so the artifact directory itself is never written to at runtime.
    Returns the number of entries merged (0 = nothing shipped/usable)."""
    from tensorflowdistributedlearning_tpu.utils import compile_cache

    sub = os.path.join(directory, ARTIFACT_CACHE_SUBDIR)
    if not os.path.isdir(sub):
        return 0
    recorded = (manifest or {}).get("compile_cache")
    if recorded and recorded.get("fingerprint"):
        fresh = compile_cache.fingerprint(sub)
        if fresh["fingerprint"] != recorded["fingerprint"]:
            logger.warning(
                "artifact %s ships a compile cache whose fingerprint does "
                "not match its manifest (%s entries on disk vs %s recorded) "
                "— skipping the shipped cache; warmup will compile",
                directory, fresh["entries"], recorded.get("entries"),
            )
            return 0
    dst = compile_cache.active_dir()
    if dst is None:
        dst = tempfile.mkdtemp(prefix="tfdl-compile-cache-")
        if not compile_cache.configure(dst):
            return 0
    merged = compile_cache.merge(sub, dst)
    if merged:
        logger.info(
            "loaded %d shipped compile-cache entries from %s", merged, sub
        )
    return merged


def _tree_map(fn, tree):
    """Apply ``fn`` to every output leaf. Dict outputs (what both tasks'
    ``predictions`` return) take a direct path — ``jax.tree_util.tree_map``
    costs ~10µs per call, which at one call per request per batch is real
    money on the request path."""
    if isinstance(tree, dict):
        return {k: fn(v) for k, v in tree.items()}
    import jax

    return jax.tree_util.tree_map(fn, tree)


class InferenceEngine:
    """Pads request batches into a fixed bucket ladder and runs ``serve_fn``.

    ``serve_fn`` maps ``x [B, *example_shape] -> pytree of arrays [B, ...]``
    with parameters baked in (exactly what ``train/serving.py`` artifacts and
    the trainers' ``serving_fn()`` closures provide). ``infer`` is thread-safe:
    registry instrument updates are GIL-atomic appends/increments, and the
    pad scratch buffers are thread-local (the single batcher worker
    materializes exactly one ladder of them).
    """

    def __init__(
        self,
        serve_fn: Callable,
        example_shape: Sequence[int],
        *,
        buckets: Sequence[int] = DEFAULT_BUCKETS,
        input_dtype="float32",
        registry: Optional[MetricsRegistry] = None,
        quantization: Optional[Dict] = None,
        tracer: Optional[trace_lib.Tracer] = None,
    ):
        self.serve_fn = serve_fn
        self.example_shape = tuple(int(d) for d in example_shape)
        self.buckets = tuple(sorted({int(b) for b in buckets}))
        if not self.buckets or self.buckets[0] < 1:
            raise ValueError(f"buckets must be positive ints, got {buckets!r}")
        self.input_dtype = np.dtype(input_dtype)
        # manifest self-description of the artifact's precision recipe
        # (train/quantize.py section); None for raw closures / legacy
        # artifacts — informational: the graph itself carries the dtypes
        self.quantization = quantization
        self.registry = registry if registry is not None else MetricsRegistry()
        # per-request tracing (obs/trace.py): infer() emits pad/compute spans
        # that nest under the batcher's batch span; the null tracer keeps the
        # request path branch-free when tracing is off
        self.tracer = tracer if tracer is not None else trace_lib.NULL_TRACER
        self._pad_h = self.registry.histogram("serve/pad")
        self._compute_h = self.registry.histogram("serve/compute")
        # pre-create so /metrics shows the whole ladder even before traffic
        self._hit_counters = {
            b: self.registry.counter(f"serve/bucket_hits/{b}")
            for b in self.buckets
        }
        # real examples per bucket, beside the hit counts: hits*bucket vs
        # examples is the ladder's padding-waste — the utilization signal
        # that says whether the ladder fits the traffic
        self._example_counters = {
            b: self.registry.counter(f"serve/bucket_examples/{b}")
            for b in self.buckets
        }
        # per-bucket scratch pad the request path copies into instead of
        # allocating (np.concatenate allocated a fresh bucket-sized array
        # per dispatch); thread-local so concurrent infer() callers never
        # share a buffer — one worker thread (the batcher) materializes
        # exactly one ladder of buffers
        self._scratch = threading.local()
        self.warmed = False
        # buckets actually compiled so far — warmup(budget=N) may leave the
        # top of the ladder cold on purpose; a cold bucket compiles on its
        # first hit and that hit is counted (serve/cold_bucket_hits/{b})
        # so the tradeoff is visible in /metrics and the ledger
        self.warmed_buckets: set = set()
        self._cold_counters = {
            b: self.registry.counter(f"serve/cold_bucket_hits/{b}")
            for b in self.buckets
        }

    @classmethod
    def from_artifact(
        cls,
        directory: str,
        *,
        buckets: Sequence[int] = DEFAULT_BUCKETS,
        registry: Optional[MetricsRegistry] = None,
        tracer: Optional[trace_lib.Tracer] = None,
    ) -> "InferenceEngine":
        """Engine over an exported StableHLO artifact (``train/serving.py``).

        The manifest supplies the example shape and input dtype. An artifact
        exported with a FIXED batch dimension (``batch_polymorphic=False``)
        supports exactly one shape, so the ladder collapses to that single
        bucket regardless of ``buckets``.
        """
        from tensorflowdistributedlearning_tpu.train import serving as serving_lib

        serve = serving_lib.load_serving_artifact(directory)
        manifest = serving_lib.read_manifest(directory)
        # shipped cache entries must be active BEFORE warmup compiles the
        # ladder — this is what turns a replica spawn into a load, not a
        # compile (failures degrade to a normal compiling warmup)
        try:
            consume_artifact_cache(directory, manifest)
        except Exception:  # noqa: BLE001 — a bad cache must not block serving
            logger.warning("shipped compile cache unusable", exc_info=True)
        shape = manifest["input_shape"]
        if any(d is None for d in shape[1:]):
            raise ValueError(
                f"artifact input shape {shape} has a symbolic non-batch dim — "
                "the engine needs static example shapes to pad against"
            )
        if shape[0] is not None:
            logger.info(
                "artifact %s was exported with fixed batch %d — bucket ladder "
                "collapses to that single bucket", directory, shape[0],
            )
            buckets = (int(shape[0]),)
        return cls(
            serve,
            tuple(shape[1:]),
            buckets=buckets,
            # read_manifest applied the legacy float32 default (and rejected
            # corrupt quantization metadata) — the engine just consumes
            input_dtype=manifest["input_dtype"],
            registry=registry,
            quantization=manifest.get("quantization"),
            tracer=tracer,
        )

    @property
    def compute_dtype(self) -> Optional[str]:
        """The manifest-declared matmul/conv arithmetic dtype
        (``quantization.compute_dtype``, read_manifest-defaulted): "int8"
        when the graph was traced through the quantized-compute kernels,
        the float dtype for dequantize-in-graph/plain artifacts, ``None``
        for raw closures with no quantization section. Informational — the
        exported graph carries its own arithmetic; this is how telemetry
        and the quantize-check gate know which budget applies."""
        if not self.quantization:
            return None
        return self.quantization.get("compute_dtype")

    @property
    def max_batch_size(self) -> int:
        return self.buckets[-1]

    @property
    def bucket_hits(self) -> Dict[int, int]:
        return {
            b: self.registry.counter(f"serve/bucket_hits/{b}").value
            for b in self.buckets
        }

    @property
    def padding_waste(self) -> Dict[int, float]:
        """Per-bucket fraction of compiled batch slots filled with padding:
        ``1 - examples / (hits * bucket)``. Only buckets that saw traffic
        appear — 32-client closed-loop traffic all landing in bucket 64
        shows up as waste 0.5 there (at most 32 live rows per compiled
        64-slot batch), an all-singletons pattern through bucket 4 as
        waste 0.75."""
        waste: Dict[int, float] = {}
        for b in self.buckets:
            hits = self._hit_counters[b].value
            if hits:
                examples = self._example_counters[b].value
                waste[b] = round(1.0 - examples / (hits * b), 4)
        return waste

    def _scratch_for(self, bucket: int) -> np.ndarray:
        bufs = getattr(self._scratch, "bufs", None)
        if bufs is None:
            bufs = self._scratch.bufs = {}
        buf = bufs.get(bucket)
        if buf is None or buf.dtype != self.input_dtype:
            # allocated in the ARTIFACT's wire dtype, never a float32
            # default: an int8/bf16-input artifact padding through a f32
            # scratch would silently upcast (and re-cast) every request
            # batch before dispatch. The dtype recheck keeps a cached
            # ladder from going stale if input_dtype is ever rebound.
            buf = bufs[bucket] = np.zeros(
                (bucket, *self.example_shape), self.input_dtype
            )
        return buf

    def select_bucket(self, n: int) -> int:
        """Smallest bucket that fits ``n`` examples."""
        if n < 1:
            raise ValueError(f"cannot serve an empty batch (n={n})")
        i = bisect.bisect_left(self.buckets, n)
        if i == len(self.buckets):
            raise RequestTooLargeError(
                f"{n} examples exceeds the largest bucket "
                f"({self.max_batch_size}); chunk the request"
            )
        return self.buckets[i]

    def warmup(
        self,
        telemetry=None,
        *,
        budget: Optional[int] = None,
        mark_warm: bool = True,
    ) -> Dict[int, float]:
        """Compile the bucket ladder up front (zeros input), returning
        per-bucket wall seconds. After this, steady-state serving touches
        only warmed shapes — when ``telemetry`` is passed, its recompile
        detector is marked warm so any later compile is flagged (and
        ledgered) as the goodput bug it is.

        ``budget`` caps how many buckets are compiled, smallest first (the
        registry's ``prewarm_budget`` / ``serve --prewarm-buckets`` knob):
        spawn-to-ready time trades against a first-request compile stall on
        each cold bucket. Cold buckets are excluded from the recompile
        detector's warm mark only in the sense that their first hit is
        ledgered per bucket (``serve/cold_bucket_hits/{b}``).

        ``mark_warm=False`` defers arming the recompile detector: a replica
        loading SEVERAL engines (multi-tenant registry load) warms them in
        sequence and must mark warm once, after the LAST — otherwise every
        engine after the first would be flagged as a steady-state
        recompile.

        Buckets compile CONCURRENTLY (XLA releases the GIL for the whole
        backend compile) after the smallest bucket compiles alone — the
        first-ever call through a loaded Exported must not race itself (see
        the comment below), so ladder warmup costs ~smallest + slowest
        instead of the sum. Each bucket joins ``warmed_buckets`` as its own
        compile lands, and the detector's warm mark still happens strictly
        after every bucket — the ordering contract is unchanged."""
        import jax

        to_warm = self.buckets
        if budget is not None and budget < len(self.buckets):
            to_warm = self.buckets[: max(0, int(budget))]
        timings: Dict[int, float] = {}

        def _compile(b: int) -> float:
            # transient zeros: the request-path scratch pads are thread-local
            # and the batcher worker is a different thread than the one
            # running warmup — filling this thread's ladder would just leave
            # a dead duplicate alive for the engine's lifetime
            x = np.zeros((b, *self.example_shape), self.input_dtype)
            t0 = time.perf_counter()
            jax.block_until_ready(self.serve_fn(x))
            return round(time.perf_counter() - t0, 6)

        if to_warm:
            # The FIRST call must be alone: jax caches the jitted wrapper
            # around a loaded Exported under an lru keyed on the exported
            # object, and concurrent first-ever calls race its miss path —
            # each builds its own wrapper, the bucket executables split
            # across them, and only one wrapper survives in the cache. The
            # survivor is then missing the other threads' shapes, so the
            # first request on a "lost" bucket recompiles AFTER the warm
            # mark — the exact goodput bug warmup exists to prevent
            # (surfaced as a flaky post-warmup recompile under the full
            # test sweep). Warming the smallest bucket synchronously
            # populates the cache entry; the remaining buckets then share
            # the one wrapper and still overlap their compiles.
            timings[to_warm[0]] = _compile(to_warm[0])
            self.warmed_buckets.add(to_warm[0])
        rest = to_warm[1:]
        if len(rest) > 1:
            from concurrent.futures import ThreadPoolExecutor, as_completed

            with ThreadPoolExecutor(
                max_workers=len(rest), thread_name_prefix="warmup"
            ) as pool:
                futures = {pool.submit(_compile, b): b for b in rest}
                for fut in as_completed(futures):
                    b = futures[fut]
                    timings[b] = fut.result()
                    self.warmed_buckets.add(b)
        else:
            for b in rest:
                timings[b] = _compile(b)
                self.warmed_buckets.add(b)
        self.warmed = True
        if telemetry is not None:
            warm_fields = {}
            if self.quantization is not None:
                warm_fields["serving_dtype"] = self.quantization.get("dtype")
                if self.quantization.get("compute_dtype"):
                    warm_fields["compute_dtype"] = self.quantization[
                        "compute_dtype"
                    ]
            cold = [b for b in self.buckets if b not in self.warmed_buckets]
            if cold:
                warm_fields["cold_buckets"] = [str(b) for b in cold]
                warm_fields["prewarm_budget"] = len(to_warm)
            telemetry.event(
                "serve_warmup",
                buckets={str(b): timings[b] for b in sorted(timings)},
                example_shape=list(self.example_shape),
                input_dtype=str(self.input_dtype),
                **warm_fields,
            )
            if mark_warm:
                telemetry.mark_warm()
            # bucket compilation is the serving tier's peak-HBM moment on
            # most artifacts — ledger it as the compile-phase watermark
            # before request traffic attributes anything to "infer"
            sample = getattr(telemetry, "sample_watermark", None)
            if sample is not None:
                from tensorflowdistributedlearning_tpu.obs import (
                    capacity as capacity_lib,
                )

                sample(capacity_lib.PHASE_COMPILE)
        return timings

    def infer(self, x) -> Dict:
        """Forward ``x [n, *example_shape]`` through the bucket ladder: pad to
        the selected bucket, run, slice every output back to ``n`` rows."""
        import jax

        x = np.asarray(x, self.input_dtype)
        if x.shape[1:] != self.example_shape:
            raise ValueError(
                f"expected examples of shape {self.example_shape}, "
                f"got batch {x.shape}"
            )
        n = x.shape[0]
        bucket = self.select_bucket(n)
        if self.warmed and bucket not in self.warmed_buckets:
            # cold bucket past a budgeted warmup: this dispatch pays the
            # compile. Count it (per bucket) and fold the bucket into the
            # warmed set — the executable is cached from here on.
            self._cold_counters[bucket].inc()
            self.warmed_buckets.add(bucket)
        # trace spans nest under the caller's active span (the batcher's
        # batch span) via the tracer's thread-local stack; disabled tracing
        # costs one attribute read per infer
        traced = self.tracer.enabled
        attrs = {"bucket": bucket, "n": n} if traced else None
        t0 = time.perf_counter()
        with (
            self.tracer.span(trace_lib.SPAN_PAD, attrs=attrs)
            if traced
            else _NULL_CTX
        ):
            if n != bucket:
                # copy into the bucket's reusable scratch pad (zeroing the
                # tail, which may hold rows from a previous, fuller dispatch)
                # instead of concatenating into a fresh allocation every
                # call. infer() blocks until the device result is ready
                # before returning, so within a thread the buffer is never
                # overwritten mid-compute.
                buf = self._scratch_for(bucket)
                buf[:n] = x
                buf[n:] = 0
                x = buf
        self._pad_h.record(time.perf_counter() - t0)
        t0 = time.perf_counter()
        with (
            self.tracer.span(trace_lib.SPAN_COMPUTE, attrs=attrs)
            if traced
            else _NULL_CTX
        ):
            out = jax.block_until_ready(self.serve_fn(x))
        self._compute_h.record(time.perf_counter() - t0)
        self._hit_counters[bucket].inc()
        self._example_counters[bucket].inc(n)
        return _tree_map(lambda a: np.asarray(a)[:n], out)
