"""Versioned artifact registry: the control-plane record of what a fleet serves.

A single-tenant fleet needs one path: ``--artifact-dir``. A multi-tenant
fleet needs a *document* — which models exist, which artifact version each
one currently serves, how much of its bucket ladder to pre-warm, what SLO it
promised, and how its replicas share the host's chips. That document is
``registry.json`` in the fleet workdir, and this module is its single
reader/writer.

Design rules, in the order they bit previous subsystems:

* **Versioned schema, strict reads.** ``schema_version`` is checked and every
  field — top-level and per-entry — is validated at read time; unknown fields
  are rejected rather than ignored, so a typo'd ``prewarm_budgit`` fails the
  fleet at spawn instead of silently warming everything (the manifest.json
  lesson from train/serving.py).
* **No flag-day.** A workdir that holds a legacy single-artifact layout (no
  ``registry.json``) loads as an *implicit* one-entry registry under
  :data:`DEFAULT_MODEL`, so every existing fleet, test, and CLI invocation
  keeps working unchanged.
* **Atomic flips.** Promotion completes by rewriting the registry through a
  tmp-file + ``os.replace`` so a crashed promoter can never leave a torn
  document; the version counter is the client-visible artifact identity
  (``/healthz`` grows it) and only ever moves forward.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
from typing import Dict, List, Optional, Tuple

REGISTRY_FILENAME = "registry.json"
SCHEMA_VERSION = 1

# the implicit tenant name legacy single-artifact fleets (and requests that
# don't name a model) resolve to
DEFAULT_MODEL = "default"

# ledger event emitted when a registry entry's version flips (promotion)
REGISTRY_FLIP_EVENT = "registry_flip"


class RegistryError(ValueError):
    """The registry document is corrupt, unknown-versioned, or carries
    fields this build does not understand."""


def _expect(cond: bool, msg: str) -> None:
    if not cond:
        raise RegistryError(f"registry.json: {msg}")


@dataclasses.dataclass
class ModelEntry:
    """One tenant: a named model with its own artifact, ladder, and SLO.

    ``weight`` is the fair-share weight the router sheds against under
    saturation; ``chips_per_replica``/``device_slots`` describe how this
    model's replicas claim chips on the host (device_slots are visible-device
    masks handed round-robin to the model's replicas, the PR-9 follow-on
    that lets two tenants split one multi-chip host).
    """

    name: str
    artifact_dir: str
    version: int = 1
    buckets: Optional[Tuple[int, ...]] = None  # None -> fleet default ladder
    prewarm_budget: Optional[int] = None  # None -> warm the whole ladder
    weight: float = 1.0
    slo_p99_ms: Optional[float] = None
    slo_error_budget: Optional[float] = None
    replicas: int = 1  # initial replica count at fleet start
    min_replicas: int = 1
    max_replicas: Optional[int] = None
    chips_per_replica: int = 1
    device_slots: Optional[Tuple[str, ...]] = None

    # every key the on-disk entry may carry; anything else is a hard error
    _FIELDS = (
        "name",
        "artifact_dir",
        "version",
        "buckets",
        "prewarm_budget",
        "weight",
        "slo_p99_ms",
        "slo_error_budget",
        "replicas",
        "min_replicas",
        "max_replicas",
        "chips_per_replica",
        "device_slots",
    )

    def __post_init__(self):
        _expect(
            isinstance(self.name, str) and self.name,
            f"model name must be a non-empty string, got {self.name!r}",
        )
        _expect(
            "/" not in self.name and not self.name.startswith("."),
            f"model name {self.name!r} must not look like a path",
        )
        _expect(
            isinstance(self.artifact_dir, str) and self.artifact_dir,
            f"model {self.name!r}: artifact_dir must be a non-empty string",
        )
        _expect(
            isinstance(self.version, int)
            and not isinstance(self.version, bool)
            and self.version >= 1,
            f"model {self.name!r}: version must be an int >= 1, "
            f"got {self.version!r}",
        )
        if self.buckets is not None:
            _expect(
                all(isinstance(b, int) and b >= 1 for b in self.buckets)
                and len(self.buckets) > 0,
                f"model {self.name!r}: buckets must be positive ints",
            )
            self.buckets = tuple(sorted({int(b) for b in self.buckets}))
        if self.prewarm_budget is not None:
            _expect(
                isinstance(self.prewarm_budget, int)
                and not isinstance(self.prewarm_budget, bool)
                and self.prewarm_budget >= 0,
                f"model {self.name!r}: prewarm_budget must be an int >= 0",
            )
        _expect(
            isinstance(self.weight, (int, float))
            and not isinstance(self.weight, bool)
            and self.weight > 0,
            f"model {self.name!r}: weight must be > 0",
        )
        for knob in ("slo_p99_ms", "slo_error_budget"):
            v = getattr(self, knob)
            if v is not None:
                _expect(
                    isinstance(v, (int, float))
                    and not isinstance(v, bool)
                    and v > 0,
                    f"model {self.name!r}: {knob} must be > 0",
                )
        _expect(
            isinstance(self.replicas, int) and self.replicas >= 1,
            f"model {self.name!r}: replicas must be an int >= 1",
        )
        _expect(
            isinstance(self.min_replicas, int) and self.min_replicas >= 1,
            f"model {self.name!r}: min_replicas must be an int >= 1",
        )
        if self.max_replicas is not None:
            _expect(
                isinstance(self.max_replicas, int)
                and self.max_replicas >= self.min_replicas,
                f"model {self.name!r}: max_replicas must be >= min_replicas",
            )
        _expect(
            isinstance(self.chips_per_replica, int)
            and self.chips_per_replica >= 1,
            f"model {self.name!r}: chips_per_replica must be an int >= 1",
        )
        if self.device_slots is not None:
            _expect(
                len(self.device_slots) > 0
                and all(
                    isinstance(s, str) and s for s in self.device_slots
                ),
                f"model {self.name!r}: device_slots must be non-empty "
                "strings (visible-device masks like '0,1')",
            )
            self.device_slots = tuple(self.device_slots)

    @classmethod
    def from_json(cls, obj: Dict) -> "ModelEntry":
        _expect(
            isinstance(obj, dict),
            f"model entry must be an object, got {type(obj).__name__}",
        )
        unknown = sorted(set(obj) - set(cls._FIELDS))
        _expect(
            not unknown,
            f"model entry {obj.get('name')!r} carries unknown field(s) "
            f"{unknown} — this build does not understand them",
        )
        _expect("name" in obj, "model entry missing required field 'name'")
        _expect(
            "artifact_dir" in obj,
            f"model {obj['name']!r} missing required field 'artifact_dir'",
        )
        kwargs = dict(obj)
        for seq_field in ("buckets", "device_slots"):
            if kwargs.get(seq_field) is not None:
                _expect(
                    isinstance(kwargs[seq_field], list),
                    f"model {obj['name']!r}: {seq_field} must be a list",
                )
                kwargs[seq_field] = tuple(kwargs[seq_field])
        return cls(**kwargs)

    def to_json(self) -> Dict:
        out: Dict = {
            "name": self.name,
            "artifact_dir": self.artifact_dir,
            "version": self.version,
        }
        for field in self._FIELDS[3:]:
            v = getattr(self, field)
            default = next(
                f.default for f in dataclasses.fields(self) if f.name == field
            )
            if v != default:
                out[field] = list(v) if isinstance(v, tuple) else v
        return out

    def device_slot(self, ordinal: int) -> Optional[str]:
        """Visible-device mask for this model's ``ordinal``-th replica
        (round-robin over the declared slots)."""
        if not self.device_slots:
            return None
        return self.device_slots[ordinal % len(self.device_slots)]


class Registry:
    """The loaded document: ordered model entries plus the path to flip."""

    def __init__(
        self,
        models: List[ModelEntry],
        *,
        path: Optional[str] = None,
        implicit: bool = False,
    ):
        _expect(len(models) > 0, "registry must hold at least one model")
        names = [m.name for m in models]
        _expect(
            len(set(names)) == len(names),
            f"duplicate model names: {sorted(names)}",
        )
        self.models: Dict[str, ModelEntry] = {m.name: m for m in models}
        self.path = path
        # True when synthesized from a legacy single-artifact workdir —
        # there is no document on disk to rewrite
        self.implicit = implicit
        self._lock = threading.Lock()

    def __contains__(self, name: str) -> bool:
        return name in self.models

    def __len__(self) -> int:
        return len(self.models)

    def entry(self, name: str) -> ModelEntry:
        try:
            return self.models[name]
        except KeyError:
            raise RegistryError(
                f"unknown model {name!r}; registry holds "
                f"{sorted(self.models)}"
            ) from None

    def names(self) -> List[str]:
        return list(self.models)

    def total_weight(self) -> float:
        return sum(m.weight for m in self.models.values())

    def to_json(self) -> Dict:
        return {
            "schema_version": SCHEMA_VERSION,
            "models": [m.to_json() for m in self.models.values()],
        }

    def save(self, path: Optional[str] = None) -> str:
        """Atomically persist the document (tmp + rename)."""
        path = path or self.path
        if path is None:
            raise RegistryError("registry has no path to save to")
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(self.to_json(), f, indent=2, sort_keys=True)
            f.write("\n")
        os.replace(tmp, path)
        self.path = path
        return path

    def set_version(
        self,
        name: str,
        artifact_dir: str,
        *,
        version: Optional[int] = None,
        telemetry=None,
    ) -> ModelEntry:
        """The promotion flip: point ``name`` at a new artifact dir and bump
        its version, rewriting the on-disk document atomically. Other
        entries are untouched — tenants keep serving through the flip."""
        with self._lock:
            entry = self.entry(name)
            old_version = entry.version
            entry.artifact_dir = artifact_dir
            entry.version = (
                version if version is not None else old_version + 1
            )
            _expect(
                entry.version > old_version,
                f"model {name!r}: version must move forward "
                f"({old_version} -> {entry.version})",
            )
            if not self.implicit and self.path:
                self.save()
        if telemetry is not None:
            telemetry.event(
                REGISTRY_FLIP_EVENT,
                model=name,
                artifact_dir=artifact_dir,
                version=entry.version,
                previous_version=old_version,
            )
        return entry


def registry_path(workdir: str) -> str:
    return os.path.join(workdir, REGISTRY_FILENAME)


def write_registry(workdir: str, models: List[ModelEntry]) -> Registry:
    reg = Registry(models, path=registry_path(workdir))
    reg.save()
    return reg


def _load_document(path: str) -> List[ModelEntry]:
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except json.JSONDecodeError as e:
        raise RegistryError(f"registry.json is not valid JSON: {e}") from e
    _expect(isinstance(doc, dict), "top level must be an object")
    unknown = sorted(set(doc) - {"schema_version", "models"})
    _expect(not unknown, f"unknown top-level field(s) {unknown}")
    _expect(
        doc.get("schema_version") == SCHEMA_VERSION,
        f"schema_version {doc.get('schema_version')!r} is not the "
        f"supported version {SCHEMA_VERSION}",
    )
    _expect(
        isinstance(doc.get("models"), list) and doc["models"],
        "'models' must be a non-empty list",
    )
    return [ModelEntry.from_json(m) for m in doc["models"]]


def read_registry(
    workdir: str,
    *,
    default_artifact_dir: Optional[str] = None,
    path: Optional[str] = None,
) -> Registry:
    """Load the workdir's registry, or synthesize the legacy implicit one.

    Resolution order:

    1. explicit ``path`` (``serve-fleet --registry``),
    2. ``<workdir>/registry.json``,
    3. legacy fallback — ``default_artifact_dir`` (the old
       ``--artifact-dir`` flag) becomes a one-entry implicit registry under
       :data:`DEFAULT_MODEL`.
    """
    if path is not None:
        return Registry(_load_document(path), path=path)
    candidate = registry_path(workdir) if workdir else None
    if candidate and os.path.exists(candidate):
        return Registry(_load_document(candidate), path=candidate)
    if default_artifact_dir is not None:
        return Registry(
            [ModelEntry(name=DEFAULT_MODEL, artifact_dir=default_artifact_dir)],
            implicit=True,
        )
    raise RegistryError(
        f"no {REGISTRY_FILENAME} in {workdir!r} and no legacy "
        "--artifact-dir to fall back to"
    )
